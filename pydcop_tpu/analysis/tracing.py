"""graftlint pass 2: JAX tracing hazards in jit-reachable code.

A function is *traced* when it is decorated with ``jit`` (including
``partial(jax.jit, static_argnames=...)``), passed to a jax combinator
(``lax.scan``/``while_loop``/``cond``/``fori_loop``/``switch``,
``vmap``, ``pmap``, ``shard_map``, ...), nested inside a traced
function and handed to a combinator, or called from a traced function
(per-call-site argument tracedness is propagated, module-locally).

Within a traced function, *traced values* are its non-static
parameters and anything data-derived from them or from ``jnp``/``lax``
calls.  Shape/dtype/ndim/size attributes are compile-time constants
and never traced; ``x is None`` / ``isinstance`` tests are static
dispatch and never flagged.

Rules:

* ``trace-python-branch`` — Python ``if``/``while`` on a traced value:
  raises ``TracerBoolConversionError`` at trace time (or silently
  freezes one branch under ``vmap``/``scan``).
* ``trace-host-sync`` — ``.item()``, ``.tolist()``, ``float()``/
  ``int()``/``bool()``, ``np.asarray()`` or ``jax.device_get`` on a
  traced value: blocks on device transfer, or fails under jit.
* ``trace-impure-call`` — ``time.*``, ``random.*``, ``np.random.*``,
  ``datetime.now``, ``uuid`` inside traced code: executes once at
  trace time and is baked into the compiled program as a constant.
* ``trace-shape-loop`` — a Python loop whose trip count depends on an
  argument's shape (``range(x.shape[0])``, ``range(len(x))``, or
  iterating a traced array): unrolls into the program and recompiles
  for every new shape.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, Rule, SourceFile, dotted_name as _dotted

__all__ = ["RULES", "run"]

#: bumped when the pass's behavior changes, so the incremental lint
#: cache (analysis/cache.py) never serves findings from an older rule set
VERSION = 1

RULES = (
    Rule(
        "trace-python-branch",
        "error",
        "Python if/while on a traced value inside jit-reachable code",
    ),
    Rule(
        "trace-host-sync",
        "error",
        "host synchronisation on a traced value inside jit-reachable code",
    ),
    Rule(
        "trace-impure-call",
        "warning",
        "impure call inside traced code runs once at trace time",
    ),
    Rule(
        "trace-shape-loop",
        "warning",
        "shape-dependent Python loop unrolls and recompiles per shape",
    ),
)

#: rule id -> (doc, minimal failing example) for ``lint --explain``
EXPLAIN = {
    "trace-python-branch": (
        "Python `if`/`while` tests a traced value inside jit-reachable "
        "code: tracing raises TracerBoolConversionError (or freezes "
        "one branch under vmap/scan). Use jnp.where or lax.cond.",
        "@jax.jit\n"
        "def f(x):\n"
        "    if x > 0:  # x is traced\n"
        "        return x\n"
        "    return -x\n",
    ),
    "trace-host-sync": (
        ".item()/.tolist(), float()/int()/bool(), np.asarray or "
        "device_get on a traced value: fails under jit, and eagerly it "
        "blocks on a device->host transfer.",
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(x.sum())\n",
    ),
    "trace-impure-call": (
        "time.*, random.*, np.random.*, datetime.now, uuid.* inside "
        "traced code runs ONCE at trace time and is baked into the "
        "compiled program as a constant.",
        "@jax.jit\n"
        "def f(x):\n"
        "    return x + time.time()  # frozen at trace time\n",
    ),
    "trace-shape-loop": (
        "A Python loop whose trip count depends on an argument's shape "
        "(range(x.shape[0]), range(len(x)), or iterating a traced "
        "array) unrolls into the program and recompiles for every new "
        "shape. Use lax.scan / lax.fori_loop.",
        "@jax.jit\n"
        "def f(x):\n"
        "    for i in range(x.shape[0]):\n"
        "        ...\n",
    ),
}

#: ``profiled_jit`` (telemetry/profiling.py) is a drop-in jax.jit with
#: compile observability — its functions trace identically, so the
#: tracing-hazard analysis must cover them the same way
_JIT_NAMES = {"jit", "pjit", "profiled_jit"}
_COMBINATOR_TAILS = {
    "scan", "while_loop", "fori_loop", "cond", "switch", "associative_scan",
    "jit", "pjit", "vmap", "pmap", "shard_map", "grad", "value_and_grad",
    "remat", "checkpoint", "custom_jvp", "custom_vjp",
}
_COMBINATOR_BARE = {
    "jit", "pjit", "vmap", "pmap", "shard_map", "scan", "while_loop",
    "fori_loop", "cond", "switch",
}
_JAX_ROOTS = ("jax", "lax", "jnp", "pjit")
# .shape/.dtype/... are compile-time constants under tracing; the
# n_vars/n_edges/... names are this repo's DeviceDCOP static pytree aux
# fields (kernels.py registers the scalar shape fields as aux data, so
# they stay concrete ints under jit)
_STATIC_ATTRS = {
    "shape", "ndim", "dtype", "size", "sharding", "aval",
    "n_vars", "n_edges", "max_domain", "n_constraints", "arity",
}
_STATIC_FUNCS = {"isinstance", "callable", "len", "hasattr", "type",
                 "getattr", "id", "repr", "str"}
_CAST_FUNCS = {"float", "int", "bool", "complex"}
_NP_SYNC = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
            "onp.asarray", "onp.array", "jax.device_get", "device_get"}
_SYNC_METHODS = {"item", "tolist", "__array__"}
_IMPURE_EXACT = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.process_time", "time.time_ns", "time.sleep",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow", "uuid.uuid4", "uuid.uuid1",
    "os.urandom", "input",
}
_IMPURE_PREFIXES = ("random.", "np.random.", "numpy.random.",
                    "secrets.")


def _decorator_jit_statics(
    fn: ast.FunctionDef,
) -> Optional[Tuple[Set[str], Set[int]]]:
    """(static_argnames, static_argnums) when ``fn`` is jit-decorated,
    else None."""
    for dec in fn.decorator_list:
        target = dec
        names: Set[str] = set()
        nums: Set[int] = set()
        if isinstance(dec, ast.Call):
            d = _dotted(dec.func)
            if d and (d.split(".")[-1] == "partial"):
                # partial(jax.jit, static_argnames=...)
                if not dec.args:
                    continue
                inner = _dotted(dec.args[0])
                if not inner or inner.split(".")[-1] not in _JIT_NAMES:
                    continue
                for kw in dec.keywords:
                    if kw.arg == "static_argnames":
                        names |= _str_elements(kw.value)
                    elif kw.arg == "static_argnums":
                        nums |= _int_elements(kw.value)
                return names, nums
            if d and d.split(".")[-1] in _JIT_NAMES:
                # @jax.jit(static_argnames=...)
                for kw in dec.keywords:
                    if kw.arg == "static_argnames":
                        names |= _str_elements(kw.value)
                    elif kw.arg == "static_argnums":
                        nums |= _int_elements(kw.value)
                return names, nums
            continue
        d = _dotted(target)
        if d and d.split(".")[-1] in _JIT_NAMES:
            return names, nums
    return None


def _str_elements(node: ast.expr) -> Set[str]:
    out: Set[str] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(
                elt.value, str
            ):
                out.add(elt.value)
    return out


def _int_elements(node: ast.expr) -> Set[int]:
    out: Set[int] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(
                elt.value, int
            ):
                out.add(elt.value)
    return out


_FuncNode = ast.FunctionDef  # AsyncFunctionDef never traced


@dataclass
class _Analysis:
    sf: SourceFile
    findings: List[Finding]
    module_funcs: Dict[str, ast.FunctionDef]
    # every def at any nesting depth, for combinator-callback seeding
    # (closures handed to lax.scan inside undecorated host functions)
    all_funcs: Dict[str, ast.FunctionDef]
    # (id(func), traced-param signature) already analyzed
    seen: Set[Tuple[int, Tuple[bool, ...]]]


def _param_names(fn: ast.FunctionDef) -> List[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args]
    if args.vararg:
        names.append(args.vararg.arg)
    names += [a.arg for a in args.kwonlyargs]
    if args.kwarg:
        names.append(args.kwarg.arg)
    return names


class _TracedFunctionChecker:
    """Walks one traced function body with a name -> traced map."""

    def __init__(
        self,
        an: _Analysis,
        fn: ast.FunctionDef,
        env: Dict[str, bool],
        local_funcs: Dict[str, ast.FunctionDef],
    ) -> None:
        self.an = an
        self.fn = fn
        self.env = env
        self.local_funcs = dict(local_funcs)
        for stmt in fn.body:
            if isinstance(stmt, ast.FunctionDef):
                self.local_funcs[stmt.name] = stmt

    # -- tracedness ---------------------------------------------------

    def is_traced(self, node: ast.expr) -> bool:
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return self.env.get(node.id, False)
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.is_traced(node.value)
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d:
                root = d.split(".")[0]
                tail = d.split(".")[-1]
                if tail in _STATIC_FUNCS or tail in _CAST_FUNCS:
                    return False
                if root in _JAX_ROOTS:
                    return True
            # unknown callee: data flows through (helper functions on
            # traced operands return traced results)
            return any(
                self.is_traced(a)
                for a in list(node.args)
                + [kw.value for kw in node.keywords]
            ) or (
                isinstance(node.func, ast.Attribute)
                and self.is_traced(node.func.value)
            )
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.is_traced(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(
                self.is_traced(v)
                for v in list(node.keys) + list(node.values)
                if v is not None
            )
        if isinstance(node, ast.Compare):
            if self._is_static_compare(node):
                return False
            return self.is_traced(node.left) or any(
                self.is_traced(c) for c in node.comparators
            )
        if isinstance(node, ast.BoolOp):
            return any(self.is_traced(v) for v in node.values)
        if isinstance(node, (ast.BinOp,)):
            return self.is_traced(node.left) or self.is_traced(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_traced(node.operand)
        if isinstance(node, ast.Subscript):
            return self.is_traced(node.value) or self.is_traced(node.slice)
        if isinstance(node, ast.IfExp):
            return (
                self.is_traced(node.test)
                or self.is_traced(node.body)
                or self.is_traced(node.orelse)
            )
        if isinstance(node, ast.Starred):
            return self.is_traced(node.value)
        if isinstance(node, (ast.Lambda, ast.FunctionDef)):
            return False
        if isinstance(node, ast.JoinedStr):
            return False
        return any(
            self.is_traced(c)
            for c in ast.iter_child_nodes(node)
            if isinstance(c, ast.expr)
        )

    @staticmethod
    def _is_static_compare(node: ast.Compare) -> bool:
        """``x is None`` / ``x is not None``: static dispatch, not a
        data comparison."""
        return all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
        )

    # -- reporting ----------------------------------------------------

    def _emit(self, rule: str, severity: str, node: ast.AST,
              message: str) -> None:
        self.an.findings.append(
            Finding(
                rule=rule,
                severity=severity,
                path=self.an.sf.path,
                line=getattr(node, "lineno", self.fn.lineno),
                col=getattr(node, "col_offset", 0) + 1,
                message=message,
            )
        )

    # -- walk ---------------------------------------------------------

    def check(self) -> None:
        self._visit_body(self.fn.body)

    def _visit_body(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            self._visit_stmt(stmt)

    def _visit_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.FunctionDef):
            # nested defs are analyzed when passed to a combinator or
            # called; the def itself executes nothing
            return
        if isinstance(stmt, (ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(stmt, ast.Assign):
            self._visit_expr(stmt.value)
            traced = self.is_traced(stmt.value)
            for t in stmt.targets:
                self._bind_target(t, traced)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._visit_expr(stmt.value)
                self._bind_target(stmt.target, self.is_traced(stmt.value))
            return
        if isinstance(stmt, ast.AugAssign):
            self._visit_expr(stmt.value)
            if isinstance(stmt.target, ast.Name):
                prev = self.env.get(stmt.target.id, False)
                self.env[stmt.target.id] = (
                    prev or self.is_traced(stmt.value)
                )
            return
        if isinstance(stmt, ast.If):
            self._visit_expr(stmt.test)
            if self.is_traced(stmt.test):
                self._emit(
                    "trace-python-branch",
                    "error",
                    stmt,
                    f"`if` on traced value inside "
                    f"{self.fn.name}(): Python control flow is "
                    f"evaluated at trace time; use jnp.where / "
                    f"lax.cond",
                )
            self._visit_body(stmt.body)
            self._visit_body(stmt.orelse)
            return
        if isinstance(stmt, ast.While):
            self._visit_expr(stmt.test)
            if self.is_traced(stmt.test):
                self._emit(
                    "trace-python-branch",
                    "error",
                    stmt,
                    f"`while` on traced value inside "
                    f"{self.fn.name}(): use lax.while_loop",
                )
            self._visit_body(stmt.body)
            self._visit_body(stmt.orelse)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._visit_expr(stmt.iter)
            self._check_shape_loop(stmt)
            self._bind_target(stmt.target, self.is_traced(stmt.iter))
            self._visit_body(stmt.body)
            self._visit_body(stmt.orelse)
            return
        if isinstance(stmt, ast.Try):
            self._visit_body(stmt.body)
            for h in stmt.handlers:
                self._visit_body(h.body)
            self._visit_body(stmt.orelse)
            self._visit_body(stmt.finalbody)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._visit_expr(item.context_expr)
            self._visit_body(stmt.body)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._visit_expr(child)

    def _bind_target(self, target: ast.expr, traced: bool) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = traced
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind_target(elt, traced)
        elif isinstance(target, ast.Starred):
            self._bind_target(target.value, traced)

    def _check_shape_loop(self, stmt) -> None:
        it = stmt.iter
        if isinstance(it, ast.Call):
            d = _dotted(it.func)
            if (
                d
                and d.split(".")[-1] in (
                    "zip", "enumerate", "reversed", "items", "keys",
                    "values",
                )
                and not any(self.is_traced(a) for a in it.args)
            ):
                # looping over zipped static containers of arrays is
                # the idiomatic static unroll, not a traced iteration —
                # but enumerate/zip over a traced array still unrolls
                # per shape, so the exemption needs untraced arguments
                return
        if self.is_traced(it):
            self._emit(
                "trace-shape-loop",
                "warning",
                stmt,
                f"Python loop over a traced array in "
                f"{self.fn.name}() unrolls into the program; use "
                f"lax.scan / lax.fori_loop",
            )
            return
        if isinstance(it, ast.Call):
            d = _dotted(it.func)
            if d in ("range", "builtins.range"):
                for arg in it.args:
                    if self._is_shape_dependent(arg):
                        self._emit(
                            "trace-shape-loop",
                            "warning",
                            stmt,
                            f"loop trip count in {self.fn.name}() "
                            f"depends on an argument's shape: the "
                            f"loop unrolls and recompiles for every "
                            f"new shape",
                        )
                        return

    def _is_shape_dependent(self, node: ast.expr) -> bool:
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Attribute)
                and sub.attr in ("shape", "size", "ndim")
                and self._mentions_traced_name(sub.value)
            ):
                return True
            if isinstance(sub, ast.Call):
                d = _dotted(sub.func)
                if d == "len" and sub.args and self._mentions_traced_name(
                    sub.args[0]
                ):
                    return True
        return False

    def _mentions_traced_name(self, node: ast.expr) -> bool:
        return any(
            isinstance(n, ast.Name) and self.env.get(n.id, False)
            for n in ast.walk(node)
        )

    def _visit_expr(self, node: ast.expr) -> None:
        if isinstance(node, (ast.Lambda, ast.FunctionDef)):
            return
        if isinstance(node, ast.Call):
            self._visit_call(node)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._visit_expr(child)

    def _visit_call(self, node: ast.Call) -> None:
        d = _dotted(node.func)
        args = list(node.args) + [kw.value for kw in node.keywords]

        if d is not None:
            tail = d.split(".")[-1]
            # impure host calls baked in at trace time
            if d in _IMPURE_EXACT or any(
                d.startswith(p) for p in _IMPURE_PREFIXES
            ):
                self._emit(
                    "trace-impure-call",
                    "warning",
                    node,
                    f"{d}() inside traced {self.fn.name}() runs once "
                    f"at trace time and becomes a compiled constant",
                )
            # host sync: float(x) / np.asarray(x) / device_get(x)
            if (
                tail in _CAST_FUNCS and d == tail
                or d in _NP_SYNC
            ) and any(self.is_traced(a) for a in args):
                self._emit(
                    "trace-host-sync",
                    "error",
                    node,
                    f"{d}() on a traced value in {self.fn.name}() "
                    f"forces a host transfer (fails under jit)",
                )
            # combinator: analyze function-valued arguments as traced
            if tail in _COMBINATOR_TAILS and (
                d.split(".")[0] in _JAX_ROOTS or d in _COMBINATOR_BARE
            ):
                for arg in node.args:
                    self._maybe_analyze_fn_arg(arg)
                for kw in node.keywords:
                    self._maybe_analyze_fn_arg(kw.value)
        # .item() / .tolist() on a traced value
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _SYNC_METHODS
            and self.is_traced(node.func.value)
        ):
            self._emit(
                "trace-host-sync",
                "error",
                node,
                f".{node.func.attr}() on a traced value in "
                f"{self.fn.name}() forces a host transfer",
            )
        # call of a module-local / nested function: propagate per-arg
        # tracedness into its body
        if isinstance(node.func, ast.Name):
            target = self.local_funcs.get(
                node.func.id
            ) or self.an.module_funcs.get(node.func.id)
            if target is not None:
                flags = self._call_flags(target, node)
                _analyze_traced(
                    self.an, target, flags, dict(self.env),
                    self.local_funcs,
                )
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._visit_expr(child)

    def _call_flags(
        self, target: ast.FunctionDef, call: ast.Call
    ) -> Dict[str, bool]:
        names = _param_names(target)
        flags = {n: False for n in names}
        pos = [a.arg for a in target.args.posonlyargs + target.args.args]
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break
            if i < len(pos):
                flags[pos[i]] = self.is_traced(arg)
        for kw in call.keywords:
            if kw.arg in flags:
                flags[kw.arg] = self.is_traced(kw.value)
        return flags

    def _maybe_analyze_fn_arg(self, arg: ast.expr) -> None:
        if isinstance(arg, ast.Name):
            target = self.local_funcs.get(
                arg.id
            ) or self.an.module_funcs.get(arg.id)
            if target is not None:
                flags = {n: True for n in _param_names(target)}
                _analyze_traced(
                    self.an, target, flags, dict(self.env),
                    self.local_funcs,
                )
        elif isinstance(arg, (ast.List, ast.Tuple)):
            for elt in arg.elts:
                self._maybe_analyze_fn_arg(elt)
        # lambdas: no statements, so branch/loop rules cannot apply;
        # walk the body expression for sync/impure calls with the
        # parameters traced
        elif isinstance(arg, ast.Lambda):
            sub = _TracedFunctionChecker.__new__(_TracedFunctionChecker)
            sub.an = self.an
            sub.fn = self.fn
            sub.env = dict(self.env)
            for a in (
                arg.args.posonlyargs + arg.args.args + arg.args.kwonlyargs
            ):
                sub.env[a.arg] = True
            sub.local_funcs = self.local_funcs
            sub._visit_expr(arg.body)


def _analyze_traced(
    an: _Analysis,
    fn: ast.FunctionDef,
    param_flags: Dict[str, bool],
    closure_env: Dict[str, bool],
    local_funcs: Dict[str, ast.FunctionDef],
) -> None:
    names = _param_names(fn)
    sig = tuple(param_flags.get(n, False) for n in names)
    key = (id(fn), sig)
    if key in an.seen or len(an.seen) > 4000:
        return
    an.seen.add(key)
    env = dict(closure_env)
    for n in names:
        env[n] = param_flags.get(n, False)
    for skip in ("self", "cls"):
        if skip in env:
            env[skip] = False
    _TracedFunctionChecker(an, fn, env, local_funcs).check()


def _module_functions(tree: ast.Module) -> Dict[str, ast.FunctionDef]:
    return {
        n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)
    }


def _collect_seeds(
    an: _Analysis, tree: ast.Module
) -> None:
    # jit-decorated functions anywhere (module level, methods, nested)
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        statics = _decorator_jit_statics(node)
        if statics is None:
            continue
        static_names, static_nums = statics
        names = _param_names(node)
        pos = [
            a.arg for a in node.args.posonlyargs + node.args.args
        ]
        flags = {n: n not in static_names for n in names}
        for i in static_nums:
            if 0 <= i < len(pos):
                flags[pos[i]] = False
        _analyze_traced(an, node, flags, {}, {})
    # module-level `f` passed to a combinator outside any traced
    # function (e.g. `stepper = jax.jit(step_fn)`)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if not d:
            continue
        tail = d.split(".")[-1]
        if tail not in _COMBINATOR_TAILS or not (
            d.split(".")[0] in _JAX_ROOTS or d in _COMBINATOR_BARE
        ):
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name):
                target = an.module_funcs.get(arg.id) or an.all_funcs.get(
                    arg.id
                )
                if target is not None:
                    flags = {n: True for n in _param_names(target)}
                    _analyze_traced(an, target, flags, {}, {})


def run(files: List[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        an = _Analysis(
            sf=sf,
            findings=[],
            module_funcs=_module_functions(sf.tree),
            all_funcs={
                n.name: n for n in ast.walk(sf.tree)
                if isinstance(n, ast.FunctionDef)
            },
            seen=set(),
        )
        _collect_seeds(an, sf.tree)
        # de-duplicate repeats from multi-signature analysis of the
        # same function: keep one finding per (rule, line, col)
        uniq: Dict[Tuple[str, int, int], Finding] = {}
        for f in an.findings:
            uniq.setdefault((f.rule, f.line, f.col), f)
        findings.extend(uniq.values())
    return findings
