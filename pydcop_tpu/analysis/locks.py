"""graftlint pass 1: lock discipline over thread-shared classes.

Inference, per class: an attribute is a *lock* when it is assigned a
``threading.Lock``/``RLock``/``Condition``/``Semaphore`` in any method,
or when its name looks lock-like and it appears as a ``with self.X:``
context.  An attribute is *guarded by* a lock when some method writes it
inside a ``with``-block on that lock.

Rules:

* ``lock-unguarded-write`` — a guarded attribute is written outside any
  lock scope (outside ``__init__``).
* ``lock-unguarded-read`` — a guarded attribute is read outside any
  lock scope (outside ``__init__``).
* ``lock-post-outside`` — a value computed under a lock decides or
  feeds a message post *after* the lock was released (the discovery.py
  directory-event race: a concurrent subscriber can interleave between
  the decision and the send).
* ``lock-order-cycle`` — the class's lock-acquisition-order graph
  (direct ``with`` nesting plus one-class method calls made while
  holding a lock) contains a cycle: a potential deadlock.

Code inside nested functions and lambdas runs at an unknown time, so it
neither establishes guarded-by facts nor triggers access findings.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .core import Finding, Rule, SourceFile

__all__ = ["RULES", "run"]

#: bumped when the pass's behavior changes, so the incremental lint
#: cache (analysis/cache.py) never serves findings from an older rule set
VERSION = 1

RULES = (
    Rule(
        "lock-unguarded-write",
        "error",
        "attribute written under a lock elsewhere is written without it",
    ),
    Rule(
        "lock-unguarded-read",
        "warning",
        "attribute written under a lock elsewhere is read without it",
    ),
    Rule(
        "lock-post-outside",
        "error",
        "message post decided/fed by lock-guarded state after release",
    ),
    Rule(
        "lock-order-cycle",
        "warning",
        "lock acquisition order cycle (potential deadlock)",
    ),
)

#: rule id -> (doc, minimal failing example) for ``lint --explain``
EXPLAIN = {
    "lock-unguarded-write": (
        "An attribute that is written inside `with self._lock:` "
        "somewhere in the class is also written with no lock held "
        "(outside __init__): the unguarded write races every guarded "
        "reader.",
        "def put(self, k, v):\n"
        "    with self._lock:\n"
        "        self._items[k] = v\n"
        "def clear_fast(self):\n"
        "    self._items = {}  # races put()\n",
    ),
    "lock-unguarded-read": (
        "An attribute the class treats as lock-guarded is read with no "
        "lock held: the reader can observe a torn/mid-update value.",
        "def peek(self, k):\n"
        "    return self._items.get(k)  # guarded writes elsewhere\n",
    ),
    "lock-post-outside": (
        "A value computed under a lock decides or feeds a post_msg/"
        "send-style call after the lock is released — the state can "
        "change between the decision and the send (the discovery.py "
        "directory-event race).",
        "with self._lock:\n"
        "    emptied = not self._cbs\n"
        "if emptied:\n"
        "    self.post_msg(d, unsubscribe())  # decided under the lock\n",
    ),
    "lock-order-cycle": (
        "Two locks are acquired in opposite orders on different paths "
        "(directly nested `with`, or a method call made while holding "
        "one): two threads can deadlock holding one lock each.",
        "def a(self):\n"
        "    with self._l1:\n"
        "        with self._l2: ...\n"
        "def b(self):\n"
        "    with self._l2:\n"
        "        with self._l1: ...\n",
    ),
}

_LOCK_NAME_RE = re.compile(r"(?i)(lock|mutex|mtx)")
_LOCK_CTORS = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
}
_MUTATORS = {
    "append", "appendleft", "add", "insert", "extend", "update",
    "setdefault", "pop", "popitem", "remove", "discard", "clear",
}
_SEND_NAMES = {"post_msg", "send_msg", "send", "post", "publish", "emit"}
_NESTED_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                  ast.ClassDef)


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _callee_tail(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _names_in(node: ast.AST) -> Set[str]:
    return {
        n.id for n in ast.walk(node)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }


@dataclass
class _Access:
    attr: str
    kind: str  # 'read' | 'write'
    line: int
    col: int
    method: str
    locks: FrozenSet[str]


@dataclass
class _MethodFacts:
    name: str
    accesses: List[_Access] = field(default_factory=list)
    # locks this method acquires anywhere in its own body
    acquires: Set[str] = field(default_factory=set)
    # methods of the same class it calls while holding each lock set
    calls: List[Tuple[str, FrozenSet[str], int, int]] = field(
        default_factory=list
    )
    # direct `with B:` inside `with A:` -> (A, B, line, col)
    nest_edges: List[Tuple[str, str, int, int]] = field(
        default_factory=list
    )
    # send-like call outside any lock that uses a name computed under a
    # lock released before the call
    post_outside: List[Tuple[str, str, int, int]] = field(
        default_factory=list
    )


class _MethodVisitor:
    """One walk of a method body, tracking the held-lock stack, the
    enclosing-``if`` condition names, and names assigned under a lock."""

    def __init__(self, lock_attrs: Set[str], method: str) -> None:
        self.lock_attrs = lock_attrs
        self.facts = _MethodFacts(method)
        # name -> end line of the with-block it was computed in
        self.lock_computed: Dict[str, int] = {}
        self._locks: List[str] = []
        self._if_names: List[Set[str]] = []

    # -- access recording ---------------------------------------------

    def _record(self, attr: str, kind: str, node: ast.AST) -> None:
        if attr in self.lock_attrs:
            return
        self.facts.accesses.append(
            _Access(
                attr,
                kind,
                getattr(node, "lineno", 0),
                getattr(node, "col_offset", 0) + 1,
                self.facts.name,
                frozenset(self._locks),
            )
        )

    def _record_target(self, target: ast.expr) -> None:
        """A write target: ``self.x``, ``self.x[k]``, or a tuple of
        those.  Subscript/slice stores mutate the underlying container,
        so they count as writes of the attribute."""
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._record_target(elt)
            return
        if isinstance(target, ast.Starred):
            self._record_target(target.value)
            return
        inner = target
        while isinstance(inner, ast.Subscript):
            inner = inner.value
        attr = _self_attr(inner)
        if attr is not None:
            self._record(attr, "write", target)
            sub = target
            while isinstance(sub, ast.Subscript):
                self._visit_expr(sub.slice)
                sub = sub.value
            return
        # plain local name: remember it when computed under a lock, for
        # the post-outside rule; a rebind outside any lock clears the
        # taint (the sent value is no longer lock-derived)
        if isinstance(target, ast.Name):
            if self._locks:
                self.lock_computed.setdefault(target.id, self._with_end)
            else:
                self.lock_computed.pop(target.id, None)
        self._visit_expr(target)

    # -- statement walk -----------------------------------------------

    def visit_body(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self.visit_stmt(stmt)

    def visit_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, _NESTED_SCOPES):
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._visit_with(stmt)
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            if value is not None:
                self._visit_expr(value)
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign)
                else [stmt.target]
            )
            if isinstance(stmt, ast.AugAssign):
                # x += v reads then writes x
                attr = _self_attr(stmt.target)
                if attr is not None:
                    self._record(attr, "read", stmt.target)
            for t in targets:
                self._record_target(t)
            return
        if isinstance(stmt, ast.If):
            self._visit_expr(stmt.test)
            self._if_names.append(_names_in(stmt.test))
            self.visit_body(stmt.body)
            self.visit_body(stmt.orelse)
            self._if_names.pop()
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._visit_expr(stmt.iter)
            self._record_target(stmt.target)
            self.visit_body(stmt.body)
            self.visit_body(stmt.orelse)
            return
        if isinstance(stmt, ast.While):
            self._visit_expr(stmt.test)
            self.visit_body(stmt.body)
            self.visit_body(stmt.orelse)
            return
        if isinstance(stmt, ast.Try):
            self.visit_body(stmt.body)
            for h in stmt.handlers:
                self.visit_body(h.body)
            self.visit_body(stmt.orelse)
            self.visit_body(stmt.finalbody)
            return
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._record_target(t)
            return
        # everything else: expression-walk the children, but recurse
        # into sub-statements properly
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                self.visit_stmt(child)
            elif isinstance(child, ast.expr):
                self._visit_expr(child)

    _with_end: int = 0

    def _visit_with(self, stmt) -> None:
        n_pushed = 0
        for item in stmt.items:
            attr = _self_attr(item.context_expr)
            if attr is not None and attr in self.lock_attrs:
                # push immediately so a later item of the same `with`
                # (`with self._a, self._b:`) sees the earlier one held
                # — multi-item acquisition orders deadlock like nested
                # blocks do
                if self._locks:
                    outer = self._locks[-1]
                    if outer != attr:
                        self.facts.nest_edges.append(
                            (outer, attr, stmt.lineno,
                             stmt.col_offset + 1)
                        )
                self._locks.append(attr)
                n_pushed += 1
                self.facts.acquires.add(attr)
            else:
                self._visit_expr(item.context_expr)
            if item.optional_vars is not None:
                self._record_target(item.optional_vars)
        prev_end = self._with_end
        if n_pushed:
            self._with_end = getattr(stmt, "end_lineno", stmt.lineno)
        self.visit_body(stmt.body)
        for _ in range(n_pushed):
            self._locks.pop()
        self._with_end = prev_end

    # -- expression walk ----------------------------------------------

    def _visit_expr(self, node: ast.expr) -> None:
        if isinstance(node, _NESTED_SCOPES):
            return
        if isinstance(node, ast.Call):
            self._visit_call(node)
            return
        attr = _self_attr(node)
        if attr is not None:
            kind = (
                "write"
                if isinstance(node.ctx, (ast.Store, ast.Del))
                else "read"
            )
            self._record(attr, kind, node)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._visit_expr(child)


    def _visit_call(self, node: ast.Call) -> None:
        func = node.func
        tail = _callee_tail(func)
        # self.attr.mutator(...) is a write of attr
        if (
            isinstance(func, ast.Attribute)
            and tail in _MUTATORS
        ):
            attr = _self_attr(func.value)
            if attr is not None:
                self._record(attr, "write", func.value)
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    self._visit_expr(arg)
                return
        # self.method(...) while holding locks: lock-order edge source
        if (
            isinstance(func, ast.Attribute)
            and _self_attr(func) is not None
            and self._locks
        ):
            self.facts.calls.append(
                (func.attr, frozenset(self._locks), node.lineno,
                 node.col_offset + 1)
            )
        # send-like call outside any lock using lock-computed values
        if tail in _SEND_NAMES and not self._locks:
            used = set()
            for arg in list(node.args) + [
                kw.value for kw in node.keywords
            ]:
                used |= _names_in(arg)
            for names in self._if_names:
                used |= names
            for name in sorted(used):
                end = self.lock_computed.get(name)
                if end is not None and node.lineno > end:
                    self.facts.post_outside.append(
                        (name, tail, node.lineno, node.col_offset + 1)
                    )
                    break
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._visit_expr(child)


def _class_lock_attrs(cls: ast.ClassDef) -> Set[str]:
    locks: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign):
            if isinstance(node.value, ast.Call):
                tail = _callee_tail(node.value.func)
                if tail in _LOCK_CTORS:
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr is not None:
                            locks.add(attr)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr is not None and _LOCK_NAME_RE.search(attr):
                    locks.add(attr)
    return locks


def _find_cycle(
    edges: Dict[str, Set[str]]
) -> Optional[List[str]]:
    """First lock-name cycle in deterministic order, as a node list
    ``[a, b, ..., a]``; None when the graph is acyclic."""
    state: Dict[str, int] = {}  # 1 = on stack, 2 = done
    path: List[str] = []

    def dfs(node: str) -> Optional[List[str]]:
        state[node] = 1
        path.append(node)
        for nxt in sorted(edges.get(node, ())):
            if state.get(nxt) == 1:
                i = path.index(nxt)
                return path[i:] + [nxt]
            if state.get(nxt, 0) == 0:
                found = dfs(nxt)
                if found:
                    return found
        path.pop()
        state[node] = 2
        return None

    for start in sorted(edges):
        if state.get(start, 0) == 0:
            found = dfs(start)
            if found:
                return found
    return None


def _check_class(sf: SourceFile, cls: ast.ClassDef) -> List[Finding]:
    lock_attrs = _class_lock_attrs(cls)
    if not lock_attrs:
        return []
    methods: List[ast.FunctionDef] = [
        n for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    facts: Dict[str, _MethodFacts] = {}
    for m in methods:
        v = _MethodVisitor(lock_attrs, m.name)
        v.visit_body(m.body)
        # a method re-visited under the same name (overload shadowing)
        # keeps the last definition, like the interpreter does
        facts[m.name] = v.facts

    findings: List[Finding] = []

    # guarded-by: attributes written under some lock in any method
    guarded: Dict[str, Set[str]] = {}
    for f in facts.values():
        for acc in f.accesses:
            if acc.kind == "write" and acc.locks:
                guarded.setdefault(acc.attr, set()).update(acc.locks)

    for f in facts.values():
        if f.name == "__init__":
            continue
        for acc in f.accesses:
            if acc.attr not in guarded or acc.locks:
                continue
            rule = (
                "lock-unguarded-write" if acc.kind == "write"
                else "lock-unguarded-read"
            )
            lock = "/".join(sorted(guarded[acc.attr]))
            findings.append(
                Finding(
                    rule=rule,
                    severity=(
                        "error" if acc.kind == "write" else "warning"
                    ),
                    path=sf.path,
                    line=acc.line,
                    col=acc.col,
                    message=(
                        f"{cls.name}.{acc.attr} is guarded by "
                        f"self.{lock} elsewhere but "
                        f"{'written' if acc.kind == 'write' else 'read'}"
                        f" without it in {f.name}()"
                    ),
                )
            )

    for f in facts.values():
        for name, send, line, col in f.post_outside:
            findings.append(
                Finding(
                    rule="lock-post-outside",
                    severity="error",
                    path=sf.path,
                    line=line,
                    col=col,
                    message=(
                        f"{cls.name}.{f.name}() calls {send}() after "
                        f"releasing the lock under which {name!r} was "
                        f"computed; a concurrent writer can interleave "
                        f"between the decision and the send"
                    ),
                )
            )

    # lock order graph: direct nesting + calls made while holding
    acquires_closure: Dict[str, Set[str]] = {
        name: set(f.acquires) for name, f in facts.items()
    }
    changed = True
    while changed:
        changed = False
        for name, f in facts.items():
            for callee, _, _, _ in f.calls:
                extra = acquires_closure.get(callee)
                if extra and not extra <= acquires_closure[name]:
                    acquires_closure[name] |= extra
                    changed = True
    edges: Dict[str, Set[str]] = {}
    edge_site: Dict[Tuple[str, str], Tuple[int, int]] = {}
    for f in facts.values():
        for a, b, line, col in f.nest_edges:
            edges.setdefault(a, set()).add(b)
            edge_site.setdefault((a, b), (line, col))
        for callee, held, line, col in f.calls:
            for b in acquires_closure.get(callee, ()):
                for a in held:
                    if a != b:
                        edges.setdefault(a, set()).add(b)
                        edge_site.setdefault((a, b), (line, col))
    cycle = _find_cycle(edges)
    if cycle:
        a, b = cycle[0], cycle[1]
        line, col = edge_site.get((a, b), (cls.lineno, cls.col_offset + 1))
        findings.append(
            Finding(
                rule="lock-order-cycle",
                severity="warning",
                path=sf.path,
                line=line,
                col=col,
                message=(
                    f"{cls.name}: locks acquired in a cycle "
                    f"{' -> '.join('self.' + n for n in cycle)}; "
                    f"two threads taking them in different orders can "
                    f"deadlock"
                ),
            )
        )
    return findings


def run(files: List[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(_check_class(sf, node))
    return findings
