"""graftperf budget: a machine-checked dispatch/readback census per
engine path.

The engine's contract — "fused = one dispatch + one packed readback",
"chunked = one dispatch per timeout chunk", "checkpointing adds zero
dispatches" — used to live in CHANGES.md prose.  This module derives
the census *statically* (pure AST, no jax import: the lint tooling must
run anywhere) and pins it against ``tools/perf_budget.json``:

* ``static_census`` parses the engine regions named in the manifest and
  counts dispatch sites (calls to module-local jit entry points — the
  same entry points graftprof labels) and readback sites (``to_host`` /
  ``jax.device_get``), classified *straight* (always executed),
  *conditional* (under an ``if``) or *loop* (inside a ``for``/
  ``while`` — i.e. per-chunk).
* ``check_budget`` diffs the manifest's pinned counts against a fresh
  census; any mismatch is a build-failing finding, so an extra dispatch
  or readback cannot land silently.
* The chunked path's dispatch *count* is shape-dependent:
  ``dispatches == chunk_count(n_cycles)`` with the doubling schedule
  pinned in the manifest and cross-checked against the
  ``TIMEOUT_CHUNK``/``MAX_CHUNK`` constants in base.py.

The runtime half of the manifest (``"runtime"``) pins what graftprof's
``jit_census()``/readback counters must report for a warm solve on each
path; ``tests/test_analysis_perf.py`` cross-validates static == runtime.

Region grammar: ``path/to/file.py::fn`` is a whole function body;
``::run_cycles[fused]`` is the body of the first ``if`` in the function
whose test mentions ``timeout`` (the fused fast path), and
``::run_cycles[chunked]`` is everything after it.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import dotted_name as _dotted
from .perf import _jit_entry_names

__all__ = [
    "MANIFEST_PATH",
    "load_manifest",
    "static_census",
    "check_budget",
    "chunk_schedule",
    "chunk_count",
]

MANIFEST_PATH = os.path.join("tools", "perf_budget.json")

_READBACK_EXACT = {"jax.device_get", "device_get"}
_REGION_RE = re.compile(r"^(?P<fn>\w+)(?:\[(?P<variant>\w+)\])?$")


def load_manifest(path: Optional[str] = None) -> Dict:
    with open(path or MANIFEST_PATH, "r", encoding="utf-8") as fh:
        return json.load(fh)


# ---------------------------------------------------------------------------
# census
# ---------------------------------------------------------------------------


def _find_function(tree: ast.Module, name: str) -> Optional[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    return None


def _region_stmts(
    fn: ast.FunctionDef, variant: Optional[str]
) -> List[ast.stmt]:
    if variant is None:
        return list(fn.body)
    anchor = None
    for stmt in fn.body:
        if isinstance(stmt, ast.If) and any(
            isinstance(n, ast.Name) and n.id == "timeout"
            for n in ast.walk(stmt.test)
        ):
            anchor = stmt
            break
    if anchor is None:
        raise ValueError(
            f"{fn.name}: no `if` on `timeout` to anchor [{variant}]"
        )
    if variant == "fused":
        return list(anchor.body)
    if variant == "chunked":
        idx = fn.body.index(anchor)
        return list(fn.body[idx + 1:])
    raise ValueError(f"unknown region variant [{variant}]")


class _SiteCounter:
    """Counts dispatch/readback call sites with straight/conditional/
    loop classification (loop wins over conditional)."""

    def __init__(self, jit_entries: Set[str]) -> None:
        self.jit_entries = jit_entries
        self.dispatch = {"straight": 0, "conditional": 0, "loop": 0}
        self.readback = {"straight": 0, "conditional": 0, "loop": 0}

    def count(self, stmts: Sequence[ast.stmt]) -> None:
        self._stmts(stmts, 0, 0)

    def _stmts(
        self, body: Sequence[ast.stmt], loops: int, conds: int
    ) -> None:
        for stmt in body:
            if isinstance(
                stmt,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._expr(stmt.iter, loops, conds)
                self._stmts(stmt.body, loops + 1, conds)
                self._stmts(stmt.orelse, loops + 1, conds)
                continue
            if isinstance(stmt, ast.While):
                self._expr(stmt.test, loops, conds)
                self._stmts(stmt.body, loops + 1, conds)
                self._stmts(stmt.orelse, loops + 1, conds)
                continue
            if isinstance(stmt, ast.If):
                self._expr(stmt.test, loops, conds)
                self._stmts(stmt.body, loops, conds + 1)
                self._stmts(stmt.orelse, loops, conds + 1)
                continue
            if isinstance(stmt, ast.Try):
                self._stmts(stmt.body, loops, conds)
                for h in stmt.handlers:
                    self._stmts(h.body, loops, conds + 1)
                self._stmts(stmt.orelse, loops, conds + 1)
                self._stmts(stmt.finalbody, loops, conds)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._expr(item.context_expr, loops, conds)
                self._stmts(stmt.body, loops, conds)
                continue
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._expr(child, loops, conds)

    def _expr(self, node: ast.expr, loops: int, conds: int) -> None:
        if isinstance(node, (ast.Lambda, ast.FunctionDef)):
            return
        if isinstance(
            node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)
        ):
            for gen in node.generators:
                self._expr(gen.iter, loops, conds)
            self._expr(node.elt, loops + 1, conds)
            return
        if isinstance(node, ast.DictComp):
            for gen in node.generators:
                self._expr(gen.iter, loops, conds)
            self._expr(node.key, loops + 1, conds)
            self._expr(node.value, loops + 1, conds)
            return
        if isinstance(node, ast.Call):
            self._call(node, loops, conds)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child, loops, conds)

    def _call(self, node: ast.Call, loops: int, conds: int) -> None:
        bucket = (
            "loop" if loops > 0
            else "conditional" if conds > 0
            else "straight"
        )
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in self.jit_entries
        ):
            self.dispatch[bucket] += 1
            return
        d = _dotted(node.func)
        if d and (d.split(".")[-1] == "to_host" or d in _READBACK_EXACT):
            self.readback[bucket] += 1


def _module_int_constants(
    tree: ast.Module, names: Sequence[str]
) -> Dict[str, int]:
    out: Dict[str, int] = {}
    wanted = set(names)
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign):
            continue
        if not (
            isinstance(stmt.value, ast.Constant)
            and isinstance(stmt.value.value, int)
        ):
            continue
        for t in stmt.targets:
            if isinstance(t, ast.Name) and t.id in wanted:
                out[t.id] = stmt.value.value
    return out


def _parse_file(root: str, rel: str) -> ast.Module:
    path = os.path.join(root, rel)
    with open(path, "r", encoding="utf-8") as fh:
        return ast.parse(fh.read(), filename=path)


def static_census(
    manifest: Dict, root: str = "."
) -> Dict[str, Dict]:
    """Fresh AST-derived census for every region the manifest names."""
    out: Dict[str, Dict] = {}
    trees: Dict[str, ast.Module] = {}
    for key, spec in manifest.get("static", {}).items():
        region = spec["region"]
        file_part, _, fn_part = region.partition("::")
        m = _REGION_RE.match(fn_part)
        if m is None:
            raise ValueError(f"bad region spec {region!r}")
        if file_part not in trees:
            trees[file_part] = _parse_file(root, file_part)
        tree = trees[file_part]
        fn = _find_function(tree, m.group("fn"))
        if fn is None:
            raise ValueError(f"{region!r}: function not found")
        counter = _SiteCounter(_jit_entry_names(tree))
        counter.count(_region_stmts(fn, m.group("variant")))
        out[key] = {
            "region": region,
            "dispatch_sites": counter.dispatch,
            "readback_sites": counter.readback,
        }
    cs = manifest.get("chunk_schedule")
    if cs:
        if cs["file"] not in trees:
            trees[cs["file"]] = _parse_file(root, cs["file"])
        consts = _module_int_constants(
            trees[cs["file"]], ("TIMEOUT_CHUNK", "MAX_CHUNK")
        )
        out["chunk_schedule"] = {
            "start": consts.get("TIMEOUT_CHUNK"),
            "cap": consts.get("MAX_CHUNK"),
        }
    return out


def check_budget(
    manifest: Dict, census: Optional[Dict] = None, root: str = "."
) -> List[str]:
    """Mismatches between the pinned manifest and a fresh census —
    empty means the budget holds."""
    if census is None:
        census = static_census(manifest, root=root)
    problems: List[str] = []
    for key, spec in manifest.get("static", {}).items():
        got = census.get(key)
        if got is None:
            problems.append(f"{key}: no census computed")
            continue
        for field in ("dispatch_sites", "readback_sites"):
            if spec[field] != got[field]:
                problems.append(
                    f"{key}.{field}: manifest pins {spec[field]} but "
                    f"{got['region']} now has {got[field]}"
                )
    cs = manifest.get("chunk_schedule")
    if cs:
        got_cs = census.get("chunk_schedule", {})
        for mkey, ckey in (("start", "start"), ("cap", "cap")):
            if cs.get(mkey) != got_cs.get(ckey):
                problems.append(
                    f"chunk_schedule.{mkey}: manifest pins "
                    f"{cs.get(mkey)} but {cs['file']} defines "
                    f"{got_cs.get(ckey)}"
                )
    return problems


# ---------------------------------------------------------------------------
# chunk schedule (the doubling ladder run_cycles walks)
# ---------------------------------------------------------------------------


def chunk_schedule(
    n_cycles: int, start: int = 16, cap: int = 1024
) -> List[int]:
    """Chunk lengths run_cycles dispatches for ``n_cycles`` on the
    timeout path: start at ``start``, double up to ``cap``."""
    out: List[int] = []
    done, chunk = 0, start
    while done < n_cycles:
        length = min(chunk, n_cycles - done)
        out.append(length)
        done += length
        chunk = min(chunk * 2, cap)
    return out


def chunk_count(n_cycles: int, manifest: Optional[Dict] = None) -> int:
    cs = (manifest or {}).get("chunk_schedule", {})
    return len(
        chunk_schedule(
            n_cycles,
            start=cs.get("start", 16),
            cap=cs.get("cap", 1024),
        )
    )
