"""graftlint core: source loading, finding model, suppressions,
fingerprints and the pass registry.

A *pass* is a module exposing ``RULES`` (iterable of :class:`Rule`) and
``run(files) -> List[Finding]`` over the whole file set — protocol
consistency needs cross-file state, so passes always see every file.
"""

from __future__ import annotations

import ast
import hashlib
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Rule",
    "Finding",
    "SourceFile",
    "load_source_file",
    "source_from_text",
    "gather_files",
    "iter_source_paths",
    "collect_findings",
    "iter_rules",
    "pass_versions",
    "PASS_NAMES",
]

SEVERITIES = ("error", "warning", "info")


def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.lax.scan`` from an Attribute/Name chain, None for anything
    else (calls, subscripts) — the shared callee-resolution helper for
    every pass."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None

# line comment switching rules off for that line:
#   x = self._foo  # graftlint: disable=lock-unguarded-read
#   y = bar()      # graftlint: disable            (all rules)
# `# graftflow: disable=...`, `# graftproto: disable=...` and
# `# graftperf: disable=...` are accepted as aliases so pass-specific
# suppressions read naturally next to their markers
# (`# graftflow: batchable`, `# graftproto: replies=`, `# graftperf: hot`)
_SUPPRESS_RE = re.compile(
    r"#\s*graft(?:lint|flow|proto|perf):\s*disable(?:=(?P<rules>[\w\-, ]+))?"
)


@dataclass(frozen=True)
class Rule:
    id: str
    severity: str
    summary: str

    def __post_init__(self) -> None:
        if self.severity not in SEVERITIES:
            raise ValueError(f"bad severity {self.severity!r}")


@dataclass
class Finding:
    rule: str
    severity: str
    path: str
    line: int
    col: int
    message: str
    fingerprint: str = ""

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.severity} [{self.rule}] {self.message}"
        )

    def as_dict(self) -> Dict[str, object]:
        return {
            "fingerprint": self.fingerprint,
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }


@dataclass
class SourceFile:
    path: str  # as reported in findings (posix, relative when possible)
    text: str
    tree: ast.Module
    lines: List[str] = field(default_factory=list)
    # line number -> set of suppressed rule ids; empty set = all rules
    suppressions: Dict[int, Optional[Set[str]]] = field(default_factory=dict)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


def _parse_suppressions(text: str) -> Dict[int, Optional[Set[str]]]:
    """Map line number -> suppressed rules (None = every rule).

    Comments are located with the tokenizer, so a ``# graftlint:``
    inside a string literal does not suppress anything."""
    out: Dict[int, Optional[Set[str]]] = {}
    try:
        import io

        toks = tokenize.generate_tokens(io.StringIO(text).readline)
        for tok in toks:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rules = m.group("rules")
            if rules is None:
                out[tok.start[0]] = None
            else:
                ids = {r.strip() for r in rules.split(",") if r.strip()}
                prev = out.get(tok.start[0], set())
                out[tok.start[0]] = (
                    None if prev is None else (prev | ids)
                )
    except tokenize.TokenError:
        pass
    return out


def source_from_text(
    text: str, report_path: str
) -> Optional[SourceFile]:
    """Parse already-read source text; returns None when it cannot be
    parsed (syntax errors are not graftlint's business).  The cache
    path feeds the SAME text it hashed, so key and findings can never
    describe different file contents."""
    try:
        tree = ast.parse(text)
    except (SyntaxError, ValueError):
        return None
    return SourceFile(
        path=report_path.replace(os.sep, "/"),
        text=text,
        tree=tree,
        lines=text.splitlines(),
        suppressions=_parse_suppressions(text),
    )


def load_source_file(
    os_path: str, report_path: Optional[str] = None
) -> Optional[SourceFile]:
    """Read + parse one file; returns None when it cannot be read or
    parsed."""
    try:
        with open(os_path, "r", encoding="utf-8", errors="replace") as f:
            text = f.read()
    except OSError:
        return None
    return source_from_text(text, report_path or os_path)


def iter_source_paths(paths: Sequence[str]) -> List[Tuple[str, str]]:
    """Expand files/directories into ``(os_path, report_path)`` pairs in
    deterministic order; report paths are relative to the CWD when
    possible so fingerprints do not depend on where the repo is checked
    out.  Shared by :func:`gather_files` and the incremental cache's
    hashing walk, so the two can never disagree about the file set.

    A path that does not exist raises ValueError: silently linting
    nothing would make a typo'd CI path vacuously green (and a typo'd
    --write-baseline would erase the baseline)."""
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        raise ValueError(f"no such file or directory: {missing}")
    out: List[Tuple[str, str]] = []
    seen: Set[str] = set()
    cwd = os.getcwd()

    def report_path(p: str) -> str:
        ap = os.path.abspath(p)
        try:
            rel = os.path.relpath(ap, cwd)
        except ValueError:  # different drive (windows)
            return ap
        return ap if rel.startswith("..") else rel

    for p in paths:
        if os.path.isdir(p):
            for root, dirnames, names in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if not d.startswith(".") and d != "__pycache__"
                )
                for name in sorted(names):
                    if not name.endswith(".py"):
                        continue
                    fp = os.path.join(root, name)
                    ap = os.path.abspath(fp)
                    if ap in seen:
                        continue
                    seen.add(ap)
                    out.append((fp, report_path(fp)))
        else:
            ap = os.path.abspath(p)
            if ap in seen:
                continue
            seen.add(ap)
            out.append((p, report_path(p)))
    return out


def gather_files(paths: Sequence[str]) -> List[SourceFile]:
    """Expand files/directories into parsed sources (see
    :func:`iter_source_paths` for the walk contract)."""
    files: List[SourceFile] = []
    for os_path, rpath in iter_source_paths(paths):
        sf = load_source_file(os_path, rpath)
        if sf is not None:
            files.append(sf)
    return files


def _suppressed(sf: SourceFile, finding: Finding) -> bool:
    rules = sf.suppressions.get(finding.line, "absent")
    if rules == "absent":
        return False
    return rules is None or finding.rule in rules  # type: ignore[operator]


def fingerprint_findings(
    findings: List[Finding], files: Dict[str, SourceFile]
) -> None:
    """Stable identity per finding: rule + path + the *text* of the
    flagged line (so unrelated edits shifting line numbers do not churn
    the baseline) + an occurrence index disambiguating repeats of the
    same line text."""
    counts: Dict[Tuple[str, str, str], int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        sf = files.get(f.path)
        norm = sf.line_text(f.line).strip() if sf else ""
        key = (f.rule, f.path, norm)
        idx = counts.get(key, 0)
        counts[key] = idx + 1
        h = hashlib.sha256(
            "\x1f".join((f.rule, f.path, norm, str(idx))).encode("utf-8")
        ).hexdigest()
        f.fingerprint = h[:16]


PASS_NAMES = ("locks", "tracing", "protocol", "arrays", "proto", "perf")


def _passes():
    from . import arrays, locks, perf, proto, protocol, tracing

    return {
        "locks": locks,
        "tracing": tracing,
        "protocol": protocol,
        "arrays": arrays,
        "proto": proto,
        "perf": perf,
    }


def pass_versions() -> Dict[str, int]:
    """Per-pass behavior versions (the ``VERSION`` module attribute).
    Part of the incremental lint cache key: bumping a pass's VERSION
    invalidates every cached finding set it contributed to."""
    return {
        name: int(getattr(mod, "VERSION", 0))
        for name, mod in _passes().items()
    }


def iter_rules() -> List[Rule]:
    rules: List[Rule] = []
    for name in PASS_NAMES:
        rules.extend(_passes()[name].RULES)
    return rules


def collect_findings(
    paths: Sequence[str],
    select: Optional[Iterable[str]] = None,
    passes: Optional[Iterable[str]] = None,
    use_cache: bool = False,
) -> List[Finding]:
    """Run the requested passes (default: all) over ``paths`` and return
    suppression-filtered, fingerprinted findings in file order.

    ``select`` restricts the output to specific rule ids.  With
    ``use_cache`` the per-file content-hash cache under
    ``$PYDCOP_TPU_STATE_DIR`` is consulted first (see :mod:`.cache`) —
    a hit skips parsing and every pass, and on a miss the passes parse
    the very text the key hashed (one read per file, no
    hash-then-reread window)."""
    cache_key = None
    files: Optional[List[SourceFile]] = None
    if use_cache:
        from . import cache as _cache

        pairs = _cache.read_fileset(paths)
        if pairs is not None:
            cache_key = _cache.key_for(pairs, select, passes)
            hit = _cache.lookup(cache_key)
            if hit is not None:
                return hit
            files = []
            for rpath, text in pairs:
                sf = source_from_text(text, rpath)
                if sf is not None:
                    files.append(sf)
    if files is None:
        files = gather_files(paths)
    by_path = {sf.path: sf for sf in files}
    wanted = set(passes) if passes is not None else set(PASS_NAMES)
    unknown = wanted - set(PASS_NAMES)
    if unknown:
        raise ValueError(f"unknown pass(es): {sorted(unknown)}")
    findings: List[Finding] = []
    for name in PASS_NAMES:
        if name in wanted:
            findings.extend(_passes()[name].run(files))
    if select is not None:
        chosen = set(select)
        known = {r.id for r in iter_rules()}
        bad = chosen - known
        if bad:
            raise ValueError(f"unknown rule(s): {sorted(bad)}")
        findings = [f for f in findings if f.rule in chosen]
    findings = [
        f for f in findings
        if f.path not in by_path or not _suppressed(by_path[f.path], f)
    ]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    fingerprint_findings(findings, by_path)
    if cache_key is not None:
        from . import cache as _cache

        _cache.store(cache_key, findings)
    return findings
