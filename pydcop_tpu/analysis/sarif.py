"""SARIF 2.1.0 export for graftlint findings.

One run, one tool (``graftlint``), the full rule catalogue as
``tool.driver.rules`` with metadata drawn from each pass's EXPLAIN dict
(doc paragraph -> ``fullDescription``, minimal failing example ->
``help``), and one result per finding.  When a baseline ratchet is in
play, results carry ``baselineState`` (``new`` vs ``unchanged``) so CI
annotators can highlight exactly what the build would fail on; the
stable graftlint fingerprint is exported under ``partialFingerprints``
so SARIF consumers can track findings across commits the same way the
baseline does.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .core import Finding, iter_rules

__all__ = ["SARIF_VERSION", "SARIF_SCHEMA", "sarif_report"]

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)

_LEVELS = {"error": "error", "warning": "warning", "info": "note"}


def _explain_entries() -> Dict[str, Tuple[str, str]]:
    from .core import _passes

    out: Dict[str, Tuple[str, str]] = {}
    for mod in _passes().values():
        out.update(getattr(mod, "EXPLAIN", {}) or {})
    return out


def _rule_objects() -> List[dict]:
    explain = _explain_entries()
    rules = []
    for rule in iter_rules():
        obj: dict = {
            "id": rule.id,
            "shortDescription": {"text": rule.summary},
            "defaultConfiguration": {
                "level": _LEVELS.get(rule.severity, "warning")
            },
        }
        entry = explain.get(rule.id)
        if entry is not None:
            doc, example = entry
            obj["fullDescription"] = {"text": doc}
            obj["help"] = {
                "text": f"Minimal failing example:\n{example}"
            }
        rules.append(obj)
    return rules


def _result(
    f: Finding, index: Dict[str, int], state: Optional[str]
) -> dict:
    out: dict = {
        "ruleId": f.rule,
        "level": _LEVELS.get(f.severity, "warning"),
        "message": {"text": f.message},
        "locations": [
            {
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {
                        "startLine": f.line,
                        "startColumn": max(1, f.col),
                    },
                }
            }
        ],
        "partialFingerprints": {"graftlint/v1": f.fingerprint},
    }
    if f.rule in index:
        out["ruleIndex"] = index[f.rule]
    if state is not None:
        out["baselineState"] = state
    return out


def sarif_report(
    new: List[Finding],
    known: List[Finding],
    baseline_used: bool,
) -> dict:
    """The SARIF 2.1.0 document for one lint run.  ``new``/``known`` is
    the ratchet partition; without a baseline everything is in ``new``
    and no ``baselineState`` is emitted."""
    rules = _rule_objects()
    index = {r["id"]: i for i, r in enumerate(rules)}
    results = [
        _result(f, index, "new" if baseline_used else None) for f in new
    ] + [
        _result(f, index, "unchanged" if baseline_used else None)
        for f in known
    ]
    results.sort(
        key=lambda r: (
            r["locations"][0]["physicalLocation"]["artifactLocation"][
                "uri"
            ],
            r["locations"][0]["physicalLocation"]["region"]["startLine"],
            r["ruleId"],
        )
    )
    return {
        "version": SARIF_VERSION,
        "$schema": SARIF_SCHEMA,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftlint",
                        "rules": rules,
                    }
                },
                # columnKind omitted on purpose: startColumn comes from
                # ast col_offset (UTF-8 byte offsets), which matches the
                # spec default (unicodeCodePoints) exactly on the ASCII
                # lines this codebase is made of, and declaring
                # utf16CodeUnits would be wrong whenever they differ
                "results": results,
            }
        ],
    }
