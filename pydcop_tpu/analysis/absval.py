"""graftflow's abstract-value lattice.

The array-flow pass (:mod:`.arrays`) interprets jit-reachable functions
over *abstract values*: symbolic shapes (tuples of named dimensions like
``n_edges`` or concrete ints), a dtype lattice mirroring JAX's promotion
semantics (including weak types — Python scalars that adapt instead of
widening), and optional sharding annotations.  This module is pure data:
the lattice, joins, broadcasting, and the promotion table.  It knows
nothing about the AST.

Dimensions (``Dim``) are ``int`` (concrete), ``str`` (a symbol from the
documented shape vocabulary, e.g. a ``DeviceDCOP`` field comment
``# [n_vars, D]``) or ``None`` (unknown).  Two distinct symbols are not
*provably* unequal, so shape checks distinguish **hard** conflicts (two
unequal concrete dims, neither 1 — guaranteed broadcast error) from
**soft** conflicts (two different symbols from the known vocabulary —
almost certainly a layout mix-up, e.g. adding an ``[n_vars, D]`` plane
to an ``[n_edges, D]`` plane).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, Optional, Tuple, Union

__all__ = [
    "Dim",
    "AbsVal",
    "UNKNOWN",
    "array",
    "scalar",
    "record",
    "join",
    "promote",
    "broadcast",
    "canonical_dtype",
    "format_shape",
    "is_float",
    "is_int",
    "DTYPE_WIDTH",
]

Dim = Union[int, str, None]

# -- dtypes ------------------------------------------------------------

# canonical names + the short tokens shape comments use
_DTYPE_TOKENS: Dict[str, str] = {
    "bool": "bool", "bool_": "bool",
    "i8": "int8", "int8": "int8",
    "i16": "int16", "int16": "int16",
    "i32": "int32", "int32": "int32",
    "i64": "int64", "int64": "int64",
    "u8": "uint8", "uint8": "uint8",
    "u16": "uint16", "uint16": "uint16",
    "u32": "uint32", "uint32": "uint32",
    "u64": "uint64", "uint64": "uint64",
    "bf16": "bfloat16", "bfloat16": "bfloat16",
    "f16": "float16", "float16": "float16", "half": "float16",
    "f32": "float32", "float32": "float32", "float": "float32",
    "f64": "float64", "float64": "float64", "double": "float64",
    "c64": "complex64", "complex64": "complex64",
    "c128": "complex128", "complex128": "complex128",
}

#: bit width used to detect silent widening (int32 -> int64, f32 -> f64)
DTYPE_WIDTH: Dict[str, int] = {
    "bool": 8,
    "int8": 8, "int16": 16, "int32": 32, "int64": 64,
    "uint8": 8, "uint16": 16, "uint32": 32, "uint64": 64,
    "bfloat16": 16, "float16": 16, "float32": 32, "float64": 64,
    "complex64": 64, "complex128": 128,
}

_FLOATS = ("bfloat16", "float16", "float32", "float64")
_INTS = ("int8", "int16", "int32", "int64",
         "uint8", "uint16", "uint32", "uint64")


def canonical_dtype(token: Optional[str]) -> Optional[str]:
    """``f32``/``jnp.float32``/``"float32"`` -> ``float32``; None when the
    token is not a recognizable dtype."""
    if token is None:
        return None
    tail = token.split(".")[-1].strip().strip("'\"").lower()
    return _DTYPE_TOKENS.get(tail)


def is_float(dtype: Optional[str]) -> bool:
    return dtype in _FLOATS


def is_int(dtype: Optional[str]) -> bool:
    return dtype in _INTS


def _category(dtype: str) -> str:
    if dtype == "bool":
        return "bool"
    if dtype in _INTS:
        return "int"
    if dtype in _FLOATS:
        return "float"
    return "complex"


def promote(
    d1: Optional[str], w1: bool, d2: Optional[str], w2: bool
) -> Tuple[Optional[str], bool]:
    """JAX-style dtype promotion of two operands.

    ``w*`` marks *weak* types (Python scalars / weakly-typed arrays):
    a weak operand adapts to the strong one's dtype instead of widening
    it — the property that makes ``x * 2.0`` safe on an f32 plane.
    Returns ``(dtype, weak)``; unknown inputs poison to unknown."""
    if d1 is None or d2 is None:
        return None, False
    if d1 == d2:
        return d1, w1 and w2
    c1, c2 = _category(d1), _category(d2)
    # weak operand of a same-or-lower category adapts to the strong dtype
    if w1 and not w2:
        if c1 == c2 or c2 == "float" and c1 in ("int", "bool") or (
            c2 == "int" and c1 == "bool"
        ):
            return d2, False
    if w2 and not w1:
        if c1 == c2 or c1 == "float" and c2 in ("int", "bool") or (
            c1 == "int" and c2 == "bool"
        ):
            return d1, False
    both_weak = w1 and w2
    # bool adapts to anything
    if c1 == "bool":
        return d2, both_weak
    if c2 == "bool":
        return d1, both_weak
    # int + float -> the float operand's dtype (jnp: i32 + f32 -> f32;
    # i32 + bf16 -> bf16)
    if c1 == "int" and c2 == "float":
        return d2, both_weak
    if c2 == "int" and c1 == "float":
        return d1, both_weak
    # same category, different width: the wider wins (the widening the
    # dtype-flow rules care about).  bf16 vs f16 promotes to f32 in JAX.
    if c1 == c2:
        if {d1, d2} == {"bfloat16", "float16"}:
            return "float32", both_weak
        wide = d1 if DTYPE_WIDTH[d1] >= DTYPE_WIDTH[d2] else d2
        return wide, both_weak
    return None, False


# -- the value lattice -------------------------------------------------


@dataclass(frozen=True)
class AbsVal:
    """One abstract value.

    kind:
      ``array``   — shape/dtype/weak/sharding meaningful
      ``scalar``  — a Python/device scalar; ``dim`` holds the symbolic
                    dimension it denotes when it is a size (``dev.n_vars``
                    reads as scalar with ``dim="n_vars"``)
      ``record``  — a NamedTuple-like bag of fields
      ``tuple``   — ordered elements (e.g. ``x.shape``)
      ``func``    — a callable (never invoked abstractly except locally)
      ``unknown`` — top
    """

    kind: str = "unknown"
    shape: Optional[Tuple[Dim, ...]] = None
    dtype: Optional[str] = None
    weak: bool = False
    sharding: Optional[str] = None
    fields: Optional[Tuple[Tuple[str, "AbsVal"], ...]] = None
    elems: Optional[Tuple["AbsVal", ...]] = None
    dim: Dim = None
    origin: str = ""

    def field(self, name: str) -> "AbsVal":
        if self.fields:
            for k, v in self.fields:
                if k == name:
                    return v
        return UNKNOWN

    def with_(self, **kw) -> "AbsVal":
        return replace(self, **kw)

    @property
    def rank(self) -> Optional[int]:
        return None if self.shape is None else len(self.shape)

    def describe(self) -> str:
        if self.kind == "array":
            s = format_shape(self.shape)
            d = self.dtype or "?"
            return f"{s} {d}" + (" (weak)" if self.weak else "")
        if self.kind == "scalar":
            if self.dim is not None:
                return f"scalar {self.dim}"
            return f"scalar {self.dtype or '?'}"
        return self.kind


UNKNOWN = AbsVal()


def array(
    shape: Optional[Tuple[Dim, ...]],
    dtype: Optional[str] = None,
    weak: bool = False,
    origin: str = "",
    sharding: Optional[str] = None,
) -> AbsVal:
    return AbsVal(
        kind="array", shape=shape, dtype=dtype, weak=weak,
        origin=origin, sharding=sharding,
    )


def scalar(
    dtype: Optional[str] = None,
    weak: bool = True,
    dim: Dim = None,
    origin: str = "",
) -> AbsVal:
    return AbsVal(kind="scalar", dtype=dtype, weak=weak, dim=dim,
                  origin=origin)


def record(fields: Dict[str, AbsVal], origin: str = "") -> AbsVal:
    return AbsVal(
        kind="record", fields=tuple(fields.items()), origin=origin
    )


def _join_dim(a: Dim, b: Dim) -> Dim:
    return a if a == b else None


def join(a: AbsVal, b: AbsVal) -> AbsVal:
    """Least upper bound: used to merge branch environments."""
    if a is b:
        return a
    if a.kind != b.kind:
        return UNKNOWN
    if a.kind == "array":
        if a.shape is None or b.shape is None or len(a.shape) != len(
            b.shape
        ):
            shape = None
        else:
            shape = tuple(
                _join_dim(x, y) for x, y in zip(a.shape, b.shape)
            )
        dtype = a.dtype if a.dtype == b.dtype else None
        return array(
            shape, dtype, a.weak and b.weak,
            sharding=a.sharding if a.sharding == b.sharding else None,
        )
    if a.kind == "scalar":
        return scalar(
            a.dtype if a.dtype == b.dtype else None,
            a.weak and b.weak,
            _join_dim(a.dim, b.dim),
        )
    if a.kind == "record" and a.fields == b.fields:
        return a
    if a.kind == "tuple" and a.elems is not None and b.elems is not None:
        if len(a.elems) == len(b.elems):
            return AbsVal(
                kind="tuple",
                elems=tuple(
                    join(x, y) for x, y in zip(a.elems, b.elems)
                ),
            )
    if a.kind == "func":
        return a if a.origin == b.origin else AbsVal(kind="func")
    return UNKNOWN


# -- broadcasting ------------------------------------------------------


@dataclass
class BroadcastResult:
    shape: Optional[Tuple[Dim, ...]]
    #: (axis-from-the-right, dim_a, dim_b) of a guaranteed mismatch
    hard: list = field(default_factory=list)
    #: same, for symbol-vs-symbol disagreements (possible mismatch)
    soft: list = field(default_factory=list)


def broadcast(
    s1: Optional[Tuple[Dim, ...]], s2: Optional[Tuple[Dim, ...]]
) -> BroadcastResult:
    """NumPy-style broadcast of two symbolic shapes.

    Hard conflict: both dims concrete ints, unequal, neither 1.
    Soft conflict: two different *symbols* (or symbol vs concrete > 1)
    — not provably wrong, but in a vocabulary where symbols name
    distinct extents (n_vars vs n_edges) it almost always is.
    """
    if s1 is None or s2 is None:
        return BroadcastResult(None)
    out: list = []
    res = BroadcastResult(None)
    n = max(len(s1), len(s2))
    for i in range(1, n + 1):
        d1 = s1[-i] if i <= len(s1) else 1
        d2 = s2[-i] if i <= len(s2) else 1
        if d1 == 1:
            out.append(d2)
        elif d2 == 1:
            out.append(d1)
        elif d1 is None or d2 is None:
            out.append(d1 if d2 is None else d2 if d1 is None else None)
        elif d1 == d2:
            out.append(d1)
        elif isinstance(d1, int) and isinstance(d2, int):
            res.hard.append((i, d1, d2))
            out.append(None)
        else:
            res.soft.append((i, d1, d2))
            out.append(None)
    res.shape = tuple(reversed(out))
    return res


def format_shape(shape: Optional[Tuple[Dim, ...]]) -> str:
    if shape is None:
        return "[?]"
    return "[" + ", ".join(
        "?" if d is None else str(d) for d in shape
    ) + "]"
