"""graftflow (pass 4): abstract shape/dtype/sharding interpretation of
jit-reachable array code.

Where :mod:`.tracing` asks "is this *value* traced?", graftflow asks
"what *array* is this?" — it propagates symbolic shapes (``[n_edges,
D]``), a dtype lattice with JAX's weak-type promotion, and sharding
annotations through jit-reachable functions, interprocedurally
(module-local calls are evaluated with the caller's abstract
arguments).

Abstract inputs come from the *documented* signatures:

* parameters annotated with a ``NamedTuple`` class whose fields carry
  shape comments (``valid_mask: jnp.ndarray  # [n_vars, D] bool``)
  become abstract records with those field shapes/dtypes — this is how
  ``DeviceDCOP`` flows through ``_solve_fused``/``_while_chunk``/
  ``_scan_cycles``, the dpop wave functions, ``_bb_loop`` and the
  pallas kernels;
* ``jnp.ndarray``/``jax.Array`` annotations become unknown arrays;
* ``int`` parameters become symbolic dimensions named after the
  parameter (so ``x[:n_real]`` and ``jnp.zeros((n_real, d))`` get
  *equal* symbolic extents).

Rule families (all ratcheted through the graftlint baseline):

dtype-flow
  * ``flow-f64-widen`` — 64-bit dtype mentioned or produced by
    promotion inside jit-reachable code (silent 2x memory + slow path
    on TPU; silently downcast when x64 is off).
  * ``flow-int-promote`` — an int32 index array widened to int64 by
    promotion, or a float-dtyped expression used as an index.
  * ``flow-bf16-mixed`` — bf16/f16 plane mixed into an f32/f64 op
    without an explicit cast (implicit upcast hides the precision
    boundary).

shape/layout
  * ``flow-shape-mismatch`` — broadcasting two shapes that provably
    (hard: unequal concrete dims) or almost certainly (soft: two
    different dimension symbols from the documented vocabulary, e.g.
    ``n_vars`` vs ``n_edges``) cannot align.
  * ``flow-plane-reshape`` — ``reshape`` that swaps the two axes of a
    2-D plane: reshape reinterprets row-major data, it does not
    transpose (the square-plane ambiguity class from PR 1).

batch-axis discipline
  * ``flow-batch-axis`` — axis-0 hardcoding (``x[0]``, ``.at[0]``,
    ``x.shape[0]``, ``axis=0`` reductions) inside a function marked
    ``# graftflow: batchable``: the marker declares the function must
    stay vmap-able over a leading batch axis.  ENFORCED (severity
    error) since graftserve — ``serve/batch.py`` actually maps the
    marked solve path over a leading instance axis, so a finding is a
    live batching bug, not a ratchet advisory.

transfer/sharding
  * ``flow-host-transfer`` — ``float()``/``np.asarray()``/
    ``device_get``/``.item()``/``.tolist()`` on an abstract array
    inside jit-reachable code (host round trip; fails under jit).
  * ``flow-sharding-axis`` — a ``PartitionSpec`` naming a mesh axis no
    ``Mesh``/axis declaration in the scanned files defines.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .absval import (
    AbsVal,
    UNKNOWN,
    array,
    broadcast,
    canonical_dtype,
    format_shape,
    is_float,
    join,
    promote,
    record,
    scalar,
)
from .core import Finding, Rule, SourceFile, dotted_name as _dotted
from .tracing import (
    _CAST_FUNCS,
    _COMBINATOR_BARE,
    _COMBINATOR_TAILS,
    _JAX_ROOTS,
    _NP_SYNC,
    _SYNC_METHODS,
    _decorator_jit_statics,
    _param_names,
)

__all__ = ["RULES", "EXPLAIN", "run"]

#: bumped when the pass's behavior changes, so the incremental lint
#: cache (analysis/cache.py) never serves findings from an older rule set
VERSION = 1

RULES = (
    Rule(
        "flow-f64-widen",
        "warning",
        "64-bit dtype inside jit-reachable code (accidental widening)",
    ),
    Rule(
        "flow-int-promote",
        "warning",
        "index array silently promoted past int32 / float used as index",
    ),
    Rule(
        "flow-bf16-mixed",
        "warning",
        "bf16/f16 plane mixed into f32 math without an explicit cast",
    ),
    Rule(
        "flow-shape-mismatch",
        "warning",
        "broadcast of provably or near-certainly incompatible shapes",
    ),
    Rule(
        "flow-plane-reshape",
        "warning",
        "reshape swaps 2-D plane axes (reinterprets, does not transpose)",
    ),
    Rule(
        # ENFORCED (error, not warning) since the graftserve PR: the
        # markers are load-bearing — serve/batch.py actually vmaps the
        # marked solve path over a leading instance axis, so an axis-0
        # hardcoding is a real batching bug, not advice
        "flow-batch-axis",
        "error",
        "axis-0 hardcoding in a '# graftflow: batchable' function",
    ),
    Rule(
        "flow-host-transfer",
        "warning",
        "implicit host transfer inside jit-reachable code",
    ),
    Rule(
        "flow-sharding-axis",
        "error",
        "PartitionSpec names a mesh axis no scanned Mesh declares",
    ),
)

#: rule id -> (one-paragraph doc, minimal failing example) for
#: ``pydcop_tpu lint --explain``
EXPLAIN: Dict[str, Tuple[str, str]] = {
    "flow-f64-widen": (
        "A float64/int64 dtype appears inside jit-reachable code. With "
        "jax_enable_x64 off (the default) the request is silently "
        "downcast; with it on, every derived plane doubles in memory "
        "and TPUs take the slow path. Use explicit 32-bit dtypes, or "
        "suppress with a justification when the 64-bit width is "
        "deliberately x64-gated.",
        "@jax.jit\n"
        "def f(x):\n"
        "    return x.astype(jnp.float64)  # silent 2x widening\n",
    ),
    "flow-int-promote": (
        "An int32 index array met an int64 operand (promoting the "
        "whole index plane to int64), or a float-dtyped expression is "
        "used as an index. Gather/scatter indices should stay int32; "
        "float indices raise at trace time.",
        "@jax.jit\n"
        "def f(idx, big):  # idx int32, big int64\n"
        "    return idx + big  # idx silently becomes int64\n",
    ),
    "flow-bf16-mixed": (
        "A bfloat16/float16 plane is combined with float32/float64 "
        "values without an explicit cast: the upcast is implicit, so "
        "the precision boundary (and its quality budget) is invisible "
        "at the call site. Cast explicitly with .astype at the "
        "reduction boundary.",
        "@jax.jit\n"
        "def f(msgs_bf16, unary_f32):\n"
        "    return msgs_bf16 + unary_f32  # implicit upcast\n",
    ),
    "flow-shape-mismatch": (
        "Two arrays are broadcast whose symbolic shapes cannot align: "
        "either two unequal concrete dims (guaranteed XLA error), or "
        "two different documented dimension symbols such as n_vars vs "
        "n_edges (almost always a plane-layout mix-up).",
        "@jax.jit\n"
        "def f(dev):  # unary [n_vars, D], edge_var [n_edges]\n"
        "    return dev.unary + dev.edge_var  # n_vars/D vs n_edges\n",
    ),
    "flow-plane-reshape": (
        "A 2-D plane is reshaped to its transposed shape: reshape "
        "reinterprets row-major memory and silently scrambles the "
        "plane (for square planes the shapes even agree, so nothing "
        "fails). Use .T / jnp.transpose to swap axes.",
        "@jax.jit\n"
        "def f(plane):  # [n_edges, D]\n"
        "    return plane.reshape(plane.shape[1], plane.shape[0])\n",
    ),
    "flow-batch-axis": (
        "A function marked '# graftflow: batchable' hardcodes axis 0: "
        "x[0], .at[0], x.shape[0], or an axis=0 reduction. Batchable "
        "functions must stay clean for a leading batch axis so "
        "jax.vmap can serve many instances with one dispatch — and "
        "since graftserve, serve/batch.py REALLY vmaps the marked solve "
        "path, so this is an ERROR (enforced), not advice; index from "
        "the trailing axes or take the axis as a parameter instead.",
        "# graftflow: batchable\n"
        "def step(dev, values):\n"
        "    return values.shape[0]  # n_vars? batch size? ambiguous\n",
    ),
    "flow-host-transfer": (
        "float()/int(), np.asarray/np.array, jax.device_get, .item() "
        "or .tolist() touches an abstract array inside jit-reachable "
        "code: under jit this raises; eagerly it forces a device->host "
        "round trip (~50 ms on a tunneled TPU relay). Keep the value "
        "on device, or move the transfer out of the jit-reachable "
        "path.",
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(x.sum())  # host sync inside jit\n",
    ),
    "flow-sharding-axis": (
        "A PartitionSpec names a mesh axis that no Mesh(...) / "
        "axis-name declaration in the scanned files defines: "
        "with_sharding_constraint/NamedSharding will raise at runtime "
        "on the first sharded call. Keep axis names in sync with "
        "parallel/mesh.py.",
        "spec = PartitionSpec('shards')  # mesh declares only 'agents'\n",
    ),
}

# -- shape-comment and marker syntax -----------------------------------

# trailing field comment:  `valid_mask: jnp.ndarray  # [n_vars, D] bool`
_SHAPE_COMMENT_RE = re.compile(
    r"#\s*\[([^\]]*)\]\s*([A-Za-z0-9_]+)?"
)
_SCALAR_COMMENT_RE = re.compile(r"#\s*scalar\b")
_BATCHABLE_RE = re.compile(r"#\s*graftflow:\s*batchable\b")
_IDENT_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")

#: static record fields whose name is not the symbol the shape comments
#: use for the same extent
_DIM_ALIASES = {"max_domain": "D"}

_ARRAY_ANNOTATIONS = {"ndarray", "Array", "ArrayLike", "DeviceArray"}

# the host-transfer surface is shared with tracing.py's trace-host-sync
# rule (same calls, different evidence: that pass needs the VALUE to be
# provably traced, this one an abstract array in jit-reachable code) —
# one set, so the two rules can never drift
_HOST_CAST_FUNCS = _CAST_FUNCS
_HOST_NP_FUNCS = _NP_SYNC
_HOST_METHODS = _SYNC_METHODS

_REDUCTIONS = {
    "sum", "prod", "mean", "median", "max", "min", "amax", "amin",
    "argmax", "argmin", "any", "all", "count_nonzero", "std", "var",
    "nanmin", "nanmax", "nansum", "logsumexp", "segment_sum",
    "segment_max", "segment_min",
}
_ELEMENTWISE = {
    "abs", "exp", "log", "sqrt", "negative", "sign", "floor", "ceil",
    "round", "clip", "maximum", "minimum", "add", "subtract",
    "multiply", "divide", "mod", "power", "logical_and", "logical_or",
    "logical_not", "isnan", "isfinite", "tanh", "sin", "cos",
}

_SIXTYFOUR = {"float64", "int64", "uint64", "complex128"}


def _parse_field_absval(line: str) -> Optional[AbsVal]:
    """Abstract value of one NamedTuple array field from its trailing
    shape comment, or None when the line documents no layout."""
    m = _SHAPE_COMMENT_RE.search(line)
    if m is None:
        if _SCALAR_COMMENT_RE.search(line):
            return array((), None)
        return None
    dims: List = []
    body = m.group(1).strip()
    if body:
        for tok in body.split(","):
            tok = tok.strip()
            if not tok:
                continue
            if tok.lstrip("-").isdigit():
                dims.append(int(tok))
            elif _IDENT_RE.match(tok):
                dims.append(tok)
            else:
                dims.append(None)  # derived extent like D**arity
    dtype = canonical_dtype(m.group(2))
    return array(tuple(dims), dtype)


@dataclass
class _Analysis:
    sf: SourceFile
    findings: List[Finding]
    module_funcs: Dict[str, ast.FunctionDef]
    all_funcs: Dict[str, ast.FunctionDef]
    records: Dict[str, AbsVal]  # NamedTuple name -> abstract record
    known_dims: Set[str]  # documented dimension vocabulary
    mesh_axes: Set[str]  # axis names any scanned Mesh declares
    batchable: Set[int]  # id() of marked FunctionDef nodes
    seen: Set[Tuple[int, Tuple]]  # interprocedural memo


def _collect_records(
    files: Sequence[SourceFile],
) -> Tuple[Dict[str, AbsVal], Set[str]]:
    """NamedTuple classes with shape-commented fields -> abstract
    records, plus the dimension-symbol vocabulary they document."""
    records_out: Dict[str, AbsVal] = {}
    dims: Set[str] = set()
    for sf in files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            if not any(
                (d := _dotted(b)) and d.split(".")[-1] == "NamedTuple"
                for b in node.bases
            ):
                continue
            fields: Dict[str, AbsVal] = {}
            documented = False
            for item in node.body:
                if not isinstance(item, ast.AnnAssign) or not isinstance(
                    item.target, ast.Name
                ):
                    continue
                name = item.target.id
                line = sf.line_text(item.lineno)
                ann = _dotted(item.annotation)
                ann_tail = ann.split(".")[-1] if ann else ""
                if ann_tail == "int":
                    fields[name] = scalar(
                        "int32", weak=True,
                        dim=_DIM_ALIASES.get(name, name),
                    )
                    continue
                av = _parse_field_absval(line)
                if av is not None:
                    documented = True
                    fields[name] = av
                    for d in av.shape or ():
                        if isinstance(d, str):
                            dims.add(d)
                elif ann_tail in _ARRAY_ANNOTATIONS:
                    fields[name] = array(None)
                else:
                    fields[name] = UNKNOWN
            if documented:
                records_out[node.name] = record(fields, origin=node.name)
    return records_out, dims


def _collect_mesh_axes(files: Sequence[SourceFile]) -> Set[str]:
    """Axis names the scanned files declare: string constants assigned
    to *AXIS* names, ``axis_name=...`` parameter defaults, and string
    tuples passed to ``Mesh(...)``."""
    axes: Set[str] = set()

    def strings_of(node: ast.expr) -> List[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return [node.value]
        if isinstance(node, (ast.Tuple, ast.List)):
            out = []
            for e in node.elts:
                out.extend(strings_of(e))
            return out
        return []

    for sf in files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and "AXIS" in t.id.upper():
                        axes.update(strings_of(node.value))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                args = node.args
                pos = list(args.posonlyargs) + list(args.args)
                for a, dflt in zip(pos[-len(args.defaults):],
                                   args.defaults) if args.defaults else []:
                    if "axis" in a.arg:
                        axes.update(strings_of(dflt))
                for a, dflt in zip(args.kwonlyargs, args.kw_defaults):
                    if dflt is not None and "axis" in a.arg:
                        axes.update(strings_of(dflt))
            elif isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d and d.split(".")[-1] == "Mesh" and len(node.args) >= 2:
                    axes.update(strings_of(node.args[1]))
    return axes


def _is_batchable(sf: SourceFile, fn: ast.FunctionDef) -> bool:
    """True when ``# graftflow: batchable`` appears on the def line,
    a decorator line, or the line directly above the def block."""
    first = min(
        [fn.lineno] + [d.lineno for d in fn.decorator_list]
    )
    for ln in range(max(1, first - 1), fn.lineno + 1):
        if _BATCHABLE_RE.search(sf.line_text(ln)):
            return True
    return False


def _annotation_absval(
    an: _Analysis, ann: Optional[ast.expr], pname: str
) -> AbsVal:
    if ann is None:
        return UNKNOWN
    d = _dotted(ann)
    if d is None:
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            d = ann.value  # string annotation
        else:
            return UNKNOWN
    tail = d.split(".")[-1]
    if tail in an.records:
        return an.records[tail]
    if tail in _ARRAY_ANNOTATIONS:
        return array(None, origin=pname)
    if tail == "int":
        return scalar("int32", weak=True, dim=pname)
    if tail == "float":
        return scalar("float32", weak=True)
    if tail == "bool":
        return scalar("bool", weak=True)
    if tail in ("Callable",):
        return AbsVal(kind="func", origin=pname)
    return UNKNOWN


def _sig_summary(env: Dict[str, AbsVal], names: List[str]) -> Tuple:
    return tuple(
        (v.kind, v.shape, v.dtype, v.dim)
        for v in (env.get(n, UNKNOWN) for n in names)
    )


class _Interp:
    """Abstract interpreter over one function body."""

    def __init__(
        self,
        an: _Analysis,
        fn: ast.FunctionDef,
        env: Dict[str, AbsVal],
        jit_reachable: bool,
        batchable: bool,
        depth: int,
        local_funcs: Dict[str, ast.FunctionDef],
    ) -> None:
        self.an = an
        self.fn = fn
        self.env = env
        self.jit = jit_reachable
        self.batchable = batchable
        self.depth = depth
        self.returns: List[AbsVal] = []
        self.local_funcs = dict(local_funcs)
        for stmt in fn.body:
            if isinstance(stmt, ast.FunctionDef):
                self.local_funcs[stmt.name] = stmt

    # -- reporting -----------------------------------------------------

    def emit(self, rule: str, severity: str, node: ast.AST,
             message: str) -> None:
        self.an.findings.append(
            Finding(
                rule=rule,
                severity=severity,
                path=self.an.sf.path,
                line=getattr(node, "lineno", self.fn.lineno),
                col=getattr(node, "col_offset", 0) + 1,
                message=message,
            )
        )

    # -- statements ----------------------------------------------------

    def exec_body(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            self.exec_stmt(stmt)

    def exec_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, ast.Return):
            self.returns.append(
                self.eval(stmt.value) if stmt.value else UNKNOWN
            )
            return
        if isinstance(stmt, ast.Assign):
            val = self.eval(stmt.value)
            for t in stmt.targets:
                self.bind(t, val)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self.bind(stmt.target, self.eval(stmt.value))
            return
        if isinstance(stmt, ast.AugAssign):
            val = self.eval(
                ast.BinOp(
                    left=stmt.target, op=stmt.op, right=stmt.value,
                    lineno=stmt.lineno, col_offset=stmt.col_offset,
                )
            )
            if isinstance(stmt.target, ast.Name):
                self.env[stmt.target.id] = val
            return
        if isinstance(stmt, ast.If):
            self.eval(stmt.test)
            before = dict(self.env)
            self.exec_body(stmt.body)
            after_body = self.env
            self.env = dict(before)
            self.exec_body(stmt.orelse)
            merged = {}
            for k in set(after_body) | set(self.env):
                merged[k] = join(
                    after_body.get(k, UNKNOWN), self.env.get(k, UNKNOWN)
                )
            self.env = merged
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            it = self.eval(stmt.iter)
            elem = UNKNOWN
            if it.kind == "array" and it.shape:
                elem = array(it.shape[1:], it.dtype, it.weak)
            self.bind(stmt.target, elem)
            self.exec_body(stmt.body)
            self.exec_body(stmt.orelse)
            return
        if isinstance(stmt, ast.While):
            self.eval(stmt.test)
            self.exec_body(stmt.body)
            self.exec_body(stmt.orelse)
            return
        if isinstance(stmt, ast.Try):
            self.exec_body(stmt.body)
            for h in stmt.handlers:
                self.exec_body(h.body)
            self.exec_body(stmt.orelse)
            self.exec_body(stmt.finalbody)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.eval(item.context_expr)
            self.exec_body(stmt.body)
            return
        if isinstance(stmt, ast.Expr):
            self.eval(stmt.value)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self.eval(child)

    def bind(self, target: ast.expr, val: AbsVal) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = val
        elif isinstance(target, (ast.Tuple, ast.List)):
            elems = (
                val.elems
                if val.kind == "tuple" and val.elems is not None
                and len(val.elems) == len(target.elts)
                else None
            )
            for i, elt in enumerate(target.elts):
                self.bind(elt, elems[i] if elems else UNKNOWN)
        elif isinstance(target, ast.Starred):
            self.bind(target.value, UNKNOWN)
        # subscript/attribute targets: no binding tracked

    # -- expression evaluation ----------------------------------------

    def eval(self, node: Optional[ast.expr]) -> AbsVal:
        if node is None:
            return UNKNOWN
        if isinstance(node, ast.Constant):
            v = node.value
            if isinstance(v, bool):
                return scalar("bool", weak=True)
            if isinstance(v, int):
                return scalar("int32", weak=True, dim=v)
            if isinstance(v, float):
                return scalar("float32", weak=True)
            return AbsVal(kind="other")
        if isinstance(node, ast.Name):
            return self.env.get(node.id, UNKNOWN)
        if isinstance(node, ast.Attribute):
            return self.eval_attribute(node)
        if isinstance(node, ast.Call):
            return self.eval_call(node)
        if isinstance(node, ast.BinOp):
            return self.eval_binop(node)
        if isinstance(node, ast.UnaryOp):
            inner = self.eval(node.operand)
            if (
                isinstance(node.op, ast.USub)
                and inner.kind == "scalar"
                and isinstance(inner.dim, int)
            ):
                return inner.with_(dim=-inner.dim)
            return inner
        if isinstance(node, ast.BoolOp):
            for v in node.values:
                self.eval(v)
            return UNKNOWN
        if isinstance(node, ast.Compare):
            left = self.eval(node.left)
            out = left
            for comp in node.comparators:
                right = self.eval(comp)
                out = self.combine(node, out, right, compare=True)
            if out.kind == "array":
                return out.with_(dtype="bool", weak=False)
            return scalar("bool", weak=True)
        if isinstance(node, ast.Subscript):
            return self.eval_subscript(node)
        if isinstance(node, ast.IfExp):
            self.eval(node.test)
            return join(self.eval(node.body), self.eval(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List)):
            return AbsVal(
                kind="tuple",
                elems=tuple(self.eval(e) for e in node.elts),
            )
        if isinstance(node, ast.Starred):
            return self.eval(node.value)
        if isinstance(node, (ast.Lambda,)):
            return AbsVal(kind="func")
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            for gen in node.generators:
                self.eval(gen.iter)
                self.bind(gen.target, UNKNOWN)
                for cond in gen.ifs:
                    self.eval(cond)
            if isinstance(node, ast.DictComp):
                self.eval(node.key)
                self.eval(node.value)
            else:
                self.eval(node.elt)
            return UNKNOWN
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.eval(child)
        return UNKNOWN

    # -- attributes ----------------------------------------------------

    def eval_attribute(self, node: ast.Attribute) -> AbsVal:
        d = _dotted(node)
        if d is not None:
            root = d.split(".")[0]
            if root in ("jnp", "np", "numpy", "jax", "lax", "onp"):
                dt = canonical_dtype(d)
                if dt is not None:
                    if dt in _SIXTYFOUR and self.jit:
                        self.emit(
                            "flow-f64-widen", "warning", node,
                            f"{d} inside jit-reachable "
                            f"{self.fn.name}(): 64-bit dtypes silently "
                            f"double memory (or downcast with x64 "
                            f"off); use a 32-bit dtype or justify",
                        )
                    return AbsVal(kind="other", dtype=dt)
                return AbsVal(kind="other", origin=d)
        base = self.eval(node.value)
        attr = node.attr
        if attr == "shape" and base.kind != "record":
            # ANY .shape read yields a shape tuple (origin tracked so
            # batchable functions can flag shape[0] even on arrays the
            # interpreter knows nothing about)
            shp = base.shape if base.kind == "array" else None
            if shp is None:
                return AbsVal(kind="tuple", origin="shape")
            return AbsVal(
                kind="tuple",
                elems=tuple(
                    scalar("int32", weak=True, dim=dm) for dm in shp
                ),
                origin="shape",
            )
        if attr == "at" and base.kind != "record":
            # same reach as .shape: .at[...] is jnp-only syntax, so an
            # unknown base is still an array update view
            return AbsVal(
                kind="atview",
                fields=(
                    ("base", base if base.kind == "array" else UNKNOWN),
                ),
            )
        if base.kind == "record":
            return base.field(attr)
        if base.kind == "array":
            if attr == "T":
                return base.with_(
                    shape=(
                        tuple(reversed(base.shape))
                        if base.shape is not None else None
                    )
                )
            if attr == "dtype":
                return AbsVal(kind="other", dtype=base.dtype)
            if attr in ("ndim", "size"):
                return scalar("int32", weak=True)
            if attr == "at":
                return AbsVal(
                    kind="atview", fields=(("base", base),)
                )
            if attr in ("real", "imag"):
                return base
            return UNKNOWN
        if base.kind == "atview":
            return base
        return UNKNOWN

    # -- subscripts ----------------------------------------------------

    def _index_parts(self, sl: ast.expr) -> List[ast.expr]:
        if isinstance(sl, ast.Tuple):
            return list(sl.elts)
        return [sl]

    def _check_index_dtype(self, part: ast.expr) -> None:
        iv = self.eval(part)
        if iv.kind == "array" and is_float(iv.dtype):
            self.emit(
                "flow-int-promote", "warning", part,
                f"float-dtyped expression used as an index in "
                f"{self.fn.name}() (indices must be integers; a "
                f"promoted index plane raises at trace time)",
            )

    def eval_subscript(self, node: ast.Subscript) -> AbsVal:
        base = self.eval(node.value)
        sl = node.slice
        parts = self._index_parts(sl)
        for p in parts:
            if not isinstance(p, ast.Slice):
                self._check_index_dtype(p)
            else:
                for b in (p.lower, p.upper, p.step):
                    if b is not None:
                        self.eval(b)

        zero_index = (
            parts
            and isinstance(parts[0], ast.Constant)
            and parts[0].value == 0
        )
        if base.kind == "atview":
            if self.batchable and zero_index:
                self.emit(
                    "flow-batch-axis", "error", node,
                    f".at[0] in batchable {self.fn.name}() hardcodes "
                    f"the leading axis; a vmap'd batch puts the batch "
                    f"there (ROADMAP item 3)",
                )
            return base
        if base.kind == "tuple":
            if (
                base.origin == "shape"
                and self.batchable
                and zero_index
            ):
                self.emit(
                    "flow-batch-axis", "error", node,
                    f"shape[0] in batchable {self.fn.name}() reads "
                    f"the leading extent; under vmap that is the "
                    f"batch size, not n_vars — use a static field or "
                    f"a trailing axis",
                )
            if (
                base.elems is not None
                and len(parts) == 1
                and isinstance(parts[0], ast.Constant)
                and isinstance(parts[0].value, int)
                and -len(base.elems) <= parts[0].value < len(base.elems)
            ):
                return base.elems[parts[0].value]
            return UNKNOWN
        if base.kind != "array":
            return UNKNOWN
        if self.batchable and zero_index:
            self.emit(
                "flow-batch-axis", "error", node,
                f"[0] index in batchable {self.fn.name}() hardcodes "
                f"the leading axis; a vmap'd batch puts the batch "
                f"there (ROADMAP item 3)",
            )
        if base.shape is None:
            return array(None, base.dtype, base.weak)
        # consume leading dims per index part
        shape = list(base.shape)
        out: List = []
        i = 0
        for p in parts:
            if isinstance(p, ast.Constant) and p.value is None:
                # x[:, None] newaxis: INSERTS a size-1 dim, consumes
                # none — handled before the exhaustion check because it
                # is valid even past the last real axis
                out.append(1)
                continue
            if i >= len(shape):
                break
            if isinstance(p, ast.Slice):
                dim = shape[i]
                if p.lower is None and p.upper is None:
                    out.append(dim)
                else:
                    # slice length: upper - lower when both are known
                    # non-negative ints; a symbolic upper only names the
                    # length when the lower bound is zero.  Anything
                    # else (negative bounds, steps, symbolic lowers) is
                    # unknown — never guess a concrete length that
                    # could hard-fire a mismatch on valid code.
                    lo: Optional[int] = 0 if p.lower is None else None
                    if p.lower is not None:
                        lv = self.eval(p.lower)
                        if (
                            lv.kind == "scalar"
                            and isinstance(lv.dim, int)
                            and lv.dim >= 0
                        ):
                            lo = lv.dim
                    length = None
                    if p.upper is not None:
                        uv = self.eval(p.upper)
                        if lo is not None and uv.kind == "scalar":
                            if isinstance(uv.dim, int):
                                if uv.dim >= lo >= 0:
                                    length = uv.dim - lo
                            elif uv.dim is not None and lo == 0:
                                length = uv.dim
                    if p.step is not None:
                        self.eval(p.step)
                        length = None
                    out.append(length)
                i += 1
            elif isinstance(p, ast.Constant) and p.value is Ellipsis:
                keep = len(shape) - i - (len(parts) - parts.index(p) - 1)
                out.extend(shape[i:i + max(0, keep)])
                i += max(0, keep)
            else:
                iv = self.eval(p)
                if iv.kind == "array":
                    # advanced indexing: gather — index shape replaces dim
                    out.extend(
                        iv.shape if iv.shape is not None else (None,)
                    )
                i += 1
        out.extend(shape[i:])
        return array(tuple(out), base.dtype, base.weak,
                     sharding=base.sharding)

    # -- binary ops ----------------------------------------------------

    def combine(
        self, node: ast.AST, left: AbsVal, right: AbsVal,
        compare: bool = False,
    ) -> AbsVal:
        """Broadcast + promote two operands, firing dtype/shape rules."""
        if left.kind == "scalar" and right.kind == "scalar":
            dt, wk = promote(left.dtype, left.weak, right.dtype,
                             right.weak)
            return scalar(dt, wk)
        if left.kind not in ("array", "scalar") or right.kind not in (
            "array", "scalar"
        ):
            return UNKNOWN
        ls = left.shape if left.kind == "array" else ()
        rs = right.shape if right.kind == "array" else ()
        bc = broadcast(ls, rs)
        for _, d1, d2 in bc.hard:
            self.emit(
                "flow-shape-mismatch", "error", node,
                f"broadcast of {format_shape(ls)} with "
                f"{format_shape(rs)} in {self.fn.name}(): dims {d1} "
                f"and {d2} can never align",
            )
        for _, d1, d2 in bc.soft:
            if (
                isinstance(d1, str) and d1 in self.an.known_dims
                and isinstance(d2, str) and d2 in self.an.known_dims
            ):
                self.emit(
                    "flow-shape-mismatch", "warning", node,
                    f"broadcast of {format_shape(ls)} with "
                    f"{format_shape(rs)} in {self.fn.name}(): "
                    f"documented extents {d1!r} and {d2!r} name "
                    f"different dimensions",
                )
        dt, wk = promote(left.dtype, left.weak, right.dtype, right.weak)
        if not compare:
            self._check_promotion(node, left, right, dt)
        return array(bc.shape, dt, wk)

    def _check_promotion(
        self, node: ast.AST, left: AbsVal, right: AbsVal,
        result: Optional[str],
    ) -> None:
        d1, d2 = left.dtype, right.dtype
        if d1 is None or d2 is None or d1 == d2:
            return
        pair = {d1, d2}
        strong = not (left.weak or right.weak)
        if strong and pair & {"bfloat16", "float16"} and pair & {
            "float32", "float64"
        }:
            self.emit(
                "flow-bf16-mixed", "warning", node,
                f"{d1} mixed with {d2} in {self.fn.name}(): the "
                f"upcast is implicit — cast explicitly (astype) so "
                f"the precision boundary is visible",
            )
        narrow = pair & {"int32", "float32"}
        if strong and result in _SIXTYFOUR and narrow:
            kindword = sorted(narrow)[0]
            other = d2 if d1 == kindword else d1
            self.emit(
                "flow-int-promote" if kindword == "int32"
                else "flow-f64-widen",
                "warning", node,
                f"{kindword} operand silently widened to {result} in "
                f"{self.fn.name}() by promotion with a {other} "
                f"operand",
            )

    def eval_binop(self, node: ast.BinOp) -> AbsVal:
        left = self.eval(node.left)
        right = self.eval(node.right)
        if isinstance(node.op, ast.MatMult):
            # @ contracts, it does not broadcast
            return self._matmul(node, left, right)
        if left.kind == "scalar" and right.kind == "scalar":
            # dims survive +/-/* only as unknown; equality of symbols is
            # what matters, arithmetic on them is opaque
            dt, wk = promote(left.dtype, left.weak, right.dtype,
                             right.weak)
            return scalar(dt, wk)
        return self.combine(node, left, right)

    # -- calls ---------------------------------------------------------

    def _kw(self, node: ast.Call, name: str) -> Optional[ast.expr]:
        for kw in node.keywords:
            if kw.arg == name:
                return kw.value
        return None

    def _dtype_of_arg(self, node: Optional[ast.expr]) -> Optional[str]:
        if node is None:
            return None
        d = _dotted(node)
        dt = canonical_dtype(d) if d else None
        if dt is None and isinstance(node, ast.Constant) and isinstance(
            node.value, str
        ):
            dt = canonical_dtype(node.value)
        if dt is None:
            av = self.eval(node)
            dt = av.dtype
        return dt

    def _check_dtype_arg(
        self, node: ast.Call, expr: Optional[ast.expr],
        dt: Optional[str],
    ) -> None:
        # dotted forms (jnp.float64) already fire at attribute
        # evaluation; only string-literal dtypes need a check here
        if (
            dt in _SIXTYFOUR and self.jit
            and isinstance(expr, ast.Constant)
        ):
            self.emit(
                "flow-f64-widen", "warning", node,
                f"explicit {dt} in jit-reachable {self.fn.name}(): "
                f"64-bit planes double memory (or downcast with x64 "
                f"off)",
            )

    def _shape_from_expr(self, node: ast.expr) -> Optional[Tuple]:
        """Shape tuple from a constructor's shape argument."""
        if isinstance(node, (ast.Tuple, ast.List)):
            dims = []
            for e in node.elts:
                av = self.eval(e)
                if av.kind == "scalar" and av.dim is not None:
                    dims.append(av.dim if av.dim != -1 else None)
                else:
                    dims.append(None)
            return tuple(dims)
        av = self.eval(node)
        if av.kind == "scalar":
            return (
                (av.dim,) if av.dim is not None and av.dim != -1
                else (None,)
            )
        if av.kind == "tuple" and av.elems is not None:
            return tuple(
                e.dim if e.kind == "scalar" else None for e in av.elems
            )
        return None

    def _axis_arg(
        self, node: ast.Call, pos: int
    ) -> Optional[ast.expr]:
        """The axis argument expression: the ``axis=`` keyword, or the
        positional slot ``pos`` (0 for ``x.sum(0)``, 1 for
        ``jnp.sum(x, 0)``)."""
        ax = self._kw(node, "axis")
        if ax is None and len(node.args) > pos >= 0:
            ax = node.args[pos]
        return ax

    @staticmethod
    def _axis_int(ax: Optional[ast.expr]) -> Optional[int]:
        if isinstance(ax, ast.Constant) and isinstance(ax.value, int):
            return ax.value
        if isinstance(ax, ast.UnaryOp) and isinstance(
            ax.op, ast.USub
        ) and isinstance(ax.operand, ast.Constant) and isinstance(
            ax.operand.value, int
        ):
            return -ax.operand.value
        return None

    def _check_axis0(self, node: ast.Call, what: str, pos: int) -> None:
        if self.batchable and self._axis_int(
            self._axis_arg(node, pos)
        ) == 0:
            self.emit(
                "flow-batch-axis", "error", node,
                f"axis=0 {what} in batchable {self.fn.name}() reduces "
                f"over the would-be batch axis (ROADMAP item 3)",
            )

    def _reduce(
        self, node: ast.Call, x: AbsVal, to_dtype: Optional[str],
        pos: int,
    ) -> AbsVal:
        if x.kind != "array":
            return scalar(to_dtype or (x.dtype if x.kind == "scalar"
                                       else None), weak=False)
        dt = to_dtype or x.dtype
        if x.shape is None:
            return array(None, dt)
        kd = self._kw(node, "keepdims")
        keepdims = isinstance(kd, ast.Constant) and kd.value is True
        ax_expr = self._axis_arg(node, pos)
        if ax_expr is None:
            if keepdims:
                return array((1,) * len(x.shape), dt)
            return array((), dt)  # full reduction
        axis = self._axis_int(ax_expr)
        if axis is None:
            return array(None, dt)
        shape = list(x.shape)
        if -len(shape) <= axis < len(shape):
            if keepdims:
                shape[axis] = 1
            else:
                del shape[axis]
        return array(tuple(shape), dt)

    def _host_transfer(self, node: ast.Call, what: str) -> None:
        if self.jit:
            self.emit(
                "flow-host-transfer", "warning", node,
                f"{what} on an abstract array in jit-reachable "
                f"{self.fn.name}(): forces a device->host transfer "
                f"(fails under jit)",
            )

    def eval_call(self, node: ast.Call) -> AbsVal:
        d = _dotted(node.func)
        args = [self.eval(a) for a in node.args]
        for kw in node.keywords:
            if kw.arg != "axis":
                self.eval(kw.value)

        # method calls on abstract values -----------------------------
        if isinstance(node.func, ast.Attribute) and not (
            d and d.split(".")[0] in (
                "jnp", "np", "jax", "lax", "numpy", "onp", "pl",
            )
        ):
            base = self.eval(node.func.value)
            meth = node.func.attr
            res = self._method_call(node, base, meth, args)
            if res is not None:
                return res

        if d is not None:
            res = self._named_call(node, d, args)
            if res is not None:
                return res

        # module-local / nested function: interprocedural step
        if isinstance(node.func, ast.Name):
            target = self.local_funcs.get(
                node.func.id
            ) or self.an.module_funcs.get(node.func.id)
            if target is not None:
                return self._call_local(node, target, args)
        return UNKNOWN

    def _method_call(
        self, node: ast.Call, base: AbsVal, meth: str,
        args: List[AbsVal],
    ) -> Optional[AbsVal]:
        if base.kind == "atview":
            if meth in ("set", "add", "multiply", "divide", "min",
                        "max", "get", "apply"):
                return base.field("base")
            return UNKNOWN
        if base.kind == "record":
            if meth == "_replace":
                fields = dict(base.fields or ())
                for kw in node.keywords:
                    if kw.arg in fields:
                        fields[kw.arg] = self.eval(kw.value)
                return record(fields, origin=base.origin)
            return UNKNOWN
        if base.kind != "array":
            return None
        if meth in _HOST_METHODS:
            self._host_transfer(node, f".{meth}()")
            return UNKNOWN
        if meth == "astype":
            expr = node.args[0] if node.args else self._kw(node, "dtype")
            dt = self._dtype_of_arg(expr)
            self._check_dtype_arg(node, expr, dt)
            return base.with_(dtype=dt, weak=False)
        if meth == "reshape":
            return self._reshape(node, base, node.args)
        if meth in ("ravel", "flatten"):
            return array((None,), base.dtype, base.weak)
        if meth in ("transpose",):
            return base.with_(
                shape=(
                    tuple(reversed(base.shape))
                    if base.shape is not None and not node.args
                    else None
                )
            )
        if meth in _REDUCTIONS:
            self._check_axis0(node, f".{meth}()", pos=0)
            to = (
                "int32" if meth in ("argmin", "argmax") else
                "bool" if meth in ("any", "all") else None
            )
            return self._reduce(node, base, to, pos=0)
        if meth in ("copy", "block_until_ready", "clip", "squeeze"):
            return base
        return base.with_(weak=base.weak)

    def _reshape(
        self, node: ast.Call, x: AbsVal, shape_args: List[ast.expr]
    ) -> AbsVal:
        if len(shape_args) == 1:
            new_shape = self._shape_from_expr(shape_args[0])
        elif shape_args:
            dims = []
            for e in shape_args:
                av = self.eval(e)
                dims.append(
                    av.dim if av.kind == "scalar" and av.dim != -1
                    else None
                )
            new_shape = tuple(dims)
        else:
            new_shape = None
        if (
            new_shape is not None
            and x.shape is not None
            and len(x.shape) == 2
            and len(new_shape) == 2
            and new_shape == (x.shape[1], x.shape[0])
            and x.shape[0] is not None
            and x.shape[1] is not None
            and x.shape[0] != x.shape[1]
        ):
            self.emit(
                "flow-plane-reshape", "warning", node,
                f"reshape {format_shape(x.shape)} -> "
                f"{format_shape(new_shape)} in {self.fn.name}() "
                f"reinterprets row-major data; use .T/transpose to "
                f"swap plane axes",
            )
        return array(new_shape, x.dtype, x.weak)

    def _named_call(
        self, node: ast.Call, d: str, args: List[AbsVal]
    ) -> Optional[AbsVal]:
        tail = d.split(".")[-1]
        root = d.split(".")[0]
        jaxish = root in ("jnp", "np", "jax", "lax", "numpy", "onp")

        # host transfers ----------------------------------------------
        if tail in _HOST_CAST_FUNCS and d == tail:
            if any(a.kind == "array" for a in args):
                self._host_transfer(node, f"{d}()")
            return scalar(
                "float32" if tail == "float" else
                "int32" if tail == "int" else "bool",
                weak=True,
            )
        if d in _HOST_NP_FUNCS and any(a.kind == "array" for a in args):
            self._host_transfer(node, f"{d}()")
            return args[0] if args else UNKNOWN

        # sharding: PartitionSpec axes are checked module-wide in run()
        # (the spec may be built outside any jit-reachable function)
        if tail in ("PartitionSpec", "P"):
            return AbsVal(kind="other", origin="spec")
        if tail == "with_sharding_constraint":
            return args[0] if args else UNKNOWN

        if not jaxish:
            return None

        # dtype constructors: jnp.float32(x), jnp.int32(x)...
        asdt = canonical_dtype(d)
        if asdt is not None:
            if asdt in _SIXTYFOUR and self.jit:
                self.emit(
                    "flow-f64-widen", "warning", node,
                    f"{d}() in jit-reachable {self.fn.name}(): 64-bit "
                    f"dtypes silently double memory (or downcast with "
                    f"x64 off)",
                )
            if args and args[0].kind == "array":
                return args[0].with_(dtype=asdt, weak=False)
            return scalar(asdt, weak=False)

        dt_kw_expr = self._kw(node, "dtype")
        dt_kw = self._dtype_of_arg(dt_kw_expr)
        if dt_kw is not None:
            self._check_dtype_arg(node, dt_kw_expr, dt_kw)

        if tail in ("zeros", "ones", "empty", "full"):
            shape = (
                self._shape_from_expr(node.args[0]) if node.args
                else None
            )
            dt = dt_kw
            if dt is None and tail == "full" and len(node.args) >= 2:
                fill = self.eval(node.args[1])
                dt = fill.dtype
            if dt is None:
                dt = "float32"
            return array(shape, dt)
        if tail in ("zeros_like", "ones_like", "full_like",
                    "empty_like"):
            x = args[0] if args else UNKNOWN
            return (
                x.with_(dtype=dt_kw or x.dtype) if x.kind == "array"
                else UNKNOWN
            )
        if tail in ("asarray", "array", "atleast_1d"):
            x = args[0] if args else UNKNOWN
            if x.kind == "array":
                return x.with_(
                    dtype=dt_kw or x.dtype,
                    weak=x.weak and dt_kw is None,
                )
            if x.kind == "scalar":
                return array((), dt_kw or x.dtype,
                             x.weak and dt_kw is None)
            if x.kind == "tuple" and x.elems is not None:
                return array((len(x.elems),), dt_kw)
            return array(None, dt_kw)
        if tail == "arange":
            shape = None
            if len(node.args) == 1:
                av = args[0]
                shape = (
                    (av.dim,) if av.kind == "scalar" and av.dim
                    is not None else (None,)
                )
            # jnp.arange returns a STRONG int32 array (weak_type=False)
            return array(shape, dt_kw or "int32", weak=False)
        if tail == "where":
            if len(args) >= 3:
                self.combine(node, args[0], args[1])
                return self.combine(node, args[1], args[2])
            return UNKNOWN
        if tail in _REDUCTIONS:
            self._check_axis0(node, f"{d}()", pos=1)
            x = args[0] if args else UNKNOWN
            to = (
                "int32" if tail in ("argmin", "argmax") else
                "bool" if tail in ("any", "all") else None
            )
            if tail.startswith("segment_"):
                return array(None, x.dtype if x.kind == "array"
                             else None)
            return self._reduce(node, x, to, pos=1)
        if tail == "reshape":
            x = args[0] if args else UNKNOWN
            return self._reshape(
                node, x if x.kind == "array" else array(None),
                node.args[1:],
            )
        if tail in ("transpose", "swapaxes", "moveaxis"):
            x = args[0] if args else UNKNOWN
            if (
                tail == "transpose" and x.kind == "array"
                and x.shape is not None and len(node.args) == 1
            ):
                return x.with_(shape=tuple(reversed(x.shape)))
            return array(
                None, x.dtype if x.kind == "array" else None
            )
        if tail in ("concatenate", "stack", "vstack", "hstack"):
            parts = args[0] if args else UNKNOWN
            elems = (
                list(parts.elems) if parts.kind == "tuple"
                and parts.elems is not None else []
            )
            arrs = [e for e in elems if e.kind == "array"]
            dt: Optional[str] = None
            wk = True
            for i, a in enumerate(arrs):
                if i == 0:
                    dt, wk = a.dtype, a.weak
                else:
                    dt, wk = promote(dt, wk, a.dtype, a.weak)
            if tail == "stack" and arrs and arrs[0].shape is not None:
                return array(
                    (len(elems),) + arrs[0].shape, dt, wk
                )
            if arrs and arrs[0].shape is not None:
                ax = self._axis_int(self._axis_arg(node, 1)) or 0
                shape = list(arrs[0].shape)
                if -len(shape) <= ax < len(shape):
                    shape[ax] = None
                return array(tuple(shape), dt, wk)
            return array(None, dt, wk)
        if tail in ("matmul", "dot"):
            if len(args) >= 2:
                return self._matmul(node, args[0], args[1])
            return UNKNOWN
        if tail == "take":
            x = args[0] if args else UNKNOWN
            if len(node.args) >= 2:
                self._check_index_dtype(node.args[1])
            return array(None, x.dtype if x.kind == "array" else None)
        if tail in ("expand_dims",):
            x = args[0] if args else UNKNOWN
            ax = self._axis_int(self._axis_arg(node, 1))
            if x.kind == "array" and x.shape is not None and ax is not None:
                shape = list(x.shape)
                if 0 <= ax <= len(shape):
                    shape.insert(ax, 1)
                    return array(tuple(shape), x.dtype, x.weak)
            return array(None, x.dtype if x.kind == "array" else None)
        if tail in ("uniform", "normal", "randint", "bernoulli"):
            shape_arg = self._kw(node, "shape") or (
                node.args[1] if len(node.args) >= 2 else None
            )
            shape = (
                self._shape_from_expr(shape_arg)
                if shape_arg is not None else ()
            )
            return array(shape, dt_kw or "float32")
        if tail in ("PRNGKey", "fold_in", "split"):
            return array(None, "uint32")
        if tail in ("maximum", "minimum", "add", "subtract", "multiply",
                    "divide", "mod", "power"):
            if len(args) >= 2:
                return self.combine(node, args[0], args[1])
            return UNKNOWN
        if tail in _ELEMENTWISE:
            x = args[0] if args else UNKNOWN
            return x if x.kind in ("array", "scalar") else UNKNOWN
        if tail in ("cond", "scan", "while_loop", "fori_loop", "switch",
                    "pallas_call", "vmap", "pmap", "shard_map", "jit",
                    "pjit", "checkpoint", "remat"):
            # combinator: callbacks analyzed by the seeder; result opaque
            return UNKNOWN
        if tail == "bitcast_convert_type":
            dt = self._dtype_of_arg(
                node.args[1] if len(node.args) >= 2 else None
            )
            x = args[0] if args else UNKNOWN
            return array(
                None, dt, False
            ) if x.kind == "array" else UNKNOWN
        return UNKNOWN

    def _matmul(
        self, node: ast.AST, a: AbsVal, b: AbsVal
    ) -> AbsVal:
        dt, wk = promote(a.dtype, a.weak, b.dtype, b.weak)
        self._check_promotion(node, a, b, dt)
        if (
            a.kind == "array" and b.kind == "array"
            and a.shape is not None and b.shape is not None
            and len(a.shape) == 2 and len(b.shape) == 2
        ):
            inner_a, inner_b = a.shape[1], b.shape[0]
            if (
                isinstance(inner_a, int) and isinstance(inner_b, int)
                and inner_a != inner_b
            ):
                self.emit(
                    "flow-shape-mismatch", "error", node,
                    f"matmul inner dims {inner_a} and {inner_b} in "
                    f"{self.fn.name}() can never contract",
                )
            elif (
                isinstance(inner_a, str) and isinstance(inner_b, str)
                and inner_a != inner_b
                and inner_a in self.an.known_dims
                and inner_b in self.an.known_dims
            ):
                self.emit(
                    "flow-shape-mismatch", "warning", node,
                    f"matmul contracts documented extents "
                    f"{inner_a!r} with {inner_b!r} in "
                    f"{self.fn.name}()",
                )
            return array((a.shape[0], b.shape[1]), dt, wk)
        return array(None, dt, wk)

    def _call_local(
        self, node: ast.Call, target: ast.FunctionDef,
        args: List[AbsVal],
    ) -> AbsVal:
        names = _param_names(target)
        env: Dict[str, AbsVal] = {}
        pos = [
            a.arg for a in target.args.posonlyargs + target.args.args
        ]
        for i, a in enumerate(node.args):
            if isinstance(a, ast.Starred):
                break
            if i < len(pos):
                env[pos[i]] = args[i] if i < len(args) else UNKNOWN
        for kw in node.keywords:
            if kw.arg in names:
                env[kw.arg] = self.eval(kw.value)
        # unsupplied params fall back to annotation-derived values
        for a in (
            list(target.args.posonlyargs)
            + list(target.args.args)
            + list(target.args.kwonlyargs)
        ):
            if a.arg not in env:
                env[a.arg] = _annotation_absval(
                    self.an, a.annotation, a.arg
                )
        for n in names:
            if n not in env:
                env[n] = UNKNOWN
        return _interpret(
            self.an, target, env,
            jit_reachable=self.jit,
            batchable=id(target) in self.an.batchable,
            depth=self.depth + 1,
            local_funcs=self.local_funcs,
        )


_MAX_DEPTH = 4


def _interpret(
    an: _Analysis,
    fn: ast.FunctionDef,
    env: Dict[str, AbsVal],
    jit_reachable: bool,
    batchable: bool,
    depth: int,
    local_funcs: Dict[str, ast.FunctionDef],
) -> AbsVal:
    """Evaluate ``fn`` under ``env``; returns its abstract return value.
    Memoized per (function, signature summary) so the pass terminates
    on recursion and repeated call sites."""
    if depth > _MAX_DEPTH:
        return UNKNOWN
    names = _param_names(fn)
    key = (id(fn), _sig_summary(env, names), jit_reachable, batchable)
    if key in an.seen or len(an.seen) > 4000:
        return UNKNOWN
    an.seen.add(key)
    full_env = dict(env)
    for n in names:
        full_env.setdefault(n, UNKNOWN)
    for skip in ("self", "cls"):
        if skip in full_env:
            full_env[skip] = UNKNOWN
    interp = _Interp(
        an, fn, full_env, jit_reachable, batchable, depth, local_funcs
    )
    interp.exec_body(fn.body)
    out = UNKNOWN
    for r in interp.returns:
        out = r if out is UNKNOWN else join(out, r)
    return out


def _seed_env(an: _Analysis, fn: ast.FunctionDef) -> Dict[str, AbsVal]:
    env: Dict[str, AbsVal] = {}
    args = fn.args
    for a in list(args.posonlyargs) + list(args.args) + list(
        args.kwonlyargs
    ):
        env[a.arg] = _annotation_absval(an, a.annotation, a.arg)
    if args.vararg:
        env[args.vararg.arg] = UNKNOWN
    if args.kwarg:
        env[args.kwarg.arg] = UNKNOWN
    return env


def _collect_seeds(an: _Analysis) -> None:
    tree = an.sf.tree
    # 1. jit-decorated functions (profiled_jit included) + batchable-marked
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        statics = _decorator_jit_statics(node)
        marked = id(node) in an.batchable
        if statics is None and not marked:
            continue
        env = _seed_env(an, node)
        if statics is not None:
            static_names, static_nums = statics
            pos = [
                a.arg for a in node.args.posonlyargs + node.args.args
            ]
            for n in static_names:
                if n in env and env[n].kind == "unknown":
                    env[n] = scalar("int32", weak=True, dim=n)
            for i in static_nums:
                if 0 <= i < len(pos):
                    env.setdefault(pos[i], UNKNOWN)
        _interpret(
            an, node, env,
            jit_reachable=statics is not None,
            batchable=marked,
            depth=0, local_funcs={},
        )
    # 2. functions handed to jax combinators anywhere in the module
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if not d:
            continue
        tail = d.split(".")[-1]
        if tail == "pallas_call" or (
            tail in _COMBINATOR_TAILS
            and (d.split(".")[0] in _JAX_ROOTS or d in _COMBINATOR_BARE)
        ):
            for arg in list(node.args) + [
                kw.value for kw in node.keywords
            ]:
                if isinstance(arg, ast.Name):
                    target = an.module_funcs.get(
                        arg.id
                    ) or an.all_funcs.get(arg.id)
                    if target is not None:
                        _interpret(
                            an, target, _seed_env(an, target),
                            jit_reachable=True,
                            batchable=id(target) in an.batchable,
                            depth=0, local_funcs={},
                        )


def _check_partition_specs(
    sf: SourceFile, mesh_axes: Set[str], findings: List[Finding]
) -> None:
    """Module-wide PartitionSpec axis check — specs are often built
    outside any jit-reachable function, so this is a syntactic sweep,
    not part of the abstract interpretation.  With no Mesh/axis
    declaration anywhere there is no vocabulary to judge against."""
    if not mesh_axes:
        return
    spec_aliases = {"PartitionSpec"}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "PartitionSpec" and alias.asname:
                    spec_aliases.add(alias.asname)
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if not d or d.split(".")[-1] not in spec_aliases:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            elts = (
                arg.elts if isinstance(arg, (ast.Tuple, ast.List))
                else [arg]
            )
            for e in elts:
                if (
                    isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                    and e.value not in mesh_axes
                ):
                    findings.append(
                        Finding(
                            rule="flow-sharding-axis",
                            severity="error",
                            path=sf.path,
                            line=e.lineno,
                            col=e.col_offset + 1,
                            message=(
                                f"PartitionSpec axis {e.value!r} is "
                                f"not declared by any scanned Mesh "
                                f"(declared: {sorted(mesh_axes)})"
                            ),
                        )
                    )


def run(files: List[SourceFile]) -> List[Finding]:
    records_map, known_dims = _collect_records(files)
    mesh_axes = _collect_mesh_axes(files)
    findings: List[Finding] = []
    for sf in files:
        batchable = {
            id(n)
            for n in ast.walk(sf.tree)
            if isinstance(n, ast.FunctionDef) and _is_batchable(sf, n)
        }
        an = _Analysis(
            sf=sf,
            findings=[],
            module_funcs={
                n.name: n for n in sf.tree.body
                if isinstance(n, ast.FunctionDef)
            },
            all_funcs={
                n.name: n for n in ast.walk(sf.tree)
                if isinstance(n, ast.FunctionDef)
            },
            records=records_map,
            known_dims=known_dims,
            mesh_axes=mesh_axes,
            batchable=batchable,
            seen=set(),
        )
        _collect_seeds(an)
        _check_partition_specs(sf, mesh_axes, an.findings)
        uniq: Dict[Tuple[str, int, int], Finding] = {}
        for f in an.findings:
            uniq.setdefault((f.rule, f.line, f.col), f)
        findings.extend(uniq.values())
    return findings
