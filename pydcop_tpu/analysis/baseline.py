"""Baseline ratchet: a checked-in JSON inventory of accepted findings.

The baseline stores each accepted finding's fingerprint (plus
human-readable context).  A lint run against a baseline partitions the
live findings into *new* (fingerprint absent from the baseline — these
fail the build) and *known*; baseline entries that no longer match
anything are reported as *fixed* so the file can be re-ratcheted with
``--write-baseline``.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List

from .core import Finding

__all__ = [
    "BaselineDiff",
    "load_baseline",
    "write_baseline",
    "diff_against_baseline",
]

FORMAT_VERSION = 1


@dataclass
class BaselineDiff:
    new: List[Finding] = field(default_factory=list)
    known: List[Finding] = field(default_factory=list)
    fixed: List[Dict[str, object]] = field(default_factory=list)


def load_baseline(path: str) -> Dict[str, Dict[str, object]]:
    """fingerprint -> recorded entry.  Raises ValueError on a malformed
    file — a silently ignored baseline would un-ratchet the build."""
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if (
        not isinstance(data, dict)
        or data.get("version") != FORMAT_VERSION
        or not isinstance(data.get("findings"), list)
    ):
        raise ValueError(f"{path}: not a graftlint baseline (version 1)")
    out: Dict[str, Dict[str, object]] = {}
    for entry in data["findings"]:
        fp = entry.get("fingerprint") if isinstance(entry, dict) else None
        if not isinstance(fp, str) or not fp:
            raise ValueError(f"{path}: baseline entry without fingerprint")
        out[fp] = entry
    return out


def write_baseline(path: str, findings: List[Finding]) -> None:
    data = {
        "version": FORMAT_VERSION,
        "findings": [f.as_dict() for f in findings],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=False)
        f.write("\n")


def diff_against_baseline(
    findings: List[Finding], baseline: Dict[str, Dict[str, object]]
) -> BaselineDiff:
    diff = BaselineDiff()
    live = set()
    for f in findings:
        live.add(f.fingerprint)
        (diff.known if f.fingerprint in baseline else diff.new).append(f)
    diff.fixed = [
        entry for fp, entry in baseline.items() if fp not in live
    ]
    return diff
