"""graftlint command line: ``python -m pydcop_tpu.analysis`` and the
engine behind ``pydcop_tpu lint``.

Exit codes: 0 = clean (no findings outside the baseline), 1 = new
findings, 2 = usage or I/O error.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from .baseline import (
    diff_against_baseline,
    load_baseline,
    write_baseline,
)
from .core import PASS_NAMES, collect_findings, iter_rules

__all__ = ["build_parser", "main"]


def build_parser(
    parser: Optional[argparse.ArgumentParser] = None,
) -> argparse.ArgumentParser:
    if parser is None:
        parser = argparse.ArgumentParser(
            prog="graftlint",
            description=(
                "static analysis: lock discipline, JAX tracing "
                "hazards, message-protocol consistency"
            ),
        )
    parser.add_argument(
        "paths", nargs="*", default=["pydcop_tpu"],
        help="files or directories to lint (default: pydcop_tpu)",
    )
    parser.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="ratchet file: findings recorded there are tolerated, "
        "new ones fail",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="rewrite --baseline with the current findings and exit 0",
    )
    parser.add_argument(
        "--select", default=None, metavar="RULES",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--passes", default=None, metavar="PASSES",
        help=f"comma-separated passes from {', '.join(PASS_NAMES)}",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        dest="fmt", help="output format",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print every rule id with its severity and exit",
    )
    parser.add_argument(
        "--quiet", action="store_true",
        help="print only new findings and the summary line",
    )
    return parser


def run_lint(args: argparse.Namespace, out=None) -> int:
    out = out or sys.stdout

    if args.list_rules:
        for rule in iter_rules():
            print(f"{rule.id:28} {rule.severity:8} {rule.summary}",
                  file=out)
        return 0

    select = (
        [r.strip() for r in args.select.split(",") if r.strip()]
        if args.select else None
    )
    passes = (
        [p.strip() for p in args.passes.split(",") if p.strip()]
        if args.passes else None
    )
    try:
        findings = collect_findings(args.paths, select=select,
                                    passes=passes)
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        if not args.baseline:
            print(
                "graftlint: --write-baseline requires --baseline",
                file=sys.stderr,
            )
            return 2
        if select or passes:
            # a filtered write would silently drop every accepted
            # finding of the filtered-out rules from the baseline
            print(
                "graftlint: refusing --write-baseline with "
                "--select/--passes (it would erase the other rules' "
                "accepted findings)",
                file=sys.stderr,
            )
            return 2
        write_baseline(args.baseline, findings)
        print(
            f"graftlint: wrote {len(findings)} finding(s) to "
            f"{args.baseline}",
            file=out,
        )
        return 0

    baseline = None
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"graftlint: cannot load baseline: {e}",
                  file=sys.stderr)
            return 2

    if baseline is None:
        new, known, fixed = findings, [], []
    else:
        diff = diff_against_baseline(findings, baseline)
        new, known, fixed = diff.new, diff.known, diff.fixed

    if args.fmt == "json":
        json.dump(
            {
                "new": [f.as_dict() for f in new],
                "known": [f.as_dict() for f in known],
                "fixed": fixed,
            },
            out,
            indent=2,
        )
        out.write("\n")
    else:
        for f in new:
            print(f.format() + "  [NEW]", file=out)
        if not args.quiet:
            for f in known:
                print(f.format() + "  [baseline]", file=out)
            for entry in fixed:
                print(
                    f"{entry.get('path')}:{entry.get('line')}: fixed "
                    f"[{entry.get('rule')}] — re-ratchet with "
                    f"--write-baseline",
                    file=out,
                )
        summary = (
            f"graftlint: {len(new)} new, {len(known)} baselined, "
            f"{len(fixed)} fixed finding(s)"
        )
        print(summary, file=out)
    return 1 if new else 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    return run_lint(parser.parse_args(argv))
