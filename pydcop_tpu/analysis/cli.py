"""graftlint command line: ``python -m pydcop_tpu.analysis`` and the
engine behind ``pydcop_tpu lint``.

Exit codes: 0 = clean (no findings outside the baseline), 1 = new
findings, 2 = usage or I/O error.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from .baseline import (
    diff_against_baseline,
    load_baseline,
    write_baseline,
)
from .core import PASS_NAMES, collect_findings, iter_rules

__all__ = ["build_parser", "main"]


def explain_rule(rule_id: str, out) -> int:
    """``--explain <rule>``: the rule's summary plus the pass module's
    EXPLAIN entry (doc paragraph + minimal failing example)."""
    from .core import _passes

    rule = next((r for r in iter_rules() if r.id == rule_id), None)
    if rule is None:
        known = ", ".join(r.id for r in iter_rules())
        print(
            f"graftlint: unknown rule {rule_id!r} (known: {known})",
            file=sys.stderr,
        )
        return 2
    print(f"{rule.id} ({rule.severity}): {rule.summary}", file=out)
    for mod in _passes().values():
        entry = getattr(mod, "EXPLAIN", {}).get(rule_id)
        if entry is not None:
            doc, example = entry
            print(f"\n{doc}\n\nMinimal failing example:\n", file=out)
            for line in example.rstrip("\n").splitlines():
                print(f"    {line}", file=out)
            break
    else:
        print("\n(no extended doc recorded for this rule)", file=out)
    return 0


def _rule_count_table(new, known, out) -> None:
    """Per-rule count summary: how many new vs baselined findings each
    rule produced in this run (rules with no findings are omitted)."""
    counts = {}
    for f in new:
        counts.setdefault(f.rule, [0, 0])[0] += 1
    for f in known:
        counts.setdefault(f.rule, [0, 0])[1] += 1
    if not counts:
        return
    width = max(len(r) for r in counts)
    print(f"{'rule'.ljust(width)}  {'new':>4}  {'base':>4}", file=out)
    for rule_id in sorted(counts):
        n, k = counts[rule_id]
        print(f"{rule_id.ljust(width)}  {n:>4}  {k:>4}", file=out)


def build_parser(
    parser: Optional[argparse.ArgumentParser] = None,
) -> argparse.ArgumentParser:
    if parser is None:
        parser = argparse.ArgumentParser(
            prog="graftlint",
            description=(
                "static analysis: lock discipline, JAX tracing "
                "hazards, message-protocol consistency, graftflow "
                "array flow, graftproto conversation verification, "
                "graftperf performance discipline"
            ),
        )
    parser.add_argument(
        "paths", nargs="*", default=["pydcop_tpu"],
        help="files or directories to lint (default: pydcop_tpu)",
    )
    parser.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="ratchet file: findings recorded there are tolerated, "
        "new ones fail",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="rewrite --baseline with the current findings and exit 0",
    )
    parser.add_argument(
        "--select", default=None, metavar="RULES",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--passes", default=None, metavar="PASSES",
        help=f"comma-separated passes from {', '.join(PASS_NAMES)}",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        dest="fmt",
        help="output format (sarif = SARIF 2.1.0 with rule metadata, "
        "for CI/editor annotation)",
    )
    parser.add_argument(
        "--no-cache", action="store_true",
        help="skip the incremental finding cache under "
        "$PYDCOP_TPU_STATE_DIR (default .bench_state/)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print every rule id with its severity and exit",
    )
    parser.add_argument(
        "--explain", default=None, metavar="RULE",
        help="print one rule's doc and a minimal failing example, "
        "then exit",
    )
    parser.add_argument(
        "--quiet", action="store_true",
        help="print only new findings and the summary line",
    )
    return parser


def run_lint(args: argparse.Namespace, out=None) -> int:
    out = out or sys.stdout

    if args.list_rules:
        for rule in iter_rules():
            print(f"{rule.id:28} {rule.severity:8} {rule.summary}",
                  file=out)
        return 0

    if args.explain:
        return explain_rule(args.explain, out)

    select = (
        [r.strip() for r in args.select.split(",") if r.strip()]
        if args.select else None
    )
    passes = (
        [p.strip() for p in args.passes.split(",") if p.strip()]
        if args.passes else None
    )
    try:
        findings = collect_findings(
            args.paths, select=select, passes=passes,
            use_cache=not getattr(args, "no_cache", False),
        )
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        if not args.baseline:
            print(
                "graftlint: --write-baseline requires --baseline",
                file=sys.stderr,
            )
            return 2
        if select or passes:
            # a filtered write would silently drop every accepted
            # finding of the filtered-out rules from the baseline
            print(
                "graftlint: refusing --write-baseline with "
                "--select/--passes (it would erase the other rules' "
                "accepted findings)",
                file=sys.stderr,
            )
            return 2
        write_baseline(args.baseline, findings)
        print(
            f"graftlint: wrote {len(findings)} finding(s) to "
            f"{args.baseline}",
            file=out,
        )
        return 0

    baseline = None
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"graftlint: cannot load baseline: {e}",
                  file=sys.stderr)
            return 2

    if baseline is None:
        new, known, fixed = findings, [], []
    else:
        diff = diff_against_baseline(findings, baseline)
        new, known, fixed = diff.new, diff.known, diff.fixed

    if args.fmt == "json":
        json.dump(
            {
                "new": [f.as_dict() for f in new],
                "known": [f.as_dict() for f in known],
                "fixed": fixed,
            },
            out,
            indent=2,
        )
        out.write("\n")
    elif args.fmt == "sarif":
        from .sarif import sarif_report

        json.dump(
            sarif_report(new, known, baseline_used=baseline is not None),
            out,
            indent=2,
        )
        out.write("\n")
    else:
        for f in new:
            print(f.format() + "  [NEW]", file=out)
        if not args.quiet:
            for f in known:
                print(f.format() + "  [baseline]", file=out)
            for entry in fixed:
                print(
                    f"{entry.get('path')}:{entry.get('line')}: fixed "
                    f"[{entry.get('rule')}] — re-ratchet with "
                    f"--write-baseline",
                    file=out,
                )
        # per-rule count table: always in full output; in --quiet mode
        # only when something new fired (so CI failures are self-
        # explanatory but green runs stay one line)
        if not args.quiet or new:
            _rule_count_table(new, known, out)
        summary = (
            f"graftlint: {len(new)} new, {len(known)} baselined, "
            f"{len(fixed)} fixed finding(s)"
        )
        print(summary, file=out)
    return 1 if new else 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    return run_lint(parser.parse_args(argv))
