"""graftlint pass 6: performance discipline ("graftperf").

The engine's entire advantage over the reference implementation is that
the per-cycle loop runs on-device: one dispatch and one packed readback
per solve (fused path), one dispatch per timeout chunk (chunked path).
PAPER.md's core claim evaporates if a host sync, a per-item dispatch,
or a recompile hazard silently creeps into a hot path.  This pass makes
those invariants lintable:

* ``perf-host-sync`` — ``.item()``/``.tolist()``, ``float()``/``int()``
  /``bool()``, ``np.asarray``/``jax.device_get`` or an implicit
  ``__bool__`` (Python ``if``/``while``) on a traced value inside
  jit-decorated functions, combinator bodies, or code reachable from
  the engine hot roots ``_fused_core``/``_while_chunk``/
  ``_scan_cycles``.  Reuses graftflow's memoized traced-function
  walker, so per-call-site argument tracedness propagates
  module-locally exactly like pass 2.
* ``perf-dispatch-in-loop`` — a jit/``profiled_jit``-wrapped callable
  invoked inside a Python ``for``/``while`` (or comprehension): one
  compiled-program dispatch per iteration where a scan, a fused kernel
  or a batched call should be.
* ``perf-transfer-in-loop`` — ``to_device``/``device_put`` inside a
  loop body: a host->device upload per iteration.
* ``perf-recompile-hazard`` — jit static arguments fed from unstable
  values (``len()`` of a container mutated in the same function,
  dict/set iteration order) and float constants compared with
  ``is``/``is not``.
* ``perf-donate-miss`` — a jit entry point that threads a large carry
  record (DeviceDCOP/PulseCarry-style NamedTuples, recognized from
  graftflow's shape-comment signature grammar) and returns an updated
  copy without ``donate_argnums``/``donate_argnames``: the carry
  buffers are copied on every dispatch.
* ``perf-nonjit-hot`` — a function marked ``# graftperf: hot`` (the
  per-cycle step kernels) that runs ``jnp``/``lax`` code eagerly:
  neither jit-decorated, nor wrapped/passed/returned into a traced
  context, nor reachable from one module-locally.

Suppression uses the shared comment machinery with the pass-local
alias: ``# graftperf: disable=perf-dispatch-in-loop (reason)``.

The static half of the perf *budget* (dispatch/readback site census per
engine path, ``tools/perf_budget.json``) lives in :mod:`.budget`.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .arrays import _collect_records
from .core import Finding, Rule, SourceFile, dotted_name as _dotted
from .tracing import (
    _Analysis,
    _COMBINATOR_BARE,
    _COMBINATOR_TAILS,
    _JAX_ROOTS,
    _JIT_NAMES,
    _analyze_traced,
    _collect_seeds,
    _decorator_jit_statics,
    _module_functions,
    _param_names,
)

__all__ = ["RULES", "run"]

#: bumped when the pass's behavior changes, so the incremental lint
#: cache (analysis/cache.py) never serves findings from an older rule set
VERSION = 1

RULES = (
    Rule(
        "perf-host-sync",
        "error",
        "host synchronisation inside a jit body or an engine hot path",
    ),
    Rule(
        "perf-dispatch-in-loop",
        "warning",
        "jit-compiled callable dispatched inside a Python loop",
    ),
    Rule(
        "perf-transfer-in-loop",
        "warning",
        "host->device transfer inside a Python loop body",
    ),
    Rule(
        "perf-recompile-hazard",
        "warning",
        "jit static argument fed from an unstable value",
    ),
    Rule(
        "perf-donate-miss",
        "warning",
        "carry record passed to a jit entry point without donation",
    ),
    Rule(
        "perf-nonjit-hot",
        "warning",
        "'# graftperf: hot' function runs jnp code outside any jit",
    ),
)

#: rule id -> (doc, minimal failing example) for ``lint --explain``
EXPLAIN = {
    "perf-host-sync": (
        "A host synchronisation (.item()/.tolist(), float()/int()/"
        "bool(), np.asarray, jax.device_get, or an implicit __bool__ "
        "from Python if/while) on a traced value inside a jit body, a "
        "scan/while combinator body, or code reachable from the engine "
        "hot roots _fused_core/_while_chunk/_scan_cycles. Each sync "
        "stalls the device pipeline exactly the way the reference's "
        "per-message host loop does. Overlaps trace-host-sync by "
        "design; this rule additionally walks the hot-root call graph.",
        "@jax.jit\n"
        "def step(x):\n"
        "    return x + float(x.sum())  # device->host round trip\n",
    ),
    "perf-dispatch-in-loop": (
        "A jit/profiled_jit-wrapped callable invoked inside a Python "
        "for/while loop (or comprehension): one compiled-program "
        "dispatch per iteration. Per-cycle or per-message dispatch is "
        "the reference implementation's perf ceiling — fuse the loop "
        "into the program (lax.scan / the fused engine path) or batch "
        "the items (vmap). The engine's chunk loop is the one sanctioned "
        "exception and carries an inline suppression naming why.",
        "@jax.jit\n"
        "def kernel(x): ...\n"
        "def drive(xs):\n"
        "    for x in xs:\n"
        "        kernel(x)  # dispatch per item\n",
    ),
    "perf-transfer-in-loop": (
        "to_device()/jax.device_put() inside a loop body uploads "
        "host data to the device once per iteration. Move the transfer "
        "out of the loop (upload once, index on device) or batch the "
        "items into one array.",
        "def drive(rows):\n"
        "    for r in rows:\n"
        "        use(to_device(r))  # upload per iteration\n",
    ),
    "perf-recompile-hazard": (
        "A jit static argument fed from an unstable value: len() of a "
        "container mutated in the same function, dict/set iteration "
        "order (list(d.keys()), tuple(s)), or a float compared with "
        "`is`. Every new static value compiles a new program variant — "
        "the compile cache churns instead of hitting. Sort or freeze "
        "the value before it reaches the static argument.",
        "@partial(jax.jit, static_argnames=('n',))\n"
        "def kernel(x, n): ...\n"
        "def drive(x, acc):\n"
        "    acc.append(x)\n"
        "    kernel(x, n=len(acc))  # recompiles every call\n",
    ),
    "perf-donate-miss": (
        "A jit entry point threads a large carry record (a shape-"
        "commented NamedTuple like DeviceDCOP/PulseCarry) and returns "
        "an updated copy, but the decorator has no donate_argnums/"
        "donate_argnames: XLA must copy the carry buffers on every "
        "dispatch instead of updating them in place.",
        "@jax.jit  # missing donate_argnums=(0,)\n"
        "def advance(state: CarryState) -> CarryState:\n"
        "    return state._replace(step=state.step + 1)\n",
    ),
    "perf-nonjit-hot": (
        "A function marked `# graftperf: hot` (the per-cycle step "
        "kernels) runs jnp/lax code eagerly: it is neither "
        "jit-decorated nor wrapped/passed/returned into a traced "
        "context, so every call dispatches op-by-op. This is the "
        "shape of the PR-8 lanes-fallback regression (~6x): a hot "
        "kernel silently running outside the compiled path.",
        "# graftperf: hot\n"
        "def step(dev, values):\n"
        "    return jnp.argmin(local_costs(dev, values), axis=1)\n"
        "step(dev, values)  # eager, op-by-op dispatch\n",
    ),
}

#: engine hot roots: the fused kernel body and the chunk kernels — code
#: reachable from these runs once per cycle on-device, so host syncs
#: inside are walked even though _fused_core itself is not decorated
_HOT_ROOT_NAMES = {"_fused_core", "_while_chunk", "_scan_cycles"}

#: same placement grammar as ``# graftflow: batchable`` (arrays.py):
#: the def line, a decorator line, or the line directly above
_HOT_RE = re.compile(r"#\s*graftperf:\s*hot\b")

_TRANSFER_TAILS = {"to_device", "device_put"}

_ARRAYISH_ANN = {
    "ndarray", "Array", "ArrayLike", "DeviceArray", "Tuple", "tuple",
}

_MUTATORS = {
    "append", "extend", "add", "insert", "update", "remove", "discard",
    "pop", "popitem", "clear", "setdefault",
}


# ---------------------------------------------------------------------------
# perf-host-sync: tracing's memoized walker, re-rooted at the engine
# hot paths and remapped to the perf rule id
# ---------------------------------------------------------------------------


def _ann_traced(ann: Optional[ast.expr], record_names: Set[str]) -> bool:
    """Conservative per-parameter tracedness from the annotation, for
    seeding undecorated hot roots: arrays and carry records are traced,
    ``Callable``/``int``/``bool``/``str`` configuration is static."""
    if ann is None:
        return True
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        tail = ann.value.split(".")[-1].split("[")[0]
        return tail in _ARRAYISH_ANN or tail in record_names
    if isinstance(ann, ast.Subscript):
        base = _dotted(ann.value)
        tail = base.split(".")[-1] if base else ""
        if tail == "Optional":
            return _ann_traced(ann.slice, record_names)
        return tail in ("Tuple", "tuple", "List", "list", "Sequence")
    d = _dotted(ann)
    if d is None:
        return False
    tail = d.split(".")[-1]
    return tail in _ARRAYISH_ANN or tail in record_names


def _seed_hot_roots(
    an: _Analysis, record_names: Set[str]
) -> None:
    """Walk undecorated engine hot roots with annotation-derived
    tracedness (decorated ones are already seeded with their real
    static_argnames by :func:`tracing._collect_seeds`)."""
    for node in ast.walk(an.sf.tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        if node.name not in _HOT_ROOT_NAMES:
            continue
        if _decorator_jit_statics(node) is not None:
            continue
        flags = {}
        args = node.args
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            flags[a.arg] = _ann_traced(a.annotation, record_names)
        _analyze_traced(an, node, flags, {}, {})


_SYNC_RULE_MAP = {
    "trace-host-sync": "",
    "trace-python-branch": "implicit __bool__ host sync: ",
}


def _host_sync_findings(
    sf: SourceFile, record_names: Set[str]
) -> List[Finding]:
    an = _Analysis(
        sf=sf,
        findings=[],
        module_funcs=_module_functions(sf.tree),
        all_funcs={
            n.name: n for n in ast.walk(sf.tree)
            if isinstance(n, ast.FunctionDef)
        },
        seen=set(),
    )
    _collect_seeds(an, sf.tree)
    _seed_hot_roots(an, record_names)
    out: List[Finding] = []
    for f in an.findings:
        prefix = _SYNC_RULE_MAP.get(f.rule)
        if prefix is None:
            continue  # trace-impure-call / trace-shape-loop: pass 2's job
        out.append(
            Finding(
                rule="perf-host-sync",
                severity="error",
                path=f.path,
                line=f.line,
                col=f.col,
                message=prefix + f.message,
            )
        )
    return out


# ---------------------------------------------------------------------------
# perf-dispatch-in-loop / perf-transfer-in-loop
# ---------------------------------------------------------------------------


def _jit_entry_names(tree: ast.Module) -> Set[str]:
    """Module-local names that dispatch a compiled program when called:
    jit-decorated defs and ``X = jit(f)`` / ``X = profiled_jit(f)``
    assignments."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            if _decorator_jit_statics(node) is not None:
                out.add(node.name)
        elif isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        ):
            d = _dotted(node.value.func)
            if d and d.split(".")[-1] in _JIT_NAMES:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


class _LoopScanner:
    """Counts loop depth and flags jit dispatches / device transfers
    inside loop bodies (rules 2 and 3)."""

    def __init__(
        self,
        sf: SourceFile,
        scope_name: str,
        jit_entries: Set[str],
        findings: List[Finding],
    ) -> None:
        self.sf = sf
        self.scope = scope_name
        self.jit_entries = jit_entries
        self.findings = findings

    def scan(self, body: Sequence[ast.stmt]) -> None:
        self._stmts(body, 0)

    def _stmts(self, body: Sequence[ast.stmt], depth: int) -> None:
        for stmt in body:
            if isinstance(
                stmt,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                continue  # scanned as their own scope
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._expr(stmt.iter, depth)
                self._stmts(stmt.body, depth + 1)
                self._stmts(stmt.orelse, depth + 1)
                continue
            if isinstance(stmt, ast.While):
                self._expr(stmt.test, depth)
                self._stmts(stmt.body, depth + 1)
                self._stmts(stmt.orelse, depth + 1)
                continue
            if isinstance(stmt, ast.If):
                self._expr(stmt.test, depth)
                self._stmts(stmt.body, depth)
                self._stmts(stmt.orelse, depth)
                continue
            if isinstance(stmt, ast.Try):
                self._stmts(stmt.body, depth)
                for h in stmt.handlers:
                    self._stmts(h.body, depth)
                self._stmts(stmt.orelse, depth)
                self._stmts(stmt.finalbody, depth)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._expr(item.context_expr, depth)
                self._stmts(stmt.body, depth)
                continue
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._expr(child, depth)

    def _expr(self, node: ast.expr, depth: int) -> None:
        if isinstance(node, (ast.Lambda, ast.FunctionDef)):
            return
        if isinstance(
            node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)
        ):
            for gen in node.generators:
                self._expr(gen.iter, depth)
            self._expr(node.elt, depth + 1)
            return
        if isinstance(node, ast.DictComp):
            for gen in node.generators:
                self._expr(gen.iter, depth)
            self._expr(node.key, depth + 1)
            self._expr(node.value, depth + 1)
            return
        if isinstance(node, ast.Call):
            self._call(node, depth)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child, depth)

    def _call(self, node: ast.Call, depth: int) -> None:
        if depth <= 0:
            return
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in self.jit_entries
        ):
            self.findings.append(
                Finding(
                    rule="perf-dispatch-in-loop",
                    severity="warning",
                    path=self.sf.path,
                    line=node.lineno,
                    col=node.col_offset + 1,
                    message=(
                        f"{node.func.id}() is jit-compiled and "
                        f"dispatched inside a loop in {self.scope}: "
                        f"one program launch per iteration — fuse "
                        f"(lax.scan) or batch (vmap) instead"
                    ),
                )
            )
            return
        d = _dotted(node.func)
        if d and d.split(".")[-1] in _TRANSFER_TAILS:
            self.findings.append(
                Finding(
                    rule="perf-transfer-in-loop",
                    severity="warning",
                    path=self.sf.path,
                    line=node.lineno,
                    col=node.col_offset + 1,
                    message=(
                        f"{d}() inside a loop in {self.scope}: one "
                        f"host->device upload per iteration — move "
                        f"the transfer out of the loop or batch the "
                        f"items"
                    ),
                )
            )


def _traced_wrapped_names(tree: ast.Module) -> Set[str]:
    """Names passed into a jit wrapper or jax combinator anywhere in
    the file (``profiled_jit(replay, ...)``, ``lax.scan(body, ...)``):
    their bodies trace — a loop inside them unrolls into ONE compiled
    program instead of dispatching per iteration."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if not d:
            continue
        tail = d.split(".")[-1]
        if tail not in _JIT_NAMES and not (
            tail in _COMBINATOR_TAILS
            and (d.split(".")[0] in _JAX_ROOTS or d in _COMBINATOR_BARE)
        ):
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name):
                out.add(arg.id)
    return out


def _loop_findings(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    jit_entries = _jit_entry_names(sf.tree)
    traced_wrapped = _traced_wrapped_names(sf.tree)
    # module top level (import-time loops)
    _LoopScanner(sf, "<module>", jit_entries, findings).scan(sf.tree.body)
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        if (
            _decorator_jit_statics(node) is not None
            or node.name in traced_wrapped
        ):
            # inside jit the loop unrolls into ONE program — that is
            # trace-shape-loop territory, not a dispatch per iteration
            continue
        _LoopScanner(
            sf, f"{node.name}()", jit_entries, findings
        ).scan(node.body)
    return findings


# ---------------------------------------------------------------------------
# perf-recompile-hazard
# ---------------------------------------------------------------------------


def _jit_static_map(
    tree: ast.Module,
) -> Dict[str, Tuple[Set[str], Set[int], List[str]]]:
    """name -> (static_argnames, static_argnums, positional params) for
    every jit-decorated def with at least one static argument."""
    out: Dict[str, Tuple[Set[str], Set[int], List[str]]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        statics = _decorator_jit_statics(node)
        if statics is None:
            continue
        names, nums = statics
        if not names and not nums:
            continue
        pos = [a.arg for a in node.args.posonlyargs + node.args.args]
        out[node.name] = (names, nums, pos)
    return out


class _HazardScanner:
    def __init__(
        self,
        sf: SourceFile,
        scope_name: str,
        jit_statics: Dict[str, Tuple[Set[str], Set[int], List[str]]],
        findings: List[Finding],
    ) -> None:
        self.sf = sf
        self.scope = scope_name
        self.jit_statics = jit_statics
        self.findings = findings
        self.mutated: Set[str] = set()
        self.set_bound: Set[str] = set()

    def scan(self, body: Sequence[ast.stmt]) -> None:
        stmts = list(self._own_stmts(body))
        for stmt in stmts:
            self._collect_state(stmt)
        for stmt in stmts:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    self._check_call(sub)
                elif isinstance(sub, ast.Compare):
                    self._check_float_identity(sub)

    def _own_stmts(self, body: Sequence[ast.stmt]):
        """Statements of this scope, not descending into nested defs."""
        for stmt in body:
            if isinstance(
                stmt,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                continue
            yield stmt

    def _collect_state(self, stmt: ast.stmt) -> None:
        for sub in ast.walk(stmt):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in _MUTATORS
                and isinstance(sub.func.value, ast.Name)
            ):
                self.mutated.add(sub.func.value.id)
            elif isinstance(sub, ast.AugAssign) and isinstance(
                sub.target, ast.Name
            ):
                self.mutated.add(sub.target.id)
            elif isinstance(sub, ast.Assign):
                v = sub.value
                is_set = isinstance(v, ast.Set) or (
                    isinstance(v, ast.Call)
                    and _dotted(v.func) in ("set", "frozenset")
                )
                if is_set:
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            self.set_bound.add(t.id)

    def _check_call(self, node: ast.Call) -> None:
        if not isinstance(node.func, ast.Name):
            return
        entry = self.jit_statics.get(node.func.id)
        if entry is None:
            return
        static_names, static_nums, pos = entry
        static_exprs: List[Tuple[str, ast.expr]] = []
        for i, arg in enumerate(node.args):
            if isinstance(arg, ast.Starred):
                break
            pname = pos[i] if i < len(pos) else ""
            if i in static_nums or pname in static_names:
                static_exprs.append((pname or f"#{i}", arg))
        for kw in node.keywords:
            if kw.arg in static_names:
                static_exprs.append((kw.arg, kw.value))
        for pname, expr in static_exprs:
            reason = self._unstable_reason(expr)
            if reason:
                self.findings.append(
                    Finding(
                        rule="perf-recompile-hazard",
                        severity="warning",
                        path=self.sf.path,
                        line=expr.lineno,
                        col=expr.col_offset + 1,
                        message=(
                            f"static argument {pname!r} of "
                            f"{node.func.id}() in {self.scope} is fed "
                            f"from {reason}: every new value compiles "
                            f"a fresh program variant"
                        ),
                    )
                )

    def _unstable_reason(self, expr: ast.expr) -> Optional[str]:
        for sub in ast.walk(expr):
            if not isinstance(sub, ast.Call):
                continue
            d = _dotted(sub.func)
            if d == "sorted":
                return None  # explicitly stabilized
            if (
                d == "len"
                and sub.args
                and isinstance(sub.args[0], ast.Name)
                and sub.args[0].id in self.mutated
            ):
                return (
                    f"len({sub.args[0].id}) of a container mutated in "
                    f"the same scope"
                )
            if d in ("list", "tuple") and sub.args:
                inner = sub.args[0]
                inner_d = (
                    _dotted(inner.func)
                    if isinstance(inner, ast.Call)
                    else None
                )
                if inner_d and inner_d.split(".")[-1] in (
                    "keys", "values", "items",
                ):
                    return "dict iteration order"
                if isinstance(inner, ast.Set) or (
                    isinstance(inner, ast.Name)
                    and inner.id in self.set_bound
                ):
                    return "set iteration order"
        return None

    def _check_float_identity(self, node: ast.Compare) -> None:
        if not any(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
        ):
            return
        operands = [node.left] + list(node.comparators)
        if any(
            isinstance(o, ast.Constant) and isinstance(o.value, float)
            for o in operands
        ):
            self.findings.append(
                Finding(
                    rule="perf-recompile-hazard",
                    severity="warning",
                    path=self.sf.path,
                    line=node.lineno,
                    col=node.col_offset + 1,
                    message=(
                        f"float compared with `is` in {self.scope}: "
                        f"identity of float objects is an interning "
                        f"accident — as a jit-static discriminator it "
                        f"recompiles unpredictably; use =="
                    ),
                )
            )


def _hazard_findings(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    jit_statics = _jit_static_map(sf.tree)
    _HazardScanner(sf, "<module>", jit_statics, findings).scan(
        sf.tree.body
    )
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.FunctionDef):
            _HazardScanner(
                sf, f"{node.name}()", jit_statics, findings
            ).scan(node.body)
    return findings


# ---------------------------------------------------------------------------
# perf-donate-miss
# ---------------------------------------------------------------------------


def _decorator_donates(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        kws = list(dec.keywords)
        if any(
            kw.arg in ("donate_argnums", "donate_argnames") for kw in kws
        ):
            return True
    return False


def _ann_record(
    ann: Optional[ast.expr], record_names: Set[str]
) -> Optional[str]:
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        tail = ann.value.split(".")[-1].split("[")[0]
        return tail if tail in record_names else None
    if isinstance(ann, ast.Subscript):
        base = _dotted(ann.value)
        if base and base.split(".")[-1] == "Optional":
            return _ann_record(ann.slice, record_names)
        return None
    d = _dotted(ann)
    if d is None:
        return None
    tail = d.split(".")[-1]
    return tail if tail in record_names else None


def _returns_updated_record(
    fn: ast.FunctionDef, params: Dict[str, str]
) -> Optional[str]:
    """Param name when the function returns ``param._replace(...)`` or
    a fresh construction of a param's record class."""
    classes = set(params.values())
    for node in ast.walk(fn):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        for sub in ast.walk(node.value):
            if not isinstance(sub, ast.Call):
                continue
            if (
                isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "_replace"
                and isinstance(sub.func.value, ast.Name)
                and sub.func.value.id in params
            ):
                return sub.func.value.id
            d = _dotted(sub.func)
            if d and d.split(".")[-1] in classes:
                for p, cls in params.items():
                    if cls == d.split(".")[-1]:
                        return p
    return None


def _donate_findings(
    sf: SourceFile, record_names: Set[str]
) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        if _decorator_jit_statics(node) is None:
            continue
        if _decorator_donates(node):
            continue
        args = node.args
        params: Dict[str, str] = {}
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            rec = _ann_record(a.annotation, record_names)
            if rec is not None:
                params[a.arg] = rec
        if not params:
            continue
        p = _returns_updated_record(node, params)
        if p is None:
            continue
        findings.append(
            Finding(
                rule="perf-donate-miss",
                severity="warning",
                path=sf.path,
                line=node.lineno,
                col=node.col_offset + 1,
                message=(
                    f"jit entry {node.name}() threads carry record "
                    f"{p!r} ({params[p]}) and returns an updated copy "
                    f"without donate_argnums/donate_argnames: the "
                    f"carry buffers are copied on every dispatch"
                ),
            )
        )
    return findings


# ---------------------------------------------------------------------------
# perf-nonjit-hot
# ---------------------------------------------------------------------------


def _is_hot_marked(sf: SourceFile, fn: ast.FunctionDef) -> bool:
    first = min([fn.lineno] + [d.lineno for d in fn.decorator_list])
    for ln in range(max(1, first - 1), fn.lineno + 1):
        if _HOT_RE.search(sf.line_text(ln)):
            return True
    return False


def _first_jax_call(fn: ast.FunctionDef) -> Optional[ast.Call]:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d and d.split(".")[0] in _JAX_ROOTS:
                return node
    return None


def _covered_names(tree: ast.Module) -> Set[str]:
    """Function names that execute inside a traced context (or escape
    to a caller who chooses one): jit-decorated, wrapped by a jit call,
    passed by name as a call argument, returned from a factory, or
    called (module-locally) from any covered function."""
    all_funcs = {
        n.name: n for n in ast.walk(tree)
        if isinstance(n, ast.FunctionDef)
    }
    covered: Set[str] = set()
    for name, fn in all_funcs.items():
        if _decorator_jit_statics(fn) is not None:
            covered.add(name)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            for arg in list(node.args) + [
                kw.value for kw in node.keywords
            ]:
                if isinstance(arg, ast.Name) and arg.id in all_funcs:
                    covered.add(arg.id)
        elif isinstance(node, ast.Return) and node.value is not None:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name) and sub.id in all_funcs:
                    covered.add(sub.id)
    # propagate along the module-local call graph: a callee of a
    # covered function runs in (or escapes to) the same context
    edges: Dict[str, Set[str]] = {}
    for name, fn in all_funcs.items():
        callees: Set[str] = set()
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in all_funcs
            ):
                callees.add(node.func.id)
        edges[name] = callees
    frontier = list(covered)
    while frontier:
        name = frontier.pop()
        for callee in edges.get(name, ()):
            if callee not in covered:
                covered.add(callee)
                frontier.append(callee)
    return covered


def _hot_findings(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    covered = _covered_names(sf.tree)
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        if not _is_hot_marked(sf, node):
            continue
        if node.name in covered:
            continue
        call = _first_jax_call(node)
        if call is None:
            continue
        d = _dotted(call.func) or "jnp"
        findings.append(
            Finding(
                rule="perf-nonjit-hot",
                severity="warning",
                path=sf.path,
                line=node.lineno,
                col=node.col_offset + 1,
                message=(
                    f"{node.name}() is marked `# graftperf: hot` but "
                    f"runs {d}() eagerly (line {call.lineno}): not "
                    f"jit-decorated and never handed to a traced "
                    f"context — every call dispatches op-by-op "
                    f"(the PR-8 lanes-fallback ~6x shape)"
                ),
            )
        )
    return findings


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def run(files: List[SourceFile]) -> List[Finding]:
    record_names = set(_collect_records(files)[0])
    # the engine carries live in algorithms/base.py; when linting a
    # subset that does not include it (fixtures), still recognize them
    record_names |= {"DeviceDCOP", "PulseCarry"}
    findings: List[Finding] = []
    for sf in files:
        per_file: List[Finding] = []
        per_file.extend(_host_sync_findings(sf, record_names))
        per_file.extend(_loop_findings(sf))
        per_file.extend(_hazard_findings(sf))
        per_file.extend(_donate_findings(sf, record_names))
        per_file.extend(_hot_findings(sf))
        # de-duplicate repeats from multi-signature analysis of the
        # same function: keep one finding per (rule, line, col)
        uniq: Dict[Tuple[str, int, int], Finding] = {}
        for f in per_file:
            uniq.setdefault((f.rule, f.line, f.col), f)
        findings.extend(uniq.values())
    return findings
