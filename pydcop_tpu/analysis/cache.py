"""Incremental lint cache: content-hash keyed finding sets.

Five passes over ~40k LoC are no longer instant, and the common CI/dev
loop re-lints an unchanged tree.  The cache stores the *final* finding
set (post-suppression, fingerprinted) keyed by everything that could
change it:

* the per-file content hash of every ``.py`` file the walk would lint
  (the walk itself is :func:`..core.iter_source_paths`, shared with
  ``gather_files`` so the two can never disagree about the file set —
  suppression comments live in file content, so they are covered);
* each pass's ``VERSION`` attribute (bump it when a pass's behavior
  changes) **plus** a digest of the analysis package's own sources, so
  an un-bumped pass edit still invalidates;
* the ``--select`` / ``--passes`` configuration.

Because the protocol/graftproto passes are whole-file-set analyses (a
handler in one file answers a declaration in another), a change to ANY
file invalidates the whole run — there is no sound per-file reuse.  The
win is the warm case: an unchanged tree re-lints in hash-the-files time
instead of parse-and-interpret time.

The cache lives in ``$PYDCOP_TPU_STATE_DIR`` (default ``.bench_state/``,
the same state dir batch campaigns use), holds a handful of entries
(different path/select configurations; oldest-stored evicted first —
hits deliberately do not rewrite the file, so a warm run stays
read-only), and degrades
to a no-op on any I/O or format problem — a broken cache must never
break a lint run.  ``--no-cache`` on the CLI opts out entirely.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Iterable, List, Optional

from .core import Finding, iter_source_paths, pass_versions

__all__ = [
    "CACHE_FORMAT",
    "cache_path",
    "read_fileset",
    "key_for",
    "lookup",
    "store",
]

#: bump on any change to the cache file layout itself
CACHE_FORMAT = 1

#: configurations kept per cache file (path/select/pass combinations)
MAX_ENTRIES = 16

_FINDING_FIELDS = (
    "rule", "severity", "path", "line", "col", "message", "fingerprint",
)


def _state_dir() -> str:
    return os.environ.get("PYDCOP_TPU_STATE_DIR") or ".bench_state"


def cache_path() -> str:
    return os.path.join(_state_dir(), "graftlint_cache.json")


def _analysis_digest() -> str:
    """Digest of the analysis package's own sources: a pass edit without
    a VERSION bump must still invalidate."""
    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    try:
        for name in sorted(os.listdir(pkg_dir)):
            if not name.endswith(".py"):
                continue
            with open(os.path.join(pkg_dir, name), "rb") as f:
                h.update(name.encode("utf-8"))
                h.update(f.read())
    except OSError:
        return "unreadable"
    return h.hexdigest()


def read_fileset(
    paths: Iterable[str],
) -> Optional[List[Tuple[str, str]]]:
    """Read the whole lint file set ONCE as ``(report_path, text)``
    pairs — the same text is hashed by :func:`key_for` and parsed by
    the passes, so a file edited mid-run can never store findings
    under a key describing different contents.  Returns None when any
    file cannot be read (no caching then); missing paths raise
    ValueError exactly like ``gather_files``."""
    pairs: List[Tuple[str, str]] = []
    for os_path, rpath in iter_source_paths(list(paths)):
        try:
            with open(
                os_path, "r", encoding="utf-8", errors="replace"
            ) as f:
                pairs.append((rpath, f.read()))
        except OSError:
            return None
    return pairs


def key_for(
    pairs: List[Tuple[str, str]],
    select: Optional[Iterable[str]] = None,
    passes: Optional[Iterable[str]] = None,
) -> str:
    """The cache key for one lint configuration over the given file
    contents."""
    h = hashlib.sha256()
    h.update(
        json.dumps(
            {
                "cache": CACHE_FORMAT,
                "passes": pass_versions(),
                "analysis": _analysis_digest(),
                "select": sorted(select) if select is not None else None,
                "run_passes": (
                    sorted(passes) if passes is not None else None
                ),
            },
            sort_keys=True,
        ).encode("utf-8")
    )
    for rpath, text in pairs:
        h.update(rpath.encode("utf-8", "replace"))
        h.update(b"\x1f")
        h.update(text.encode("utf-8", "replace"))
        h.update(b"\x1e")
    return h.hexdigest()


def _load_file() -> Optional[dict]:
    try:
        with open(cache_path(), "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if (
        not isinstance(data, dict)
        or data.get("format") != CACHE_FORMAT
        or not isinstance(data.get("entries"), dict)
    ):
        return None
    return data


def lookup(key: str) -> Optional[List[Finding]]:
    """The cached finding list for ``key``, or None on a miss (or any
    malformed entry — never let a bad cache poison a lint run)."""
    data = _load_file()
    if data is None:
        return None
    entry = data["entries"].get(key)
    if not isinstance(entry, dict):
        return None
    rows = entry.get("findings")
    if not isinstance(rows, list):
        return None
    out: List[Finding] = []
    for row in rows:
        if not isinstance(row, dict):
            return None
        try:
            out.append(
                Finding(
                    rule=str(row["rule"]),
                    severity=str(row["severity"]),
                    path=str(row["path"]),
                    line=int(row["line"]),
                    col=int(row["col"]),
                    message=str(row["message"]),
                    fingerprint=str(row["fingerprint"]),
                )
            )
        except (KeyError, TypeError, ValueError):
            return None
    return out


def store(key: str, findings: List[Finding]) -> None:
    """Record one configuration's findings; silent no-op on I/O errors."""
    data = _load_file() or {"format": CACHE_FORMAT, "entries": {}}
    entries = data["entries"]
    entries[key] = {
        "t": time.time(),
        "findings": [
            {f_: getattr(f, f_) for f_ in _FINDING_FIELDS}
            for f in findings
        ],
    }
    if len(entries) > MAX_ENTRIES:
        for stale in sorted(
            entries, key=lambda k: entries[k].get("t", 0.0)
        )[: len(entries) - MAX_ENTRIES]:
            del entries[stale]
    path = cache_path()
    tmp = path + ".tmp"
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(data, f)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
