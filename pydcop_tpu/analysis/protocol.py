"""graftlint pass 3: message-protocol consistency.

The runtime's protocol is declared in two halves that nothing ties
together at import time: ``X = message_type("name", [...])`` declares a
message class, and ``@register("name")`` on a computation method wires
the dispatch.  A typo'd or forgotten handler silently drops messages at
runtime (``MessagePassingComputation`` logs-and-ignores unknown types);
this pass makes the two halves check each other, across the whole
scanned file set.

Rules:

* ``proto-unhandled-message`` — a declared message type that no
  ``@register`` handler anywhere accepts: messages of that type are
  silently dropped by every receiver.
* ``proto-dead-handler`` — a ``@register("x")`` handler for a message
  type no ``message_type`` declaration produces: dead dispatch (often a
  renamed message on one side only).
* ``proto-duplicate-handler`` — two handlers in one class registered
  for the same message type: the metaclass keeps whichever it sees
  last, silently shadowing the other.
* ``proto-handler-signature`` — a handler whose signature is not
  ``(self, sender, msg, t)``-shaped: dispatch raises ``TypeError`` the
  first time that message type actually arrives.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, Rule, SourceFile, dotted_name as _dotted

__all__ = ["RULES", "run"]

#: bumped when the pass's behavior changes, so the incremental lint
#: cache (analysis/cache.py) never serves findings from an older rule set
VERSION = 1

RULES = (
    Rule(
        "proto-unhandled-message",
        "warning",
        "declared message type with no @register handler anywhere",
    ),
    Rule(
        "proto-dead-handler",
        "warning",
        "@register handler for a message type never declared",
    ),
    Rule(
        "proto-duplicate-handler",
        "error",
        "same message type registered twice in one class",
    ),
    Rule(
        "proto-handler-signature",
        "error",
        "handler signature incompatible with (self, sender, msg, t)",
    ),
)

#: rule id -> (doc, minimal failing example) for ``lint --explain``
EXPLAIN = {
    "proto-unhandled-message": (
        "A message_type(...) declaration has no @register handler "
        "anywhere in the scanned files: every receiver silently drops "
        "messages of that type (MessagePassingComputation logs-and-"
        "ignores unknown types).",
        "PingMsg = message_type('ping', ['n'])\n"
        "# ... and no class has @register('ping')\n",
    ),
    "proto-dead-handler": (
        "An @register handler names a message type that no "
        "message_type declaration or raw Message(...) construction "
        "produces: dead dispatch, usually a rename on one side only.",
        "@register('pong')  # nothing ever sends 'pong'\n"
        "def _on_pong(self, sender, msg, t): ...\n",
    ),
    "proto-duplicate-handler": (
        "One class registers the same message type twice; the handler "
        "collector keeps whichever it sees last, silently shadowing "
        "the other.",
        "@register('tick')\n"
        "def _a(self, sender, msg, t): ...\n"
        "@register('tick')\n"
        "def _b(self, sender, msg, t): ...  # shadows _a\n",
    ),
    "proto-handler-signature": (
        "Dispatch calls handlers positionally as (sender, msg, t); a "
        "handler that cannot accept that call raises TypeError the "
        "first time its message type actually arrives.",
        "@register('tick')\n"
        "def _on_tick(self, msg): ...  # missing sender/t\n",
    ),
}

# dispatched positionally as handler(sender, msg, t)
_HANDLER_ARITY = 3


@dataclass
class _Declared:
    name: str
    sf: SourceFile
    node: ast.Call


@dataclass
class _Handler:
    msg_type: str
    cls: str
    method: str
    sf: SourceFile
    node: ast.FunctionDef


def _collect_declared(sf: SourceFile) -> List[_Declared]:
    out: List[_Declared] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if not d or d.split(".")[-1] != "message_type":
            continue
        name: Optional[str] = None
        if node.args and isinstance(node.args[0], ast.Constant):
            if isinstance(node.args[0].value, str):
                name = node.args[0].value
        for kw in node.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                if isinstance(kw.value.value, str):
                    name = kw.value.value
        if name:
            out.append(_Declared(name, sf, node))
    return out


def _register_msg_type(fn: ast.FunctionDef) -> Optional[str]:
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        d = _dotted(dec.func)
        if not d or d.split(".")[-1] != "register":
            continue
        if dec.args and isinstance(dec.args[0], ast.Constant):
            if isinstance(dec.args[0].value, str):
                return dec.args[0].value
    return None


def _collect_raw_constructed(sf: SourceFile) -> Set[str]:
    """Types put on the wire as raw ``Message("x", ...)`` constructions
    (the orchestration layer's device-readback idiom): they exist even
    without a ``message_type`` declaration, so a handler for them is
    not dead."""
    out: Set[str] = set()
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if not d or d.split(".")[-1] != "Message":
            continue
        if node.args and isinstance(node.args[0], ast.Constant):
            if isinstance(node.args[0].value, str):
                out.add(node.args[0].value)
    return out


def _collect_handlers(sf: SourceFile) -> List[_Handler]:
    out: List[_Handler] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for item in node.body:
            if not isinstance(
                item, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            t = _register_msg_type(item)
            if t is not None:
                out.append(_Handler(t, node.name, item.name, sf, item))
    return out


def _signature_problem(fn: ast.FunctionDef) -> Optional[str]:
    args = fn.args
    # dispatch is purely positional, so a required keyword-only
    # parameter always raises — even with *args present
    required_kwonly = [
        a.arg
        for a, default in zip(args.kwonlyargs, args.kw_defaults)
        if default is None
    ]
    if required_kwonly:
        return (
            f"has required keyword-only argument(s) "
            f"{required_kwonly}, but dispatch passes only positional "
            f"(sender, msg, t)"
        )
    if args.vararg is not None:
        return None  # *args swallows anything
    positional = list(args.posonlyargs) + list(args.args)
    names = [a.arg for a in positional]
    if names and names[0] in ("self", "cls"):
        positional = positional[1:]
    n = len(positional)
    n_defaults = len(args.defaults)
    required = n - n_defaults
    if required > _HANDLER_ARITY:
        return (
            f"takes {required} required arguments after self, but "
            f"dispatch passes {_HANDLER_ARITY} (sender, msg, t)"
        )
    if n < _HANDLER_ARITY:
        return (
            f"accepts only {n} arguments after self, but dispatch "
            f"passes {_HANDLER_ARITY} (sender, msg, t)"
        )
    return None


def run(files: List[SourceFile]) -> List[Finding]:
    declared: List[_Declared] = []
    handlers: List[_Handler] = []
    raw_constructed: Set[str] = set()
    for sf in files:
        declared.extend(_collect_declared(sf))
        handlers.extend(_collect_handlers(sf))
        raw_constructed |= _collect_raw_constructed(sf)

    handled_types: Set[str] = {h.msg_type for h in handlers}
    declared_types: Set[str] = (
        {d.name for d in declared} | raw_constructed
    )
    findings: List[Finding] = []

    seen_decl: Set[str] = set()
    for d in declared:
        if d.name in handled_types or d.name in seen_decl:
            continue
        seen_decl.add(d.name)
        findings.append(
            Finding(
                rule="proto-unhandled-message",
                severity="warning",
                path=d.sf.path,
                line=d.node.lineno,
                col=d.node.col_offset + 1,
                message=(
                    f"message type {d.name!r} is declared but no "
                    f"@register({d.name!r}) handler exists in the "
                    f"scanned files: receivers silently drop it"
                ),
            )
        )

    for h in handlers:
        if h.msg_type not in declared_types:
            findings.append(
                Finding(
                    rule="proto-dead-handler",
                    severity="warning",
                    path=h.sf.path,
                    line=h.node.lineno,
                    col=h.node.col_offset + 1,
                    message=(
                        f"{h.cls}.{h.method}() handles "
                        f"{h.msg_type!r} but no message_type"
                        f"({h.msg_type!r}) declaration exists in the "
                        f"scanned files: dead dispatch"
                    ),
                )
            )
        problem = _signature_problem(h.node)
        if problem is not None:
            findings.append(
                Finding(
                    rule="proto-handler-signature",
                    severity="error",
                    path=h.sf.path,
                    line=h.node.lineno,
                    col=h.node.col_offset + 1,
                    message=(
                        f"{h.cls}.{h.method}() handles "
                        f"{h.msg_type!r} but {problem}"
                    ),
                )
            )

    by_class: Dict[Tuple[str, str, str], List[_Handler]] = {}
    for h in handlers:
        by_class.setdefault((h.sf.path, h.cls, h.msg_type), []).append(h)
    for (_, cls, msg_type), hs in sorted(by_class.items()):
        if len(hs) < 2:
            continue
        dup = hs[-1]
        others = ", ".join(f"{h.method}()" for h in hs[:-1])
        findings.append(
            Finding(
                rule="proto-duplicate-handler",
                severity="error",
                path=dup.sf.path,
                line=dup.node.lineno,
                col=dup.node.col_offset + 1,
                message=(
                    f"{cls} registers {msg_type!r} more than once "
                    f"({others} and {dup.method}()); the handler "
                    f"collector keeps only one, silently shadowing "
                    f"the rest"
                ),
            )
        )
    return findings
