"""graftlint pass 5 (graftproto): conversation-level protocol
verification of the distributed control plane.

Pass 3 (:mod:`.protocol`) cross-checks protocol *registrations* —
``message_type`` declarations against ``@register`` dispatch.  This pass
checks the *conversations* those registrations carry.  Every serious bug
the graftucs review caught — a stale ack releasing a later round's
barrier, the repair freeze pausing the control plane itself, a duplicate
accept stranding a commit — was a conversation-shape defect invisible to
registration cross-checks.  graftproto extracts a per-computation-class
conversation graph from ``@register`` handlers and ``post_msg`` send
sites and verifies it:

* ``proto-reply-gap`` — a handler for a request-shaped message (reply
  set declared with a ``# graftproto: replies=accept,refuse`` annotation
  on the handler) has an exit path that posts none of the declared
  replies: the shape that hangs an owner's frontier walk forever.
* ``proto-stale-guard`` — a handler whose message carries a round/epoch
  field mutates shared negotiation/barrier state without ever comparing
  that field to the live round: the exact PR-10 stale-ack bug.
* ``proto-handler-blocking`` — ``.wait()``/``.join()`` without a
  timeout, or an HTTP call without ``timeout=``, inside an ``@register``
  handler (directly or through a module-local/same-class helper): the
  single mgt thread wedges, the repair-freeze failure class.
* ``proto-send-under-lock`` — a send-like call made while holding a lock
  in a class that also registers message handlers: in-process delivery
  can run a handler of the same class on the same stack and re-acquire
  the lock (deadlock + reentrancy shape, fused with the locks pass's
  lock inference).
* ``proto-field-mismatch`` — a message construction whose arguments do
  not match the ``message_type(...)`` field declaration: TypeError on
  the send path, usually a rarely-taken error branch.
* ``proto-unsent-message`` — a type that is declared AND handled but
  constructed nowhere: a dead conversation (complements pass 3's
  orphan/dead-handler rules, which each only see one half missing).
* ``proto-wait-unbounded`` — an ``Event``/``Condition``/``Barrier``
  ``.wait()`` with no timeout anywhere in infrastructure code: a lost
  ack parks the caller forever instead of producing a diagnosable
  timeout.

Like the arrays pass, the analysis is interprocedural-lite: reply and
blocking verdicts follow calls into same-class methods and module-local
functions (depth-capped, memoized).  Suppress with
``# graftproto: disable=<rule>`` via the shared comment machinery.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .core import Finding, Rule, SourceFile, dotted_name as _dotted
from .locks import (
    _SEND_NAMES,
    _class_lock_attrs,
    _self_attr,
)

__all__ = ["RULES", "EXPLAIN", "run"]

#: bumped when the pass's behavior changes, so the incremental lint
#: cache (analysis/cache.py) never serves findings from an older rule set
VERSION = 1

RULES = (
    Rule(
        "proto-reply-gap",
        "error",
        "handler exit path posts none of its declared replies",
    ),
    Rule(
        "proto-stale-guard",
        "error",
        "epoch-carrying message mutates state without a round check",
    ),
    Rule(
        "proto-handler-blocking",
        "error",
        "unbounded wait/join/HTTP call inside a message handler",
    ),
    Rule(
        "proto-send-under-lock",
        "warning",
        "send while holding a lock in a handler-bearing class",
    ),
    Rule(
        "proto-field-mismatch",
        "error",
        "message construction disagrees with its message_type fields",
    ),
    Rule(
        "proto-unsent-message",
        "warning",
        "message type declared and handled but never constructed",
    ),
    Rule(
        "proto-wait-unbounded",
        "warning",
        "Event/Condition/Barrier wait with neither timeout nor TTL",
    ),
)

#: rule id -> (one-paragraph doc, minimal failing example) for
#: ``pydcop_tpu lint --explain``
EXPLAIN: Dict[str, Tuple[str, str]] = {
    "proto-reply-gap": (
        "A handler annotated '# graftproto: replies=a,b' (a request-"
        "shaped message whose sender waits for one of those types) has "
        "an exit path — a return or a fall-through — on which none of "
        "the declared replies is posted.  The requester's state machine "
        "then waits forever (or until a visit timeout charges an "
        "innocent peer).  Replies posted by same-class methods or "
        "module-local helpers count; posts of undeterminable type get "
        "the benefit of the doubt.",
        "@register('ucs_visit')  # graftproto: replies=accept,refuse\n"
        "def _on_visit(self, sender, msg, t):\n"
        "    if self.full:\n"
        "        return  # silent: the owner's walk hangs\n"
        "    self.post_msg(sender, AcceptMessage(comp=msg.comp))\n",
    ),
    "proto-stale-guard": (
        "The handler's message type declares a round/epoch field "
        "(round, epoch, round_id, cycle_id) — the protocol is versioned "
        "— yet the handler mutates shared state (barrier sets, "
        "negotiation ledgers) without ever comparing that field to the "
        "live round.  A stale or chaos-duplicated message from a "
        "previous round then acts on the current one: the exact PR-10 "
        "bug where a late replication ack released the NEXT round's "
        "barrier.  Guard with an epoch comparison (early return), or "
        "delegate the message/field to a method that does.",
        "AckMsg = message_type('ack', ['agent', 'round'])\n"
        "@register('ack')\n"
        "def _on_ack(self, sender, msg, t):\n"
        "    self.acked.add(msg.agent)   # msg.round never checked\n"
        "    self.barrier.set()          # stale ack releases it\n",
    ),
    "proto-handler-blocking": (
        "An @register handler (or a helper it calls) blocks without a "
        "bound: .wait()/.join() with no timeout, or an HTTP call "
        "without timeout=.  Handlers run on the agent's single mgt "
        "thread — while one blocks, every other control-plane message "
        "(stop acks, repair coordination, replication) queues behind "
        "it.  This is the repair-freeze wedge class: one blocked "
        "handler reads as a dead agent.",
        "@register('setup_repair')\n"
        "def _on_setup(self, sender, msg, t):\n"
        "    self.ready_evt.wait()  # wedges the mgt thread\n",
    ),
    "proto-send-under-lock": (
        "A class that registers message handlers posts a message while "
        "holding one of its locks.  With in-process transport, delivery "
        "can be synchronous: the post may run a handler of this same "
        "class further down the stack, which re-acquires the lock "
        "(deadlock on Lock, silent reentrancy on RLock) — and on HTTP "
        "transports the lock is held across network retries.  Post "
        "after releasing, or hand the message to the agent queue.",
        "class C(MessagePassingComputation):\n"
        "    @register('tick')\n"
        "    def _on_tick(self, sender, msg, t):\n"
        "        with self._lock: ...\n"
        "    def kick(self):\n"
        "        with self._lock:\n"
        "            self.post_msg('peer', TickMessage())  # reentrant\n",
    ),
    "proto-field-mismatch": (
        "A construction of a message_type class passes a keyword no "
        "field declares, misses a required field, or passes more "
        "positionals than fields exist.  The constructor raises "
        "TypeError at runtime — typically on a rarely-exercised error "
        "branch, where it surfaces as a crashed agent thread instead "
        "of a clean protocol error.",
        "AckMsg = message_type('ack', ['agent', 'round'])\n"
        "AckMsg(agent='a1', epoch=3)  # 'epoch' is not a field\n",
    ),
    "proto-unsent-message": (
        "A message type is declared AND has an @register handler, but "
        "no code constructs it (neither its class nor a raw "
        "Message('x', ...)): a dead conversation.  Pass 3's rules each "
        "need one half absent; this catches both halves present with "
        "nothing ever on the wire — usually a handshake whose send "
        "side was never wired (the setup_repair/repair_run shape this "
        "rule found and this release fixed).",
        "PingMsg = message_type('ping', ['n'])\n"
        "@register('ping')\n"
        "def _on_ping(self, sender, msg, t): ...\n"
        "# ...and nothing ever constructs PingMsg\n",
    ),
    "proto-wait-unbounded": (
        "An Event/Condition/Barrier attribute is waited on with no "
        "timeout.  In a distributed control plane every barrier wait "
        "must be bounded: a crashed peer, a dropped ack or a chaos "
        "fault otherwise parks the waiter forever with no diagnostic, "
        "where a timeout produces a named culprit (see "
        "replication_timeout_detail).  Waits inside @register handlers "
        "are covered by proto-handler-blocking instead.",
        "self.ready = threading.Event()\n"
        "def sync(self):\n"
        "    self.ready.wait()  # no timeout: parks forever on a kill\n",
    ),
}

# ---------------------------------------------------------------------
# shared vocabulary
# ---------------------------------------------------------------------

#: message fields that version a conversation (round epochs)
_EPOCH_FIELDS = {"round", "epoch", "round_id", "cycle_id"}

#: the send calls whose message argument names a conversation edge
_REPLY_SENDS = {"post_msg", "post_sync_msg"}

#: constructors of waitable synchronization primitives
_EVENT_CTORS = {"Event", "Condition", "Barrier"}

#: container mutators + Event.set/clear — "mutates shared state"
_MUTATOR_TAILS = {
    "append", "appendleft", "add", "insert", "extend", "update",
    "setdefault", "pop", "popitem", "remove", "discard", "clear", "set",
}

_HTTP_VERBS = {"get", "post", "put", "delete", "head", "request"}

_REPLIES_RE = re.compile(r"#\s*graftproto:\s*replies=([\w\-, ]+)")

_NESTED = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)


def _callee_tail(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _walk_pruned(node: ast.AST) -> Iterator[ast.AST]:
    """Walk a subtree, skipping nested function/class/lambda scopes —
    code in those runs at an unknown time, like the locks pass treats
    it."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        if isinstance(n, _NESTED):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _calls_in(node: ast.AST) -> Iterator[ast.Call]:
    for n in _walk_pruned(node):
        if isinstance(n, ast.Call):
            yield n


def _register_msg_type(fn: ast.FunctionDef) -> Optional[str]:
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        d = _dotted(dec.func)
        if not d or d.split(".")[-1] != "register":
            continue
        if dec.args and isinstance(dec.args[0], ast.Constant):
            if isinstance(dec.args[0].value, str):
                return dec.args[0].value
    return None


# ---------------------------------------------------------------------
# cross-file census
# ---------------------------------------------------------------------


@dataclass
class _MsgClass:
    type_name: str
    fields: Optional[Tuple[str, ...]]  # None when not statically known
    sf: SourceFile
    node: ast.AST
    ambiguous: bool = False  # same var name bound to different types


@dataclass
class _Census:
    #: message-class variable name -> declaration record
    classes: Dict[str, _MsgClass] = field(default_factory=dict)
    #: variable name -> EVERY type it was bound to (an ambiguous name —
    #: rebound across files — credits all its candidates as
    #: constructed, so the unsent rule never false-fires on a rebind)
    class_types: Dict[str, Set[str]] = field(default_factory=dict)
    #: message type name -> declared field tuple (first statically
    #: resolvable declaration wins)
    declared_fields: Dict[str, Optional[Tuple[str, ...]]] = field(
        default_factory=dict
    )
    #: type name -> first declaration site
    decl_site: Dict[str, Tuple[SourceFile, ast.AST]] = field(
        default_factory=dict
    )
    #: types constructed anywhere (class call or raw Message("x", ...))
    constructed: Set[str] = field(default_factory=set)
    #: types with at least one @register handler
    handled: Set[str] = field(default_factory=set)
    #: attribute names assigned an Event/Condition/Barrier anywhere
    event_attrs: Set[str] = field(default_factory=set)


def _static_fields(call: ast.Call) -> Optional[Tuple[str, ...]]:
    expr = call.args[1] if len(call.args) >= 2 else None
    for kw in call.keywords:
        if kw.arg == "fields":
            expr = kw.value
    if not isinstance(expr, (ast.List, ast.Tuple)):
        return None
    out: List[str] = []
    for e in expr.elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            out.append(e.value)
        else:
            return None
    return tuple(out)


def _collect_census(files: Sequence[SourceFile]) -> _Census:
    census = _Census()
    for sf in files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign):
                val = node.value
                if isinstance(val, ast.Call):
                    d = _dotted(val.func)
                    tail = d.split(".")[-1] if d else None
                    if tail == "message_type":
                        name: Optional[str] = None
                        if val.args and isinstance(
                            val.args[0], ast.Constant
                        ) and isinstance(val.args[0].value, str):
                            name = val.args[0].value
                        for kw in val.keywords:
                            if kw.arg == "name" and isinstance(
                                kw.value, ast.Constant
                            ) and isinstance(kw.value.value, str):
                                name = kw.value.value
                        if name is None:
                            continue
                        fields_ = _static_fields(val)
                        census.declared_fields.setdefault(name, fields_)
                        census.decl_site.setdefault(name, (sf, val))
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                census.class_types.setdefault(
                                    t.id, set()
                                ).add(name)
                                prev = census.classes.get(t.id)
                                if prev is not None and (
                                    prev.type_name != name
                                ):
                                    prev.ambiguous = True
                                else:
                                    census.classes[t.id] = _MsgClass(
                                        name, fields_, sf, val
                                    )
                    elif tail in _EVENT_CTORS:
                        for t in node.targets:
                            attr = _self_attr(t)
                            if attr is not None:
                                census.event_attrs.add(attr)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                t = _register_msg_type(node)
                if t is not None:
                    census.handled.add(t)
    # construction census (second walk: class names may be declared in a
    # later file than their construction sites)
    for sf in files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            tail = d.split(".")[-1] if d else None
            if tail is None:
                continue
            if tail == "Message" and node.args and isinstance(
                node.args[0], ast.Constant
            ) and isinstance(node.args[0].value, str):
                census.constructed.add(node.args[0].value)
                continue
            # every type the name was ever bound to counts as
            # constructed — for an ambiguous (rebound) name the pass
            # cannot tell which one this call builds, and a missed dead
            # conversation beats a false build failure
            census.constructed.update(census.class_types.get(tail, ()))
    return census


# ---------------------------------------------------------------------
# proto-reply-gap: the conversation's reply obligation
# ---------------------------------------------------------------------


def _handler_replies(sf: SourceFile, fn: ast.FunctionDef) -> Optional[Set[str]]:
    """The declared reply set from a ``# graftproto: replies=...``
    annotation on the def line, a decorator line, or the line directly
    above — same placement grammar as ``# graftflow: batchable``."""
    first = min([fn.lineno] + [d.lineno for d in fn.decorator_list])
    for ln in range(max(1, first - 1), fn.lineno + 1):
        m = _REPLIES_RE.search(sf.line_text(ln))
        if m:
            return {
                t.strip() for t in m.group(1).split(",") if t.strip()
            }
    return None


class _ReplyCtx:
    """Reply-post resolution for one handler: which calls put a declared
    reply on the wire, interprocedural-lite through same-class methods
    and module-local functions (memoized, depth-capped)."""

    _MAX_DEPTH = 3

    def __init__(
        self,
        replies: Set[str],
        classes: Dict[str, _MsgClass],
        class_methods: Dict[str, ast.FunctionDef],
        module_funcs: Dict[str, ast.FunctionDef],
    ) -> None:
        self.replies = replies
        self.classes = classes
        self.class_methods = class_methods
        self.module_funcs = module_funcs
        self._memo: Dict[int, bool] = {}
        self._stack: Set[int] = set()
        self._depth = 0

    def _msg_type_of(self, expr: ast.expr) -> Optional[str]:
        if isinstance(expr, ast.Call):
            d = _dotted(expr.func)
            tail = d.split(".")[-1] if d else None
            if tail == "Message" and expr.args and isinstance(
                expr.args[0], ast.Constant
            ) and isinstance(expr.args[0].value, str):
                return expr.args[0].value
            mc = self.classes.get(tail) if tail else None
            if mc is not None and not mc.ambiguous:
                return mc.type_name
        return None

    def _is_reply_post(self, call: ast.Call) -> bool:
        tail = _callee_tail(call.func)
        if tail not in _REPLY_SENDS:
            return False
        msg_expr: Optional[ast.expr] = (
            call.args[1] if len(call.args) >= 2 else None
        )
        if msg_expr is None:
            for kw in call.keywords:
                if kw.arg == "msg":
                    msg_expr = kw.value
        if msg_expr is None:
            return True  # cannot tell what is sent: benefit of the doubt
        t = self._msg_type_of(msg_expr)
        return t is None or t in self.replies

    def _resolve(self, func: ast.expr) -> Optional[ast.FunctionDef]:
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
        ):
            return self.class_methods.get(func.attr)
        if isinstance(func, ast.Name):
            return self.module_funcs.get(func.id)
        return None

    def _helper_replies(self, fn: ast.FunctionDef) -> bool:
        """Does this helper post a declared reply on EVERY exit path?"""
        key = id(fn)
        if key in self._memo:
            return self._memo[key]
        if key in self._stack or self._depth >= self._MAX_DEPTH:
            return False
        self._stack.add(key)
        self._depth += 1
        try:
            falls, replied, gaps = _reply_walk(fn.body, False, self)
            verdict = not gaps and (replied or not falls)
        finally:
            self._depth -= 1
            self._stack.discard(key)
        self._memo[key] = verdict
        return verdict

    def stmt_posts_reply(self, stmt: ast.AST) -> bool:
        for call in _calls_in(stmt):
            if self._is_reply_post(call):
                return True
            target = self._resolve(call.func)
            if target is not None and self._helper_replies(target):
                return True
        return False


def _reply_walk(
    stmts: Sequence[ast.stmt], replied: bool, ctx: _ReplyCtx
) -> Tuple[bool, bool, List[ast.stmt]]:
    """Abstract walk of a statement list: returns (falls_through,
    replied_on_fallthrough, exits_without_reply).  ``raise`` exits are
    not gaps — an exception is a loud failure, not a silent hang."""
    gaps: List[ast.stmt] = []
    for stmt in stmts:
        if isinstance(stmt, _NESTED):
            continue
        if isinstance(stmt, ast.Return):
            posts = stmt.value is not None and ctx.stmt_posts_reply(stmt)
            if not replied and not posts:
                gaps.append(stmt)
            return False, replied, gaps
        if isinstance(stmt, ast.Raise):
            return False, replied, gaps
        if isinstance(stmt, ast.If):
            if not replied and ctx.stmt_posts_reply(stmt.test):
                replied = True
            ft_b, rep_b, g_b = _reply_walk(stmt.body, replied, ctx)
            ft_e, rep_e, g_e = _reply_walk(stmt.orelse, replied, ctx)
            gaps.extend(g_b)
            gaps.extend(g_e)
            if not ft_b and not ft_e:
                return False, replied, gaps
            if ft_b and ft_e:
                replied = rep_b and rep_e
            else:
                replied = rep_b if ft_b else rep_e
            continue
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            # zero-iteration possibility: the loop body's reply does not
            # carry past the loop; its gap exits still count
            _, _, g = _reply_walk(stmt.body, replied, ctx)
            gaps.extend(g)
            _, _, g2 = _reply_walk(stmt.orelse, replied, ctx)
            gaps.extend(g2)
            continue
        if isinstance(stmt, ast.Try):
            ft_b, rep_b, g_b = _reply_walk(stmt.body, replied, ctx)
            gaps.extend(g_b)
            branches = [(ft_b, rep_b)]
            for h in stmt.handlers:
                ft_h, rep_h, g_h = _reply_walk(h.body, replied, ctx)
                gaps.extend(g_h)
                branches.append((ft_h, rep_h))
            falls = [r for f, r in branches if f]
            if not falls:
                return False, replied, gaps
            replied = all(falls)
            ft_o, rep_o, g_o = _reply_walk(stmt.orelse, replied, ctx)
            gaps.extend(g_o)
            replied = rep_o if ft_o else replied
            ft_f, rep_f, g_f = _reply_walk(stmt.finalbody, replied, ctx)
            gaps.extend(g_f)
            if not ft_f and stmt.finalbody:
                return False, replied, gaps
            replied = rep_f if stmt.finalbody else replied
            continue
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            ft, rep, g = _reply_walk(stmt.body, replied, ctx)
            gaps.extend(g)
            if not ft:
                return False, rep, gaps
            replied = rep
            continue
        # simple statement: any reply post anywhere in it counts
        if not replied and ctx.stmt_posts_reply(stmt):
            replied = True
    return True, replied, gaps


# ---------------------------------------------------------------------
# proto-stale-guard
# ---------------------------------------------------------------------


def _epoch_reads(
    body: Sequence[ast.stmt], msg_name: str
) -> Tuple[Set[str], List[ast.AST]]:
    """(epoch field names read off the message, the read nodes):
    ``msg.round`` attributes and ``getattr(msg, "round", ...)`` calls."""
    fields_read: Set[str] = set()
    nodes: List[ast.AST] = []
    for stmt in body:
        for n in [stmt, *_walk_pruned(stmt)]:
            if (
                isinstance(n, ast.Attribute)
                and isinstance(n.value, ast.Name)
                and n.value.id == msg_name
                and n.attr in _EPOCH_FIELDS
            ):
                fields_read.add(n.attr)
                nodes.append(n)
            elif (
                isinstance(n, ast.Call)
                and _callee_tail(n.func) == "getattr"
                and len(n.args) >= 2
                and isinstance(n.args[0], ast.Name)
                and n.args[0].id == msg_name
                and isinstance(n.args[1], ast.Constant)
                and n.args[1].value in _EPOCH_FIELDS
            ):
                fields_read.add(n.args[1].value)
                nodes.append(n)
    return fields_read, nodes


def _contains_any(node: ast.AST, targets: List[ast.AST],
                  aliases: Set[str]) -> bool:
    target_ids = {id(t) for t in targets}
    for n in [node, *ast.walk(node)]:
        if id(n) in target_ids:
            return True
        if isinstance(n, ast.Name) and n.id in aliases:
            return True
    return False


def _mutates_self_state(fn: ast.FunctionDef) -> bool:
    for n in _walk_pruned(fn):
        if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                n.targets if isinstance(n, ast.Assign) else [n.target]
            )
            for t in targets:
                inner = t
                while isinstance(inner, ast.Subscript):
                    inner = inner.value
                if _self_attr(inner) is not None:
                    return True
        elif isinstance(n, ast.Delete):
            for t in n.targets:
                inner = t
                while isinstance(inner, ast.Subscript):
                    inner = inner.value
                if _self_attr(inner) is not None:
                    return True
        elif isinstance(n, ast.Call):
            func = n.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in _MUTATOR_TAILS
            ):
                inner = func.value
                while isinstance(inner, ast.Subscript):
                    inner = inner.value
                if _self_attr(inner) is not None:
                    return True
    return False


def _check_stale_guard(
    sf: SourceFile,
    cls: ast.ClassDef,
    fn: ast.FunctionDef,
    msg_type: str,
    census: _Census,
    findings: List[Finding],
) -> None:
    declared = census.declared_fields.get(msg_type) or ()
    pos = list(fn.args.posonlyargs) + list(fn.args.args)
    # dispatch shape (self, sender, msg, t): the message is arg 2
    if len(pos) < 3:
        return
    msg_name = pos[2].arg
    fields_read, read_nodes = _epoch_reads(fn.body, msg_name)
    epoch_fields = (set(declared) & _EPOCH_FIELDS) | fields_read
    if not epoch_fields:
        return
    if not _mutates_self_state(fn):
        return
    # aliases: locals assigned from an expression containing an epoch
    # read, transitively (`r = msg.round; rr = r`).  Iterated to a
    # fixpoint because _walk_pruned's visit order is not source order —
    # a single pass could see `rr = r` before `r = msg.round`
    aliases: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for n in _walk_pruned(fn):
            if isinstance(n, ast.Assign) and _contains_any(
                n.value, read_nodes, aliases
            ):
                for t in n.targets:
                    if isinstance(t, ast.Name) and t.id not in aliases:
                        aliases.add(t.id)
                        changed = True
    # guarded: the epoch value is compared to something, or the message /
    # epoch value is delegated to another call (which may compare it)
    for n in _walk_pruned(fn):
        if isinstance(n, ast.Compare) and _contains_any(
            n, read_nodes, aliases
        ):
            return
        if isinstance(n, ast.Call):
            if _callee_tail(n.func) == "getattr":
                continue  # the read itself, not a delegation
            for arg in list(n.args) + [kw.value for kw in n.keywords]:
                if isinstance(arg, ast.Name) and arg.id == msg_name:
                    return  # whole message delegated
                if _contains_any(arg, read_nodes, aliases):
                    return  # epoch value delegated
    fields_s = "/".join(sorted(epoch_fields))
    findings.append(
        Finding(
            rule="proto-stale-guard",
            severity="error",
            path=sf.path,
            line=fn.lineno,
            col=fn.col_offset + 1,
            message=(
                f"{cls.name}.{fn.name}() handles {msg_type!r} which "
                f"carries the {fields_s!r} epoch field, and mutates "
                f"shared state without comparing it to the live round: "
                f"a stale or duplicated message acts on the wrong round "
                f"(the graftucs stale-ack bug shape)"
            ),
        )
    )


# ---------------------------------------------------------------------
# proto-handler-blocking
# ---------------------------------------------------------------------


def _direct_blocking_calls(
    fn: ast.FunctionDef,
) -> List[Tuple[ast.Call, str]]:
    out: List[Tuple[ast.Call, str]] = []
    for call in _calls_in(fn):
        tail = _callee_tail(call.func)
        if (
            isinstance(call.func, ast.Attribute)
            and tail in ("wait", "join")
            and not call.args
            and not call.keywords
        ):
            out.append((call, f".{tail}() with no timeout"))
            continue
        d = _dotted(call.func)
        if d:
            parts = d.split(".")
            root, last = parts[0], parts[-1]
            is_http = last == "urlopen" or (
                root in ("requests", "httpx") and last in _HTTP_VERBS
            )
            if is_http and not any(
                kw.arg == "timeout" for kw in call.keywords
            ):
                out.append((call, f"{d}() without timeout="))
    return out


def _check_handler_blocking(
    sf: SourceFile,
    cls: ast.ClassDef,
    fn: ast.FunctionDef,
    class_methods: Dict[str, ast.FunctionDef],
    module_funcs: Dict[str, ast.FunctionDef],
    findings: List[Finding],
) -> None:
    for call, desc in _direct_blocking_calls(fn):
        findings.append(
            Finding(
                rule="proto-handler-blocking",
                severity="error",
                path=sf.path,
                line=call.lineno,
                col=call.col_offset + 1,
                message=(
                    f"{cls.name}.{fn.name}() blocks on {desc} inside a "
                    f"message handler: the agent's single mgt thread "
                    f"wedges and every control-plane message queues "
                    f"behind it"
                ),
            )
        )
    # one level of same-class/module-local helpers
    for call in _calls_in(fn):
        func = call.func
        target: Optional[ast.FunctionDef] = None
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
        ):
            target = class_methods.get(func.attr)
        elif isinstance(func, ast.Name):
            target = module_funcs.get(func.id)
        if target is None or target is fn:
            continue
        for _bcall, desc in _direct_blocking_calls(target):
            findings.append(
                Finding(
                    rule="proto-handler-blocking",
                    severity="error",
                    path=sf.path,
                    line=call.lineno,
                    col=call.col_offset + 1,
                    message=(
                        f"{cls.name}.{fn.name}() calls "
                        f"{target.name}() which blocks on {desc}: the "
                        f"mgt thread wedges inside a message handler"
                    ),
                )
            )
            break  # one finding per helper call site is enough


# ---------------------------------------------------------------------
# proto-send-under-lock
# ---------------------------------------------------------------------


def _check_send_under_lock(
    sf: SourceFile, cls: ast.ClassDef, findings: List[Finding]
) -> None:
    methods = [
        n for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    if not any(_register_msg_type(m) is not None for m in methods):
        return
    lock_attrs = _class_lock_attrs(cls)
    if not lock_attrs:
        return

    def visit(node: ast.AST, held: List[str], method: str) -> None:
        if isinstance(node, _NESTED):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr is not None and attr in lock_attrs:
                    held.append(attr)
                    pushed += 1
            for s in node.body:
                visit(s, held, method)
            for _ in range(pushed):
                held.pop()
            return
        if isinstance(node, ast.Call) and held:
            tail = _callee_tail(node.func)
            if tail in _SEND_NAMES:
                findings.append(
                    Finding(
                        rule="proto-send-under-lock",
                        severity="warning",
                        path=sf.path,
                        line=node.lineno,
                        col=node.col_offset + 1,
                        message=(
                            f"{cls.name}.{method}() calls {tail}() "
                            f"while holding self.{held[-1]}; this "
                            f"class registers message handlers, so "
                            f"in-process delivery can re-enter it on "
                            f"the same stack and re-acquire the lock"
                        ),
                    )
                )
        for child in ast.iter_child_nodes(node):
            visit(child, held, method)

    for m in methods:
        for stmt in m.body:
            visit(stmt, [], m.name)


# ---------------------------------------------------------------------
# proto-field-mismatch
# ---------------------------------------------------------------------


def _check_constructions(
    sf: SourceFile, census: _Census, findings: List[Finding]
) -> None:
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        tail = d.split(".")[-1] if d else None
        mc = census.classes.get(tail) if tail else None
        if mc is None or mc.ambiguous or mc.fields is None:
            continue
        if any(isinstance(a, ast.Starred) for a in node.args):
            continue
        if any(kw.arg is None for kw in node.keywords):
            continue  # **kwargs: not statically checkable
        fields_ = mc.fields
        problems: List[str] = []
        if len(node.args) > len(fields_):
            problems.append(
                f"takes {len(fields_)} field(s), got "
                f"{len(node.args)} positional"
            )
        given = set(fields_[: len(node.args)])
        kw_names = [kw.arg for kw in node.keywords]
        for kw in kw_names:
            if kw not in fields_:
                problems.append(f"unknown field {kw!r}")
            elif kw in given:
                problems.append(f"field {kw!r} given twice")
            given.add(kw)
        missing = [f for f in fields_ if f not in given]
        if missing:
            problems.append(f"missing field(s) {missing}")
        if problems:
            findings.append(
                Finding(
                    rule="proto-field-mismatch",
                    severity="error",
                    path=sf.path,
                    line=node.lineno,
                    col=node.col_offset + 1,
                    message=(
                        f"{tail}(...) disagrees with message_type"
                        f"({mc.type_name!r}, {list(fields_)}): "
                        + "; ".join(problems)
                        + " — TypeError on this send path at runtime"
                    ),
                )
            )


# ---------------------------------------------------------------------
# proto-wait-unbounded
# ---------------------------------------------------------------------


def _handler_spans(sf: SourceFile) -> List[Tuple[int, int]]:
    spans = []
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _register_msg_type(node) is not None:
                first = min(
                    [node.lineno] + [d.lineno for d in node.decorator_list]
                )
                spans.append(
                    (first, getattr(node, "end_lineno", node.lineno))
                )
    return spans


def _check_unbounded_waits(
    sf: SourceFile, census: _Census, findings: List[Finding]
) -> None:
    spans = _handler_spans(sf)

    def in_handler(line: int) -> bool:
        return any(a <= line <= b for a, b in spans)

    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        local_events: Set[str] = set()
        for n in _walk_pruned(node):
            if isinstance(n, ast.Assign) and isinstance(
                n.value, ast.Call
            ):
                tail = _callee_tail(n.value.func)
                if tail in _EVENT_CTORS:
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            local_events.add(t.id)
        for call in _calls_in(node):
            func = call.func
            if (
                not isinstance(func, ast.Attribute)
                or func.attr != "wait"
                or call.args
                or call.keywords
            ):
                continue
            recv = func.value
            name: Optional[str] = None
            if isinstance(recv, ast.Attribute) and (
                recv.attr in census.event_attrs
            ):
                name = recv.attr
            elif isinstance(recv, ast.Name) and recv.id in local_events:
                name = recv.id
            if name is None or in_handler(call.lineno):
                continue
            findings.append(
                Finding(
                    rule="proto-wait-unbounded",
                    severity="warning",
                    path=sf.path,
                    line=call.lineno,
                    col=call.col_offset + 1,
                    message=(
                        f"unbounded .wait() on {name!r} in "
                        f"{node.name}(): a lost ack or crashed peer "
                        f"parks this thread forever — pass a timeout "
                        f"so the barrier fails with a named culprit"
                    ),
                )
            )


# ---------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------


def run(files: List[SourceFile]) -> List[Finding]:
    census = _collect_census(files)
    findings: List[Finding] = []

    # conversation-global rules
    for type_name, (sf, node) in sorted(census.decl_site.items()):
        if (
            type_name in census.handled
            and type_name not in census.constructed
        ):
            findings.append(
                Finding(
                    rule="proto-unsent-message",
                    severity="warning",
                    path=sf.path,
                    line=node.lineno,
                    col=node.col_offset + 1,
                    message=(
                        f"message type {type_name!r} is declared and "
                        f"handled but never constructed anywhere in the "
                        f"scanned files: a dead conversation (is the "
                        f"send half wired?)"
                    ),
                )
            )

    for sf in files:
        module_funcs = {
            n.name: n for n in sf.tree.body
            if isinstance(n, ast.FunctionDef)
        }
        _check_constructions(sf, census, findings)
        _check_unbounded_waits(sf, census, findings)
        for cls in ast.walk(sf.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            class_methods = {
                n.name: n for n in cls.body
                if isinstance(n, ast.FunctionDef)
            }
            _check_send_under_lock(sf, cls, findings)
            for fn in cls.body:
                if not isinstance(fn, ast.FunctionDef):
                    continue
                msg_type = _register_msg_type(fn)
                if msg_type is None:
                    continue
                _check_handler_blocking(
                    sf, cls, fn, class_methods, module_funcs, findings
                )
                _check_stale_guard(
                    sf, cls, fn, msg_type, census, findings
                )
                replies = _handler_replies(sf, fn)
                if replies:
                    ctx = _ReplyCtx(
                        replies, census.classes, class_methods,
                        module_funcs,
                    )
                    falls, replied, gaps = _reply_walk(
                        fn.body, False, ctx
                    )
                    for g in gaps:
                        findings.append(
                            Finding(
                                rule="proto-reply-gap",
                                severity="error",
                                path=sf.path,
                                line=g.lineno,
                                col=g.col_offset + 1,
                                message=(
                                    f"{cls.name}.{fn.name}() handles "
                                    f"{msg_type!r} but this exit posts "
                                    f"none of its declared replies "
                                    f"({sorted(replies)}): the "
                                    f"requester waits forever"
                                ),
                            )
                        )
                    if falls and not replied:
                        findings.append(
                            Finding(
                                rule="proto-reply-gap",
                                severity="error",
                                path=sf.path,
                                line=fn.lineno,
                                col=fn.col_offset + 1,
                                message=(
                                    f"{cls.name}.{fn.name}() handles "
                                    f"{msg_type!r} but can fall "
                                    f"through without posting any of "
                                    f"its declared replies "
                                    f"({sorted(replies)}): the "
                                    f"requester waits forever"
                                ),
                            )
                        )
    # duplicates can arise when the same call matches several patterns
    uniq: Dict[Tuple[str, str, int, int], Finding] = {}
    for f in findings:
        uniq.setdefault((f.rule, f.path, f.line, f.col), f)
    return list(uniq.values())
