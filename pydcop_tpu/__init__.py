"""pydcop_tpu: a TPU-native framework for Distributed Constraint Optimization.

Re-imagines pyDCOP (Orange-OpenSource/pyDcop) for JAX/XLA: the computation
graph is compiled once into gather/scatter index arrays, and every algorithm
cycle advances all agents in lock-step as a single compiled step function over
padded cost tensors.  See SURVEY.md at the repo root for the structural
analysis of the reference this build is based on.
"""

__version__ = "0.1.0"

import os as _os

if _os.environ.get("JAX_PLATFORMS") == "cpu":
    # honor the documented env var even when a site plugin (e.g. the axon
    # TPU relay) forces its own platform via jax.config during registration:
    # re-assert the cpu selection at import time so tests and host-only CLI
    # invocations never touch the accelerator tunnel.
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

# Public names are resolved lazily (PEP 562) so that merely importing the
# package — which every CLI invocation, including --help and host-only
# verbs, does — never pulls jax.  ``pydcop_tpu.solve`` et al. still work;
# they just import their module on first attribute access.
_LAZY = {
    "solve": ("pydcop_tpu.api", "solve"),
    "solve_result": ("pydcop_tpu.api", "solve_result"),
    "DCOP": ("pydcop_tpu.dcop", "DCOP"),
    "AgentDef": ("pydcop_tpu.dcop", "AgentDef"),
    "Domain": ("pydcop_tpu.dcop", "Domain"),
    "Variable": ("pydcop_tpu.dcop", "Variable"),
    "constraint_from_str": ("pydcop_tpu.dcop", "constraint_from_str"),
    "load_dcop": ("pydcop_tpu.dcop", "load_dcop"),
    "load_dcop_from_file": ("pydcop_tpu.dcop", "load_dcop_from_file"),
}

# PEP 562 lazy loading leaves module globals empty, which would make
# ``from pydcop_tpu import *`` bind nothing — __all__ restores the
# star-import surface (ADVICE round 4)
__all__ = sorted(_LAZY)


def __getattr__(name):
    import importlib

    try:
        module_name, attr = _LAZY[name]
    except KeyError:
        # the eager imports used to bind submodules (pydcop_tpu.api,
        # pydcop_tpu.dcop, ...) as package attributes; keep that working
        try:
            return importlib.import_module(f"{__name__}.{name}")
        except ModuleNotFoundError as e:
            if e.name and e.name != f"{__name__}.{name}":
                # the submodule exists but one of ITS imports is missing
                # (e.g. broken jax install): surface the real failure, not
                # a misleading 'no attribute' (ADVICE round 4)
                raise
            raise AttributeError(
                f"module {__name__!r} has no attribute {name!r}"
            ) from None
    value = getattr(importlib.import_module(module_name), attr)
    globals()[name] = value  # cache: __getattr__ runs once per name
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
