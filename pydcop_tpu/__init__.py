"""pydcop_tpu: a TPU-native framework for Distributed Constraint Optimization.

Re-imagines pyDCOP (Orange-OpenSource/pyDcop) for JAX/XLA: the computation
graph is compiled once into gather/scatter index arrays, and every algorithm
cycle advances all agents in lock-step as a single compiled step function over
padded cost tensors.  See SURVEY.md at the repo root for the structural
analysis of the reference this build is based on.
"""

__version__ = "0.1.0"

import os as _os

if _os.environ.get("JAX_PLATFORMS") == "cpu":
    # honor the documented env var even when a site plugin (e.g. the axon
    # TPU relay) forces its own platform via jax.config during registration:
    # re-assert the cpu selection at import time so tests and host-only CLI
    # invocations never touch the accelerator tunnel.
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

from .api import solve, solve_result
from .dcop import (
    DCOP,
    AgentDef,
    Domain,
    Variable,
    constraint_from_str,
    load_dcop,
    load_dcop_from_file,
)
