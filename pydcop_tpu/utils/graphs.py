"""Graph helper utilities over variables and constraints.

Role parity with /root/reference/pydcop/utils/graphs.py (:36-289):
bipartite variable/constraint views, diameter, cycle counts, pair
enumeration.  Fresh implementation on plain adjacency dicts with optional
networkx export (networkx is only needed for the export/display helpers).
"""

from __future__ import annotations

import itertools
from collections import deque
from typing import Any, Dict, Iterable, List, Sequence, Set, Tuple

__all__ = [
    "as_bipartite_graph",
    "as_networkx_graph",
    "as_networkx_bipartite_graph",
    "graph_diameter",
    "cycles_count",
    "all_pairs",
]


def all_pairs(elements: Sequence[Any]) -> List[Tuple[Any, Any]]:
    """All unordered pairs of distinct elements (reference :289)."""
    return list(itertools.combinations(elements, 2))


def _adjacency(variables, relations) -> Dict[str, Set[str]]:
    """Variable-to-variable adjacency induced by shared constraints."""
    adj: Dict[str, Set[str]] = {v.name: set() for v in variables}
    for r in relations:
        names = [v.name for v in r.dimensions]
        for a, b in all_pairs(names):
            if a in adj and b in adj:
                adj[a].add(b)
                adj[b].add(a)
    return adj


def as_bipartite_graph(
    variables, relations
) -> Dict[str, List[str]]:
    """Bipartite adjacency: variable and constraint names -> neighbor names
    (reference :68)."""
    adj: Dict[str, List[str]] = {}
    for v in variables:
        adj[v.name] = []
    for r in relations:
        adj[r.name] = [v.name for v in r.dimensions]
        for v in r.dimensions:
            if v.name in adj:
                adj[v.name].append(r.name)
    return adj


def _bfs_depths(adj: Dict[str, Set[str]], root: str) -> Dict[str, int]:
    depths = {root: 0}
    q = deque([root])
    while q:
        n = q.popleft()
        for m in adj[n]:
            if m not in depths:
                depths[m] = depths[n] + 1
                q.append(m)
    return depths


def graph_diameter(variables, relations) -> int:
    """Longest shortest path over the constraint graph; for forests, the max
    diameter over components (reference :270)."""
    adj = _adjacency(variables, relations)
    seen: Set[str] = set()
    diameter = 0
    for root in adj:
        if root in seen:
            continue
        comp_depths = _bfs_depths(adj, root)
        seen |= set(comp_depths)
        comp = list(comp_depths)
        if len(comp) <= 512:
            # small component: exact all-pairs BFS
            best = 0
            for n in comp:
                best = max(
                    best, max(_bfs_depths(adj, n).values(), default=0)
                )
        else:
            # large component: double sweep (2 BFS) — exact on trees, a
            # tight lower bound on general graphs
            far1 = max(comp_depths, key=comp_depths.get)
            d2 = _bfs_depths(adj, far1)
            best = max(d2.values(), default=0)
        diameter = max(diameter, best)
    return diameter


def cycles_count(variables, relations) -> int:
    """Number of independent cycles in the constraint graph: E - V + C
    (reference :263)."""
    adj = _adjacency(variables, relations)
    n_edges = sum(len(nbrs) for nbrs in adj.values()) // 2
    seen: Set[str] = set()
    components = 0
    for root in adj:
        if root in seen:
            continue
        components += 1
        seen |= set(_bfs_depths(adj, root))
    return n_edges - len(adj) + components


def as_networkx_graph(variables, relations):
    """Constraint graph as a networkx Graph (reference :131)."""
    import networkx as nx

    g = nx.Graph()
    g.add_nodes_from(v.name for v in variables)
    for r in relations:
        for a, b in all_pairs([v.name for v in r.dimensions]):
            g.add_edge(a, b)
    return g


def as_networkx_bipartite_graph(variables, relations):
    """Bipartite factor graph as a networkx Graph with ``bipartite`` node
    attributes (reference :157)."""
    import networkx as nx

    g = nx.Graph()
    g.add_nodes_from((v.name for v in variables), bipartite=0)
    g.add_nodes_from((r.name for r in relations), bipartite=1)
    for r in relations:
        for v in r.dimensions:
            g.add_edge(r.name, v.name)
    return g
