"""Lightweight object <-> plain-data serialization.

Equivalent in role to the reference's ``SimpleRepr`` mixin
(/root/reference/pydcop/utils/simple_repr.py:68-175): model objects
(variables, constraints, agent definitions, computation defs, distributions)
must round-trip through plain dicts/lists so they can be written to YAML/JSON
and shipped across hosts.

Fresh design: instead of the reference's constructor-argument introspection,
classes declare ``_repr_fields`` (constructor kwarg names) or override
``_simple_repr_extra``.  A module-qualified ``__qualname__`` key makes
``from_repr`` self-describing.
"""

from __future__ import annotations

import importlib
from typing import Any, Dict

__all__ = ["SimpleRepr", "simple_repr", "from_repr", "SimpleReprException"]


class SimpleReprException(Exception):
    pass


def _encode(value: Any) -> Any:
    if isinstance(value, SimpleRepr):
        return simple_repr(value)
    if isinstance(value, (list, tuple)):
        return [_encode(v) for v in value]
    if isinstance(value, frozenset):
        return sorted(_encode(v) for v in value)
    if isinstance(value, dict):
        return {k: _encode(v) for k, v in value.items()}
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    # numpy scalars and arrays
    item = getattr(value, "item", None)
    if item is not None and getattr(value, "shape", None) == ():
        return value.item()
    tolist = getattr(value, "tolist", None)
    if tolist is not None:
        return value.tolist()
    raise SimpleReprException(f"cannot build a simple repr for {value!r}")


def _decode(value: Any) -> Any:
    if isinstance(value, dict):
        if "__qualname__" in value:
            return from_repr(value)
        return {k: _decode(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_decode(v) for v in value]
    return value


class SimpleRepr:
    """Mixin: subclasses set ``_repr_fields`` = tuple of constructor kwargs,
    each matching an attribute named either ``<field>`` or ``_<field>``."""

    _repr_fields: tuple = ()

    def _simple_repr(self) -> Dict[str, Any]:
        r: Dict[str, Any] = {
            "__qualname__": type(self).__qualname__,
            "__module__": type(self).__module__,
        }
        for field in self._repr_fields:
            if hasattr(self, field):
                v = getattr(self, field)
            elif hasattr(self, "_" + field):
                v = getattr(self, "_" + field)
            else:
                raise SimpleReprException(
                    f"{type(self).__name__} declares repr field {field!r} "
                    "but has no matching attribute"
                )
            r[field] = _encode(v)
        return r


def simple_repr(obj: Any) -> Any:
    if isinstance(obj, SimpleRepr):
        return obj._simple_repr()
    return _encode(obj)


def from_repr(r: Any) -> Any:
    if not isinstance(r, dict) or "__qualname__" not in r:
        return _decode(r)
    module = importlib.import_module(r["__module__"])
    cls = module
    for part in r["__qualname__"].split("."):
        cls = getattr(cls, part)
    kwargs = {
        k: _decode(v)
        for k, v in r.items()
        if k not in ("__qualname__", "__module__")
    }
    build = getattr(cls, "_from_repr", None)
    if build is not None:
        return build(**kwargs)
    return cls(**kwargs)
