"""Checkpoint / resume of solver state.

The reference has NO state checkpointing: its replicas ship computation
*definitions* and repaired computations restart from scratch
(/root/reference/pydcop/replication/dist_ucs_hostingcosts.py:60-84, SURVEY.md
§5.4).  On TPU the whole solver state is a pytree of device arrays, so real
checkpoint/resume is cheap: serialize the leaves with their treedef to one
``.npz`` file, restore into the same structure.

Two layers:

- ``save_checkpoint`` / ``load_checkpoint``: any pytree of arrays <-> file.
- ``DynamicMaxSum.save`` / ``DynamicMaxSum.restore`` (algorithms/
  maxsum_dynamic.py) and the orchestrator's repair path use these to carry
  warm solver state across failures instead of restarting fresh.

Uses numpy's npz container (always available); orbax remains the right tool
for sharded multi-host arrays — ``save_checkpoint(..., use_orbax=True)``
delegates to it when installed.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

logger = logging.getLogger("pydcop_tpu.checkpoint")

__all__ = ["save_checkpoint", "load_checkpoint", "CheckpointError"]


class CheckpointError(Exception):
    pass


def _flatten(state: Any) -> Tuple[List[np.ndarray], Any]:
    leaves, treedef = jax.tree_util.tree_flatten(state)
    return [np.asarray(l) for l in leaves], treedef


def save_checkpoint(
    path: str,
    state: Any,
    metadata: Optional[Dict[str, Any]] = None,
    use_orbax: bool = False,
) -> None:
    """Write a pytree of (device or host) arrays to ``path``.

    The treedef is stored structurally: restoring requires a ``like`` pytree
    with the same structure (the normal case — the caller owns the state
    type), or returns the flat leaf list when no template is given.
    """
    if use_orbax:
        try:
            import orbax.checkpoint as ocp

            ckptr = ocp.PyTreeCheckpointer()
            ckptr.save(os.path.abspath(path), state, force=True)
            return
        except ImportError:
            pass  # fall through to npz
    leaves, treedef = _flatten(state)
    # npz cannot round-trip non-native dtypes (ml_dtypes' bfloat16 loads
    # back as raw void): store those leaves as bit-preserving uint8 views
    # and record the original dtype for the loader
    leaf_dtypes: Dict[str, str] = {}
    arrays = {}
    for i, leaf in enumerate(leaves):
        if leaf.dtype.kind == "V":
            leaf_dtypes[str(i)] = leaf.dtype.name
            leaf = np.ascontiguousarray(leaf).view(np.uint8)
        arrays[f"leaf_{i}"] = leaf
    arrays["__meta__"] = np.frombuffer(
        json.dumps(
            {
                "n_leaves": len(leaves),
                "treedef": str(treedef),
                "metadata": metadata or {},
                "leaf_dtypes": leaf_dtypes,
            }
        ).encode("utf-8"),
        dtype=np.uint8,
    )
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, path)  # atomic: no torn checkpoints on crash


def load_checkpoint(
    path: str, like: Any = None
) -> Tuple[Any, Dict[str, Any]]:
    """Read a checkpoint.  With ``like`` (a pytree of the same structure),
    returns (state, metadata); without, returns (flat leaf list, metadata)."""
    if not os.path.exists(path):
        raise CheckpointError(f"no checkpoint at {path}")
    with np.load(path) as data:
        meta = json.loads(bytes(data["__meta__"]).decode("utf-8"))
        leaves = [data[f"leaf_{i}"] for i in range(meta["n_leaves"])]
    for i_str, dtype_name in meta.get("leaf_dtypes", {}).items():
        # bit-preserving view back to the recorded non-native dtype
        # (np.dtype resolves e.g. 'bfloat16' once ml_dtypes is registered,
        # which importing jax guarantees)
        i = int(i_str)
        leaves[i] = leaves[i].view(np.dtype(dtype_name))
    if like is None:
        return leaves, meta.get("metadata", {})
    like_leaves, treedef = jax.tree_util.tree_flatten(like)
    if len(like_leaves) != len(leaves):
        raise CheckpointError(
            f"checkpoint has {len(leaves)} leaves, template has "
            f"{len(like_leaves)}"
        )
    # leaf count alone is not enough: a checkpoint from a different problem
    # with the same tree shape would silently corrupt the solver state, so
    # validate per-leaf shape/dtype and the stored tree structure too
    for i, (stored, tmpl) in enumerate(zip(leaves, like_leaves)):
        t_shape = np.shape(tmpl)
        t_dtype = np.asarray(tmpl).dtype
        if stored.shape != t_shape or stored.dtype != t_dtype:
            raise CheckpointError(
                f"leaf {i} mismatch: checkpoint {stored.shape}/"
                f"{stored.dtype} vs template {t_shape}/{t_dtype}"
            )
    stored_treedef = meta.get("treedef")
    if stored_treedef is not None and stored_treedef != str(treedef):
        # str(PyTreeDef) is not stable across jax versions, and per-leaf
        # shapes/dtypes were already validated strictly above — so a repr
        # mismatch alone is a warning, not an error
        logger.warning(
            "checkpoint tree repr differs from template (leaf shapes/"
            "dtypes match): %s vs %s", stored_treedef, treedef,
        )
    state = jax.tree_util.tree_unflatten(treedef, leaves)
    return state, meta.get("metadata", {})
