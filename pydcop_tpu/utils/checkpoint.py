"""Checkpoint / resume of solver state.

The reference has NO state checkpointing: its replicas ship computation
*definitions* and repaired computations restart from scratch
(/root/reference/pydcop/replication/dist_ucs_hostingcosts.py:60-84, SURVEY.md
§5.4).  On TPU the whole solver state is a pytree of device arrays, so real
checkpoint/resume is cheap: serialize the leaves with their treedef to one
``.npz`` file, restore into the same structure.

Two layers:

- ``save_checkpoint`` / ``load_checkpoint``: any pytree of arrays <-> file.
- ``DynamicMaxSum.save`` / ``DynamicMaxSum.restore`` (algorithms/
  maxsum_dynamic.py) and the orchestrator's repair path use these to carry
  warm solver state across failures instead of restarting fresh.

Uses numpy's npz container (always available); orbax remains the right tool
for sharded multi-host arrays — ``save_checkpoint(..., use_orbax=True)``
delegates to it when installed.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# jax is imported INSIDE the functions that flatten/unflatten pytrees:
# this module also rides the host-only `checkpoints` verb's import chain
# (via durability.manager), which must stay jax-free — listing manifests
# reads JSON sidecars, never arrays.

logger = logging.getLogger("pydcop_tpu.checkpoint")

__all__ = [
    "save_checkpoint",
    "load_checkpoint",
    "CheckpointError",
    "atomic_write_json",
]


class CheckpointError(Exception):
    pass


def atomic_write_json(path: str, obj: Any, **json_kwargs: Any) -> None:
    """tmp-write + ``os.replace``: a crash mid-write leaves the previous
    file (or nothing), never a torn JSON — the one audited home of the
    pattern every graftdur manifest writer uses."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f, **json_kwargs)
        f.write("\n")
    os.replace(tmp, path)


def _flatten(state: Any) -> Tuple[List[np.ndarray], Any]:
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(state)
    return [np.asarray(l) for l in leaves], treedef


def _identity_note(metadata: Dict[str, Any]) -> str:
    """The checkpoint's own account of what it belongs to, appended to
    every mismatch error: a graftdur manifest names the problem
    fingerprint + algorithm, which turns 'leaf 3 mismatch' into 'you are
    resuming a dsa checkpoint of problem 8c1f... against maxsum'."""
    if not isinstance(metadata, dict):
        return ""
    parts = []
    if metadata.get("algo"):
        parts.append(f"algo={metadata['algo']}")
    if metadata.get("fingerprint"):
        parts.append(f"problem fingerprint={metadata['fingerprint']}")
    if metadata.get("n_vars") is not None:
        parts.append(f"n_vars={metadata['n_vars']}")
    if not parts:
        return ""
    return f" (checkpoint identity: {', '.join(parts)})"


def _template_shape_dtype(tmpl) -> Tuple[Tuple[int, ...], np.dtype]:
    """Shape/dtype of a template leaf — concrete arrays and
    ``jax.ShapeDtypeStruct``-style abstract leaves both qualify, so a
    resume can validate against ``jax.eval_shape`` output without paying
    a device dispatch to materialize the template."""
    shape = getattr(tmpl, "shape", None)
    dtype = getattr(tmpl, "dtype", None)
    if shape is not None and dtype is not None:
        return tuple(shape), np.dtype(dtype)
    arr = np.asarray(tmpl)
    return arr.shape, arr.dtype


def save_checkpoint(
    path: str,
    state: Any,
    metadata: Optional[Dict[str, Any]] = None,
    use_orbax: bool = False,
) -> None:
    """Write a pytree of (device or host) arrays to ``path``.

    The treedef is stored structurally: restoring requires a ``like`` pytree
    with the same structure (the normal case — the caller owns the state
    type), or returns the flat leaf list when no template is given.
    """
    if use_orbax:
        try:
            import orbax.checkpoint as ocp

            ckptr = ocp.PyTreeCheckpointer()
            ckptr.save(os.path.abspath(path), state, force=True)
            # orbax owns the array payload; the manifest rides a sidecar
            # (atomic, like the npz path) so load_checkpoint round-trips
            # metadata identically on both branches
            atomic_write_json(
                os.path.abspath(path) + ".meta.json",
                {"metadata": metadata or {}}, sort_keys=True,
            )
            return
        except ImportError:
            pass  # fall through to npz
    leaves, treedef = _flatten(state)
    # npz cannot round-trip non-native dtypes (ml_dtypes' bfloat16 loads
    # back as raw void): store those leaves as bit-preserving uint8 views
    # and record the original dtype for the loader
    leaf_dtypes: Dict[str, str] = {}
    arrays = {}
    for i, leaf in enumerate(leaves):
        if leaf.dtype.kind == "V":
            leaf_dtypes[str(i)] = leaf.dtype.name
            leaf = np.ascontiguousarray(leaf).view(np.uint8)
        arrays[f"leaf_{i}"] = leaf
    arrays["__meta__"] = np.frombuffer(
        json.dumps(
            {
                "n_leaves": len(leaves),
                "treedef": str(treedef),
                "metadata": metadata or {},
                "leaf_dtypes": leaf_dtypes,
            }
        ).encode("utf-8"),
        dtype=np.uint8,
    )
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, path)  # atomic: no torn checkpoints on crash


def _load_orbax(path: str, like: Any) -> Tuple[Any, Dict[str, Any]]:
    """Restore a checkpoint written by ``save_checkpoint(use_orbax=True)``
    (an orbax directory + ``.meta.json`` sidecar).  The same like-template
    validation as the npz path applies afterwards."""
    try:
        import orbax.checkpoint as ocp
    except ImportError as e:
        raise CheckpointError(
            f"{path} is an orbax checkpoint directory but orbax is not "
            f"installed ({e})"
        )
    metadata: Dict[str, Any] = {}
    meta_path = os.path.abspath(path) + ".meta.json"
    if os.path.exists(meta_path):
        try:
            with open(meta_path, "r", encoding="utf-8") as f:
                metadata = json.load(f).get("metadata", {})
        except (OSError, ValueError):
            pass
    import jax

    ckptr = ocp.PyTreeCheckpointer()
    state = ckptr.restore(os.path.abspath(path))
    leaves, _ = jax.tree_util.tree_flatten(state)
    leaves = [np.asarray(l) for l in leaves]
    if like is None:
        return leaves, metadata
    like_leaves, treedef = jax.tree_util.tree_flatten(like)
    _validate_leaves(leaves, like_leaves, metadata, path)
    return jax.tree_util.tree_unflatten(treedef, leaves), metadata


def _validate_leaves(
    leaves: List[np.ndarray],
    like_leaves: List[Any],
    metadata: Dict[str, Any],
    path: str,
) -> None:
    note = _identity_note(metadata)
    if len(like_leaves) != len(leaves):
        raise CheckpointError(
            f"checkpoint {path} has {len(leaves)} leaves, template has "
            f"{len(like_leaves)}{note}"
        )
    # leaf count alone is not enough: a checkpoint from a different problem
    # with the same tree shape would silently corrupt the solver state, so
    # validate per-leaf shape/dtype and the stored tree structure too
    for i, (stored, tmpl) in enumerate(zip(leaves, like_leaves)):
        t_shape, t_dtype = _template_shape_dtype(tmpl)
        if stored.shape != t_shape or stored.dtype != t_dtype:
            raise CheckpointError(
                f"leaf {i} mismatch: checkpoint {stored.shape}/"
                f"{stored.dtype} vs template {t_shape}/{t_dtype}{note}"
            )


def load_checkpoint(
    path: str, like: Any = None
) -> Tuple[Any, Dict[str, Any]]:
    """Read a checkpoint.  With ``like`` (a pytree of the same structure;
    leaves may be arrays or ``jax.ShapeDtypeStruct``), returns
    (state, metadata); without, returns (flat leaf list, metadata).
    Mismatch errors carry the checkpoint's own manifest identity
    (problem fingerprint + algorithm) when it recorded one."""
    if not os.path.exists(path):
        raise CheckpointError(f"no checkpoint at {path}")
    if os.path.isdir(path):
        # save_checkpoint(use_orbax=True) writes a directory
        return _load_orbax(path, like)
    with np.load(path) as data:
        meta = json.loads(bytes(data["__meta__"]).decode("utf-8"))
        leaves = [data[f"leaf_{i}"] for i in range(meta["n_leaves"])]
    if meta.get("leaf_dtypes"):
        # np.dtype resolves e.g. 'bfloat16' only once ml_dtypes is
        # registered, which importing jax guarantees; native-dtype loads
        # (incl. the manifest-fallback read) stay jax-free
        import jax  # noqa: F401

    for i_str, dtype_name in meta.get("leaf_dtypes", {}).items():
        # bit-preserving view back to the recorded non-native dtype
        i = int(i_str)
        leaves[i] = leaves[i].view(np.dtype(dtype_name))
    if like is None:
        return leaves, meta.get("metadata", {})
    import jax

    like_leaves, treedef = jax.tree_util.tree_flatten(like)
    _validate_leaves(leaves, like_leaves, meta.get("metadata", {}), path)
    stored_treedef = meta.get("treedef")
    if stored_treedef is not None and stored_treedef != str(treedef):
        # str(PyTreeDef) is not stable across jax versions, and per-leaf
        # shapes/dtypes were already validated strictly above — so a repr
        # mismatch alone is a warning, not an error
        logger.warning(
            "checkpoint tree repr differs from template (leaf shapes/"
            "dtypes match): %s vs %s", stored_treedef, treedef,
        )
    state = jax.tree_util.tree_unflatten(treedef, leaves)
    return state, meta.get("metadata", {})
