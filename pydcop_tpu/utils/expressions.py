"""Python-expression constraints, compiled once and traceable to cost tables.

Plays the role of the reference's ``ExpressionFunction``
(/root/reference/pydcop/utils/expressionfunction.py:40): a constraint (or a
variable cost function) may be written as an arbitrary python expression over
variable names, e.g. ``"10000 if v0 == v1 else 0"``.

TPU-first design difference: the reference calls the compiled python function
once per assignment inside its message loops.  Here the expression object is
only ever evaluated *at compile time*, to lower the constraint into a dense
cost table (`pydcop_tpu.compile`).  At solve time the table lives on device and
the python function is never called again, so evaluation speed of this module
is a compile-time concern only.
"""

from __future__ import annotations

import ast
import builtins
import importlib.util
import math
import random
import textwrap
from typing import Any, Callable, Dict, Iterable, Optional

__all__ = ["ExpressionFunction", "expression_variables", "load_source_module"]

# Names that can appear free in an expression without being DCOP variables.
_ALLOWED_GLOBALS = {
    name for name in dir(builtins) if not name.startswith("_")
} | {"math", "random"}


def expression_variables(expression: str) -> frozenset:
    """Free variable names of a python expression (or function body).

    Builtins, ``math``/``random`` and attribute roots named ``source`` are not
    variables (``source.f(x)`` refers to an external python file, see
    /root/reference/docs/usage/file_formats/dcop_format.yml:124-133).
    """
    tree = ast.parse(_as_module(expression))
    names = set()
    assigned = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Store):
                assigned.add(node.id)
            else:
                names.add(node.id)
        elif isinstance(node, ast.FunctionDef):
            assigned.update(a.arg for a in node.args.args)
    return frozenset(
        n
        for n in names - assigned
        if n not in _ALLOWED_GLOBALS and n != "source"
    )


def _is_expression(code: str) -> bool:
    try:
        ast.parse(code, mode="eval")
        return True
    except SyntaxError:
        return False


def _returns_at_top_level(fn: ast.AST) -> bool:
    """Does the function return on ITS OWN body — not merely inside a
    nested def/lambda (whose return would not stop __expr__ from
    yielding None)?"""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Return):
            return True
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue  # a nested scope's return is not ours
        stack.extend(ast.iter_child_nodes(node))
    return False


def _as_module(code: str) -> str:
    """Wrap a multi-line function body into a module for ast analysis."""
    if _is_expression(code):
        return code
    # multi-line function body (must contain return); indent under a def
    body = "\n".join("    " + line for line in code.splitlines())
    return f"def __expr__():\n{body}\n"


def load_source_module(path: str):
    """Load an external python file declared via ``source:`` in YAML."""
    spec = importlib.util.spec_from_file_location("source", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)  # type: ignore[union-attr]
    return module


class ExpressionFunction:
    """A callable built from a python expression string.

    >>> f = ExpressionFunction("a + b * 2")
    >>> sorted(f.variable_names)
    ['a', 'b']
    >>> f(a=1, b=3)
    7
    >>> f.partial(b=3)(a=1)
    7

    Multi-line bodies with ``return`` are supported, as is the ``source.fn``
    external-file syntax (pass ``source_module``).
    """

    def __init__(
        self,
        expression: str,
        source_module=None,
        **fixed_vars: Any,
    ) -> None:
        self._expression = expression
        self._source_module = source_module
        self._fixed_vars = dict(fixed_vars)
        # normalize indentation before classifying: a mere leading space
        # (' v1 + v2', common in hand-written YAML) is an IndentationError
        # in eval mode and used to silently fall through to the
        # statement path, producing a function that returns None
        norm = textwrap.dedent(expression).strip("\n")
        all_vars = expression_variables(norm)
        unknown_fixed = set(fixed_vars) - set(all_vars)
        if unknown_fixed:
            raise ValueError(
                f"fixed variables {unknown_fixed} not in expression variables "
                f"{set(all_vars)}"
            )
        self._all_vars = all_vars
        self.variable_names = frozenset(all_vars - set(fixed_vars))

        env: Dict[str, Any] = {"math": math, "random": random}
        if source_module is not None:
            env["source"] = source_module
        if _is_expression(norm):
            code = compile(norm, "<dcop-expression>", "eval")
            self._fn: Callable[..., Any] = lambda kw: eval(  # noqa: S307
                code, {"__builtins__": builtins.__dict__, **env}, kw
            )
        else:
            args = ", ".join(sorted(all_vars))
            body = "\n".join("    " + l for l in norm.splitlines())
            src = f"def __expr__({args}):\n{body}\n"
            tree = ast.parse(src)  # raises SyntaxError with context
            if not _returns_at_top_level(tree.body[0]):
                # without this, the constraint would silently evaluate to
                # None for every assignment
                raise SyntaxError(
                    "multi-line expression must contain a return statement"
                )
            scope: Dict[str, Any] = {}
            exec(  # noqa: S102
                compile(tree, "<dcop-function>", "exec"),
                {"__builtins__": builtins.__dict__, **env},
                scope,
            )
            fn = scope["__expr__"]
            self._fn = lambda kw: fn(**kw)

    @property
    def expression(self) -> str:
        return self._expression

    @property
    def source_module(self):
        return self._source_module

    def __call__(self, *args, **kwargs) -> Any:
        if args:
            raise TypeError(
                "ExpressionFunction takes keyword arguments only "
                "(variable names are significant)"
            )
        scope = dict(self._fixed_vars)
        scope.update(kwargs)
        missing = self.variable_names - set(scope)
        if missing:
            raise TypeError(f"missing variable(s) {missing} for {self}")
        extra = set(scope) - self._all_vars
        if extra:
            # tolerate extra kwargs: callers often pass full assignments
            for k in extra:
                scope.pop(k)
        return self._fn(scope)

    def partial(self, **fixed: Any) -> "ExpressionFunction":
        merged = dict(self._fixed_vars)
        merged.update(fixed)
        return ExpressionFunction(
            self._expression, source_module=self._source_module, **merged
        )

    @property
    def fixed_vars(self) -> Dict[str, Any]:
        return dict(self._fixed_vars)

    def __repr__(self) -> str:
        return f"ExpressionFunction({self._expression!r})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, ExpressionFunction)
            and other._expression == self._expression
            and other._fixed_vars == self._fixed_vars
        )

    def __hash__(self) -> int:
        return hash((self._expression, tuple(sorted(self._fixed_vars.items()))))
