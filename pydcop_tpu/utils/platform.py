"""Backend platform helpers shared by the driver entry points.

The axon TPU plugin (registered by a sitecustomize) can block INDEFINITELY
during backend init when its relay is down — a bare ``jax.devices()`` never
returns.  So anything that may touch the TPU backend is probed in a
subprocess with a hard timeout first, and the CPU platform is pinned via
``jax.config`` (env vars alone are overridden by the plugin's registration).
Single source for the recipe used by ``__graft_entry__.py``, ``bench.py``
and ``tests/conftest.py``.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
from typing import Optional, Tuple

_PROBE_CODE = (
    "import jax; d = jax.devices(); "
    "print('PLATFORM=%s N=%d' % (d[0].platform, len(d)))"
)

_COUNT_FLAG = r"--xla_force_host_platform_device_count=\d+"


def probe_backend(
    timeout_s: float = 120.0, retries: int = 1
) -> Tuple[Optional[str], int, Optional[str]]:
    """Probe the default jax backend in a subprocess with a hard timeout.

    Returns ``(platform, n_devices, error)``: platform is e.g.
    ``"tpu"``/``"axon"``/``"cpu"`` or None if the probe failed (hung relay,
    init error); error is a one-line diagnostic or None.
    """
    error = None
    for _ in range(retries + 1):
        try:
            out = subprocess.run(
                [sys.executable, "-c", _PROBE_CODE],
                capture_output=True,
                text=True,
                timeout=timeout_s,
            )
        except subprocess.TimeoutExpired:
            error = f"backend probe timed out after {timeout_s:.0f}s"
            continue
        if out.returncode == 0:
            for line in reversed(out.stdout.strip().splitlines()):
                m = re.match(r"PLATFORM=(\S+) N=(\d+)", line)
                if m:
                    return m.group(1), int(m.group(2)), None
            error = "probe produced no PLATFORM line"
        else:
            tail = (out.stderr or "").strip().splitlines()
            error = tail[-1][:300] if tail else f"probe rc={out.returncode}"
    return None, 0, error


def probe_backend_cached(
    timeout_s: float = 20.0,
    ttl_ok: float = 60.0,
    ttl_fail: float = 60.0,
) -> Tuple[Optional[str], int, Optional[str]]:
    """probe_backend with an on-disk verdict cache.

    The probe costs a full subprocess jax import (~1-2 s) — or the whole
    timeout when an accelerator runtime hangs — which is pure overhead on
    every CLI invocation of a machine whose answer never changes.  Both
    verdicts expire after ~a minute: failures because a hung relay does
    come back, and healthy verdicts because trusting a stale one means
    initializing the accelerator in-process with no timeout — the exact
    hang the probe exists to prevent."""
    import hashlib
    import json
    import tempfile
    import time

    key = os.environ.get("JAX_PLATFORMS", "")
    digest = hashlib.md5(key.encode()).hexdigest()[:12]  # stable across runs
    cache_path = os.path.join(
        tempfile.gettempdir(),
        f"pydcop_tpu_probe_{os.getuid()}_{digest}.json",
    )
    now = time.time()
    try:
        with open(cache_path) as f:
            rec = json.load(f)
        ttl = ttl_ok if rec.get("platform") else ttl_fail
        if now - rec.get("ts", 0) < ttl:
            return rec.get("platform"), rec.get("n", 0), rec.get("error")
    except (OSError, ValueError):
        pass
    platform, n, error = probe_backend(timeout_s=timeout_s, retries=0)
    try:
        payload = json.dumps(
            {"ts": now, "platform": platform, "n": n, "error": error}
        )
        tmp = cache_path + f".{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, cache_path)
    except OSError:
        pass
    return platform, n, error


def enable_compilation_cache(
    path: Optional[str] = None, require_accelerator: bool = True
) -> None:
    """Persist compiled XLA executables on disk across processes.

    A fresh compile of the fused solve program takes ~minutes through the
    tunneled TPU relay (remote compile); the cache turns every later
    bench/CLI/driver run into a disk hit.  ACCELERATOR BACKENDS ONLY: the
    XLA:CPU AOT loader warns about machine-feature mismatches (and can in
    principle SIGILL when the cache dir is reused from a different host),
    so with ``require_accelerator`` (the default) the backend is resolved
    first — this initializes jax, so the caller must already be committed
    to touching the accelerator — and a CPU backend makes this a no-op.
    Pass ``require_accelerator=False`` only when the caller has verified
    the accelerator some other way (e.g. the CLI's subprocess probe).  A
    JAX_COMPILATION_CACHE_DIR set by the caller wins."""
    import jax

    if require_accelerator and jax.default_backend() == "cpu":
        return
    if path is None:
        path = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
            os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            ),
            ".jax_cache",
        )
    # this jax build ignores the env var; the config route works and is
    # safe before (or after) backend init
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def set_host_device_count(n_devices: int) -> None:
    """Put the virtual host-device count into XLA_FLAGS (replacing any
    existing count flag).  Must run before jax builds its first backend —
    the flag is read at backend construction."""
    flags = re.sub(
        _COUNT_FLAG, "", os.environ.get("XLA_FLAGS", "")
    ).strip()
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n_devices}"
    ).strip()


def pin_cpu(n_devices: Optional[int] = None) -> None:
    """Pin the CPU platform (optionally as ``n_devices`` virtual devices).

    Must run before jax builds its first backend: the XLA device-count flag
    is read at backend construction, and the platform pin prevents the axon
    plugin from ever being initialized in this process.
    """
    if n_devices is not None:
        set_host_device_count(n_devices)

    import jax

    jax.config.update("jax_platforms", "cpu")
