"""Backend platform helpers shared by the driver entry points.

The axon TPU plugin (registered by a sitecustomize) can block INDEFINITELY
during backend init when its relay is down — a bare ``jax.devices()`` never
returns.  So anything that may touch the TPU backend is probed in a
subprocess with a hard timeout first, and the CPU platform is pinned via
``jax.config`` (env vars alone are overridden by the plugin's registration).
Single source for the recipe used by ``__graft_entry__.py``, ``bench.py``
and ``tests/conftest.py``.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
from typing import Optional, Tuple

_PROBE_CODE = (
    "import jax; d = jax.devices(); "
    "print('PLATFORM=%s N=%d' % (d[0].platform, len(d)))"
)

_COUNT_FLAG = r"--xla_force_host_platform_device_count=\d+"


def probe_backend(
    timeout_s: float = 120.0, retries: int = 1
) -> Tuple[Optional[str], int, Optional[str]]:
    """Probe the default jax backend in a subprocess with a hard timeout.

    Returns ``(platform, n_devices, error)``: platform is e.g.
    ``"tpu"``/``"axon"``/``"cpu"`` or None if the probe failed (hung relay,
    init error); error is a one-line diagnostic or None.
    """
    error = None
    for _ in range(retries + 1):
        try:
            out = subprocess.run(
                [sys.executable, "-c", _PROBE_CODE],
                capture_output=True,
                text=True,
                timeout=timeout_s,
            )
        except subprocess.TimeoutExpired:
            error = f"backend probe timed out after {timeout_s:.0f}s"
            continue
        if out.returncode == 0:
            for line in reversed(out.stdout.strip().splitlines()):
                m = re.match(r"PLATFORM=(\S+) N=(\d+)", line)
                if m:
                    return m.group(1), int(m.group(2)), None
            error = "probe produced no PLATFORM line"
        else:
            tail = (out.stderr or "").strip().splitlines()
            error = tail[-1][:300] if tail else f"probe rc={out.returncode}"
    return None, 0, error


def pin_cpu(n_devices: Optional[int] = None) -> None:
    """Pin the CPU platform (optionally as ``n_devices`` virtual devices).

    Must run before jax builds its first backend: the XLA device-count flag
    is read at backend construction, and the platform pin prevents the axon
    plugin from ever being initialized in this process.
    """
    if n_devices is not None:
        flags = re.sub(
            _COUNT_FLAG, "", os.environ.get("XLA_FLAGS", "")
        ).strip()
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_devices}"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
