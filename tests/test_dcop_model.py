"""Model-layer tests: domains, variables, relations, DCOP container, YAML.

Mirrors the coverage strategy of the reference's tests/unit/test_dcop_*.py
(SURVEY.md §4 tier 1) with exact assertions on tiny problems.
"""

import glob
import os

import numpy as np
import pytest

from pydcop_tpu.dcop import (
    DCOP,
    AgentDef,
    Domain,
    Variable,
    constraint_from_str,
    dcop_yaml,
    join,
    load_dcop,
    load_dcop_from_file,
    projection,
)
from pydcop_tpu.dcop.objects import (
    BinaryVariable,
    VariableNoisyCostFunc,
    VariableWithCostDict,
    VariableWithCostFunc,
)
from pydcop_tpu.dcop.relations import (
    NAryMatrixRelation,
    UnaryFunctionRelation,
    assignment_cost,
    find_arg_optimal,
    find_optimum,
)
from pydcop_tpu.utils.expressions import ExpressionFunction
from pydcop_tpu.utils.simple_repr import from_repr, simple_repr

REF_INSTANCES = "/root/reference/tests/instances"


class TestDomain:
    def test_basic(self):
        d = Domain("colors", "color", ["R", "G", "B"])
        assert len(d) == 3
        assert d.index("G") == 1
        assert d[2] == "B"
        assert "R" in d

    def test_index_error(self):
        d = Domain("d", "", [1, 2])
        with pytest.raises(ValueError):
            d.index(5)


class TestVariables:
    def test_costs(self):
        d = Domain("d", "", [0, 1, 2])
        v = VariableWithCostFunc("x", d, ExpressionFunction("x * 0.5"))
        assert v.cost_for_val(2) == 1.0
        assert v.cost_vector() == [0.0, 0.5, 1.0]

    def test_cost_dict(self):
        d = Domain("d", "", ["a", "b"])
        v = VariableWithCostDict("x", d, {"a": 1.5})
        assert v.cost_vector() == [1.5, 0.0]

    def test_noisy_deterministic(self):
        d = Domain("d", "", [0, 1])
        v1 = VariableNoisyCostFunc(
            "x", d, ExpressionFunction("x * 1.0"), noise_level=0.1, seed=7
        )
        v2 = VariableNoisyCostFunc(
            "x", d, ExpressionFunction("x * 1.0"), noise_level=0.1, seed=7
        )
        assert v1.cost_vector() == v2.cost_vector()
        assert all(
            0 <= n - b < 0.1
            for n, b in zip(v1.cost_vector(), [0.0, 1.0])
        )

    def test_binary(self):
        v = BinaryVariable("b")
        assert list(v.domain.values) == [0, 1]

    def test_different_costs_not_equal(self):
        d = Domain("d", "", [0, 1])
        assert VariableWithCostDict("x", d, {0: 1.0}) != VariableWithCostDict(
            "x", d, {0: 2.0}
        )


class TestRelations:
    def setup_method(self):
        self.d = Domain("d", "", [0, 1, 2])
        self.x = Variable("x", self.d)
        self.y = Variable("y", self.d)
        self.z = Variable("z", self.d)

    def test_expression_constraint(self):
        c = constraint_from_str("c", "x + 2 * y", [self.x, self.y])
        assert c.arity == 2
        assert c(x=1, y=2) == 5

    def test_matrix_relation(self):
        m = NAryMatrixRelation(
            [self.x, self.y], np.arange(9).reshape(3, 3)
        )
        assert m(x=1, y=2) == 5.0
        sliced = m.slice({"x": 2})
        assert sliced.scope_names == ["y"]
        assert sliced(y=0) == 6.0

    def test_matrix_shape_validation(self):
        with pytest.raises(ValueError):
            NAryMatrixRelation(
                [self.x, Variable("w", [0, 1])], np.zeros((2, 3))
            )

    def test_join_is_pointwise_sum(self):
        c1 = constraint_from_str("c1", "x + y", [self.x, self.y])
        c2 = constraint_from_str("c2", "y * z", [self.y, self.z])
        j = join(c1.tabulate(), c2.tabulate())
        assert set(j.scope_names) == {"x", "y", "z"}
        assert j(x=1, y=2, z=2) == (1 + 2) + (2 * 2)

    def test_projection_min(self):
        c = constraint_from_str("c", "(x - y) * (x - y)", [self.x, self.y])
        p = projection(c.tabulate(), self.y, "min")
        assert p.scope_names == ["x"]
        # for any x there is a y with (x-y)^2 == 0
        assert all(p(x=v) == 0 for v in self.d)

    def test_find_arg_optimal(self):
        c = UnaryFunctionRelation("c", self.x, lambda v: (v - 1) ** 2)
        vals, cost = find_arg_optimal(self.x, c, "min")
        assert vals == [1] and cost == 0

    def test_find_optimum_max(self):
        c = constraint_from_str("c", "x + y", [self.x, self.y])
        assert find_optimum(c, "max") == 4

    def test_assignment_cost(self):
        c1 = constraint_from_str("c1", "x + y", [self.x, self.y])
        c2 = constraint_from_str("c2", "z", [self.z])
        assert assignment_cost({"x": 1, "y": 2, "z": 1}, [c1, c2]) == 4


class TestDCOPContainer:
    def test_iadd_registers_variables(self):
        dcop = DCOP("t")
        x = Variable("x", [0, 1])
        y = Variable("y", [0, 1])
        dcop += constraint_from_str("c", "x + y", [x, y])
        assert set(dcop.variables) == {"x", "y"}

    def test_solution_cost_violations(self):
        dcop = DCOP("t")
        x = Variable("x", [0, 1])
        y = Variable("y", [0, 1])
        dcop += constraint_from_str("c", "10000 if x == y else 0", [x, y])
        cost, viol = dcop.solution_cost({"x": 0, "y": 0}, 10000)
        assert (cost, viol) == (0.0, 1)
        cost, viol = dcop.solution_cost({"x": 0, "y": 1}, 10000)
        assert (cost, viol) == (0.0, 0)


class TestYaml:
    @pytest.mark.parametrize(
        "fname",
        sorted(
            os.path.basename(f)
            for f in glob.glob(f"{REF_INSTANCES}/*.yaml")
            + glob.glob(f"{REF_INSTANCES}/*.yml")
        ),
    )
    def test_reference_instances_load_and_roundtrip(self, fname):
        d = load_dcop_from_file(os.path.join(REF_INSTANCES, fname))
        d2 = load_dcop(dcop_yaml(d), main_dir=REF_INSTANCES)
        assert set(d2.variables) == set(d.variables)
        assert set(d2.constraints) == set(d.constraints)
        assert set(d2.agents) == set(d.agents)

    def test_extensional_quoted_tokens(self):
        d = load_dcop(
            """name: e
objective: min
domains: {d: {values: ['ok', 'too bad']}}
variables: {u: {domain: d}, w: {domain: d}}
constraints:
  ce:
    type: extensional
    variables: [u, w]
    default: 5
    values: {1: "ok 'too bad' | 'too bad' ok"}
agents: [a1]
"""
        )
        c = d.constraints["ce"]
        assert c(u="ok", w="too bad") == 1.0
        assert c(u="ok", w="ok") == 5.0

    def test_range_domain(self):
        d = load_dcop(
            """name: t
objective: min
domains: {d: {values: [1 .. 5]}}
variables: {a: {domain: d}}
agents: [a1]
"""
        )
        assert list(d.domains["d"].values) == [1, 2, 3, 4, 5]

    def test_agent_attrs_and_routes(self):
        d = load_dcop(
            """name: t
objective: min
domains: {d: {values: [0, 1]}}
variables: {a: {domain: d}}
agents:
  a1: {capacity: 11, foo: bar}
  a2: {capacity: 22}
routes:
  default: 3
  a1: {a2: 7}
hosting_costs:
  default: 100
  a1:
    default: 5
    computations: {a: 1}
"""
        )
        a1 = d.agents["a1"]
        assert a1.capacity == 11 and a1.foo == "bar"
        assert a1.route("a2") == 7
        assert d.agents["a2"].route("a1") == 7
        assert a1.hosting_cost("a") == 1
        assert a1.hosting_cost("other") == 5
        assert d.agents["a2"].hosting_cost("a") == 100

    def test_multifile_merge(self, tmp_path):
        f1 = tmp_path / "a.yaml"
        f1.write_text(
            """name: m
objective: min
domains: {d: {values: [0, 1]}}
variables: {a: {domain: d}, b: {domain: d}}
constraints: {c1: {type: intention, function: a + b}}
"""
        )
        f2 = tmp_path / "b.yaml"
        f2.write_text(
            "constraints: {c2: {type: intention, function: a * b}}\nagents: [x]\n"
        )
        d = load_dcop_from_file([str(f1), str(f2)])
        assert set(d.constraints) == {"c1", "c2"}


class TestSimpleRepr:
    def test_variable_roundtrip(self):
        v = Variable("x", Domain("d", "t", [1, 2, 3]), 2)
        v2 = from_repr(simple_repr(v))
        assert v2 == v

    def test_agentdef_roundtrip(self):
        a = AgentDef("a1", capacity=42, routes={"a2": 3}, foo="bar")
        a2 = from_repr(simple_repr(a))
        assert a2 == a
        assert a2.foo == "bar"

    def test_matrix_relation_roundtrip(self):
        x = Variable("x", [0, 1])
        m = NAryMatrixRelation([x], np.array([1.0, 2.0]), name="m")
        m2 = from_repr(simple_repr(m))
        assert m2 == m


class TestGraphHelpers:
    def _chain(self):
        from pydcop_tpu.dcop import Domain, Variable, constraint_from_str

        d = Domain("d", "", [0, 1])
        vs = [Variable(f"v{i}", d) for i in range(4)]
        cons = [
            constraint_from_str(f"c{i}", f"v{i} + v{i+1}", [vs[i], vs[i + 1]])
            for i in range(3)
        ]
        return vs, cons

    def test_diameter_and_cycles_on_chain(self):
        from pydcop_tpu.utils.graphs import cycles_count, graph_diameter

        vs, cons = self._chain()
        assert graph_diameter(vs, cons) == 3
        assert cycles_count(vs, cons) == 0

    def test_cycle_detected(self):
        from pydcop_tpu.dcop import constraint_from_str
        from pydcop_tpu.utils.graphs import cycles_count

        vs, cons = self._chain()
        cons.append(
            constraint_from_str("c_loop", "v0 + v3", [vs[0], vs[3]])
        )
        assert cycles_count(vs, cons) == 1

    def test_bipartite_and_networkx(self):
        from pydcop_tpu.utils.graphs import (
            as_bipartite_graph,
            as_networkx_bipartite_graph,
            as_networkx_graph,
        )

        vs, cons = self._chain()
        adj = as_bipartite_graph(vs, cons)
        assert adj["c0"] == ["v0", "v1"]
        assert "c0" in adj["v0"]
        g = as_networkx_graph(vs, cons)
        assert g.number_of_edges() == 3
        bg = as_networkx_bipartite_graph(vs, cons)
        assert bg.number_of_edges() == 6

    def test_all_pairs(self):
        from pydcop_tpu.utils.graphs import all_pairs

        assert all_pairs([1, 2, 3]) == [(1, 2), (1, 3), (2, 3)]
