"""Composed end-to-end scenarios ported from the reference's integration
suite (round-4 verdict item 5/missing-4): the *mechanisms* (dynamic
sessions, external variables, n-ary DPOP, multi-computation agents) are
covered by unit/api tests; these reproduce the reference's full composed
scenarios and assert the same final assignments.

- smartlights, multiple computations per agent
  (ref tests/integration/maxsum_smartlights_multiplecomputationagent.py)
- dynamic MaxSum graph coloring gated by an external variable
  (ref tests/integration/dmaxsum_external_variable.py)
- DPOP with one 4-ary relation over 4 variables
  (ref tests/integration/dpop_nonbinaryrelation_4vars.py)
"""

import pytest

from pydcop_tpu.api import solve_result
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import (
    AgentDef,
    Domain,
    ExternalVariable,
    Variable,
)
from pydcop_tpu.dcop.relations import (
    ConditionalRelation,
    NAryFunctionRelation,
    UnaryBooleanRelation,
    constraint_from_str,
)


def smartlights_dcop() -> DCOP:
    """3 dimmable lights (0-9), a scene variable y1 = round(mean
    luminosity) enforced by a hard 4-ary constraint, and a rule pushing
    y1 toward 5 with l3 off (ref scenario lines 49-106: energy costs
    0.5*l1, l2, l3; rule 10*(|y1-5| + l3))."""
    d10 = Domain("lum", "", list(range(10)))
    l1, l2, l3, y1 = (Variable(n, d10) for n in ("l1", "l2", "l3", "y1"))
    dcop = DCOP("smartlights")
    dcop += constraint_from_str("cost_l1", "0.5 * l1", [l1])
    dcop += constraint_from_str("cost_l2", "l2", [l2])
    dcop += constraint_from_str("cost_l3", "l3", [l3])
    dcop += constraint_from_str(
        "scene_rel",
        "0 if y1 == round(l1/3 + l2/3 + l3/3) else 10000",
        [l1, l2, l3, y1],
    )
    dcop += constraint_from_str("rule_rel", "10 * (abs(y1 - 5) + l3)", [l3, y1])
    # three physical bulb nodes hosting 9 computations between them, as in
    # the reference's MultipleComputationAgent deployment
    dcop.add_agents([AgentDef(f"bulb{i}") for i in range(1, 4)])
    return dcop


# the reference scenario's unique optimum (asserted verbatim there,
# maxsum_smartlights_multiplecomputationagent.py:155)
SMARTLIGHTS_OPTIMUM = {"l1": 9, "l2": 5, "l3": 0, "y1": 5}


class TestSmartlightsMultipleComputationAgents:
    def test_amaxsum_api(self):
        r = solve_result(smartlights_dcop(), "amaxsum", n_cycles=100, seed=0)
        assert r["assignment"] == SMARTLIGHTS_OPTIMUM
        assert r["violation"] == 0
        assert r["cost"] == pytest.approx(9.5)

    def test_amaxsum_through_runtime_with_multi_computation_agents(self):
        # the composed scenario proper: orchestrator + 3 agents, each
        # hosting several of the 9 computations (adhoc distribution),
        # solved through the full runtime path
        from pydcop_tpu.infrastructure.run import solve as runtime_solve

        assignment = runtime_solve(
            smartlights_dcop(), "amaxsum", "adhoc", n_cycles=100
        )
        assert assignment == SMARTLIGHTS_OPTIMUM

    def test_maxsum_agrees(self):
        r = solve_result(smartlights_dcop(), "maxsum", n_cycles=100, seed=0)
        assert r["assignment"] == SMARTLIGHTS_OPTIMUM


class TestDynamicMaxsumExternalVariable:
    """Graph coloring with a boolean external variable e1 gating the
    3-ary all-different constraint r1 (ref scenario lines 41-64): with e1
    false every variable takes its preferred color; with e1 true v2/v3
    cannot both be 'B' and exactly one of them yields (the reference
    flips e1 five times and checks the active constraints after each)."""

    def _dcop(self):
        colors = Domain("colors", "color", ["R", "G", "B"])
        v1, v2, v3, v4 = (Variable(f"v{i}", colors) for i in range(1, 5))
        booleans = Domain("boolean", "abstract", [0, 1])
        e1 = ExternalVariable("e1", booleans, value=0)
        dcop = DCOP("dmaxsum_ext")
        dcop.add_variable(e1)
        for v, pref in ((v1, "R"), (v2, "B"), (v3, "B"), (v4, "R")):
            dcop += constraint_from_str(
                f"pref_{v.name}", f"0 if {v.name} == '{pref}' else 5", [v]
            )
        dcop += ConditionalRelation(
            UnaryBooleanRelation("r1_cond", e1),
            NAryFunctionRelation(
                lambda v1, v2, v3: (
                    0 if (v1 != v2 and v2 != v3 and v1 != v3) else 100
                ),
                [v1, v2, v3],
                name="r1",
            ),
            name="r1",
        )
        dcop += constraint_from_str("r2", "0 if v2 != v4 else 100", [v2, v4])
        dcop += constraint_from_str("r3", "0 if v3 != v4 else 100", [v3, v4])
        dcop.add_agents([AgentDef(f"a{i}") for i in range(1, 5)])
        return dcop, e1

    def test_five_toggles_keep_active_constraints_satisfied(self):
        from pydcop_tpu.algorithms.maxsum_dynamic import DynamicMaxSum

        dcop, e1 = self._dcop()
        session = DynamicMaxSum(dcop, params={"noise": 0.001})
        for i in range(5):
            vals = session.run(40).assignment
            assert vals["v2"] != vals["v4"], (i, vals)  # r2
            assert vals["v3"] != vals["v4"], (i, vals)  # r3
            if e1.value:
                # r1 active: v1/v2/v3 all different
                assert len({vals["v1"], vals["v2"], vals["v3"]}) == 3, (
                    i, vals,
                )
            else:
                # r1 inactive: everyone takes the preferred color
                assert vals == {
                    "v1": "R", "v2": "B", "v3": "B", "v4": "R"
                }, (i, vals)
            e1.value = 1 - e1.value  # subscription re-lowers r1's tables


class TestDpopNonBinary4Vars:
    """One 4-ary relation |10 - sum| over four 0-9 variables plus unary
    preference windows (ref scenario lines 55-129).  The optimum cost is
    0 (all preferences satisfied, sum exactly 10); tie-break among the
    cost-0 assignments is implementation-defined, so the semantic success
    condition is asserted plus our deterministic pick."""

    def _dcop(self):
        d10 = Domain("lum", "", list(range(10)))
        xs = [Variable(f"x{i}", d10) for i in range(4)]
        dcop = DCOP("nonbinary4")
        dcop += constraint_from_str("x0_prefs", "0 if x0 > 3 else 10", [xs[0]])
        dcop += constraint_from_str(
            "x1_prefs", "0 if 2 < x1 < 7 else 10", [xs[1]]
        )
        dcop += constraint_from_str("x2_prefs", "0 if x2 < 5 else 10", [xs[2]])
        dcop += constraint_from_str(
            "x3_prefs", "0 if 0 < x3 < 5 else 10", [xs[3]]
        )
        dcop += constraint_from_str(
            "four_ary", "abs(10 - (x0 + x1 + x2 + x3))", xs
        )
        dcop.add_agents([AgentDef(f"a{i}") for i in range(4)])
        return dcop

    def test_dpop_reaches_zero_cost_optimum(self):
        r = solve_result(self._dcop(), "dpop", n_cycles=1)
        a = r["assignment"]
        assert r["cost"] == 0.0 and r["violation"] == 0
        # preference windows + exact sum, the reference's success
        # condition modulo tie-break (its pick {x0:4, x1:3, x2:0, x3:3}
        # is another of the cost-0 optima)
        assert a["x0"] > 3 and 2 < a["x1"] < 7 and a["x2"] < 5
        assert 0 < a["x3"] < 5
        assert sum(a.values()) == 10
        # deterministic on this framework: pin the exact pick so any
        # tie-break change is a conscious one.  (No cross-solver check:
        # syncbb/ncbb are binary-only like the reference's, and cost 0
        # over nonnegative constraints is optimal by construction.)
        assert a == {"x0": 4, "x1": 5, "x2": 0, "x3": 1}


class TestMaxsumEqualityNoise:
    """Tie-breaking via noisy variable costs (ref
    tests/integration/maxsum_equality.py): y1 must equal l1 + l2 (hard),
    y1 wants 5, l1/l2 each cost their value — noise picks one of the
    equally-good splits."""

    def test_y1_five_and_split_sums_to_five(self):
        from pydcop_tpu.dcop.objects import (
            VariableNoisyCostFunc,
            VariableWithCostFunc,
        )

        d10 = Domain("lum", "", list(range(10)))
        l1 = VariableNoisyCostFunc("l1", d10, lambda x: x)
        l2 = VariableNoisyCostFunc("l2", d10, lambda x: x)
        y1 = VariableWithCostFunc("y1", d10, lambda x: 10 * abs(5 - x))
        dcop = DCOP("equality")
        for v in (l1, l2, y1):
            dcop.add_variable(v)
        dcop += constraint_from_str(
            "scene", "0 if y1 == l1 + l2 else 10000", [l1, l2, y1]
        )
        dcop.add_agents([AgentDef(f"a{i}") for i in range(3)])
        r = solve_result(dcop, "amaxsum", n_cycles=80, seed=0)
        a = r["assignment"]
        assert a["y1"] == 5 and a["l1"] + a["l2"] == 5


class TestSmartlightsVariableCosts:
    """The variable-cost flavor of the smartlights scenario (ref
    maxsum_smartlights_multiplecomputationagent_variablecost.py): light
    energy modeled as VariableWithCostFunc instead of unary factors —
    same unique optimum."""

    def test_same_optimum_through_variable_costs(self):
        from pydcop_tpu.dcop.objects import VariableWithCostFunc

        d10 = Domain("lum", "", list(range(10)))
        l1 = VariableWithCostFunc("l1", d10, lambda x: 0.5 * x)
        l2 = VariableWithCostFunc("l2", d10, lambda x: x)
        l3 = VariableWithCostFunc("l3", d10, lambda x: x)
        y1 = Variable("y1", d10)
        dcop = DCOP("smartlights_vc")
        for v in (l1, l2, l3, y1):
            dcop.add_variable(v)
        dcop += constraint_from_str(
            "scene_rel",
            "0 if y1 == round(l1/3 + l2/3 + l3/3) else 10000",
            [l1, l2, l3, y1],
        )
        dcop += constraint_from_str(
            "rule_rel", "10 * (abs(y1 - 5) + l3)", [l3, y1]
        )
        dcop.add_agents([AgentDef(f"bulb{i}") for i in range(1, 4)])
        r = solve_result(dcop, "amaxsum", n_cycles=100, seed=0)
        assert r["assignment"] == SMARTLIGHTS_OPTIMUM


class TestDpopScenarios:
    """The reference's remaining DPOP integration scripts, as API tests."""

    def test_petcu_thesis_p56_max_mode(self):
        # ref dpop_PetcuThesisp56.py: 4 variables, 3 matrix relations,
        # utility maximization.  The optimum utility is 15, attained by
        # two assignments; the reference pins its own tie-break, we
        # accept either
        from pydcop_tpu.dcop.relations import NAryMatrixRelation

        abc = Domain("abc", "", ["a", "b", "c"])
        x0, x1, x2, x3 = (Variable(f"x{i}", abc) for i in range(4))
        dcop = DCOP("petcu", "max")
        dcop += NAryMatrixRelation(
            [x1, x0], [[2, 2, 3], [5, 3, 7], [6, 3, 1]], name="r1_0"
        )
        dcop += NAryMatrixRelation(
            [x2, x1], [[0, 2, 1], [3, 4, 6], [5, 2, 5]], name="r2_1"
        )
        dcop += NAryMatrixRelation(
            [x3, x1], [[6, 2, 3], [3, 3, 2], [4, 4, 1]], name="r3_1"
        )
        dcop.add_agents([AgentDef(f"a{i}") for i in range(4)])
        r = solve_result(dcop, "dpop", n_cycles=1)
        assert r["cost"] == 15.0
        assert r["assignment"] in (
            {"x0": "a", "x1": "c", "x2": "b", "x3": "a"},  # ref's pick
            {"x0": "c", "x1": "b", "x2": "b", "x3": "c"},  # equal optimum
        )

    def test_unary_constraint_max_mode(self):
        # ref dpop_unary.py: preference order a > c > b on x0, prefer
        # x0 != x1; expected x0 = 'a', x1 in {'b', 'c'}, utility 18
        abc = Domain("abc", "", ["a", "b", "c"])
        x0, x1 = Variable("x0", abc), Variable("x1", abc)
        dcop = DCOP("unary", "max")
        dcop += constraint_from_str(
            "u", "8 if x0 == 'a' else (2 if x0 == 'b' else 5)", [x0]
        )
        dcop += constraint_from_str(
            "diff", "0 if x0 == x1 else 10", [x0, x1]
        )
        dcop.add_agents([AgentDef("a0"), AgentDef("a1")])
        r = solve_result(dcop, "dpop", n_cycles=1)
        assert r["cost"] == 18.0
        assert r["assignment"]["x0"] == "a"
        assert r["assignment"]["x1"] in ("b", "c")

    def test_graphcoloring_chain(self):
        # ref dpop_graphcoloring_1.py: three colors, per-variable
        # preferences, all-different over the triangle — unique optimum
        rgb = Domain("rgb", "", ["R", "G", "B"])
        x0, x1, x2 = (Variable(f"x{i}", rgb) for i in range(3))
        dcop = DCOP("coloring1")
        dcop += constraint_from_str("p0", "0 if x0 == 'R' else 10", [x0])
        dcop += constraint_from_str("p1", "0 if x1 == 'G' else 10", [x1])
        dcop += constraint_from_str("p2", "0 if x2 == 'B' else 10", [x2])
        dcop += constraint_from_str("r01", "10 if x0 == x1 else 0", [x0, x1])
        dcop += constraint_from_str("r02", "10 if x0 == x2 else 0", [x0, x2])
        dcop += constraint_from_str("r12", "10 if x1 == x2 else 0", [x1, x2])
        dcop.add_agents([AgentDef(f"a{i}") for i in range(3)])
        r = solve_result(dcop, "dpop", n_cycles=1)
        assert r["assignment"] == {"x0": "R", "x1": "G", "x2": "B"}
        assert r["cost"] == 0.0

    def test_nonbinary_3vars(self):
        # ref dpop_nonbinaryrelation.py: 3-ary |10 - sum| + preference
        # windows; cost-0 optimum (tie-break implementation-defined, the
        # reference accepts two of them itself)
        d10 = Domain("lum", "", list(range(10)))
        xs = [Variable(f"x{i}", d10) for i in range(3)]
        dcop = DCOP("nonbinary3")
        dcop += constraint_from_str("x0p", "0 if x0 > 5 else 10", [xs[0]])
        dcop += constraint_from_str(
            "x1p", "0 if 2 < x1 < 7 else 10", [xs[1]]
        )
        dcop += constraint_from_str("x2p", "0 if x2 < 5 else 10", [xs[2]])
        dcop += constraint_from_str(
            "tri", "abs(10 - (x0 + x1 + x2))", xs
        )
        dcop.add_agents([AgentDef(f"a{i}") for i in range(3)])
        r = solve_result(dcop, "dpop", n_cycles=1)
        a = r["assignment"]
        assert r["cost"] == 0.0 and r["violation"] == 0
        assert a["x0"] > 5 and 2 < a["x1"] < 7 and a["x2"] < 5
        assert sum(a.values()) == 10
        assert a == {"x0": 6, "x1": 4, "x2": 0}  # our deterministic pick


def coloring_prefs_dcop() -> DCOP:
    """Ref maxsum_graphcoloring.py / dsa_graphcoloring.py: 2-color chain
    with preference terms folded into the factors — unique optimum
    x1=R, x2=G, x3=R."""
    rg = Domain("rg", "", ["R", "G"])
    xs = [Variable(f"x{i}", rg) for i in (1, 2, 3)]
    dcop = DCOP("coloring_prefs")
    dcop += constraint_from_str(
        "u1",
        "(1 if x1 == x2 else 0) + (-0.1 if x1 == 'R' else 0.1)"
        " + (-0.1 if x2 == 'G' else 0.1)",
        xs[:2],
    )
    dcop += constraint_from_str(
        "u2",
        "(1 if x1 == x2 else 0) + (1 if x2 == x3 else 0)"
        " + (-0.1 if x1 == 'R' else 0.1) + (-0.1 if x2 == 'G' else 0.1)"
        " + (-0.1 if x3 == 'G' else 0.1)",
        xs,
    )
    dcop += constraint_from_str(
        "u3",
        "(1 if x2 == x3 else 0) + (-0.1 if x2 == 'G' else 0.1)"
        " + (-0.1 if x3 == 'G' else 0.1)",
        xs[1:],
    )
    dcop.add_agents([AgentDef(f"a{i}") for i in range(3)])
    return dcop


class TestGraphColoringPrefs:
    EXPECTED = {"x1": "R", "x2": "G", "x3": "R"}

    def test_maxsum(self):
        r = solve_result(coloring_prefs_dcop(), "maxsum", n_cycles=60, seed=1)
        assert r["assignment"] == self.EXPECTED

    def test_dsa(self):
        # ref dsa_graphcoloring.py runs variant A over many attempts;
        # one seeded run suffices for the deterministic emulation
        r = solve_result(coloring_prefs_dcop(), "dsa", n_cycles=60, seed=1)
        assert r["assignment"] == self.EXPECTED

    def test_with_costs(self):
        # ref maxsum_graphcoloring_with_costs.py: asymmetric domains
        # (2 vs 3 colors), negative unary costs, hard all-diff
        d1 = Domain("d1", "", [0, 1])
        d2 = Domain("d2", "", [0, 1, 2])
        x1, x2 = Variable("x1", d1), Variable("x2", d2)
        dcop = DCOP("with_costs")
        dcop += constraint_from_str("x1_cost", "[0, -3][x1]", [x1])
        dcop += constraint_from_str("x2_cost", "[0, -2, -1][x2]", [x2])
        dcop += constraint_from_str(
            "all_diff", "10000 if x1 == x2 else 0", [x1, x2]
        )
        dcop.add_agents([AgentDef("a0"), AgentDef("a1")])
        r = solve_result(dcop, "maxsum", n_cycles=40, seed=0)
        assert r["assignment"] == {"x1": 1, "x2": 2}
        assert r["cost"] == pytest.approx(-4.0)


class TestDynamicMaxsumFunctionSwap:
    """Ref dmaxsum_graphcoloring.py: the 3-ary all-different factor r1
    swaps between scopes (v1,v2,v3) and (v1,v2,v4) every two seconds.
    Edge ids must stay static across a warm session, so the swap is
    expressed on the UNION scope (v1..v4) as a function change that
    ignores the inactive variable — the same device-visible dynamics
    (documented deviation: scope-changing swaps recompile topology; the
    reference's own runner rebuilds factor links too)."""

    def test_five_swaps_track_expected_assignments(self):
        from pydcop_tpu.algorithms.maxsum_dynamic import DynamicMaxSum
        from pydcop_tpu.dcop.relations import NAryFunctionRelation

        colors = Domain("colors", "color", ["R", "G", "B"])
        v1, v2, v3, v4 = (Variable(f"v{i}", colors) for i in range(1, 5))

        def allin(a, b, c):
            return 0 if (a != b and b != c and a != c) else 100

        r1_v123 = NAryFunctionRelation(
            lambda v1, v2, v3, v4: allin(v1, v2, v3),
            [v1, v2, v3, v4], name="r1",
        )
        r1_v124 = NAryFunctionRelation(
            lambda v1, v2, v3, v4: allin(v1, v2, v4),
            [v1, v2, v3, v4], name="r1",
        )
        dcop = DCOP("dmaxsum_swap")
        for v, pref in ((v1, "R"), (v2, "G"), (v3, "B"), (v4, "R")):
            dcop += constraint_from_str(
                f"pref_{v.name}", f"0 if {v.name} == '{pref}' else 5", [v]
            )
        dcop += r1_v123
        dcop += constraint_from_str("r2", "0 if v2 != v4 else 100", [v2, v4])
        dcop += constraint_from_str("r3", "0 if v3 != v4 else 100", [v3, v4])
        dcop.add_agents([AgentDef(f"a{i}") for i in range(1, 4)])

        session = DynamicMaxSum(dcop, params={"noise": 0.001})
        # the reference's own expected assignments per active function
        expected = {
            "r1_v123": {"v1": "R", "v2": "G", "v3": "B", "v4": "R"},
            "r1_v124": {"v1": "B", "v2": "G", "v3": "B", "v4": "R"},
        }
        fns = [("r1_v123", r1_v123), ("r1_v124", r1_v124)]
        cur = 0
        for i in range(5):
            vals = session.run(50).assignment
            assert vals == expected[fns[cur][0]], (i, fns[cur][0], vals)
            cur = 1 - cur
            session.change_factor_function("r1", fns[cur][1])
