"""Composed end-to-end scenarios ported from the reference's integration
suite (round-4 verdict item 5/missing-4): the *mechanisms* (dynamic
sessions, external variables, n-ary DPOP, multi-computation agents) are
covered by unit/api tests; these reproduce the reference's full composed
scenarios and assert the same final assignments.

- smartlights, multiple computations per agent
  (ref tests/integration/maxsum_smartlights_multiplecomputationagent.py)
- dynamic MaxSum graph coloring gated by an external variable
  (ref tests/integration/dmaxsum_external_variable.py)
- DPOP with one 4-ary relation over 4 variables
  (ref tests/integration/dpop_nonbinaryrelation_4vars.py)
"""

import pytest

from pydcop_tpu.api import solve_result
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import (
    AgentDef,
    Domain,
    ExternalVariable,
    Variable,
)
from pydcop_tpu.dcop.relations import (
    ConditionalRelation,
    NAryFunctionRelation,
    UnaryBooleanRelation,
    constraint_from_str,
)


def smartlights_dcop() -> DCOP:
    """3 dimmable lights (0-9), a scene variable y1 = round(mean
    luminosity) enforced by a hard 4-ary constraint, and a rule pushing
    y1 toward 5 with l3 off (ref scenario lines 49-106: energy costs
    0.5*l1, l2, l3; rule 10*(|y1-5| + l3))."""
    d10 = Domain("lum", "", list(range(10)))
    l1, l2, l3, y1 = (Variable(n, d10) for n in ("l1", "l2", "l3", "y1"))
    dcop = DCOP("smartlights")
    dcop += constraint_from_str("cost_l1", "0.5 * l1", [l1])
    dcop += constraint_from_str("cost_l2", "l2", [l2])
    dcop += constraint_from_str("cost_l3", "l3", [l3])
    dcop += constraint_from_str(
        "scene_rel",
        "0 if y1 == round(l1/3 + l2/3 + l3/3) else 10000",
        [l1, l2, l3, y1],
    )
    dcop += constraint_from_str("rule_rel", "10 * (abs(y1 - 5) + l3)", [l3, y1])
    # three physical bulb nodes hosting 9 computations between them, as in
    # the reference's MultipleComputationAgent deployment
    dcop.add_agents([AgentDef(f"bulb{i}") for i in range(1, 4)])
    return dcop


# the reference scenario's unique optimum (asserted verbatim there,
# maxsum_smartlights_multiplecomputationagent.py:155)
SMARTLIGHTS_OPTIMUM = {"l1": 9, "l2": 5, "l3": 0, "y1": 5}


class TestSmartlightsMultipleComputationAgents:
    def test_amaxsum_api(self):
        r = solve_result(smartlights_dcop(), "amaxsum", n_cycles=100, seed=0)
        assert r["assignment"] == SMARTLIGHTS_OPTIMUM
        assert r["violation"] == 0
        assert r["cost"] == pytest.approx(9.5)

    def test_amaxsum_through_runtime_with_multi_computation_agents(self):
        # the composed scenario proper: orchestrator + 3 agents, each
        # hosting several of the 9 computations (adhoc distribution),
        # solved through the full runtime path
        from pydcop_tpu.infrastructure.run import solve as runtime_solve

        assignment = runtime_solve(
            smartlights_dcop(), "amaxsum", "adhoc", n_cycles=100
        )
        assert assignment == SMARTLIGHTS_OPTIMUM

    def test_maxsum_agrees(self):
        r = solve_result(smartlights_dcop(), "maxsum", n_cycles=100, seed=0)
        assert r["assignment"] == SMARTLIGHTS_OPTIMUM


class TestDynamicMaxsumExternalVariable:
    """Graph coloring with a boolean external variable e1 gating the
    3-ary all-different constraint r1 (ref scenario lines 41-64): with e1
    false every variable takes its preferred color; with e1 true v2/v3
    cannot both be 'B' and exactly one of them yields (the reference
    flips e1 five times and checks the active constraints after each)."""

    def _dcop(self):
        colors = Domain("colors", "color", ["R", "G", "B"])
        v1, v2, v3, v4 = (Variable(f"v{i}", colors) for i in range(1, 5))
        booleans = Domain("boolean", "abstract", [0, 1])
        e1 = ExternalVariable("e1", booleans, value=0)
        dcop = DCOP("dmaxsum_ext")
        dcop.add_variable(e1)
        for v, pref in ((v1, "R"), (v2, "B"), (v3, "B"), (v4, "R")):
            dcop += constraint_from_str(
                f"pref_{v.name}", f"0 if {v.name} == '{pref}' else 5", [v]
            )
        dcop += ConditionalRelation(
            UnaryBooleanRelation("r1_cond", e1),
            NAryFunctionRelation(
                lambda v1, v2, v3: (
                    0 if (v1 != v2 and v2 != v3 and v1 != v3) else 100
                ),
                [v1, v2, v3],
                name="r1",
            ),
            name="r1",
        )
        dcop += constraint_from_str("r2", "0 if v2 != v4 else 100", [v2, v4])
        dcop += constraint_from_str("r3", "0 if v3 != v4 else 100", [v3, v4])
        dcop.add_agents([AgentDef(f"a{i}") for i in range(1, 5)])
        return dcop, e1

    def test_five_toggles_keep_active_constraints_satisfied(self):
        from pydcop_tpu.algorithms.maxsum_dynamic import DynamicMaxSum

        dcop, e1 = self._dcop()
        session = DynamicMaxSum(dcop, params={"noise": 0.001})
        for i in range(5):
            vals = session.run(40).assignment
            assert vals["v2"] != vals["v4"], (i, vals)  # r2
            assert vals["v3"] != vals["v4"], (i, vals)  # r3
            if e1.value:
                # r1 active: v1/v2/v3 all different
                assert len({vals["v1"], vals["v2"], vals["v3"]}) == 3, (
                    i, vals,
                )
            else:
                # r1 inactive: everyone takes the preferred color
                assert vals == {
                    "v1": "R", "v2": "B", "v3": "B", "v4": "R"
                }, (i, vals)
            e1.value = 1 - e1.value  # subscription re-lowers r1's tables


class TestDpopNonBinary4Vars:
    """One 4-ary relation |10 - sum| over four 0-9 variables plus unary
    preference windows (ref scenario lines 55-129).  The optimum cost is
    0 (all preferences satisfied, sum exactly 10); tie-break among the
    cost-0 assignments is implementation-defined, so the semantic success
    condition is asserted plus our deterministic pick."""

    def _dcop(self):
        d10 = Domain("lum", "", list(range(10)))
        xs = [Variable(f"x{i}", d10) for i in range(4)]
        dcop = DCOP("nonbinary4")
        dcop += constraint_from_str("x0_prefs", "0 if x0 > 3 else 10", [xs[0]])
        dcop += constraint_from_str(
            "x1_prefs", "0 if 2 < x1 < 7 else 10", [xs[1]]
        )
        dcop += constraint_from_str("x2_prefs", "0 if x2 < 5 else 10", [xs[2]])
        dcop += constraint_from_str(
            "x3_prefs", "0 if 0 < x3 < 5 else 10", [xs[3]]
        )
        dcop += constraint_from_str(
            "four_ary", "abs(10 - (x0 + x1 + x2 + x3))", xs
        )
        dcop.add_agents([AgentDef(f"a{i}") for i in range(4)])
        return dcop

    def test_dpop_reaches_zero_cost_optimum(self):
        r = solve_result(self._dcop(), "dpop", n_cycles=1)
        a = r["assignment"]
        assert r["cost"] == 0.0 and r["violation"] == 0
        # preference windows + exact sum, the reference's success
        # condition modulo tie-break (its pick {x0:4, x1:3, x2:0, x3:3}
        # is another of the cost-0 optima)
        assert a["x0"] > 3 and 2 < a["x1"] < 7 and a["x2"] < 5
        assert 0 < a["x3"] < 5
        assert sum(a.values()) == 10
        # deterministic on this framework: pin the exact pick so any
        # tie-break change is a conscious one.  (No cross-solver check:
        # syncbb/ncbb are binary-only like the reference's, and cost 0
        # over nonnegative constraints is optimal by construction.)
        assert a == {"x0": 4, "x1": 5, "x2": 0, "x3": 1}
