"""Computation-graph depth tests, modeled on the reference's pseudotree
coverage (/root/reference/tests/unit/test_graph_pseudotree.py, ~490 LoC):
DFS tree shape on chains/cycles, pseudo-parent classification of back
edges, the lowest-node constraint-attachment rule, roots/levels, and the
density metrics of every graph model."""

import pytest

pytest.importorskip("jax")

from pydcop_tpu.computations_graph import (  # noqa: E402
    constraints_hypergraph as chg,
)
from pydcop_tpu.computations_graph import factor_graph as fg  # noqa: E402
from pydcop_tpu.computations_graph import ordered_graph as og  # noqa: E402
from pydcop_tpu.computations_graph import pseudotree as pt  # noqa: E402
from pydcop_tpu.dcop.objects import Domain, Variable  # noqa: E402
from pydcop_tpu.dcop.relations import constraint_from_str  # noqa: E402


def _vars(names):
    d = Domain("d", "", [0, 1, 2])
    return {n: Variable(n, d) for n in names}


def _chain(names):
    vs = _vars(names)
    cons = [
        constraint_from_str(
            f"c{a}{b}", f"{a} + {b}", [vs[a], vs[b]]
        )
        for a, b in zip(names, names[1:])
    ]
    return vs, cons


class TestPseudoTree:
    def test_single_var(self):
        vs = _vars(["x"])
        tree = pt.build_computation_graph(
            variables=vs.values(), constraints=[]
        )
        [node] = tree.nodes
        assert node.parent is None
        assert node.children == []
        assert tree.roots[0].name == "x"

    def test_two_var_chain(self):
        vs, cons = _chain(["x", "y"])
        tree = pt.build_computation_graph(
            variables=vs.values(), constraints=cons
        )
        by_name = {n.name: n for n in tree.nodes}
        root = tree.roots[0]
        child = by_name[{"x", "y"}.difference({root.name}).pop()]
        assert child.parent == root.name
        assert root.children == [child.name]
        assert child.pseudo_parents == []
        # lowest-node rule: the constraint sits on the child
        assert [c.name for c in child.constraints] == ["cxy"]
        assert root.constraints == []

    def test_3cycle_has_one_pseudo_parent(self):
        # a triangle: DFS tree is a chain, the back edge becomes a
        # pseudo-parent link (reference test_3nodes_tree_cycle:147)
        vs = _vars(["x", "y", "z"])
        cons = [
            constraint_from_str("cxy", "x + y", [vs["x"], vs["y"]]),
            constraint_from_str("cyz", "y + z", [vs["y"], vs["z"]]),
            constraint_from_str("czx", "z + x", [vs["z"], vs["x"]]),
        ]
        tree = pt.build_computation_graph(
            variables=vs.values(), constraints=cons
        )
        by_name = {n.name: n for n in tree.nodes}
        # exactly one node carries a pseudo-parent, and it is the deepest
        deepest = max(tree.nodes, key=lambda n: n.depth)
        assert deepest.depth == 2
        pseudo_nodes = [n for n in tree.nodes if n.pseudo_parents]
        assert [n.name for n in pseudo_nodes] == [deepest.name]
        pp = pseudo_nodes[0].pseudo_parents[0]
        assert deepest.name in by_name[pp].pseudo_children
        # every constraint attached at its DFS-lowest scope variable
        attach = {
            c.name: n.name for n in tree.nodes for c in n.constraints
        }
        assert len(attach) == 3
        assert sum(len(n.constraints) for n in tree.nodes) == 3
        # the deepest node sees both of its constraints
        assert len(by_name[deepest.name].constraints) == 2

    def test_3ary_constraint_attaches_once_at_lowest(self):
        vs = _vars(["x", "y", "z"])
        c3 = constraint_from_str(
            "cxyz", "x + y + z", [vs["x"], vs["y"], vs["z"]]
        )
        tree = pt.build_computation_graph(
            variables=vs.values(), constraints=[c3]
        )
        holders = [n for n in tree.nodes if n.constraints]
        assert len(holders) == 1
        assert holders[0].depth == max(n.depth for n in tree.nodes)

    def test_every_edge_is_tree_or_pseudo(self):
        # structural invariant of a DFS pseudo-tree: every constraint edge
        # connects a node to an ancestor/descendant, never across branches
        import random

        random.seed(8)
        names = [f"v{i}" for i in range(10)]
        vs = _vars(names)
        cons = []
        for k in range(14):
            a, b = random.sample(names, 2)
            cons.append(
                constraint_from_str(f"c{k}", f"{a} + {b}", [vs[a], vs[b]])
            )
        tree = pt.build_computation_graph(
            variables=vs.values(), constraints=cons
        )
        by_name = {n.name: n for n in tree.nodes}

        def ancestors(n):
            out = set()
            p = by_name[n].parent
            while p is not None:
                out.add(p)
                p = by_name[p].parent
            return out

        for c in cons:
            a, b = (v.name for v in c.dimensions)
            assert (
                a in ancestors(b) or b in ancestors(a)
            ), f"{c.name} crosses branches"

    def test_levels_partition_by_depth(self):
        vs, cons = _chain(["a", "b", "c", "d"])
        tree = pt.build_computation_graph(
            variables=vs.values(), constraints=cons
        )
        levels = tree.levels()
        # the max-degree root heuristic roots mid-chain: whatever the
        # shape, levels must partition all nodes and group them by depth
        assert sum(len(lv) for lv in levels) == 4
        for depth, lv in enumerate(levels):
            assert all(n.depth == depth for n in lv)
        # chain: one root, everything else hangs off it contiguously
        assert len(levels[0]) == 1

    def test_forest_has_one_root_per_component(self):
        vs = _vars(["x", "y", "p", "q"])
        cons = [
            constraint_from_str("c1", "x + y", [vs["x"], vs["y"]]),
            constraint_from_str("c2", "p + q", [vs["p"], vs["q"]]),
        ]
        tree = pt.build_computation_graph(
            variables=vs.values(), constraints=cons
        )
        assert len(tree.roots) == 2

    def test_deterministic(self):
        vs, cons = _chain(["a", "b", "c"])
        t1 = pt.build_computation_graph(
            variables=vs.values(), constraints=cons
        )
        t2 = pt.build_computation_graph(
            variables=vs.values(), constraints=cons
        )
        assert [(n.name, n.parent) for n in t1.nodes] == [
            (n.name, n.parent) for n in t2.nodes
        ]


class TestOrderedGraph:
    def test_lexical_chain(self):
        vs, cons = _chain(["b", "a", "c"])
        g = og.build_computation_graph(
            variables=vs.values(), constraints=cons
        )
        names = [n.name for n in g.ordered_nodes()]
        assert names == sorted(names)


class TestDensityMetrics:
    """Reference TestMetrics (test_graph_pseudotree.py:478) across models."""

    def _two_var_one_constraint(self):
        vs, cons = _chain(["x", "y"])
        return vs, cons

    def test_factor_graph_density(self):
        vs, cons = self._two_var_one_constraint()
        g = fg.build_computation_graph(
            variables=vs.values(), constraints=cons
        )
        # bipartite: 2 edges / (2 vars * 1 factor)
        assert g.density() == pytest.approx(1.0)

    def test_hypergraph_density(self):
        vs, cons = self._two_var_one_constraint()
        g = chg.build_computation_graph(
            variables=vs.values(), constraints=cons
        )
        assert 0 < g.density() <= 1.0
