"""graftdur: checkpoint/resume wired end-to-end (docs/durability.md).

The load-bearing pin is BIT-IDENTITY: a solve killed mid-run and resumed
from a checkpoint must finish with the bitwise-identical final values,
cost and cycles_to_best of the uninterrupted seeded run — on the fused
reference path and the chunked engine alike.  Seeded per-cycle keys
(``fold_in(key, absolute_cycle)``) make this exact, not approximate.
"""

import glob
import json
import os

import numpy as np
import pytest

from pydcop_tpu.algorithms import dsa, maxsum
from pydcop_tpu.commands.generators.graphcoloring import (
    generate_coloring_arrays,
)
from pydcop_tpu.durability import (
    CheckpointManager,
    default_checkpoint_dir,
    durability,
    latest_checkpoint,
    list_manifests,
    problem_fingerprint,
    read_manifest,
    resolve_checkpoint_path,
)
from pydcop_tpu.utils.checkpoint import (
    CheckpointError,
    load_checkpoint,
    save_checkpoint,
)


@pytest.fixture(autouse=True)
def _clean_singleton():
    """Every test starts and ends with durability off — a leaked manager
    would silently re-route other tests onto the chunked engine."""
    durability.reset()
    yield
    durability.reset()


@pytest.fixture(scope="module")
def problem():
    return generate_coloring_arrays(
        200, 3, graph="scalefree", m_edge=2, seed=11
    )


def _checkpointed_solve(mod, compiled, tmp, *, params=None, n_cycles=48,
                        seed=3, every=12, keep=50, timeout=None, **kw):
    mgr = CheckpointManager(str(tmp), every_cycles=every, keep=keep)
    durability.configure(manager=mgr)
    try:
        r = mod.solve(
            compiled, dict(params or {}), n_cycles=n_cycles, seed=seed,
            timeout=timeout, **kw,
        )
    finally:
        durability.reset()
    return r, mgr


def _resumed_solve(mod, compiled, path, *, params=None, n_cycles=48,
                   seed=3, **kw):
    durability.configure(resume=str(path))
    try:
        return mod.solve(
            compiled, dict(params or {}), n_cycles=n_cycles, seed=seed,
            **kw,
        )
    finally:
        durability.reset()


class TestKillResumeBitIdentity:
    """The acceptance pin: resume == uninterrupted, bitwise."""

    def test_dsa_resume_matches_fused(self, problem, tmp_path):
        ref = dsa.solve(problem, {}, n_cycles=48, seed=3)  # fused path
        r_ck, mgr = _checkpointed_solve(dsa, problem, tmp_path)
        assert r_ck.cost == ref.cost
        assert r_ck.assignment == ref.assignment
        assert len(mgr.saved_paths) == 4  # cycles 12, 24, 36, 48
        # resume from EVERY intermediate checkpoint: each must land on
        # the identical end state
        for path in mgr.saved_paths[:-1]:
            r = _resumed_solve(dsa, problem, path)
            assert r.cost == ref.cost
            assert r.assignment == ref.assignment
            assert r.cycles == ref.cycles

    def test_dsa_resume_matches_chunked(self, problem, tmp_path):
        # uninterrupted CHUNKED run (timeout path) as the reference
        ref = dsa.solve(problem, {}, n_cycles=48, seed=3, timeout=600)
        _, mgr = _checkpointed_solve(dsa, problem, tmp_path)
        r = _resumed_solve(dsa, problem, mgr.saved_paths[1])
        assert r.cost == ref.cost
        assert r.assignment == ref.assignment

    def test_maxsum_with_noise_resume(self, problem, tmp_path):
        # in-program tie-breaking noise: the resumed run re-derives the
        # identical noise stream from (seed, draw shape) — nothing about
        # the noise is stored in the checkpoint
        params = {"damping": 0.5, "noise": 0.01, "stop_cycle": 40}
        ref = maxsum.solve(problem, dict(params), n_cycles=40, seed=7)
        _, mgr = _checkpointed_solve(
            maxsum, problem, tmp_path, params=params, n_cycles=40,
            seed=7, every=10,
        )
        mid = os.path.join(str(tmp_path), "ckpt-c000000020.npz")
        r = _resumed_solve(
            maxsum, problem, mid, params=params, n_cycles=40, seed=7
        )
        assert r.cost == ref.cost
        assert r.assignment == ref.assignment

    def test_cycles_to_best_exact_across_resume(self, problem, tmp_path):
        ref = dsa.solve(problem, {}, n_cycles=48, seed=3)
        _, mgr = _checkpointed_solve(dsa, problem, tmp_path)
        r = _resumed_solve(dsa, problem, mgr.saved_paths[0])
        # SolveResult has no cycles_to_best; pin it at the extras level
        from pydcop_tpu.algorithms.base import run_cycles, extract_values
        from pydcop_tpu.algorithms.dsa import _init, _make_step, _consts
        from pydcop_tpu.compile.kernels import to_device

        dev = to_device(problem)
        consts = _consts(
            problem,
            {"probability": 0.7, "p_mode": "fixed", "variant": "B",
             "stop_cycle": 0},
            dev,
        )
        _, _, ex_ref = run_cycles(
            problem, _init, _make_step("B"), extract_values,
            n_cycles=48, seed=3, dev=dev, consts=consts,
            return_final=False,
        )
        durability.configure(resume=mgr.saved_paths[0])
        try:
            _, _, ex_res = run_cycles(
                problem, _init, _make_step("B"), extract_values,
                n_cycles=48, seed=3, dev=dev, consts=consts,
                return_final=False,
            )
        finally:
            durability.reset()
        assert ex_res["cycles_to_best"] == ex_ref["cycles_to_best"]
        assert ex_res["best_cost"] == ex_ref["best_cost"]
        assert np.array_equal(
            ex_res["best_values"], ex_ref["best_values"]
        )
        assert ex_res["resumed_from"] == 12
        assert r.cost == ref.cost

    def test_resume_at_or_past_target_returns_checkpoint_state(
        self, problem, tmp_path
    ):
        ref = dsa.solve(problem, {}, n_cycles=24, seed=3)
        _, mgr = _checkpointed_solve(
            dsa, problem, tmp_path, n_cycles=24, every=12
        )
        # resume the FINAL checkpoint against the same target: zero
        # cycles left; the restored best must come through untouched
        r = _resumed_solve(dsa, problem, mgr.saved_paths[-1], n_cycles=24)
        assert r.cost == ref.cost
        assert r.assignment == ref.assignment


class TestRefusals:
    """A checkpoint refuses a mismatched problem LOUDLY, naming its own
    fingerprint + algorithm."""

    def test_different_problem_refused(self, problem, tmp_path):
        _, mgr = _checkpointed_solve(dsa, problem, tmp_path)
        other = generate_coloring_arrays(
            200, 3, graph="scalefree", m_edge=2, seed=99
        )
        durability.configure(resume=mgr.saved_paths[0])
        try:
            with pytest.raises(CheckpointError) as ei:
                dsa.solve(other, {}, n_cycles=48, seed=3)
        finally:
            durability.reset()
        msg = str(ei.value)
        assert "DIFFERENT problem" in msg
        assert problem_fingerprint(problem) in msg
        assert "dsa" in msg

    def test_different_algo_refused(self, problem, tmp_path):
        _, mgr = _checkpointed_solve(dsa, problem, tmp_path)
        with pytest.raises(CheckpointError, match="algorithm 'dsa'"):
            _resumed_solve(maxsum, problem, mgr.saved_paths[0],
                           params={"stop_cycle": 48})

    def test_different_seed_refused(self, problem, tmp_path):
        _, mgr = _checkpointed_solve(dsa, problem, tmp_path, seed=3)
        with pytest.raises(CheckpointError, match="seed"):
            _resumed_solve(dsa, problem, mgr.saved_paths[0], seed=4)

    def test_leaf_mismatch_error_names_checkpoint_identity(self, tmp_path):
        # satellite: the raw load_checkpoint leaf-mismatch path must
        # carry the manifest's fingerprint + algo so 'leaf 0 mismatch'
        # is attributable without opening the file
        p = str(tmp_path / "c.npz")
        save_checkpoint(
            p, {"a": np.zeros((4, 3))},
            metadata={"algo": "maxsum", "fingerprint": "deadbeef01020304",
                      "n_vars": 4},
        )
        with pytest.raises(CheckpointError) as ei:
            load_checkpoint(p, like={"a": np.zeros((5, 3))})
        msg = str(ei.value)
        assert "deadbeef01020304" in msg
        assert "maxsum" in msg

    def test_resolve_missing_path(self, tmp_path):
        with pytest.raises(CheckpointError, match="no such checkpoint"):
            resolve_checkpoint_path(str(tmp_path / "nope.npz"))
        with pytest.raises(CheckpointError, match="no checkpoint"):
            resolve_checkpoint_path(str(tmp_path))


class TestManagerMechanics:
    def test_cadence_every_cycles(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), every_cycles=16)
        assert mgr.cycles_to_boundary(0) == 16
        assert mgr.cycles_to_boundary(5) == 11
        assert mgr.cycles_to_boundary(16) == 16
        assert not mgr.due(0)
        assert mgr.due(16)
        assert not mgr.due(17)

    def test_cadence_every_seconds(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), every_seconds=0.0)
        assert mgr.cycles_to_boundary(7) is None
        assert mgr.due(3)  # 0 s elapsed since bind >= 0 s cadence

    def test_rotation_keep_last_n(self, problem, tmp_path):
        _, mgr = _checkpointed_solve(
            dsa, problem, tmp_path, every=12, keep=2
        )
        files = sorted(glob.glob(str(tmp_path / "*.npz")))
        assert [os.path.basename(f) for f in files] == [
            "ckpt-c000000036.npz", "ckpt-c000000048.npz",
        ]
        # sidecars rotate with their payloads
        assert len(glob.glob(str(tmp_path / "*.json"))) == 2

    def test_manifest_contents(self, problem, tmp_path):
        _, mgr = _checkpointed_solve(
            dsa, problem, tmp_path, n_cycles=24, every=12, seed=5
        )
        man = read_manifest(mgr.saved_paths[0])
        assert man["format"] == "graftdur-v1"
        assert man["algo"] == "dsa"
        assert man["seed"] == 5
        assert man["cycle"] == 12
        assert man["n_cycles"] == 24
        assert man["fingerprint"] == problem_fingerprint(problem)
        assert "best_cost" in man and "cycles_to_best" in man
        assert man["extra"]["has_pulse"] is False

    def test_list_latest_prune(self, problem, tmp_path):
        _, mgr = _checkpointed_solve(dsa, problem, tmp_path, every=12)
        mans = list_manifests(str(tmp_path))
        assert [m["cycle"] for m in mans] == [12, 24, 36, 48]
        assert all(m["bytes"] > 0 for m in mans)
        latest = latest_checkpoint(str(tmp_path))
        assert latest.endswith("ckpt-c000000048.npz")
        assert resolve_checkpoint_path(str(tmp_path)) == latest
        removed = CheckpointManager(str(tmp_path)).prune(keep=1)
        assert removed == 3
        assert len(list_manifests(str(tmp_path))) == 1

    def test_fingerprint_distinguishes_tables(self):
        a = generate_coloring_arrays(50, 3, graph="random",
                                     p_edge=0.05, seed=1)
        b = generate_coloring_arrays(50, 3, graph="random",
                                     p_edge=0.05, seed=2)
        assert problem_fingerprint(a) != problem_fingerprint(b)
        # stable across calls (cached on the compiled object)
        assert problem_fingerprint(a) == problem_fingerprint(a)

    def test_default_dir_under_state_dir(self, monkeypatch, tmp_path):
        monkeypatch.setenv("PYDCOP_TPU_STATE_DIR", str(tmp_path))
        assert default_checkpoint_dir() == str(tmp_path / "checkpoints")
        mgr = CheckpointManager(None)
        assert mgr.directory == str(tmp_path / "checkpoints")

    def test_durability_status_block(self, tmp_path):
        assert durability.status_block() is None
        mgr = CheckpointManager(str(tmp_path), every_cycles=8)
        durability.configure(manager=mgr)
        durability.note_extra(scenario_cursor=2)
        blk = durability.status_block()
        assert blk["directory"] == str(tmp_path)
        assert blk["every_cycles"] == 8
        assert blk["extra"]["scenario_cursor"] == 2
        durability.reset()
        assert durability.status_block() is None

    def test_take_resume_is_consumed_once(self, tmp_path):
        durability.configure(resume="x")
        assert durability.take_resume() == "x"
        assert durability.take_resume() is None

    def test_manager_claimed_by_first_problem(self, problem, tmp_path):
        # regression: a thread-runtime scenario removal repairs via an
        # MGM-2 solve of the REPAIR DCOP through the same run_cycles —
        # before the claim rule its snapshots overwrote the main solve's
        # trail under the same cycle filenames (caught driving the run
        # verb end-to-end)
        other = generate_coloring_arrays(
            60, 3, graph="random", p_edge=0.05, seed=42
        )
        mgr = CheckpointManager(str(tmp_path), every_cycles=12, keep=50)
        assert mgr.bind(problem, "dsa", 3, 0.0, 48)
        assert not mgr.bind(other, "mgm2", 0, 0.0, 48)  # refused
        assert mgr.bind(problem, "dsa", 3, 0.0, 48)  # same problem ok
        # through the solve path: the aux solve writes NOTHING
        durability.configure(manager=mgr)
        try:
            dsa.solve(problem, {}, n_cycles=48, seed=3)
            from pydcop_tpu.algorithms import mgm2

            mgm2.solve(other, {}, n_cycles=48, seed=0)
        finally:
            durability.reset()
        for man in list_manifests(str(tmp_path)):
            assert man["algo"] == "dsa"
            assert man["fingerprint"] == problem_fingerprint(problem)
        # rebind (the replay driver's factor swaps) adopts the new one
        mgr.rebind(other, "maxsum_dynamic", 0, 0.0, 10)
        assert not mgr.bind(problem, "dsa", 3, 0.0, 48)


class TestPulseCarryAcrossResume:
    def test_pulse_flip_counters_survive_resume(self, problem, tmp_path):
        from pydcop_tpu.telemetry.pulse import pulse

        pulse.reset()
        pulse.enabled = True
        try:
            ref = dsa.solve(problem, {}, n_cycles=48, seed=3)
            ref_flips = pulse.last_report["flip_summary"]
            _, mgr = _checkpointed_solve(dsa, problem, tmp_path)
            man = read_manifest(mgr.saved_paths[1])
            assert man["extra"]["has_pulse"] is True
            r = _resumed_solve(dsa, problem, mgr.saved_paths[1])
            res_flips = pulse.last_report["flip_summary"]
            assert r.cost == ref.cost
            # flip counters are part of the carry: the resumed run's
            # totals equal the uninterrupted run's, not just its tail
            assert res_flips == ref_flips
        finally:
            pulse.enabled = False
            pulse.reset()

    def test_flight_recorder_ring_survives_resume(self, problem, tmp_path):
        # a postmortem right after resume must show the PRE-KILL health
        # history: the checkpoint carries the recorder's ring and the
        # resume refills it before the first resumed chunk publishes
        from pydcop_tpu.telemetry.pulse import pulse

        pulse.reset()
        pulse.enabled = True
        try:
            _, mgr = _checkpointed_solve(dsa, problem, tmp_path)
            man = read_manifest(mgr.saved_paths[1])  # cycle 24
            assert man["extra"]["pulse_ring"]
            assert (
                man["extra"]["pulse_ring_start"]
                + len(man["extra"]["pulse_ring"]) == 24
            )
            pulse.reset()  # fresh process stands in for the resumed one
            pulse.enabled = True
            durability.configure(resume=mgr.saved_paths[1])
            try:
                dsa.solve(problem, {}, n_cycles=48, seed=3)
            finally:
                durability.reset()
            rows, start = pulse.recorder.ring()
            # ring covers pre-kill + resumed cycles contiguously
            assert start + len(rows) == 48
            assert len(rows) == 48
        finally:
            pulse.enabled = False
            pulse.reset()

    def test_pulse_off_resume_of_pulse_on_checkpoint(
        self, problem, tmp_path
    ):
        from pydcop_tpu.telemetry.pulse import pulse

        pulse.reset()
        pulse.enabled = True
        try:
            _, mgr = _checkpointed_solve(dsa, problem, tmp_path)
        finally:
            pulse.enabled = False
        ref = dsa.solve(problem, {}, n_cycles=48, seed=3)
        r = _resumed_solve(dsa, problem, mgr.saved_paths[0])
        assert r.cost == ref.cost
        assert r.assignment == ref.assignment


class TestOrbaxDelegation:
    """The use_orbax=True branch: orbax owns the array payload, the
    metadata rides a sidecar, and load_checkpoint round-trips both."""

    orbax = pytest.importorskip("orbax.checkpoint")

    def test_orbax_roundtrip_with_metadata(self, tmp_path):
        state = {
            "a": np.arange(12, dtype=np.float32).reshape(3, 4),
            "b": np.ones(5, dtype=np.int32),
        }
        p = str(tmp_path / "orbax_ckpt")
        save_checkpoint(
            p, state, metadata={"algo": "dsa", "cycle": 7},
            use_orbax=True,
        )
        assert os.path.isdir(p)  # orbax writes a directory
        like = {"a": np.zeros((3, 4), np.float32),
                "b": np.zeros(5, np.int32)}
        restored, meta = load_checkpoint(p, like=like)
        assert meta == {"algo": "dsa", "cycle": 7}
        np.testing.assert_array_equal(restored["a"], state["a"])
        np.testing.assert_array_equal(restored["b"], state["b"])

    def test_orbax_leaf_mismatch_refuses(self, tmp_path):
        p = str(tmp_path / "orbax_ckpt2")
        save_checkpoint(
            p, {"a": np.zeros((2, 2), np.float32)},
            metadata={"algo": "maxsum", "fingerprint": "feedface"},
            use_orbax=True,
        )
        with pytest.raises(CheckpointError) as ei:
            load_checkpoint(p, like={"a": np.zeros((3, 2), np.float32)})
        assert "feedface" in str(ei.value)


class TestShardedCheckpoint:
    """Mesh-sharded DeviceDCOP durability: snapshots gather to host,
    restore re-places the carry on the mesh (template shardings /
    ``mesh.shard_on_axis``) — sharded resumed solves stay cost-bit-
    identical to the single-device run."""

    @staticmethod
    def _sharded(compiled):
        from pydcop_tpu.compile.kernels import to_device
        from pydcop_tpu.parallel.mesh import (
            make_mesh,
            pad_device_dcop,
            shard_device_dcop,
        )

        mesh = make_mesh(8)
        return shard_device_dcop(
            pad_device_dcop(to_device(compiled), mesh.size), mesh
        ), mesh

    def test_sharded_checkpoint_resume_cost_identical(self, tmp_path):
        compiled = generate_coloring_arrays(
            96, 3, graph="scalefree", m_edge=2, seed=5
        )
        sharded, _mesh = self._sharded(compiled)
        p = {"layout": "ell", "noise": 0.0, "damping": 0.5,
             "stop_cycle": 16}
        ref = maxsum.solve(
            compiled, dict(p), n_cycles=16, seed=0, dev=sharded
        )
        _, mgr = _checkpointed_solve(
            maxsum, compiled, tmp_path, params=p, n_cycles=16, seed=0,
            every=4, dev=sharded,
        )
        r = _resumed_solve(
            maxsum, compiled, os.path.join(str(tmp_path),
                                           "ckpt-c000000008.npz"),
            params=p, n_cycles=16, seed=0, dev=sharded,
        )
        assert r.cost == ref.cost
        assert r.assignment == ref.assignment

    def test_restored_leaves_are_resharded(self, tmp_path):
        # the placement contract itself: a row-sharded array checkpointed
        # to host numpy comes back sharded over the same mesh axis via
        # mesh.shard_on_axis
        import jax.numpy as jnp

        from pydcop_tpu.parallel.mesh import make_mesh, shard_on_axis

        mesh = make_mesh(8)
        x = shard_on_axis(jnp.arange(64.0).reshape(16, 4), mesh, 0)
        save_checkpoint(str(tmp_path / "s.npz"), {"x": x})
        restored, _ = load_checkpoint(
            str(tmp_path / "s.npz"),
            like={"x": np.zeros((16, 4), np.float32)},
        )
        placed = shard_on_axis(jnp.asarray(restored["x"]), mesh, 0)
        assert placed.sharding.mesh.size == 8
        assert placed.sharding.spec[0] is not None
        np.testing.assert_array_equal(np.asarray(placed), np.asarray(x))


class TestScenarioReplay:
    """Replayable dynamic workloads (durability/replay.py): the event
    cursor + DynamicMaxSum state ride the manifests; a killed session
    resumes from ANY checkpoint onto the identical trajectory."""

    YAML = """
name: t
objective: min
domains: {d: {values: [0, 1, 2]}}
variables:
  v1: {domain: d}
  v2: {domain: d}
  v3: {domain: d}
constraints:
  c12: {type: intention, function: 1.0 if v1 == v2 else 0.0}
  c23: {type: intention, function: 1.0 if v2 == v3 else 0.0}
  c13: {type: intention, function: 0.5 if v1 == v3 else 0.0}
agents: [a1, a2, a3]
"""
    SCENARIO = """
events:
  - id: warm
    delay: 20
  - id: flip
    actions:
      - {type: swap_factor, constraint: c12,
         function: "3.0 if v1 != v2 else 0.0"}
  - id: settle
    delay: 20
  - id: flip2
    actions:
      - {type: swap_factor, constraint: c23,
         function: "2.0 if v2 != v3 else 0.1"}
  - id: finish
    delay: 15
"""

    def _fresh(self, tmp=None, keep=100):
        from pydcop_tpu.dcop.yamldcop import load_dcop, load_scenario
        from pydcop_tpu.durability.replay import ScenarioSession

        mgr = (
            CheckpointManager(str(tmp), keep=keep)
            if tmp is not None else None
        )
        return ScenarioSession(
            load_dcop(self.YAML), load_scenario(self.SCENARIO),
            params={"damping": 0.3}, seed=5, manager=mgr,
        )

    def test_replay_from_every_checkpoint(self, tmp_path):
        from pydcop_tpu.dcop.yamldcop import load_dcop, load_scenario
        from pydcop_tpu.durability.replay import ScenarioSession

        full = self._fresh(tmp_path)
        r_full = full.play()
        full.close()
        assert full.cursor == 5
        assert len(full.cost_trace) == 3
        mans = {
            m["extra"]["scenario_cursor"]: m["checkpoint_path"]
            for m in list_manifests(str(tmp_path))
        }
        assert mans  # action-event checkpoints overwrite same-cycle ones
        for cursor, path in mans.items():
            if cursor >= 5:
                continue
            sess = ScenarioSession.resume(
                load_dcop(self.YAML), load_scenario(self.SCENARIO),
                path, params={"damping": 0.3},
            )
            assert sess.cursor == cursor
            r = sess.play()
            assert r.cost == r_full.cost
            assert r.assignment == r_full.assignment
            n = len(sess.cost_trace)
            assert sess.cost_trace == full.cost_trace[-n:]
            sess.close()

    def test_manifest_speaks_session_dialect(self, tmp_path):
        sess = self._fresh(tmp_path)
        sess.play()
        sess.close()
        man = read_manifest(latest_checkpoint(str(tmp_path)))
        assert man["kind"] == "session"
        assert man["algo"] == "maxsum_dynamic"
        assert man["cycles_done"] == 55
        assert man["plane_layout"] in ("lanes", "edges")
        assert man["extra"]["scenario_cursor"] == 5

    def test_mutated_problem_fingerprint_refuses_wrong_dcop(
        self, tmp_path
    ):
        from pydcop_tpu.dcop.yamldcop import load_dcop, load_scenario
        from pydcop_tpu.durability.replay import ScenarioSession

        sess = self._fresh(tmp_path)
        sess.play()
        sess.close()
        other = self.YAML.replace(
            "0.5 if v1 == v3", "0.9 if v1 == v3"
        )
        with pytest.raises(CheckpointError, match="DIFFERENT problem"):
            ScenarioSession.resume(
                load_dcop(other), load_scenario(self.SCENARIO),
                latest_checkpoint(str(tmp_path)),
                params={"damping": 0.3},
            )

    def test_runtime_actions_rejected(self):
        from pydcop_tpu.dcop.yamldcop import load_dcop, load_scenario
        from pydcop_tpu.durability.replay import ScenarioSession

        bad = load_scenario(
            "events:\n  - id: x\n    actions:\n"
            "      - {type: remove_agent, agent: a1}\n"
        )
        sess = ScenarioSession(
            load_dcop(self.YAML), bad, params={"damping": 0.3}
        )
        with pytest.raises(ValueError, match="agent-runtime"):
            sess.play()
        sess.close()


class TestScenarioCursorRuntime:
    def test_play_scenario_publishes_cursor(self):
        # the orchestrator's wall-clock player notes the cursor into the
        # durability singleton after each event — that is what makes a
        # thread-runtime `run --scenario` checkpoint replayable
        from pydcop_tpu.dcop.scenario import DcopEvent, Scenario
        from pydcop_tpu.infrastructure.orchestrator import Orchestrator

        scenario = Scenario(
            [DcopEvent("e0", delay=0.0), DcopEvent("e1", delay=0.0)]
        )

        class _Bare:
            _play_scenario = Orchestrator._play_scenario

        _Bare()._play_scenario(scenario)
        extra = durability.runtime_extra()
        assert extra["scenario_cursor"] == 2
        assert extra["scenario_event"] == "e1"

    def test_cursor_stays_absolute_across_second_resume(self):
        # regression: a RESUMED run plays a SLICED scenario; without the
        # seeded base, its manifests would record cursors relative to
        # the slice and a second kill/resume would replay events onto
        # the already-mutated topology
        from pydcop_tpu.dcop.scenario import DcopEvent, Scenario
        from pydcop_tpu.infrastructure.orchestrator import Orchestrator

        class _Bare:
            _play_scenario = Orchestrator._play_scenario

        # commands/run.py seeds the base cursor after slicing events[3:]
        durability.note_extra(scenario_cursor=3)
        _Bare()._play_scenario(
            Scenario([DcopEvent("e3", delay=0.0), DcopEvent("e4", delay=0.0)])
        )
        assert durability.runtime_extra()["scenario_cursor"] == 5


class TestHostOnlySurface:
    def test_manager_import_is_jax_free(self):
        # the `checkpoints` verb contract: listing manifests must work
        # on a machine without jax (sidecar JSON only) — pin that the
        # durability import chain never pulls jax in a fresh interpreter
        import os
        import subprocess
        import sys

        env = dict(os.environ)
        # JAX_PLATFORMS=cpu makes the package __init__ itself pin the
        # backend (importing jax); the host-only contract is about a
        # plain interpreter
        env.pop("JAX_PLATFORMS", None)
        r = subprocess.run(
            [
                sys.executable, "-c",
                "import sys\n"
                "import pydcop_tpu.durability.manager as m\n"
                "assert 'jax' not in sys.modules, 'jax imported eagerly'\n"
                "m.list_manifests('.')\n"
                "assert 'jax' not in sys.modules\n"
                "print('ok')\n",
            ],
            capture_output=True, text=True, timeout=60,
            cwd="/root/repo", env=env,
        )
        assert r.returncode == 0, r.stderr
        assert "ok" in r.stdout


class TestWatchRendersDurability:
    def test_watch_durability_line(self, tmp_path):
        from pydcop_tpu.commands.watch import _render_frame

        durability.configure(
            manager=CheckpointManager(str(tmp_path), every_cycles=32)
        )
        durability.note_extra(scenario_cursor=3)
        durability.note_resumed({"cycle": 64}, "p")
        status = {
            "status": "running", "durability": durability.status_block(),
        }
        frame = _render_frame(status, {}, {})
        lines = [l for l in frame.splitlines() if "durability:" in l]
        assert len(lines) == 1
        assert str(tmp_path) in lines[0]
        assert "every=32cyc" in lines[0]
        assert "resumed@64" in lines[0]
        assert "scenario_cursor=3" in lines[0]
        # durability off -> no line
        assert "durability:" not in _render_frame(
            {"status": "running"}, {}, {}
        )


class TestServeFleetCheckpoint:
    def test_drain_writes_fleet_manifest(self, tmp_path):
        from pydcop_tpu.serve import ServeServer, SolveRequest

        srv = ServeServer(
            port=None, window_ms=5.0, max_batch=8,
            checkpoint_dir=str(tmp_path),
        )
        for i in range(3):
            srv.submit(
                SolveRequest(
                    f"t{i}",
                    generate_coloring_arrays(
                        9, 3, graph="grid", seed=100 + i
                    ),
                    "dsa", {}, 12, i,
                )
            )
        for i in range(3):
            srv.wait(f"t{i}", timeout=120)
        assert srv.shutdown(drain=True)
        path = srv.fleet_checkpoint_path
        assert path and os.path.exists(path)
        man = json.load(open(path))
        assert man["format"] == "graftdur-v1"
        assert man["kind"] == "fleet"
        assert man["state"] == "drained"
        assert man["solves"] == 3
        assert man["dead_letters"] == 0
        assert set(man["tenants"]) == {"t0", "t1", "t2"}
        for rec in man["tenants"].values():
            assert rec["status"] == "done"
            assert "cost" in rec and "assignment" in rec

    def test_no_checkpoint_dir_no_file(self, tmp_path):
        from pydcop_tpu.serve import ServeServer

        srv = ServeServer(port=None)
        assert srv.shutdown(drain=True)
        assert srv.fleet_checkpoint_path is None
