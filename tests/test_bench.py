"""Unit tests for bench.py's streaming watchdog parent.

The driver parses bench.py's stdout (headline config's line first, one
line per config), so the emit/hold-back ordering and the fallback
bookkeeping are contract, not detail.  The children and the backend probe
are faked; the real solve paths are covered by test_algorithms/test_cli.
"""

import importlib.util
import json
import os

import pytest


@pytest.fixture()
def bench():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _record(config, value=1.0, **extra):
    rec = {
        "metric": f"metric_{config}", "value": value, "unit": "s",
        "config": config,
    }
    rec.update(extra)
    return rec


def run_main(bench, monkeypatch, capsys, tpu_records, cpu_records,
             probe=("tpu", 1, None), tpu_error=None, cpu_error=None):
    """Drive bench.main() with faked children; return parsed stdout lines."""
    # one probe attempt only: the persistent window is covered separately
    monkeypatch.setenv("BENCH_PROBE_TOTAL_S", "0")
    calls = []

    def fake_run_child(flag, budget, configs, emit):
        calls.append((flag, list(configs)))
        table = tpu_records if flag == "--child" else cpu_records
        records = {}
        for key in configs:
            if key in table:
                records[key] = dict(table[key])
                emit(records[key])
        return records, (tpu_error if flag == "--child" else cpu_error)

    monkeypatch.setattr(bench, "_run_child", fake_run_child)

    class _Probe:
        @staticmethod
        def probe_backend(timeout_s, retries):
            return probe

    bench.main(_probe_module=_Probe)
    out = capsys.readouterr().out.strip().splitlines()
    return [json.loads(line) for line in out], calls


def test_headline_line_leads_and_all_configs_emit(
    bench, monkeypatch, capsys
):
    tpu = {k: _record(k) for k in bench.CONFIG_ORDER}
    lines, calls = run_main(bench, monkeypatch, capsys, tpu, {})
    assert [r["config"] for r in lines][0] == "4"
    assert sorted(r["config"] for r in lines) == sorted(bench.CONFIG_ORDER)
    # no fallback child when everything succeeded
    assert [flag for flag, _ in calls] == ["--child"]


def test_failed_headline_holds_later_configs_until_fallback(
    bench, monkeypatch, capsys
):
    # accelerator child: config 4 errors, the rest succeed
    tpu = {k: _record(k) for k in bench.CONFIG_ORDER}
    tpu["4"] = _record("4", value=None, error="boom")
    cpu = {"4": _record("4", value=2.0, device="cpu")}
    lines, calls = run_main(
        bench, monkeypatch, capsys, tpu, cpu, tpu_error=None,
    )
    # headline still first, filled by the CPU fallback
    assert lines[0]["config"] == "4"
    assert lines[0]["value"] == 2.0
    assert sorted(r["config"] for r in lines) == sorted(bench.CONFIG_ORDER)
    # the fallback only re-ran the missing config, not the held successes
    assert calls[1] == ("--child-cpu", ["4"])


def test_both_children_failing_reports_both_reasons(
    bench, monkeypatch, capsys
):
    lines, _ = run_main(
        bench, monkeypatch, capsys, {}, {},
        tpu_error="relay down", cpu_error="cpu exploded",
    )
    assert lines[0]["config"] == "4"
    for rec in lines:
        assert rec["value"] is None
        assert "relay down" in rec["error"]
        assert "cpu exploded" in rec["error"]


def test_vs_baseline_refused_off_tpu(monkeypatch):
    # a CPU fallback must never masquerade as the TPU headline: the
    # speedup field is withheld unless the record ran on a tpu device
    import bench_all

    monkeypatch.setitem(
        bench_all.CONFIGS, "4",
        lambda: {"metric": "m4", "value": 2.0, "device": "cpu"},
    )
    rec = bench_all.run_config("4")
    assert rec["vs_baseline"] is None
    assert "not claimed" in rec["vs_baseline_note"]

    monkeypatch.setitem(
        bench_all.CONFIGS, "4",
        lambda: {"metric": "m4", "value": 2.0, "device": "tpu"},
    )
    rec = bench_all.run_config("4")
    assert rec["vs_baseline"] == 5.0
    assert "vs_baseline_note" not in rec


def test_bench_records_achieved_bandwidth(monkeypatch):
    # with an analytic traffic model the record reports achieved GB/s
    # (and % of HBM peak only on a recognized TPU)
    import bench_all

    class _R:
        cost = 0.0
        violations = 0

    monkeypatch.setattr(
        bench_all, "_hbm_peak_gbps", lambda: 819.0
    )
    rec = bench_all._bench(
        "m", lambda: _R(), n_cycles=10, traffic_bytes=10_000_000
    )
    assert rec["achieved_gbps"] > 0
    assert rec["hbm_peak_pct"] == pytest.approx(
        100 * rec["achieved_gbps"] / 819.0, rel=0.02
    )


def test_probe_failure_skips_accelerator_child(bench, monkeypatch, capsys):
    cpu = {k: _record(k, device="cpu") for k in bench.CONFIG_ORDER}
    lines, calls = run_main(
        bench, monkeypatch, capsys, {}, cpu,
        probe=(None, 0, "probe timed out"),
    )
    assert [flag for flag, _ in calls] == ["--child-cpu"]
    assert lines[0]["config"] == "4"
    for rec in lines:
        assert "probe" in rec.get("error", "")


def test_persistent_probe_retries_until_relay_answers(bench, monkeypatch):
    # a flapping relay must not lose the round to one bad sample: the gate
    # keeps polling across BENCH_PROBE_TOTAL_S before falling back to CPU
    monkeypatch.setenv("BENCH_PROBE_TOTAL_S", "60")
    monkeypatch.setenv("BENCH_PROBE_RETRY_S", "0")
    answers = [(None, 0, "hang"), (None, 0, "fast error"), ("tpu", 1, None)]

    class _Flappy:
        calls = 0

        @classmethod
        def probe_backend(cls, timeout_s, retries):
            cls.calls += 1
            return answers[min(cls.calls, len(answers)) - 1]

    platform, error, attempts, window_s = bench._persistent_probe(_Flappy)
    assert platform == "tpu"
    assert error is None
    assert [a["error"] for a in attempts] == ["hang", "fast error", None]
    assert window_s >= 0


def test_persistent_probe_gives_up_after_window_with_attempt_count(
    bench, monkeypatch
):
    monkeypatch.setenv("BENCH_PROBE_TOTAL_S", "0")
    monkeypatch.setenv("BENCH_PROBE_RETRY_S", "0")

    class _Dead:
        @staticmethod
        def probe_backend(timeout_s, retries):
            return None, 0, "relay down"

    platform, error, attempts, _ = bench._persistent_probe(_Dead)
    assert platform is None
    assert "relay down" in error
    assert len(attempts) == 1


def test_emitted_records_carry_probe_attempt_log(bench, monkeypatch, capsys):
    # the JSON itself must prove how hard the gate fought (verdict item 1)
    tpu = {k: _record(k) for k in bench.CONFIG_ORDER}
    lines, _ = run_main(bench, monkeypatch, capsys, tpu, {})
    for rec in lines:
        assert rec["probe_attempts"] == 1
        assert "probe_window_s" in rec
    headline = lines[0]
    assert headline["config"] == "4"
    assert headline["probe_log"][0]["platform"] == "tpu"
