"""Unit tests for bench.py's streaming watchdog parent.

The driver parses bench.py's stdout (headline config's line first, one
line per config), so the emit/hold-back ordering and the fallback
bookkeeping are contract, not detail.  The children and the backend probe
are faked; the real solve paths are covered by test_algorithms/test_cli.
"""

import importlib.util
import json
import os

import pytest


@pytest.fixture(autouse=True)
def _isolated_probe_cache(monkeypatch, tmp_path):
    """Each test gets its own probe-verdict cache file (the real default
    lives in the system tempdir and persists across bench invocations —
    exactly the behavior that must NOT leak between tests)."""
    monkeypatch.setenv(
        "PYDCOP_TPU_PROBE_CACHE", str(tmp_path / "probe_cache.json")
    )
    monkeypatch.delenv("PYDCOP_TPU_SKIP_PROBE", raising=False)


@pytest.fixture()
def bench():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _record(config, value=1.0, **extra):
    rec = {
        "metric": f"metric_{config}", "value": value, "unit": "s",
        "config": config,
    }
    rec.update(extra)
    return rec


def run_main(bench, monkeypatch, capsys, tpu_records, cpu_records,
             probe=("tpu", 1, None), tpu_error=None, cpu_error=None):
    """Drive bench.main() with faked children; return parsed stdout lines."""
    # one probe attempt only: the persistent window is covered separately
    monkeypatch.setenv("BENCH_PROBE_TOTAL_S", "0")
    calls = []

    def fake_run_child(flag, budget, configs, emit):
        calls.append((flag, list(configs)))
        table = tpu_records if flag == "--child" else cpu_records
        records = {}
        for key in configs:
            if key in table:
                records[key] = dict(table[key])
                emit(records[key])
        return records, (tpu_error if flag == "--child" else cpu_error)

    monkeypatch.setattr(bench, "_run_child", fake_run_child)

    class _Probe:
        @staticmethod
        def probe_backend(timeout_s, retries):
            return probe

    bench.main(_probe_module=_Probe)
    out = capsys.readouterr().out.strip().splitlines()
    return [json.loads(line) for line in out], calls


def test_headline_line_leads_and_all_configs_emit(
    bench, monkeypatch, capsys
):
    tpu = {k: _record(k) for k in bench.CONFIG_ORDER}
    lines, calls = run_main(bench, monkeypatch, capsys, tpu, {})
    assert [r["config"] for r in lines][0] == "4"
    assert sorted(r["config"] for r in lines) == sorted(bench.CONFIG_ORDER)
    # no fallback child when everything succeeded
    assert [flag for flag, _ in calls] == ["--child"]


def test_failed_headline_holds_later_configs_until_fallback(
    bench, monkeypatch, capsys
):
    # accelerator child: config 4 errors, the rest succeed
    tpu = {k: _record(k) for k in bench.CONFIG_ORDER}
    tpu["4"] = _record("4", value=None, error="boom")
    cpu = {"4": _record("4", value=2.0, device="cpu")}
    lines, calls = run_main(
        bench, monkeypatch, capsys, tpu, cpu, tpu_error=None,
    )
    # headline still first, filled by the CPU fallback
    assert lines[0]["config"] == "4"
    assert lines[0]["value"] == 2.0
    assert sorted(r["config"] for r in lines) == sorted(bench.CONFIG_ORDER)
    # the fallback only re-ran the missing config, not the held successes
    assert calls[1] == ("--child-cpu", ["4"])


def test_both_children_failing_reports_both_reasons(
    bench, monkeypatch, capsys
):
    lines, _ = run_main(
        bench, monkeypatch, capsys, {}, {},
        tpu_error="relay down", cpu_error="cpu exploded",
    )
    assert lines[0]["config"] == "4"
    for rec in lines:
        assert rec["value"] is None
        assert "relay down" in rec["error"]
        assert "cpu exploded" in rec["error"]


def test_vs_baseline_refused_off_tpu(monkeypatch):
    # a CPU fallback must never masquerade as the TPU headline: the
    # speedup field is withheld unless the record ran on a tpu device
    import bench_all

    monkeypatch.setitem(
        bench_all.CONFIGS, "4",
        lambda: {"metric": "m4", "value": 2.0, "device": "cpu"},
    )
    rec = bench_all.run_config("4")
    assert rec["vs_baseline"] is None
    assert "not claimed" in rec["vs_baseline_note"]

    monkeypatch.setitem(
        bench_all.CONFIGS, "4",
        lambda: {"metric": "m4", "value": 2.0, "device": "tpu"},
    )
    rec = bench_all.run_config("4")
    assert rec["vs_baseline"] == 5.0
    assert "vs_baseline_note" not in rec


def test_bench_records_achieved_bandwidth(monkeypatch):
    # with an analytic traffic model the record reports achieved GB/s
    # (and % of HBM peak only on a recognized TPU)
    import bench_all

    class _R:
        cost = 0.0
        violations = 0

    monkeypatch.setattr(
        bench_all, "_hbm_peak_gbps", lambda: 819.0
    )
    rec = bench_all._bench(
        "m", lambda: _R(), n_cycles=10, traffic_bytes=10_000_000
    )
    assert rec["achieved_gbps"] > 0
    assert rec["hbm_peak_pct"] == pytest.approx(
        100 * rec["achieved_gbps"] / 819.0, rel=0.02
    )


def test_probe_failure_skips_accelerator_child(bench, monkeypatch, capsys):
    cpu = {k: _record(k, device="cpu") for k in bench.CONFIG_ORDER}
    lines, calls = run_main(
        bench, monkeypatch, capsys, {}, cpu,
        probe=(None, 0, "probe timed out"),
    )
    assert [flag for flag, _ in calls] == ["--child-cpu"]
    assert lines[0]["config"] == "4"
    for rec in lines:
        assert "probe" in rec.get("error", "")


def test_persistent_probe_retries_until_relay_answers(bench, monkeypatch):
    # a flapping relay must not lose the round to one bad sample: the gate
    # keeps polling across BENCH_PROBE_TOTAL_S before falling back to CPU
    monkeypatch.setenv("BENCH_PROBE_TOTAL_S", "60")
    monkeypatch.setenv("BENCH_PROBE_RETRY_S", "0")
    answers = [(None, 0, "hang"), (None, 0, "fast error"), ("tpu", 1, None)]

    class _Flappy:
        calls = 0

        @classmethod
        def probe_backend(cls, timeout_s, retries):
            cls.calls += 1
            return answers[min(cls.calls, len(answers)) - 1]

    platform, error, attempts, window_s = bench._persistent_probe(_Flappy)
    assert platform == "tpu"
    assert error is None
    assert [a["error"] for a in attempts] == ["hang", "fast error", None]
    assert window_s >= 0


def test_persistent_probe_gives_up_after_window_with_attempt_count(
    bench, monkeypatch
):
    monkeypatch.setenv("BENCH_PROBE_TOTAL_S", "0")
    monkeypatch.setenv("BENCH_PROBE_RETRY_S", "0")

    class _Dead:
        @staticmethod
        def probe_backend(timeout_s, retries):
            return None, 0, "relay down"

    platform, error, attempts, _ = bench._persistent_probe(_Dead)
    assert platform is None
    assert "relay down" in error
    assert len(attempts) == 1


def test_emitted_records_carry_probe_attempt_log(bench, monkeypatch, capsys):
    # the JSON itself must prove how hard the gate fought (verdict item 1)
    tpu = {k: _record(k) for k in bench.CONFIG_ORDER}
    lines, _ = run_main(bench, monkeypatch, capsys, tpu, {})
    for rec in lines:
        assert rec["probe_attempts"] == 1
        assert "probe_window_s" in rec
    headline = lines[0]
    assert headline["config"] == "4"
    assert headline["probe_log"][0]["platform"] == "tpu"


# ---------------------------------------------------------------------------
# graftprof round: probe-verdict caching + skip env (ISSUE 5 satellite)
# ---------------------------------------------------------------------------


def test_skip_probe_env_commits_accelerator_child(bench, monkeypatch):
    monkeypatch.setenv("PYDCOP_TPU_SKIP_PROBE", "1")

    class _MustNotProbe:
        @staticmethod
        def probe_backend(timeout_s, retries):
            raise AssertionError("probe must be skipped")

    platform, error, attempts, window_s = bench._persistent_probe(
        _MustNotProbe
    )
    assert platform == "skipped"
    assert error is None
    assert attempts == [] and window_s == 0.0


def test_failed_probe_window_cached_across_invocations(bench, monkeypatch):
    monkeypatch.setenv("BENCH_PROBE_TOTAL_S", "0")
    monkeypatch.setenv("BENCH_PROBE_RETRY_S", "0")

    class _Dead:
        calls = 0

        @classmethod
        def probe_backend(cls, timeout_s, retries):
            cls.calls += 1
            return None, 0, "relay down"

    p1, e1, attempts1, _ = bench._persistent_probe(_Dead)
    assert p1 is None and _Dead.calls == 1 and len(attempts1) == 1
    # second invocation (same "run"): the cached verdict short-circuits
    # the window — no probe attempt at all
    p2, e2, attempts2, w2 = bench._persistent_probe(_Dead)
    assert p2 is None
    assert _Dead.calls == 1
    assert attempts2 == [] and w2 == 0.0
    assert "cached verdict" in e2 and "relay down" in e2


def test_probe_cache_expires_by_ttl(bench, monkeypatch):
    monkeypatch.setenv("BENCH_PROBE_TOTAL_S", "0")
    monkeypatch.setenv("BENCH_PROBE_RETRY_S", "0")

    class _Dead:
        calls = 0

        @classmethod
        def probe_backend(cls, timeout_s, retries):
            cls.calls += 1
            return None, 0, "relay down"

    bench._persistent_probe(_Dead)
    monkeypatch.setenv("BENCH_PROBE_CACHE_TTL_S", "0")
    bench._persistent_probe(_Dead)
    assert _Dead.calls == 2  # expired cache -> real probe again


def test_healthy_probe_clears_cached_failure(bench, monkeypatch):
    monkeypatch.setenv("BENCH_PROBE_TOTAL_S", "0")
    monkeypatch.setenv("BENCH_PROBE_RETRY_S", "0")

    class _Dead:
        @staticmethod
        def probe_backend(timeout_s, retries):
            return None, 0, "relay down"

    class _Healthy:
        @staticmethod
        def probe_backend(timeout_s, retries):
            return "tpu", 1, None

    bench._persistent_probe(_Dead)
    # TTL=0 forces a real probe despite the cached failure; the healthy
    # answer must then CLEAR the cache so the next call probes again
    monkeypatch.setenv("BENCH_PROBE_CACHE_TTL_S", "0")
    p, _, _, _ = bench._persistent_probe(_Healthy)
    assert p == "tpu"
    monkeypatch.setenv("BENCH_PROBE_CACHE_TTL_S", "3600")
    assert bench._read_cached_probe_failure() is None


# ---------------------------------------------------------------------------
# graftprof round: tools/bench_gate.py (perf regression gate)
# ---------------------------------------------------------------------------


@pytest.fixture()
def bench_gate():
    spec = importlib.util.spec_from_file_location(
        "bench_gate_under_test",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "bench_gate.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _gate_rec(metric, value, device="cpu", cost=100.0, **extra):
    rec = {
        "metric": metric, "value": value, "unit": "s",
        "device": device, "cost": cost,
    }
    rec.update(extra)
    return rec


def _gate_history(bench_gate, tmp_path, rounds):
    """Write driver-wrapper history files (the real BENCH shape: records
    ride a 'tail' blob, possibly with noise lines) and load them."""
    paths = []
    for i, records in enumerate(rounds):
        tail = "stderr noise line\n" + "\n".join(
            json.dumps(r) for r in records
        )
        path = tmp_path / f"BENCH_h{i:02d}.json"
        path.write_text(json.dumps({"n": i, "rc": 0, "tail": tail}))
        paths.append(str(path))
    return bench_gate.load_history(paths)


def test_gate_passes_on_unchanged_record(bench_gate, tmp_path):
    hist_round = [
        _gate_rec("m_a", 1.0), _gate_rec("m_b", 2.0),
        _gate_rec("m_c", 0.5),
    ]
    history = _gate_history(
        bench_gate, tmp_path, [hist_round, hist_round]
    )
    rows, regressions, scales = bench_gate.compare(hist_round, history)
    assert regressions == 0
    assert scales.get("cpu", 1.0) == 1.0
    assert all(r["status"] == "ok" for r in rows)


def test_gate_fails_on_synthetic_regression(bench_gate, tmp_path):
    hist_round = [
        _gate_rec("m_a", 1.0), _gate_rec("m_b", 2.0),
        _gate_rec("m_c", 0.5),
    ]
    history = _gate_history(bench_gate, tmp_path, [hist_round])
    fresh = [
        _gate_rec("m_a", 1.0), _gate_rec("m_b", 6.0),  # 3x slower
        _gate_rec("m_c", 0.5),
    ]
    rows, regressions, _ = bench_gate.compare(fresh, history)
    assert regressions == 1
    bad = [r for r in rows if r["status"] == "REGRESSION"]
    assert bad[0]["metric"] == "m_b"
    assert "wall" in bad[0]["note"]


def test_gate_normalizes_uniform_machine_drift(bench_gate, tmp_path):
    hist_round = [
        _gate_rec("m_a", 1.0), _gate_rec("m_b", 2.0),
        _gate_rec("m_c", 0.5),
    ]
    history = _gate_history(bench_gate, tmp_path, [hist_round])
    # the whole fleet is 8x slower (slower container), no regression
    fresh = [
        _gate_rec("m_a", 8.0), _gate_rec("m_b", 16.0),
        _gate_rec("m_c", 4.0),
    ]
    rows, regressions, scales = bench_gate.compare(fresh, history)
    assert regressions == 0
    assert scales["cpu"] == pytest.approx(8.0)
    # ... but --no-normalize treats the same drift as 8 regressions' worth
    _, raw_regressions, raw_scales = bench_gate.compare(
        fresh, history, normalize=False
    )
    assert raw_scales == {}
    assert raw_regressions == 3


def test_gate_drift_scales_are_per_device(bench_gate, tmp_path):
    """A mixed TPU + CPU-fallback fresh set (bench.py's real shape): the
    CPU rows' 8x container drift must NOT normalize away a genuine TPU
    regression."""
    history = _gate_history(bench_gate, tmp_path, [[
        _gate_rec("m_cpu_a", 1.0), _gate_rec("m_cpu_b", 2.0),
        _gate_rec("m_cpu_c", 0.5),
        _gate_rec("m_tpu_a", 0.1, device="tpu"),
        _gate_rec("m_tpu_b", 0.2, device="tpu"),
    ]])
    fresh = [
        _gate_rec("m_cpu_a", 8.0), _gate_rec("m_cpu_b", 16.0),
        _gate_rec("m_cpu_c", 4.0),           # uniform 8x cpu drift: ok
        _gate_rec("m_tpu_a", 0.3, device="tpu"),  # 3x TPU regression
        _gate_rec("m_tpu_b", 0.2, device="tpu"),
    ]
    rows, regressions, scales = bench_gate.compare(fresh, history)
    assert scales["cpu"] == pytest.approx(8.0)
    assert regressions == 1
    assert [r["metric"] for r in rows if r["status"] == "REGRESSION"] == [
        "m_tpu_a"
    ]


def test_gate_flags_single_metric_beyond_drift(bench_gate, tmp_path):
    hist_round = [
        _gate_rec("m_a", 1.0), _gate_rec("m_b", 2.0),
        _gate_rec("m_c", 0.5),
    ]
    history = _gate_history(bench_gate, tmp_path, [hist_round])
    fresh = [  # uniform 8x drift, PLUS m_b regressing 3x beyond it
        _gate_rec("m_a", 8.0), _gate_rec("m_b", 48.0),
        _gate_rec("m_c", 4.0),
    ]
    rows, regressions, _ = bench_gate.compare(fresh, history)
    assert regressions == 1
    assert [r["metric"] for r in rows if r["status"] == "REGRESSION"] == [
        "m_b"
    ]


def test_gate_cost_quality_regression(bench_gate, tmp_path):
    hist_round = [_gate_rec("m_a", 1.0, cost=100.0),
                  _gate_rec("m_b", 1.0, cost=50.0)]
    history = _gate_history(bench_gate, tmp_path, [hist_round])
    fresh = [_gate_rec("m_a", 1.0, cost=150.0),  # 50% worse solution
             _gate_rec("m_b", 1.0, cost=50.0)]
    rows, regressions, _ = bench_gate.compare(fresh, history)
    assert regressions == 1
    bad = [r for r in rows if r["status"] == "REGRESSION"][0]
    assert bad["metric"] == "m_a" and "cost" in bad["note"]


def test_gate_device_mismatch_is_no_baseline(bench_gate, tmp_path):
    history = _gate_history(
        bench_gate, tmp_path,
        [[_gate_rec("m_a", 0.01, device="tpu")]],
    )
    fresh = [_gate_rec("m_a", 5.0, device="cpu")]
    rows, regressions, _ = bench_gate.compare(fresh, history)
    assert regressions == 0
    assert rows[0]["status"] == "no-baseline"


def test_gate_errored_config_skips_unless_strict(bench_gate, tmp_path):
    history = _gate_history(
        bench_gate, tmp_path, [[_gate_rec("m_a", 1.0)]]
    )
    fresh = [{"metric": "m_a", "value": None, "error": "boom",
              "device": "cpu"}]
    rows, regressions, _ = bench_gate.compare(fresh, history)
    assert regressions == 0 and rows[0]["status"] == "skipped"
    _, strict_regressions, _ = bench_gate.compare(
        fresh, history, strict=True
    )
    assert strict_regressions == 1
    # strict only bites on SAME-device history: tpu-only history cannot
    # fail an errored cpu config (it would have been no-baseline anyway)
    tpu_history = _gate_history(
        bench_gate, tmp_path, [[_gate_rec("m_x", 1.0, device="tpu")]]
    )
    fresh_x = [{"metric": "m_x", "value": None, "error": "boom",
                "device": "cpu"}]
    rows, strict_regressions, _ = bench_gate.compare(
        fresh_x, tpu_history, strict=True
    )
    assert strict_regressions == 0 and rows[0]["status"] == "skipped"


def test_gate_self_skipped_config_never_fails(bench_gate, tmp_path):
    """A config that declares itself inapplicable (config 1 without the
    /root/reference checkout) is SKIPPED — never a regression, even
    under --strict with same-device history."""
    history = _gate_history(
        bench_gate, tmp_path, [[_gate_rec("dsa_coloring50_wall", 1.0)]]
    )
    fresh = [{
        "metric": "dsa_coloring50_wall", "value": None,
        "skipped": "reference checkout not present (/root/reference)",
    }]
    for strict in (False, True):
        rows, regressions, _ = bench_gate.compare(
            fresh, history, strict=strict
        )
        assert regressions == 0, rows
        assert rows[0]["status"] == "SKIPPED"
        assert "reference checkout" in rows[0]["note"]


def test_bench_all_config_1_skips_without_reference(monkeypatch):
    """bench_all emits the self-skip record when the reference checkout
    is absent (the gate-side half is test_gate_self_skipped above)."""
    import bench_all

    monkeypatch.setattr(
        bench_all, "REFERENCE_COLORING_50",
        "/nonexistent/graph_coloring_50.yaml",
    )
    rec = bench_all.run_config("1")
    assert rec["value"] is None
    assert "reference checkout not present" in rec["skipped"]
    assert "error" not in rec


def test_gate_abs_slack_protects_millisecond_configs(bench_gate, tmp_path):
    history = _gate_history(
        bench_gate, tmp_path,
        [[_gate_rec("m_a", 0.005), _gate_rec("m_b", 0.004)]],
    )
    # 4x relative blowup but only +15 ms: under the absolute slack
    fresh = [_gate_rec("m_a", 0.020), _gate_rec("m_b", 0.016)]
    _, regressions, _ = bench_gate.compare(
        fresh, history, normalize=False
    )
    assert regressions == 0


def test_gate_main_end_to_end(bench_gate, tmp_path, capsys):
    hist_round = [_gate_rec("m_a", 1.0), _gate_rec("m_b", 2.0)]
    _gate_history(bench_gate, tmp_path, [hist_round])  # writes the files
    fresh_path = tmp_path / "fresh.jsonl"
    fresh_path.write_text(
        "\n".join(json.dumps(r) for r in hist_round) + "\n"
    )
    rc = bench_gate.main([
        "--fresh", str(fresh_path),
        "--history", str(tmp_path / "BENCH_h*.json"),
    ])
    out = capsys.readouterr().out
    assert rc == 0 and "PASS" in out
    regressed = [_gate_rec("m_a", 1.0), _gate_rec("m_b", 20.0)]
    fresh_path.write_text(
        "\n".join(json.dumps(r) for r in regressed) + "\n"
    )
    rc = bench_gate.main([
        "--fresh", str(fresh_path),
        "--history", str(tmp_path / "BENCH_h*.json"),
    ])
    out = capsys.readouterr().out
    assert rc == 1 and "FAIL" in out and "m_b" in out


def test_skip_probe_clears_stale_failure_cache(bench, monkeypatch):
    monkeypatch.setenv("BENCH_PROBE_TOTAL_S", "0")
    monkeypatch.setenv("BENCH_PROBE_RETRY_S", "0")

    class _Dead:
        @staticmethod
        def probe_backend(timeout_s, retries):
            return None, 0, "relay down"

    bench._persistent_probe(_Dead)
    assert bench._read_cached_probe_failure() is not None
    monkeypatch.setenv("PYDCOP_TPU_SKIP_PROBE", "1")
    bench._persistent_probe(_Dead)
    # the operator's health assertion cleared the stale verdict
    monkeypatch.delenv("PYDCOP_TPU_SKIP_PROBE")
    assert bench._read_cached_probe_failure() is None
