"""Compiler + device-kernel tests: padded tables, index arrays, cost parity.

The key invariant (SURVEY.md §4 plan, tier b): the device-side evaluation of
any assignment must match the host-side ``DCOP.solution_cost`` exactly.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from pydcop_tpu.compile import (  # noqa: E402
    compile_dcop,
    evaluate,
    local_costs,
    tabulate_constraint,
    to_device,
)
from pydcop_tpu.dcop import (  # noqa: E402
    DCOP,
    Domain,
    Variable,
    constraint_from_str,
    load_dcop_from_file,
)

REF = "/root/reference/tests/instances"


def total_host_cost(dcop, assignment):
    cost = 0.0
    for c in dcop.constraints.values():
        cost += c.get_value_for_assignment(
            {n: assignment[n] for n in c.scope_names}
        )
    for v in dcop.variables.values():
        if v.has_cost:
            cost += v.cost_for_val(assignment[v.name])
    return cost


class TestTabulate:
    def test_vectorized_matches_scalar(self):
        d = Domain("d", "", [0, 1, 2, 3])
        x, y = Variable("x", d), Variable("y", d)
        c = constraint_from_str(
            "c", "100 if x == y else abs(x - y) * 0.5", [x, y]
        )
        table = tabulate_constraint(c)
        for i in range(4):
            for j in range(4):
                assert table[i, j] == c(x=i, y=j)

    def test_string_domain(self):
        d = Domain("col", "", ["R", "G"])
        x, y = Variable("x", d), Variable("y", d)
        c = constraint_from_str("c", "1 if x == y else 0", [x, y])
        table = tabulate_constraint(c)
        assert table[0, 0] == 1 and table[0, 1] == 0

    def test_multiline_function_falls_back(self):
        d = Domain("d", "", [0, 1, 2])
        x = Variable("x", d)
        y = Variable("y", d)
        from pydcop_tpu.dcop.relations import NAryFunctionRelation
        from pydcop_tpu.utils.expressions import ExpressionFunction

        f = ExpressionFunction(
            "if x == y:\n    return 10\nreturn x + y"
        )
        c = NAryFunctionRelation(f, [x, y], name="c")
        table = tabulate_constraint(c)
        assert table[1, 1] == 10 and table[1, 2] == 3


class TestCompile:
    def test_mixed_domains_padding(self):
        d2 = Domain("d2", "", [0, 1])
        d4 = Domain("d4", "", [0, 1, 2, 3])
        x, y = Variable("x", d2), Variable("y", d4)
        dcop = DCOP("t")
        dcop += constraint_from_str("c", "x * y", [x, y])
        c = compile_dcop(dcop)
        assert c.max_domain == 4
        assert list(c.domain_size) == [2, 4]
        assert c.valid_mask[0].tolist() == [True, True, False, False]

    def test_unary_folding(self):
        d = Domain("d", "", [0, 1])
        x, y = Variable("x", d), Variable("y", d)
        dcop = DCOP("t")
        dcop += constraint_from_str("c", "x + y", [x, y])
        dcop += constraint_from_str("u", "x * 5", [x])
        c = compile_dcop(dcop)
        # unary constraint folded: only the binary one gets a bucket
        assert len(c.buckets) == 1 and c.buckets[0].arity == 2
        assert c.unary[0, 1] == 5.0

    def test_max_objective_negated(self):
        d = Domain("d", "", [0, 1])
        x, y = Variable("x", d), Variable("y", d)
        dcop = DCOP("t", objective="max")
        dcop += constraint_from_str("c", "x + y", [x, y])
        c = compile_dcop(dcop)
        dev = to_device(c)
        # maximizing x+y == minimizing -(x+y): best assignment is (1, 1)
        best = min(
            ((i, j) for i in range(2) for j in range(2)),
            key=lambda ij: float(
                evaluate(dev, jnp.array(ij, dtype=jnp.int32))
            ),
        )
        assert best == (1, 1)

    @pytest.mark.parametrize(
        "fname",
        [
            "graph_coloring_3agts_10vars.yaml",
            "graph_coloring1.yaml",
            "graph_coloring_10_4_15_0.1.yml",
        ],
    )
    def test_device_eval_matches_host(self, fname):
        dcop = load_dcop_from_file(f"{REF}/{fname}")
        c = compile_dcop(dcop)
        dev = to_device(c)
        rng = np.random.default_rng(1)
        for _ in range(10):
            idx = np.array(
                [rng.integers(0, s) for s in c.domain_size], dtype=np.int32
            )
            host = total_host_cost(dcop, c.assignment_from_indices(idx))
            device = float(evaluate(dev, jnp.asarray(idx)))
            assert device == pytest.approx(host, rel=1e-5)

    def test_local_costs_match_bruteforce(self):
        dcop = load_dcop_from_file(f"{REF}/graph_coloring_3agts_10vars.yaml")
        c = compile_dcop(dcop)
        dev = to_device(c)
        rng = np.random.default_rng(2)
        idx = np.array(
            [rng.integers(0, s) for s in c.domain_size], dtype=np.int32
        )
        lc = np.asarray(local_costs(dev, jnp.asarray(idx)))
        for vi in range(c.n_vars):
            vname = c.var_names[vi]
            for d in range(c.domain_size[vi]):
                idx2 = idx.copy()
                idx2[vi] = d
                a = c.assignment_from_indices(idx2)
                manual = sum(
                    cons.get_value_for_assignment(
                        {n: a[n] for n in cons.scope_names}
                    )
                    for cons in dcop.constraints.values()
                    if vname in cons.scope_names
                )
                manual += (
                    dcop.variables[vname].cost_for_val(a[vname])
                    if dcop.variables[vname].has_cost
                    else 0
                )
                assert lc[vi, d] == pytest.approx(manual, rel=1e-5)

    def test_external_variables_fixed(self):
        d = load_dcop_from_file(f"{REF}/../instances/graph_coloring1.yaml")
        # no external vars here; build one inline instead
        from pydcop_tpu.dcop import load_dcop

        dcop = load_dcop(
            """name: t
objective: min
domains: {d: {values: [0, 1]}}
variables: {a: {domain: d}}
external_variables:
  e: {domain: d, initial_value: 1}
constraints: {c: {type: intention, function: a * 10 if e else a}}
agents: [x]
"""
        )
        c = compile_dcop(dcop)
        dev = to_device(c)
        assert float(evaluate(dev, jnp.array([1], dtype=jnp.int32))) == 10.0
