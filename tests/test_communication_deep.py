"""Communication depth tests, modeled on the reference's coverage
(/root/reference/tests/unit/test_infra_communication.py, ~505 LoC):
Messaging priorities/metrics/parking, the in-process layer's
address-isolation and error modes, and the HTTP layer end-to-end
including unknown-computation handling."""

import threading
import time

import pytest

pytest.importorskip("jax")

from pydcop_tpu.infrastructure.communication import (  # noqa: E402
    HttpCommunicationLayer,
    InProcessCommunicationLayer,
    Messaging,
    MSG_ALGO,
    MSG_MGT,
    Message,
    UnknownComputation,
)


class _Sink:
    """Bare local computation recording deliveries."""

    def __init__(self):
        self.received = []


class TestMessaging:
    def _local(self):
        m = Messaging("a1", InProcessCommunicationLayer())
        m.register_computation("c1", _Sink())
        m.register_computation("c2", _Sink())
        return m

    def test_local_delivery_and_pop(self):
        m = self._local()
        m.post_msg("c1", "c2", Message("m", "hello"))
        sender, dest, msg, _ = m.next_msg(timeout=0.5)
        assert (sender, dest, msg.content) == ("c1", "c2", "hello")

    def test_next_msg_none_when_empty(self):
        m = self._local()
        assert m.next_msg(timeout=0.05) is None

    def test_priority_order_beats_fifo(self):
        # management traffic (lower prio value) must overtake algorithm
        # messages already queued (reference test_messaging priorities)
        m = self._local()
        m.post_msg("c1", "c2", Message("algo", 1), MSG_ALGO)
        m.post_msg("c1", "c2", Message("algo", 2), MSG_ALGO)
        m.post_msg("c1", "c2", Message("mgt", 3), MSG_MGT)
        order = [m.next_msg(timeout=0.5)[2].content for _ in range(3)]
        assert order == [3, 1, 2]  # mgt first, then FIFO among equals

    def test_same_priority_is_fifo(self):
        m = self._local()
        for i in range(5):
            m.post_msg("c1", "c2", Message("m", i))
        got = [m.next_msg(timeout=0.5)[2].content for _ in range(5)]
        assert got == [0, 1, 2, 3, 4]

    def test_local_messages_not_counted_as_external(self):
        m = self._local()
        m.post_msg("c1", "c2", Message("m", "x"))
        assert m.count_ext_msg.get("c1", 0) == 0

    def test_external_messages_counted_but_not_mgt(self):
        # metrics track algorithm traffic; management traffic is free
        # (reference test_do_not_count_mgt_messages:178)
        a1, a2 = InProcessCommunicationLayer(), InProcessCommunicationLayer()
        m1 = Messaging("a1", a1)
        m2 = Messaging("a2", a2)
        m2.register_computation("remote", _Sink())
        m1.register_route("remote", "a2", a2.address)
        m1.post_msg("c1", "remote", Message("m", "x"), MSG_ALGO)
        m1.post_msg("c1", "remote", Message("m", "y"), MSG_MGT)
        assert m1.count_ext_msg["c1"] == 1
        assert m1.size_ext_msg["c1"] >= 1
        # both actually arrived on a2's queue
        contents = {m2.next_msg(0.5)[2].content for _ in range(2)}
        assert contents == {"x", "y"}

    def test_parked_message_flushes_once_route_known(self):
        a1, a2 = InProcessCommunicationLayer(), InProcessCommunicationLayer()
        m1 = Messaging("a1", a1)
        m2 = Messaging("a2", a2)
        m2.register_computation("later", _Sink())
        m1.post_msg("c1", "later", Message("m", 42))
        assert m2.next_msg(timeout=0.05) is None  # parked, not lost
        m1.register_route("later", "a2", a2.address)
        assert m2.next_msg(timeout=0.5)[2].content == 42

    def test_unknown_computation_lookup_raises(self):
        m = self._local()
        with pytest.raises(UnknownComputation):
            m.computation("ghost")


class TestInProcessLayer:
    def test_addresses_not_shared_across_instances(self):
        l1, l2 = InProcessCommunicationLayer(), InProcessCommunicationLayer()
        assert l1.address is l1
        assert l1.address is not l2.address

    def test_send_delivers_to_target_queue(self):
        l1, l2 = InProcessCommunicationLayer(), InProcessCommunicationLayer()
        m1, m2 = Messaging("a1", l1), Messaging("a2", l2)
        m2.register_computation("c2", _Sink())
        l1.send_msg("a1", "a2", l2, "c1", "c2", Message("m", "direct"), 20)
        assert m2.next_msg(timeout=0.5)[2].content == "direct"


@pytest.mark.slow
class TestHttpLayer:
    def _pair(self, p1, p2):
        l1 = HttpCommunicationLayer(("127.0.0.1", p1))
        l2 = HttpCommunicationLayer(("127.0.0.1", p2))
        m1, m2 = Messaging("a1", l1), Messaging("a2", l2)
        return l1, l2, m1, m2

    def test_roundtrip_between_two_http_agents(self):
        l1, l2, m1, m2 = self._pair(19411, 19412)
        try:
            m2.register_computation("c2", _Sink())
            m1.register_computation("c1", _Sink())
            m1.register_route("c2", "a2", l2.address)
            m2.register_route("c1", "a1", l1.address)
            m1.post_msg("c1", "c2", Message("ping", {"k": [1, 2]}))
            got = m2.next_msg(timeout=3.0)
            assert got is not None
            assert got[2].content == {"k": [1, 2]}
            # and back
            m2.post_msg("c2", "c1", Message("pong", "ok"))
            assert m1.next_msg(timeout=3.0)[2].content == "ok"
        finally:
            l1.shutdown()
            l2.shutdown()

    def test_priority_travels_over_http(self):
        l1, l2, m1, m2 = self._pair(19413, 19414)
        try:
            m2.register_computation("c2", _Sink())
            m1.register_route("c2", "a2", l2.address)
            m1.post_msg("c1", "c2", Message("algo", "later"), MSG_ALGO)
            # wait for the first to land so queue ordering is meaningful
            deadline = time.time() + 3
            while m2.msg_queue_count < 1 and time.time() < deadline:
                time.sleep(0.01)
            m1.post_msg("c1", "c2", Message("mgt", "first"), MSG_MGT)
            deadline = time.time() + 3
            while m2.msg_queue_count < 2 and time.time() < deadline:
                time.sleep(0.01)
            order = [m2.next_msg(0.5)[2].content for _ in range(2)]
            assert order == ["first", "later"]
        finally:
            l1.shutdown()
            l2.shutdown()

    def test_unknown_computation_parks_for_rediscovery(self):
        # the receiver answers the reference's 404; the sender must drop
        # the stale route and park, NOT raise or lose the message
        l1, l2, m1, m2 = self._pair(19415, 19416)
        try:
            m1.register_route("ghost", "a2", l2.address)
            m1.post_msg("c1", "ghost", Message("m", 7))
            time.sleep(0.3)
            assert m2.next_msg(timeout=0.05) is None
            # deploy the computation and re-announce the route: flushes
            m2.register_computation("ghost", _Sink())
            m1.register_route("ghost", "a2", l2.address)
            got = m2.next_msg(timeout=3.0)
            assert got is not None and got[2].content == 7
        finally:
            l1.shutdown()
            l2.shutdown()


class TestHttpErrorModes:
    """The CommunicationLayer error contract (reference
    communication.py:68-79): 'ignore' swallows transport failures,
    'fail' raises UnreachableAgent, 'retry' attempts three sends with
    backoff before giving up.  None of these were exercised before
    round 5."""

    @staticmethod
    def _dead_address():
        # bind-then-close reserves a port nobody is listening on
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        addr = s.getsockname()
        s.close()
        return addr

    @staticmethod
    def _send(layer, address):
        return layer.send_msg(
            "a1", "a2", address, "c1", "c2", Message("t", None), MSG_ALGO
        )

    def test_invalid_mode_rejected(self):
        with pytest.raises(ValueError):
            InProcessCommunicationLayer(on_error="explode")

    def test_ignore_returns_false_after_one_attempt(self, caplog):
        layer = HttpCommunicationLayer(("127.0.0.1", 0), on_error="ignore")
        try:
            with caplog.at_level("WARNING"):
                ok = self._send(layer, self._dead_address())
            assert ok is False
            attempts = [
                r for r in caplog.records if "http send" in r.getMessage()
            ]
            assert len(attempts) == 1
        finally:
            layer.shutdown()

    def test_fail_raises_unreachable(self):
        from pydcop_tpu.infrastructure.communication import UnreachableAgent

        layer = HttpCommunicationLayer(("127.0.0.1", 0), on_error="fail")
        try:
            with pytest.raises(UnreachableAgent):
                self._send(layer, self._dead_address())
        finally:
            layer.shutdown()

    def test_retry_attempts_three_times_then_gives_up(self, caplog):
        layer = HttpCommunicationLayer(("127.0.0.1", 0), on_error="retry")
        try:
            with caplog.at_level("WARNING"):
                ok = self._send(layer, self._dead_address())
            assert ok is False
            attempts = [
                r for r in caplog.records if "http send" in r.getMessage()
            ]
            assert len(attempts) == 3
        finally:
            layer.shutdown()

    def test_retry_succeeds_when_peer_appears_late(self):
        # the peer binds its port only AFTER the sender's first attempt
        # has failed: retry's backoff must land the message on a later
        # attempt and report True.  jitter="none" pins the schedule
        # (sleeps 0.3s then 0.6s) so the peer at 0.25s is always up by a
        # retry — the default full jitter could draw near-zero sleeps
        from pydcop_tpu.infrastructure.retry import RetryPolicy

        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        addr = s.getsockname()
        s.close()

        peer_box = {}

        def start_peer_late():
            time.sleep(0.25)
            peer = HttpCommunicationLayer(addr, on_error="retry")
            m = Messaging("a2", peer)
            m.register_computation("c2", _Sink())
            peer_box["peer"], peer_box["m"] = peer, m

        t = threading.Thread(target=start_peer_late)
        t.start()
        sender = HttpCommunicationLayer(
            ("127.0.0.1", 0),
            on_error="retry",
            retry_policy=RetryPolicy(
                max_attempts=3, base_delay=0.3, max_delay=2.0,
                jitter="none",
            ),
        )
        try:
            assert self._send(sender, addr) is True
            t.join()
            got = peer_box["m"].next_msg(2.0)
            assert got is not None
            _sender, dest, msg, _t = got
            assert dest == "c2" and msg.type == "t"
        finally:
            sender.shutdown()
            if "peer" in peer_box:
                peer_box["peer"].shutdown()

    def test_exhausted_retries_log_error_and_count(self, caplog):
        # PR 3 satellite: a False return was indistinguishable from
        # success at call sites — exhaustion must log ONE error line and
        # increment comms.send_failures
        from pydcop_tpu.telemetry import metrics_registry

        metrics_registry.reset()
        metrics_registry.enabled = True
        layer = HttpCommunicationLayer(("127.0.0.1", 0), on_error="ignore")
        try:
            with caplog.at_level("WARNING"):
                ok = self._send(layer, self._dead_address())
            assert ok is False
            errors = [
                r for r in caplog.records
                if r.levelname == "ERROR" and "giving up" in r.getMessage()
            ]
            assert len(errors) == 1
            counter = metrics_registry.get("comms.send_failures")
            assert counter.value(agent="a1", dest="a2") == 1
        finally:
            metrics_registry.enabled = False
            layer.shutdown()


class TestParkedBounds:
    """PR 3 satellite: ``Messaging._parked`` used to grow without bound;
    now a cap + TTL dead-letter the overflow, loudly."""

    def test_parked_cap_dead_letters_oldest(self):
        m = Messaging("a1", InProcessCommunicationLayer(), parked_cap=3)
        for i in range(5):
            m.post_msg("c1", "nowhere", Message("m", i))
        assert m.parked_count == 3
        assert m.dead_letter_count == 2
        # the survivors are the NEWEST three: evicting the oldest first
        # drops the messages whose route has been missing longest
        m.register_computation("nowhere", _Sink())
        m.register_route("nowhere", "a1", m.comm.address)
        got = [m.next_msg(timeout=0.5)[2].content for _ in range(3)]
        assert got == [2, 3, 4]
        assert m.next_msg(timeout=0.05) is None

    def test_parked_ttl_expires_on_new_park(self):
        m = Messaging(
            "a1", InProcessCommunicationLayer(), parked_ttl=0.05
        )
        m.post_msg("c1", "ghost1", Message("m", "old"))
        time.sleep(0.1)
        m.post_msg("c1", "ghost2", Message("m", "new"))
        assert m.dead_letter_count == 1
        assert m.parked_count == 1

    def test_ttl_clock_survives_replay_reparks(self):
        # register_route flushes and re-parks messages still lacking a
        # route: the re-park must keep the ORIGINAL park time, or every
        # route registration would reset every TTL clock and the bound
        # would never bind
        m = Messaging(
            "a1", InProcessCommunicationLayer(), parked_ttl=0.1
        )
        m.post_msg("c1", "ghost", Message("m", "old"))
        time.sleep(0.06)
        # a route for a DIFFERENT computation flushes + re-parks 'ghost'
        m.register_computation("other", _Sink())
        m.register_route("other", "a1", m.comm.address)
        assert m.parked_count == 1
        time.sleep(0.06)  # total parked time now > TTL
        m.post_msg("c1", "ghost2", Message("m", "new"))
        assert m.dead_letter_count == 1
        assert m.parked_count == 1

    def test_route_arrival_beats_ttl(self):
        # TTL is enforced lazily on NEW parks, never on the flush: a
        # late-arriving route still delivers whatever is parked
        m = Messaging(
            "a1", InProcessCommunicationLayer(), parked_ttl=0.01
        )
        m.post_msg("c1", "late", Message("m", 7))
        time.sleep(0.05)
        m.register_computation("late", _Sink())
        m.register_route("late", "a1", m.comm.address)
        assert m.next_msg(timeout=0.5)[2].content == 7
        assert m.dead_letter_count == 0

    def test_dead_letters_counted_in_metrics(self):
        from pydcop_tpu.telemetry import metrics_registry

        metrics_registry.reset()
        metrics_registry.enabled = True
        try:
            m = Messaging(
                "agent_dl", InProcessCommunicationLayer(), parked_cap=1
            )
            m.post_msg("c1", "ghost1", Message("m", 1))
            m.post_msg("c1", "ghost2", Message("m", 2))
            counter = metrics_registry.get("comms.dead_letters")
            assert counter.value(agent="agent_dl") == 1
            gauge = metrics_registry.get("comms.parked_depth")
            assert gauge.value(agent="agent_dl") == 1
        finally:
            metrics_registry.enabled = False


class TestParkedReplayRace:
    """PR 3 satellite: a 404 re-park racing ``register_route`` under
    injected delays must deliver exactly once — the lock-swap flush in
    register_route is what makes the replay neither lose nor duplicate
    the message."""

    def test_repark_register_route_race_delivers_exactly_once(self):
        from pydcop_tpu.chaos import (
            ChaosController,
            FaultSchedule,
            MessageRule,
        )
        from pydcop_tpu.chaos.layer import ChaosCommunicationLayer

        inner1 = HttpCommunicationLayer(("127.0.0.1", 0))
        l2 = HttpCommunicationLayer(("127.0.0.1", 0))
        controller = ChaosController(
            FaultSchedule(
                seed=3,
                events=[
                    MessageRule(
                        action="delay", pattern="*", p=1.0, seconds=0.05
                    )
                ],
            )
        )
        l1 = ChaosCommunicationLayer(inner1, controller)
        m1 = Messaging("a1", l1)
        m2 = Messaging("a2", l2)
        try:
            # stale route: a2 answers 404 for 'late' until the deploy
            # thread registers it; the chaos delay stretches the window
            # in which the re-park races the route announcement
            m1.register_route("late", "a2", l2.address)

            def deploy_and_announce():
                time.sleep(0.02)
                m2.register_computation("late", _Sink())
                m1.register_route("late", "a2", l2.address)

            t = threading.Thread(target=deploy_and_announce)
            t.start()
            m1.post_msg("c1", "late", Message("m", 42))
            t.join()
            # a re-park that lost the race to the announcement flush is
            # still parked: one more announcement flushes it
            m1.register_route("late", "a2", l2.address)
            received = []
            deadline = time.time() + 3
            while time.time() < deadline:
                got = m2.next_msg(timeout=0.15)
                if got is not None:
                    received.append(got[2].content)
                elif received:
                    break
            assert received == [42]
            assert m1.dead_letter_count == 0
        finally:
            l1.shutdown()
            l2.shutdown()
