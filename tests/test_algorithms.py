"""Algorithm tests: solve quality on reference instances (SURVEY.md §4 tier 3).

Strategy mirrors the reference's api tests (tests/api/test_api_solve.py):
exact optimality asserts for complete algorithms, quality-threshold asserts
for local search — but with seeded PRNG so results are reproducible (an
explicit improvement over the reference's flaky CLI tests).
"""

import numpy as np
import pytest

pytest.importorskip("jax")

from pydcop_tpu import solve_result  # noqa: E402
from pydcop_tpu.algorithms import (  # noqa: E402
    AlgorithmDef,
    list_available_algorithms,
    load_algorithm_module,
    prepare_algo_params,
)
from pydcop_tpu.dcop import (  # noqa: E402
    DCOP,
    Domain,
    Variable,
    constraint_from_str,
    load_dcop_from_file,
)

REF = "/root/reference/tests/instances"


def simple_chain():
    """x - y - z chain, 2 colors; optimum = 0 violations impossible? no:
    chain is 2-colorable, optimum cost 0."""
    d = Domain("c", "", ["R", "G"])
    x, y, z = Variable("x", d), Variable("y", d), Variable("z", d)
    dcop = DCOP("chain")
    dcop += constraint_from_str("c1", "10 if x == y else 0", [x, y])
    dcop += constraint_from_str("c2", "10 if y == z else 0", [y, z])
    dcop.add_agents([])
    return dcop


class TestRegistry:
    def test_list_available(self):
        algos = list_available_algorithms()
        assert "maxsum" in algos and "dsa" in algos

    def test_load_module_contract(self):
        mod = load_algorithm_module("maxsum")
        assert mod.GRAPH_TYPE == "factor_graph"

    def test_unknown_algo(self):
        with pytest.raises(ImportError):
            load_algorithm_module("nosuchalgo")

    def test_params_defaults_and_validation(self):
        mod = load_algorithm_module("dsa")
        p = prepare_algo_params({}, mod.algo_params)
        assert p["probability"] == 0.7 and p["variant"] == "B"
        with pytest.raises(ValueError):
            prepare_algo_params({"variant": "Z"}, mod.algo_params)
        with pytest.raises(ValueError):
            prepare_algo_params({"nope": 1}, mod.algo_params)

    def test_algorithm_def_build(self):
        ad = AlgorithmDef.build_with_default_param(
            "maxsum", {"damping": 0.7}
        )
        assert ad.param_value("damping") == 0.7
        assert ad.param_value("noise") == 0.01


class TestMaxSum:
    def test_chain_optimal(self):
        r = solve_result(simple_chain(), "maxsum", n_cycles=30, seed=0)
        assert r["cost"] == 0.0 and r["violation"] == 0

    def test_10vars_near_optimal(self):
        # graph is not 2-colorable: optimum is exactly 1 violation
        d = load_dcop_from_file(f"{REF}/graph_coloring_3agts_10vars.yaml")
        r = solve_result(d, "maxsum", n_cycles=60, seed=0)
        assert r["violation"] <= 2  # optimum 1; allow one extra for BP

    def test_unary_costs_respected(self):
        d = load_dcop_from_file(f"{REF}/graph_coloring1.yaml")
        r = solve_result(d, "maxsum", n_cycles=30, seed=0)
        # global optimum of this instance is -0.1
        assert r["cost"] == pytest.approx(-0.1)

    def test_metrics_schema(self):
        r = solve_result(simple_chain(), "maxsum", n_cycles=10, seed=0)
        for k in (
            "status",
            "assignment",
            "cost",
            "violation",
            "msg_count",
            "msg_size",
            "cycle",
            "time",
        ):
            assert k in r
        # 2 messages per edge per cycle actually run (early convergence
        # exit may stop before n_cycles, like the reference's termination)
        assert 0 < r["cycle"] <= 10
        assert r["msg_count"] == 2 * 4 * r["cycle"]

    def test_curve_collection(self):
        r = solve_result(
            simple_chain(), "maxsum", n_cycles=10, seed=0, collect_curve=True
        )
        assert len(r["cost_curve"]) == 10


class TestMaxSumSeeding:
    """Wavefront seeding per start_messages (reference maxsum.py:311,:514)."""

    def _compiled_chain(self):
        from pydcop_tpu.compile.core import compile_dcop

        return compile_dcop(simple_chain())

    def test_leafs_only_degree_one_start(self):
        from pydcop_tpu.algorithms.maxsum import initial_active_mask

        c = self._compiled_chain()
        mask = initial_active_mask(c, "leafs")
        y = c.var_index["y"]  # degree 2, no unary: not a starter
        for e in range(c.n_edges):
            assert mask[e] == (c.edge_var[e] != y)

    def test_leafs_vars_all_variables_start(self):
        from pydcop_tpu.algorithms.maxsum import initial_active_mask

        c = self._compiled_chain()
        mask = initial_active_mask(c, "leafs_vars")
        assert mask[: c.n_edges].all()

    def test_constant_unary_with_padded_domain_not_starter(self):
        # a constant nonzero unary cost must be treated uniformly whether
        # or not the variable's domain is smaller than max_domain: padded
        # slots may not contribute to the cost range (ADVICE.md round 1)
        from pydcop_tpu.algorithms.maxsum import initial_active_mask
        from pydcop_tpu.compile.core import compile_dcop

        d3 = Domain("c3", "", ["R", "G", "B"])
        d2 = Domain("c2", "", ["R", "G"])
        v0, v1, v2 = Variable("v0", d3), Variable("v1", d2), Variable("v2", d3)
        dcop = DCOP("chain_u")
        dcop += constraint_from_str("c1", "10 if v0 == v1 else 0", [v0, v1])
        dcop += constraint_from_str("c2", "10 if v1 == v2 else 0", [v1, v2])
        dcop += constraint_from_str("u1", "5", [v1])  # constant unary
        dcop.add_agents([])
        c = compile_dcop(dcop)
        assert c.max_domain == 3 and c.domain_size[c.var_index["v1"]] == 2
        mask = initial_active_mask(c, "leafs")
        mid = c.var_index["v1"]  # degree 2, CONSTANT unary: not a starter
        for e in range(c.n_edges):
            assert mask[e] == (c.edge_var[e] != mid)

    def test_starterless_component_gets_seeded(self):
        # disconnected graph: one component has leafs, the other is a pure
        # cycle with only constant unary costs — without per-component
        # seeding the cycle would never activate and BP would "converge"
        # on its all-zero planes
        from pydcop_tpu.algorithms.maxsum import initial_active_mask
        from pydcop_tpu.compile.core import compile_dcop

        d = Domain("c", "", ["R", "G", "B"])
        x, y, z = Variable("x", d), Variable("y", d), Variable("z", d)
        a, b, cc = Variable("a", d), Variable("b", d), Variable("cc", d)
        dcop = DCOP("two_comps")
        dcop += constraint_from_str("k1", "10 if x == y else 0", [x, y])
        dcop += constraint_from_str("k2", "10 if y == z else 0", [y, z])
        dcop += constraint_from_str("k3", "10 if a == b else 0", [a, b])
        dcop += constraint_from_str("k4", "10 if b == cc else 0", [b, cc])
        dcop += constraint_from_str("k5", "10 if cc == a else 0", [cc, a])
        dcop += constraint_from_str("u1", "5", [a])  # constant unary
        dcop.add_agents([])
        c = compile_dcop(dcop)
        mask = initial_active_mask(c, "leafs")
        mid = c.var_index["y"]  # the only non-starter left
        for e in range(c.n_edges):
            assert mask[e] == (c.edge_var[e] != mid)

    def test_lanes_layout_matches_edges_layout(self):
        # the [D, n_edges] lane-major kernels are the same math as the
        # [n_edges, D] row kernels; same instance + seed must give the same
        # solution (costs exactly, modulo reduction-order float noise)
        from pydcop_tpu.algorithms import maxsum
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_coloring_arrays,
        )

        c = generate_coloring_arrays(
            120, 3, graph="scalefree", m_edge=2, seed=13
        )
        for start in ("leafs", "all"):
            base = {"damping": 0.6, "start_messages": start,
                    "stop_cycle": 25}
            rows = maxsum.solve(
                c, dict(base, layout="edges"), n_cycles=25, seed=2
            )
            lanes = maxsum.solve(
                c, dict(base, layout="lanes"), n_cycles=25, seed=2
            )
            assert lanes.violations == rows.violations
            # cost parity only: reduction order differs between layouts,
            # so near-tied argmins may legitimately flip per backend
            assert lanes.cost == pytest.approx(rows.cost, rel=1e-5)

    def test_activation_cycles_match_dynamic_rule(self):
        # the precomputed BFS wavefront (activation_cycles) must reproduce,
        # cycle by cycle, the dynamic protocol it replaced: a factor sends
        # once any of its variables has sent; a variable sends one cycle
        # after any of its factors did
        from pydcop_tpu.algorithms.maxsum import (
            activation_cycles,
            initial_active_mask,
        )
        from pydcop_tpu.compile.core import compile_dcop

        d = Domain("c", "", ["R", "G", "B"])
        vs = {n: Variable(n, d) for n in "pqrstu"}
        dcop = DCOP("wavefront")
        dcop += constraint_from_str(
            "k1", "10 if p == q else 0", [vs["p"], vs["q"]]
        )
        dcop += constraint_from_str(
            "k2", "10 if q == r else 0", [vs["q"], vs["r"]]
        )
        dcop += constraint_from_str(  # arity-3: act_f = min over 3 slots
            "k3",
            "(1 if r == s else 0) + (0 if s != t else 5)",
            [vs["r"], vs["s"], vs["t"]],
        )
        dcop += constraint_from_str(
            "k4", "10 if t == u else 0", [vs["t"], vs["u"]]
        )
        dcop.add_agents([])
        c = compile_dcop(dcop)
        act_v, act_f = activation_cycles(c, "leafs")
        va = initial_active_mask(c, "leafs")[: c.n_edges].copy()
        for i in range(8):
            assert np.array_equal(va, act_v[: c.n_edges] <= i), i
            fa_con = np.zeros(c.n_constraints, dtype=bool)
            np.logical_or.at(fa_con, c.edge_con, va)
            fa = fa_con[c.edge_con]
            assert np.array_equal(fa, act_f[: c.n_edges] <= i), i
            received = np.zeros(c.n_vars, dtype=bool)
            np.logical_or.at(received, c.edge_var, fa)
            va = va | received[c.edge_var]
        assert va.all()  # the wavefront saturates on a connected graph


class TestTimeout:
    """Real timeouts (round-2 verdict item 7): the device loop runs in
    chunks with the clock checked between them, returning the anytime-best
    with status TIMEOUT — the reference interrupts its agents and returns
    the anytime assignment (commands/solve.py:509-542)."""

    def _big(self):
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_coloring_arrays,
        )

        return generate_coloring_arrays(
            2000, 3, graph="scalefree", m_edge=2, seed=9
        )

    def test_long_solve_interrupted_within_budget(self):
        import time

        from pydcop_tpu.algorithms import dsa

        c = self._big()
        # warm-up so the measured wall is the loop, not jit compile
        dsa.solve(c, {}, n_cycles=100_000, seed=0, timeout=0.05)
        t0 = time.perf_counter()
        r = dsa.solve(c, {}, n_cycles=100_000, seed=0, timeout=0.5)
        wall = time.perf_counter() - t0
        assert r.status == "TIMEOUT"
        assert 0 < r.cycles < 100_000
        assert wall < 10  # budget + at most a few chunk lengths of overrun
        assert len(r.assignment) == c.n_vars  # valid anytime assignment
        assert np.isfinite(r.cost)

    def test_chunked_trajectory_matches_unchunked(self):
        from pydcop_tpu.algorithms import maxsum

        c = self._big()
        params = {"stop_cycle": 40}
        plain = maxsum.solve(c, dict(params), n_cycles=40, seed=3)
        # generous timeout: chunked execution, but never expires
        chunked = maxsum.solve(
            c, dict(params), n_cycles=40, seed=3, timeout=600.0
        )
        assert chunked.status == "FINISHED"
        assert chunked.assignment == plain.assignment
        assert chunked.cost == plain.cost

    def test_timeout_with_curve_collection(self):
        from pydcop_tpu.algorithms import dsa

        c = self._big()
        r = dsa.solve(
            c, {}, n_cycles=100_000, seed=0, collect_curve=True,
            timeout=0.5,
        )
        assert r.status == "TIMEOUT"
        assert 0 < r.cycles < 100_000
        assert len(r.cost_curve) == r.cycles

    def test_api_reports_timeout_status(self):
        from pydcop_tpu.api import solve_result
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_graph_coloring,
        )

        dcop = generate_graph_coloring(
            100, 3, graph="scalefree", m_edge=2, seed=9
        )
        r = solve_result(
            dcop, "dsa", n_cycles=100_000, seed=0, timeout=0.5
        )
        assert r["status"] == "TIMEOUT"
        assert len(r["assignment"]) == 100


class TestDsa:
    @pytest.mark.parametrize("variant", ["A", "B", "C"])
    def test_variants_chain(self, variant):
        ad = AlgorithmDef.build_with_default_param(
            "dsa", {"variant": variant}
        )
        r = solve_result(simple_chain(), ad, n_cycles=50, seed=1)
        assert r["cost"] == 0.0

    def test_seeded_determinism(self):
        d = load_dcop_from_file(f"{REF}/graph_coloring_3agts_10vars.yaml")
        r1 = solve_result(d, "dsa", n_cycles=30, seed=5)
        r2 = solve_result(d, "dsa", n_cycles=30, seed=5)
        assert r1["assignment"] == r2["assignment"]

    def test_10vars_quality(self):
        d = load_dcop_from_file(f"{REF}/graph_coloring_3agts_10vars.yaml")
        r = solve_result(d, "dsa", n_cycles=100, seed=0)
        assert r["violation"] <= 2

    def test_stop_cycle_param(self):
        ad = AlgorithmDef.build_with_default_param("dsa", {"stop_cycle": 7})
        r = solve_result(simple_chain(), ad, n_cycles=100, seed=0)
        assert r["cycle"] == 7


class TestMgm:
    @pytest.mark.parametrize("break_mode", ["lexic", "random"])
    def test_chain_optimal(self, break_mode):
        ad = AlgorithmDef.build_with_default_param(
            "mgm", {"break_mode": break_mode}
        )
        r = solve_result(simple_chain(), ad, n_cycles=30, seed=2)
        assert r["cost"] == 0.0 and r["violation"] == 0

    def test_monotone_curve(self):
        d = load_dcop_from_file(f"{REF}/graph_coloring_3agts_10vars.yaml")
        r = solve_result(d, "mgm", n_cycles=50, seed=3, collect_curve=True)
        curve = r["cost_curve"]
        assert all(b <= a + 1e-6 for a, b in zip(curve, curve[1:]))

    def test_seeded_determinism(self):
        d = load_dcop_from_file(f"{REF}/graph_coloring_3agts_10vars.yaml")
        r1 = solve_result(d, "mgm", n_cycles=30, seed=5)
        r2 = solve_result(d, "mgm", n_cycles=30, seed=5)
        assert r1["assignment"] == r2["assignment"]

    def test_local_optimum_reached(self):
        # after convergence no single-variable move can improve: re-running
        # longer never improves the cost further on this small instance
        d = load_dcop_from_file(f"{REF}/graph_coloring1.yaml")
        r = solve_result(d, "mgm", n_cycles=50, seed=0)
        assert r["cost"] == pytest.approx(-0.1)  # global optimum


class TestDsaTuto:
    def test_chain_optimal(self):
        r = solve_result(simple_chain(), "dsatuto", n_cycles=50, seed=0)
        assert r["cost"] == 0.0

    def test_no_params(self):
        mod = load_algorithm_module("dsatuto")
        assert mod.algo_params == []


class TestADsa:
    @pytest.mark.parametrize("variant", ["A", "B", "C"])
    def test_variants_chain(self, variant):
        ad = AlgorithmDef.build_with_default_param("adsa", {"variant": variant})
        r = solve_result(simple_chain(), ad, n_cycles=50, seed=1)
        assert r["cost"] == 0.0

    def test_quality_parity_with_sync_dsa(self):
        d = load_dcop_from_file(f"{REF}/graph_coloring_3agts_10vars.yaml")
        r = solve_result(d, "adsa", n_cycles=100, seed=0)
        assert r["violation"] <= 2  # optimum 1

    def test_seeded_determinism(self):
        d = load_dcop_from_file(f"{REF}/graph_coloring_3agts_10vars.yaml")
        r1 = solve_result(d, "adsa", n_cycles=30, seed=5)
        r2 = solve_result(d, "adsa", n_cycles=30, seed=5)
        assert r1["assignment"] == r2["assignment"]


class TestAMaxSum:
    def test_chain_optimal(self):
        r = solve_result(simple_chain(), "amaxsum", n_cycles=50, seed=0)
        assert r["cost"] == 0.0

    def test_quality_parity_with_sync_maxsum(self):
        d = load_dcop_from_file(f"{REF}/graph_coloring_3agts_10vars.yaml")
        r = solve_result(d, "amaxsum", n_cycles=100, seed=0)
        assert r["violation"] <= 2

    def test_stability_convergence_stops_early(self):
        # round-4 verdict item 5: ``stability`` must drive the same
        # approx_match stop as sync maxsum — a big cycle budget is not
        # burned once the awake subset keeps re-deriving stable messages
        r = solve_result(simple_chain(), "amaxsum", n_cycles=500, seed=0)
        assert r["status"] == "FINISHED"
        assert r["cycle"] < 500
        assert r["cost"] == 0.0

    def test_stop_cycle_disables_stability_stop(self):
        ad = AlgorithmDef("amaxsum", {"stop_cycle": 40})
        r = solve_result(simple_chain(), ad, n_cycles=500, seed=0)
        assert r["cycle"] == 40

    def test_start_messages_warns_inert(self):
        import warnings

        ad = AlgorithmDef("amaxsum", {"start_messages": "all"})
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            solve_result(simple_chain(), ad, n_cycles=10, seed=0)
        assert any(
            "start_messages" in str(w.message)
            and "no effect" in str(w.message)
            for w in caught
        )


class TestMixedDsa:
    def mixed_problem(self):
        d = Domain("c", "", ["R", "G", "B"])
        vs = [Variable(f"v{i}", d) for i in range(4)]
        m = DCOP("mix")
        m += constraint_from_str(
            "h1", "float('inf') if v0 == v1 else 0", [vs[0], vs[1]]
        )
        m += constraint_from_str(
            "h2", "float('inf') if v1 == v2 else 0", [vs[1], vs[2]]
        )
        m += constraint_from_str("s1", "3 if v2 == v3 else 1", [vs[2], vs[3]])
        m.add_agents([])
        return m

    @pytest.mark.parametrize("variant", ["A", "B", "C"])
    def test_hard_satisfied_soft_optimal(self, variant):
        ad = AlgorithmDef.build_with_default_param(
            "mixeddsa", {"variant": variant}
        )
        r = solve_result(self.mixed_problem(), ad, n_cycles=60, seed=1)
        assert r["violation"] == 0  # hard constraints all satisfied
        assert r["cost"] == 1.0  # soft optimum

    def test_soft_only_problem(self):
        d = load_dcop_from_file(f"{REF}/graph_coloring_3agts_10vars.yaml")
        r = solve_result(d, "mixeddsa", n_cycles=100, seed=0)
        assert r["violation"] <= 2


def csp_chain():
    """Hard-constraint chain: violations cost >= infinity (CSP for DBA)."""
    d = Domain("c", "", ["R", "G"])
    x, y, z = (Variable(n, d) for n in "xyz")
    dcop = DCOP("csp_chain")
    dcop += constraint_from_str("c1", "10000 if x == y else 0", [x, y])
    dcop += constraint_from_str("c2", "10000 if y == z else 0", [y, z])
    dcop.add_agents([])
    return dcop


class TestDba:
    def test_csp_chain_solved(self):
        r = solve_result(csp_chain(), "dba", n_cycles=30, seed=0)
        assert r["cost"] == 0.0 and r["violation"] == 0

    def test_10vars_quality(self):
        d = load_dcop_from_file(f"{REF}/graph_coloring_3agts_10vars.yaml")
        r = solve_result(d, "dba", n_cycles=50, seed=0)
        assert r["violation"] == 1  # optimum for this non-2-colorable graph

    def test_max_mode_rejected(self):
        d = Domain("c", "", [0, 1])
        x, y = Variable("x", d), Variable("y", d)
        dcop = DCOP("m", objective="max")
        dcop += constraint_from_str("c1", "x + y", [x, y])
        dcop.add_agents([])
        with pytest.raises(ValueError):
            solve_result(dcop, "dba", n_cycles=5)

    def test_seeded_determinism(self):
        d = load_dcop_from_file(f"{REF}/graph_coloring_3agts_10vars.yaml")
        r1 = solve_result(d, "dba", n_cycles=20, seed=4)
        r2 = solve_result(d, "dba", n_cycles=20, seed=4)
        assert r1["assignment"] == r2["assignment"]


class TestGdba:
    @pytest.mark.parametrize("modifier", ["A", "M"])
    @pytest.mark.parametrize("violation", ["NZ", "NM", "MX"])
    @pytest.mark.parametrize("increase_mode", ["E", "R", "C", "T"])
    def test_all_24_variants_chain(self, modifier, violation, increase_mode):
        ad = AlgorithmDef.build_with_default_param(
            "gdba",
            {
                "modifier": modifier,
                "violation": violation,
                "increase_mode": increase_mode,
            },
        )
        r = solve_result(simple_chain(), ad, n_cycles=30, seed=1)
        assert r["cost"] == 0.0

    def test_10vars_quality(self):
        d = load_dcop_from_file(f"{REF}/graph_coloring_3agts_10vars.yaml")
        r = solve_result(d, "gdba", n_cycles=80, seed=0)
        assert r["violation"] <= 2

    def test_escapes_local_minimum_via_weights(self):
        # GDBA's breakout mechanism should eventually leave a local optimum
        # that plain MGM-style search cannot
        d = load_dcop_from_file(f"{REF}/graph_coloring1.yaml")
        r = solve_result(d, "gdba", n_cycles=50, seed=0)
        assert r["violation"] == 0


def brute_force(dcop, infinity=10000):
    """Exhaustive optimum (cost with violations weighted at infinity)."""
    import itertools

    names = sorted(dcop.variables)
    doms = [dcop.variables[n].domain.values for n in names]
    best, bcost = None, float("inf")
    for combo in itertools.product(*doms):
        a = dict(zip(names, combo))
        c, v = dcop.solution_cost(a, infinity)
        total = c + v * infinity
        if total < bcost:
            bcost, best = total, a
    return bcost, best


class TestDpop:
    def test_chain_optimal(self):
        r = solve_result(simple_chain(), "dpop")
        assert r["cost"] == 0.0 and r["violation"] == 0
        assert r["cycle"] == 1

    def test_random_binary_matches_brute_force(self):
        import random

        random.seed(7)
        d = Domain("d", "", list(range(3)))
        for trial in range(4):
            vs = [Variable(f"v{i}", d) for i in range(6)]
            dcop = DCOP(f"t{trial}")
            for k in range(8):
                i, j = random.sample(range(6), 2)
                coeffs = [random.randint(0, 9) for _ in range(9)]
                expr = f"[{','.join(map(str, coeffs))}][v{i}*3+v{j}]"
                dcop += constraint_from_str(f"c{k}", expr, [vs[i], vs[j]])
            dcop.add_agents([])
            bc, _ = brute_force(dcop)
            r = solve_result(dcop, "dpop")
            assert r["cost"] == pytest.approx(bc)

    def test_ternary_constraint(self):
        d = Domain("d", "", [0, 1])
        x, y, z = (Variable(n, d) for n in "xyz")
        dcop = DCOP("tern")
        dcop += constraint_from_str("c1", "(x + y + z - 1) ** 2", [x, y, z])
        dcop += constraint_from_str("c2", "3 * x", [x])
        dcop.add_agents([])
        r = solve_result(dcop, "dpop")
        assert r["cost"] == 0.0
        assert r["assignment"]["x"] == 0

    def test_deep_tree_2k_vars(self):
        # level-batched UTIL schedule: trace/compile cost must be bounded by
        # tree depth, not variable count (round-2 verdict item 4) — this
        # deep random tree (depth ~800) was far past the old per-node-trace
        # compile wall.  Exactness checked against an independent numpy DP.
        import time

        from pydcop_tpu.algorithms import dpop
        from pydcop_tpu.compile.direct import compile_from_edges

        n = 2000
        rng = np.random.default_rng(3)
        parents = np.array(
            [rng.integers(max(0, i - 4), i) for i in range(1, n)]
        )
        edges = np.stack([parents, np.arange(1, n)], axis=1)
        tables = rng.uniform(0, 10, size=(len(edges), 3, 3)).astype(
            np.float32
        )
        c = compile_from_edges(n, 3, edges, tables)
        t0 = time.perf_counter()
        r = dpop.solve(c, {})
        elapsed = time.perf_counter() - t0
        # independent bottom-up DP on the tree (float64 host arithmetic)
        util = np.zeros((n, 3))
        for i in range(n - 1, 0, -1):
            p = parents[i - 1]
            util[p] += (tables[i - 1].astype(np.float64) + util[i]).min(
                axis=1
            )
        assert r.cost == pytest.approx(float(util[0].min()), rel=1e-5)
        assert elapsed < 120, elapsed

    def test_chunked_fallback_matches_in_core(self, monkeypatch):
        # wide separators must switch to the sequential chunked path, not
        # raise; force it with tiny limits and check exactness is unchanged
        import random

        from pydcop_tpu.algorithms import dpop
        from pydcop_tpu.compile.core import compile_dcop

        random.seed(11)
        d = Domain("d", "", list(range(3)))
        vs = [Variable(f"v{i}", d) for i in range(7)]
        dcop = DCOP("wide")
        for k in range(10):
            i, j = random.sample(range(7), 2)
            coeffs = [random.randint(0, 9) for _ in range(9)]
            expr = f"[{','.join(map(str, coeffs))}][v{i}*3+v{j}]"
            dcop += constraint_from_str(f"c{k}", expr, [vs[i], vs[j]])
        dcop.add_agents([])
        c = compile_dcop(dcop)
        baseline = dpop.solve(c, {})
        monkeypatch.setattr(dpop, "MAX_JOINT_ELEMS", 9)
        monkeypatch.setattr(dpop, "CHUNK_ELEMS", 9)
        chunked = dpop.solve(c, {})
        assert chunked.cost == pytest.approx(baseline.cost)
        assert chunked.assignment == baseline.assignment

    def test_forest(self):
        # two disconnected components, each solved at its own root
        d = Domain("d", "", [0, 1])
        dcop = DCOP("forest")
        a, b, c, e = (Variable(n, d) for n in "abce")
        dcop += constraint_from_str("c1", "0 if a != b else 5", [a, b])
        dcop += constraint_from_str("c2", "0 if c != e else 7", [c, e])
        dcop.add_agents([])
        r = solve_result(dcop, "dpop")
        assert r["cost"] == 0.0

    def test_max_mode(self):
        d = load_dcop_from_file(f"{REF}/graph_coloring1.yaml")
        r = solve_result(d, "dpop")
        assert r["cost"] == pytest.approx(-0.1)

    def test_10vars_exact(self):
        d = load_dcop_from_file(f"{REF}/graph_coloring_3agts_10vars.yaml")
        r = solve_result(d, "dpop")
        # this instance is not 2-colorable: known optimum is 1 violation
        assert r["violation"] == 1


class TestMgm2:
    @pytest.mark.parametrize("favor", ["unilateral", "no", "coordinated"])
    def test_chain_optimal(self, favor):
        ad = AlgorithmDef.build_with_default_param("mgm2", {"favor": favor})
        r = solve_result(simple_chain(), ad, n_cycles=40, seed=2)
        assert r["cost"] == 0.0 and r["violation"] == 0

    def test_monotone_curve(self):
        d = load_dcop_from_file(f"{REF}/graph_coloring_3agts_10vars.yaml")
        r = solve_result(d, "mgm2", n_cycles=50, seed=3, collect_curve=True)
        curve = r["cost_curve"]
        assert all(b <= a + 1e-6 for a, b in zip(curve, curve[1:]))

    def test_escapes_mgm_local_optimum(self):
        # two variables that must move together: solo moves are never
        # improving, only the coordinated 2-move reaches the optimum
        d = Domain("b", "", [0, 1])
        x, y = Variable("x", d), Variable("y", d)
        dcop = DCOP("pair")
        # cost 0 at (1,1), 5 at (0,0), 10 when they differ
        dcop += constraint_from_str(
            "c1", "0 if (x==1 and y==1) else (5 if x==y else 10)", [x, y]
        )
        dcop.add_agents([])
        found = []
        for seed in range(6):
            r = solve_result(dcop, "mgm2", n_cycles=60, seed=seed)
            found.append(r["cost"])
        assert 0.0 in found  # coordinated move found the global optimum

    def test_quality_10vars(self):
        d = load_dcop_from_file(f"{REF}/graph_coloring_3agts_10vars.yaml")
        r = solve_result(d, "mgm2", n_cycles=80, seed=0)
        assert r["violation"] <= 2

    def test_coordinates_over_parallel_constraints(self):
        # two parallel binary constraints between the same pair (the
        # round-2 build excluded such pairs from coordination): their
        # tables sum into one offer table, so the coordinated move must
        # still escape the solo-move trap at (0,0)
        d = Domain("b", "", [0, 1])
        x, y = Variable("x", d), Variable("y", d)
        dcop = DCOP("parallel_pair")
        # c1 + c2: (0,0)=1, differ=6, (1,1)=0 — solo moves from (0,0)
        # always worsen; only the pair move reaches the optimum
        dcop += constraint_from_str(
            "c1", "0 if (x==1 and y==1) else (1 if x==y else 3)", [x, y]
        )
        dcop += constraint_from_str(
            "c2", "0 if (x==1 and y==1) else 3 * (x != y)", [x, y]
        )
        dcop.add_agents([])
        found = []
        for seed in range(8):
            r = solve_result(dcop, "mgm2", n_cycles=60, seed=seed)
            found.append(r["cost"])
        assert 0.0 in found
        # monotone even with the summed table (gain formula stays exact)
        r = solve_result(
            dcop, "mgm2", n_cycles=40, seed=1, collect_curve=True
        )
        curve = r["cost_curve"]
        assert all(b <= a + 1e-6 for a, b in zip(curve, curve[1:]))

    def test_footprint_and_load_functions(self):
        # distribution inputs (reference test_algorithms_mgm2.py:57-96)
        from pydcop_tpu.algorithms import mgm2
        from pydcop_tpu.computations_graph.constraints_hypergraph import (
            build_computation_graph,
        )

        d = Domain("d", "", [0, 1, 2])
        x, y, z = (Variable(n, d) for n in "xyz")
        dcop = DCOP("t")
        dcop += constraint_from_str("c1", "x + y", [x, y])
        dcop += constraint_from_str("c2", "x + z", [x, z])
        dcop.add_agents([])
        g = build_computation_graph(dcop)
        node_x = g.computation("x")
        assert mgm2.computation_memory(node_x) == 2 * 3  # 2 neighbors
        load = mgm2.communication_load(node_x, "y")
        assert load >= 9  # at least the D*D offer table

    def test_movers_form_independent_set_or_offer_pairs(self):
        # the core MGM-2 invariant behind the reference's whole
        # offer/answer/go state machine (test_algorithms_mgm2.py:366-1233):
        # two constraint-graph neighbors never move in the same cycle
        # unless they are a committed coordinated pair — checked here
        # directly on the value trajectory of manual steps
        import random

        import jax

        from pydcop_tpu.algorithms import mgm2
        from pydcop_tpu.compile.core import compile_dcop
        from pydcop_tpu.compile.kernels import to_device

        random.seed(4)
        d = Domain("d", "", [0, 1, 2])
        vs = [Variable(f"v{i}", d) for i in range(12)]
        dcop = DCOP("inv")
        for k in range(18):
            i, j = random.sample(range(12), 2)
            coeffs = [random.randint(0, 9) for _ in range(9)]
            expr = f"[{','.join(map(str, coeffs))}][v{i}*3+v{j}]"
            dcop += constraint_from_str(f"c{k}", expr, [vs[i], vs[j]])
        dcop.add_agents([])
        c = compile_dcop(dcop)
        dev = to_device(c)
        src, dst = c.neighbor_pairs()
        import jax.numpy as jnp

        ns, nd = jnp.asarray(src), jnp.asarray(dst)
        offers = mgm2._offer_structure(c, dev)
        consts = (ns, nd) + tuple(offers)
        step = mgm2._make_step(
            0.5, "unilateral", bool(offers[0].shape[0]),
            bool(offers[6].shape[0]),
        )
        key = jax.random.PRNGKey(3)
        state = mgm2._init(dev, key, *consts)
        offer_pairs = {
            (int(s), int(t))
            for s, t in zip(np.asarray(offers[0]), np.asarray(offers[1]))
        }
        edges = list(zip(src.tolist(), dst.tolist()))
        for cycle in range(25):
            prev = np.asarray(state.values)
            state = step(dev, state, jax.random.fold_in(key, cycle))
            cur = np.asarray(state.values)
            moved = prev[: c.n_vars] != cur[: c.n_vars]
            for u, v in edges:
                if moved[u] and moved[v]:
                    assert (u, v) in offer_pairs or (
                        v, u,
                    ) in offer_pairs, (cycle, u, v)

    def test_max_mode_monotone_and_optimal(self):
        # offers/gains in max mode (reference test_algorithms_mgm2.py:157,
        # 519, 590): the anytime curve must be non-decreasing and some
        # seed reaches the known optimum of the reference instance
        d3 = Domain("b", "", [0, 1])
        x, y, z = (Variable(n, d3) for n in "xyz")
        dcop = DCOP("maxpref", "max")
        dcop += constraint_from_str("c1", "1 if x != y else 0", [x, y])
        dcop += constraint_from_str("c2", "1 if y != z else 0", [y, z])
        dcop.add_agents([])
        best = None
        for seed in range(4):
            r = solve_result(
                dcop, "mgm2", n_cycles=40, seed=seed, collect_curve=True
            )
            curve = r["cost_curve"]
            assert all(b >= a - 1e-6 for a, b in zip(curve, curve[1:]))
            best = max(best, r["cost"]) if best is not None else r["cost"]
        assert best == pytest.approx(2.0)

    def test_higher_arity_pairs_coordinate(self):
        # round-4 verdict item 6: pairs sharing a ternary constraint now
        # coordinate over its per-cycle sliced table (the reference
        # coordinates over any shared constraint, mgm2.py:399) — every
        # scope pair gets offer edges and the solve stays monotone
        from pydcop_tpu.algorithms.mgm2 import _offer_structure
        from pydcop_tpu.compile.core import compile_dcop
        from pydcop_tpu.compile.kernels import to_device

        d = Domain("b", "", [0, 1])
        x, y, z = Variable("x", d), Variable("y", d), Variable("z", d)
        dcop = DCOP("mixed")
        dcop += constraint_from_str("c1", "2 * (x != y)", [x, y])
        dcop += constraint_from_str("c2", "(x + y + z) % 2", [x, y, z])
        dcop += constraint_from_str("c3", "3 * (y != z)", [y, z])
        dcop.add_agents([])
        c = compile_dcop(dcop)
        offers = _offer_structure(c, to_device(c))
        offered = {
            (int(s), int(t))
            for s, t in zip(np.asarray(offers[0]), np.asarray(offers[1]))
        }
        xi, yi, zi = (c.var_index[n] for n in "xyz")
        # all three pairs coordinate: x-y (binary + ternary), y-z (binary
        # + ternary), x-z (ternary only)
        for pair in ((xi, yi), (yi, zi), (xi, zi)):
            assert pair in offered and pair[::-1] in offered
        # ternary-sliced entries exist, sorted by target edge
        dyn_edge = np.asarray(offers[6])
        assert dyn_edge.shape[0] == 6  # 3 scope pairs x 2 orientations
        assert (np.diff(dyn_edge) >= 0).all()
        r = solve_result(
            dcop, "mgm2", n_cycles=30, seed=0, collect_curve=True
        )
        curve = r["cost_curve"]
        assert all(b <= a + 1e-6 for a, b in zip(curve, curve[1:]))

    def test_higher_arity_coordination_escapes_binary_only_minima(self):
        # an all-equal 4-ary constraint creates local minima a unilateral
        # (or binary-only-coordinated) searcher cannot leave; with the
        # sliced-table coordination some seed must reach a zero-penalty
        # assignment
        d = Domain("s", "", [0, 1, 2])
        vs = [Variable(f"v{i}", d) for i in range(4)]
        dcop = DCOP("allequal")
        prefs = ([0, 2, 2], [2, 0, 2], [2, 2, 0], [2, 0, 2])
        for v, p in zip(vs, prefs):
            dcop += constraint_from_str(
                f"pref_{v.name}", f"[{','.join(map(str, p))}][{v.name}]", [v]
            )
        names = [v.name for v in vs]
        cond = " and ".join(f"{names[0]} == {n}" for n in names[1:])
        dcop += constraint_from_str(
            "allequal", f"0 if ({cond}) else 100", vs
        )
        dcop.add_agents([])
        best = min(
            solve_result(dcop, "mgm2", n_cycles=60, seed=s)["cost"]
            for s in range(6)
        )
        assert best < 100  # the 4-ary penalty is escaped


class TestSyncBB:
    def test_chain_optimal(self):
        r = solve_result(simple_chain(), "syncbb")
        assert r["cost"] == 0.0 and r["violation"] == 0
        assert r["cycle"] == 0  # reference reports cycle 0 for syncbb
        assert r["msg_count"] > 0

    def test_random_binary_matches_brute_force(self):
        import random

        random.seed(11)
        d = Domain("d", "", list(range(3)))
        for trial in range(3):
            vs = [Variable(f"v{i}", d) for i in range(6)]
            dcop = DCOP(f"t{trial}")
            for k in range(8):
                i, j = random.sample(range(6), 2)
                coeffs = [random.randint(0, 9) for _ in range(9)]
                expr = f"[{','.join(map(str, coeffs))}][v{i}*3+v{j}]"
                dcop += constraint_from_str(f"c{k}", expr, [vs[i], vs[j]])
            dcop.add_agents([])
            bc, _ = brute_force(dcop)
            r = solve_result(dcop, "syncbb")
            assert r["cost"] == pytest.approx(bc)

    def test_max_mode(self):
        d = load_dcop_from_file(f"{REF}/graph_coloring1.yaml")
        r = solve_result(d, "syncbb")
        assert r["cost"] == pytest.approx(-0.1)

    def test_iteration_cap_reports_timeout(self):
        # a complete solver must never silently pass off an interrupted
        # search as optimal: with a deliberately tiny max_iters the DFS
        # cannot finish and the anytime incumbent is flagged TIMEOUT
        # (reference anytime-interruption semantics, commands/solve.py:509)
        import random

        random.seed(7)
        d = Domain("d", "", list(range(3)))
        vs = [Variable(f"v{i}", d) for i in range(8)]
        dcop = DCOP("cap")
        for k in range(12):
            i, j = random.sample(range(8), 2)
            coeffs = [random.randint(0, 9) for _ in range(9)]
            expr = f"[{','.join(map(str, coeffs))}][v{i}*3+v{j}]"
            dcop += constraint_from_str(f"c{k}", expr, [vs[i], vs[j]])
        dcop.add_agents([])
        algo = AlgorithmDef.build_with_default_param(
            "syncbb", {"max_iters": 5}
        )
        r = solve_result(dcop, algo)
        assert r["status"] == "TIMEOUT"
        # uncapped, the same problem is proven optimal
        full = solve_result(dcop, "syncbb")
        assert full["status"] == "FINISHED"

    def test_ternary_rejected(self):
        d = Domain("d", "", [0, 1])
        x, y, z = (Variable(n, d) for n in "xyz")
        dcop = DCOP("tern")
        dcop += constraint_from_str("c1", "x + y + z", [x, y, z])
        dcop.add_agents([])
        with pytest.raises(ValueError, match="binary"):
            solve_result(dcop, "syncbb")

    def test_unary_costs_respected(self):
        from pydcop_tpu.dcop import VariableWithCostFunc
        from pydcop_tpu.utils.expressions import ExpressionFunction

        d = Domain("d", "", [0, 1, 2])
        v = VariableWithCostFunc(
            "v", d, ExpressionFunction("v * 2 + (v - 2) ** 2")
        )
        dcop = DCOP("u")
        dcop.add_variable(v)
        dcop += constraint_from_str("c1", "0 * v", [v])
        dcop.add_agents([])
        r = solve_result(dcop, "syncbb")
        assert r["assignment"]["v"] == 1


class TestNcbb:
    def test_chain_optimal(self):
        r = solve_result(simple_chain(), "ncbb")
        assert r["cost"] == 0.0 and r["violation"] == 0

    def test_iteration_cap_reports_timeout(self):
        # same contract as syncbb: an expired cap must be flagged
        import random

        random.seed(3)
        d = Domain("d", "", list(range(3)))
        vs = [Variable(f"v{i}", d) for i in range(8)]
        dcop = DCOP("cap")
        for k in range(12):
            i, j = random.sample(range(8), 2)
            coeffs = [random.randint(0, 9) for _ in range(9)]
            expr = f"[{','.join(map(str, coeffs))}][v{i}*3+v{j}]"
            dcop += constraint_from_str(f"c{k}", expr, [vs[i], vs[j]])
        dcop.add_agents([])
        algo = AlgorithmDef.build_with_default_param(
            "ncbb", {"max_iters": 5}
        )
        r = solve_result(dcop, algo)
        assert r["status"] == "TIMEOUT"

    def test_random_binary_matches_brute_force(self):
        import random

        random.seed(13)
        d = Domain("d", "", list(range(3)))
        for trial in range(3):
            vs = [Variable(f"v{i}", d) for i in range(6)]
            dcop = DCOP(f"t{trial}")
            for k in range(8):
                i, j = random.sample(range(6), 2)
                coeffs = [random.randint(0, 9) for _ in range(9)]
                expr = f"[{','.join(map(str, coeffs))}][v{i}*3+v{j}]"
                dcop += constraint_from_str(f"c{k}", expr, [vs[i], vs[j]])
            dcop.add_agents([])
            bc, _ = brute_force(dcop)
            r = solve_result(dcop, "ncbb")
            assert r["cost"] == pytest.approx(bc)

    def test_greedy_seed_prunes(self):
        # ncbb's greedy-init upper bound must not break optimality when the
        # greedy assignment IS the optimum (strict-bound edge case)
        d = Domain("d", "", [0, 1])
        x, y = Variable("x", d), Variable("y", d)
        dcop = DCOP("g")
        dcop += constraint_from_str("c1", "0 if x == y else 3", [x, y])
        dcop.add_agents([])
        r = solve_result(dcop, "ncbb")
        assert r["cost"] == 0.0

    def test_forest(self):
        d = Domain("d", "", [0, 1])
        dcop = DCOP("forest")
        a, b, c, e = (Variable(n, d) for n in "abce")
        dcop += constraint_from_str("c1", "0 if a != b else 5", [a, b])
        dcop += constraint_from_str("c2", "0 if c != e else 7", [c, e])
        dcop.add_agents([])
        r = solve_result(dcop, "ncbb")
        assert r["cost"] == 0.0


class TestDynamicMaxSum:
    def test_static_behaves_like_maxsum(self):
        r = solve_result(simple_chain(), "maxsum_dynamic", n_cycles=30, seed=0)
        assert r["cost"] == 0.0

    def test_factor_function_change(self):
        from pydcop_tpu.algorithms.maxsum_dynamic import DynamicMaxSum

        d = Domain("c", "", ["R", "G"])
        x, y = Variable("x", d), Variable("y", d)
        dcop = DCOP("dyn")
        c_eq = constraint_from_str("c1", "10 if x == y else 0", [x, y])
        dcop += c_eq
        dcop.add_agents([])
        session = DynamicMaxSum(dcop, params={"damping": 0.0})
        r1 = session.run(20)
        a1 = r1.assignment
        assert a1["x"] != a1["y"] and r1.cost == 0.0
        # invert the factor: now equality is free, difference costs 10
        c_neq = constraint_from_str("c1", "0 if x == y else 10", [x, y])
        session.change_factor_function("c1", c_neq)
        r2 = session.run(20)
        assert r2.assignment["x"] == r2.assignment["y"] and r2.cost == 0.0
        assert r2.cycles == 40  # cumulative cycles over the session

    def test_scope_change_rejected(self):
        from pydcop_tpu.algorithms.maxsum_dynamic import DynamicMaxSum

        d = Domain("c", "", [0, 1])
        x, y, z = (Variable(n, d) for n in "xyz")
        dcop = DCOP("dyn")
        dcop += constraint_from_str("c1", "x + y", [x, y])
        dcop += constraint_from_str("c2", "y + z", [y, z])
        dcop.add_agents([])
        session = DynamicMaxSum(dcop)
        with pytest.raises(ValueError, match="scope"):
            session.change_factor_function(
                "c1", constraint_from_str("c1", "x + z", [x, z])
            )

    def test_external_variable_update(self):
        from pydcop_tpu.algorithms.maxsum_dynamic import DynamicMaxSum
        from pydcop_tpu.dcop import ExternalVariable

        d = Domain("c", "", [0, 1])
        x = Variable("x", d)
        sensor = ExternalVariable("sensor", d, value=0)
        dcop = DCOP("ext")
        dcop.add_variable(sensor)
        # x must track the sensor: cost 5 when different
        dcop += constraint_from_str(
            "c1", "0 if x == sensor else 5", [x, sensor]
        )
        dcop.add_agents([])
        session = DynamicMaxSum(dcop, params={"noise": 0.0})
        r1 = session.run(10)
        assert r1.assignment["x"] == 0
        sensor.value = 1  # subscription re-lowers the factor tables
        r2 = session.run(10)
        assert r2.assignment["x"] == 1

    @staticmethod
    def _square_plane_dcop():
        """n_edges == max_domain == 4: the shape where a checkpoint's
        [n_edges, D] and [D, n_edges] plane orientations are
        indistinguishable by shape alone."""
        d = Domain("c", "", [0, 1, 2, 3])
        x, y, z = Variable("x", d), Variable("y", d), Variable("z", d)
        dcop = DCOP("square")
        dcop += constraint_from_str("c1", "10 if x == y else 0", [x, y])
        dcop += constraint_from_str("c2", "10 if y == z else 0", [y, z])
        dcop.add_agents([])
        return dcop

    def test_square_plane_checkpoint_cross_layout(self, tmp_path):
        # a lanes-session checkpoint restored into an edges session (and
        # vice versa) must come back in the right orientation even when
        # the planes are square: the recorded plane_layout metadata
        # disambiguates what shape checking cannot
        from pydcop_tpu.algorithms.maxsum_dynamic import DynamicMaxSum

        dcop = self._square_plane_dcop()
        src = DynamicMaxSum(dcop, params={"layout": "lanes"}, seed=0)
        try:
            src.run(4)
            assert np.asarray(src.state.v2f).shape == (4, 4)
            path = str(tmp_path / "sq.npz")
            src.save(path)
            dst = DynamicMaxSum(dcop, params={"layout": "edges"}, seed=0)
            try:
                dst.restore(path)
                # lanes stores transposed planes; the edges session must
                # see the transpose back, not the raw square array
                assert np.array_equal(
                    np.asarray(dst.state.v2f),
                    np.asarray(src.state.v2f).T,
                )
                assert np.array_equal(
                    np.asarray(dst.state.f2v),
                    np.asarray(src.state.f2v).T,
                )
                assert dst.current_assignment == src.current_assignment
                dst.run(4)  # restored state must be runnable
            finally:
                dst.close()
        finally:
            src.close()

    def test_square_plane_same_layout_roundtrip(self, tmp_path):
        from pydcop_tpu.algorithms.maxsum_dynamic import DynamicMaxSum

        dcop = self._square_plane_dcop()
        src = DynamicMaxSum(dcop, params={"layout": "edges"}, seed=0)
        try:
            src.run(4)
            path = str(tmp_path / "sq.npz")
            src.save(path)
            dst = DynamicMaxSum(dcop, params={"layout": "edges"}, seed=0)
            try:
                dst.restore(path)
                assert np.array_equal(
                    np.asarray(dst.state.v2f), np.asarray(src.state.v2f)
                )
            finally:
                dst.close()
        finally:
            src.close()

    def test_square_plane_legacy_checkpoint_prefers_untransposed(
        self, tmp_path
    ):
        # a pre-metadata legacy checkpoint (bare leaf list) with square
        # planes is genuinely ambiguous; every legacy writer stored
        # edges-layout planes, so the untransposed reading must win
        import jax.numpy as jnp

        from pydcop_tpu.algorithms.maxsum_dynamic import DynamicMaxSum
        from pydcop_tpu.utils.checkpoint import save_checkpoint

        dcop = self._square_plane_dcop()
        ses = DynamicMaxSum(dcop, params={"layout": "edges"}, seed=0)
        try:
            ses.run(4)
            v2f = np.asarray(ses.state.v2f)  # [n_edges, D], square
            f2v = np.asarray(ses.state.f2v)
            assert v2f.shape[0] == v2f.shape[1]
            path = str(tmp_path / "legacy.npz")
            # 5-leaf legacy layout: (v2f, f2v, cycle, act_v, act_f)
            save_checkpoint(
                path,
                (
                    jnp.asarray(v2f),
                    jnp.asarray(f2v),
                    jnp.asarray(4, jnp.int32),
                    jnp.zeros(1, jnp.int32),
                    jnp.zeros(1, jnp.int32),
                ),
                metadata={"cycles_done": 4, "msg_count": 32},
            )
            dst = DynamicMaxSum(dcop, params={"layout": "edges"}, seed=0)
            try:
                dst.restore(path)
                assert np.array_equal(np.asarray(dst.state.v2f), v2f)
                assert np.array_equal(np.asarray(dst.state.f2v), f2v)
            finally:
                dst.close()
        finally:
            ses.close()


class TestCompleteSolversAgree:
    """Cross-solver fuzz: on random binary instances the three complete
    solvers (DPOP, SyncBB, NCBB) must all reach the brute-force optimum —
    a disagreement in ANY of them is a correctness bug, whatever the
    trajectory differences."""

    @pytest.mark.parametrize("trial", range(6))
    def test_random_instances(self, trial):
        import random

        random.seed(100 + trial)
        n = random.randint(4, 7)
        dsize = random.choice([2, 3])
        d = Domain("d", "", list(range(dsize)))
        vs = [Variable(f"v{i}", d) for i in range(n)]
        dcop = DCOP(f"fuzz{trial}")
        for k in range(random.randint(n - 1, 2 * n)):
            i, j = random.sample(range(n), 2)
            coeffs = [
                random.randint(0, 9) for _ in range(dsize * dsize)
            ]
            expr = f"[{','.join(map(str, coeffs))}][v{i}*{dsize}+v{j}]"
            dcop += constraint_from_str(f"c{k}", expr, [vs[i], vs[j]])
        dcop.add_agents([])
        bc, _ = brute_force(dcop)
        for algo in ("dpop", "syncbb", "ncbb"):
            r = solve_result(dcop, algo)
            assert r["cost"] == pytest.approx(bc), (algo, trial)
            assert r["status"] == "FINISHED"


class TestAllAlgorithmsSmoke:
    """Every registered algorithm solves the simple chain acceptably —
    the registry-wide matrix the reference runs per-algorithm in
    tests/api/test_api_solve.py."""

    @pytest.mark.parametrize("algo", list_available_algorithms())
    def test_chain(self, algo):
        r = solve_result(simple_chain(), algo, n_cycles=50, seed=1)
        # complete solvers finish this tiny chain well inside any cap, and
        # an expired cap now reports TIMEOUT, so FINISHED is the only
        # acceptable terminal status here
        assert r["status"] == "FINISHED"
        assert set(r["assignment"]) == {"x", "y", "z"}
        # complete algorithms must reach the optimum; local search must at
        # least produce a valid full assignment with bounded cost
        if algo in ("dpop", "syncbb", "ncbb"):
            assert r["cost"] == 0.0
        else:
            # at most one of the two conflict constraints violated: rules
            # out worst-assignment convergence (cost 20)
            assert r["cost"] <= 10.0


class TestTransferCensus:
    """Round-4 verdict item 3: on a tunneled TPU every host<->device
    round trip costs ~50 ms — more than a whole 100k-variable cycle — so
    the warm solve path must be transfer-minimal.  Pins, for EVERY
    registered algorithm: a warm repeat solve performs ZERO host-to-device
    uploads (operands are device-resident cached) and at most ONE packed
    byte readback (values + scalars + cycles) on the host side."""

    @pytest.mark.parametrize("algo", list_available_algorithms())
    def test_warm_solve_zero_uploads_one_readback(self, algo, monkeypatch):
        import jax

        from pydcop_tpu.algorithms import base
        from pydcop_tpu.compile.core import compile_dcop
        from pydcop_tpu.compile.kernels import to_device

        compiled = compile_dcop(simple_chain())
        dev = to_device(compiled)
        mod = load_algorithm_module(algo)
        warm = mod.solve(compiled, {}, n_cycles=8, seed=0, dev=dev)

        readbacks = []
        orig = base.to_host
        monkeypatch.setattr(
            base, "to_host", lambda x: (readbacks.append(1), orig(x))[1]
        )
        # any upload inside the guard raises JaxRuntimeError;
        # disallow_EXPLICIT is load-bearing: plain "disallow" only covers
        # implicit transfers, letting per-solve jnp.asarray uploads (one
        # relay round trip each) slip through unseen — which is exactly
        # how mgm/dba/gdba re-uploaded their neighbor arrays every warm
        # solve until round 5
        with jax.transfer_guard_host_to_device("disallow_explicit"):
            again = mod.solve(compiled, {}, n_cycles=8, seed=0, dev=dev)
        assert len(readbacks) <= 1
        assert again.cost == warm.cost


class TestInertParamContract:
    """Round-4 verdict item 5: no silently-ignored parameter anywhere in
    the registry.  Every algorithm's declared parameter must either be
    honored or warn when explicitly set; modules declare the latter in a
    module-level ``inert_params`` dict and the warning fires through
    ``warn_inert_params``."""

    @staticmethod
    def _non_default(pdef):
        if pdef.values:
            return next(v for v in pdef.values if v != pdef.default_value)
        if pdef.type in ("int", "float"):
            return (pdef.default_value or 0) + 1
        return not pdef.default_value  # bool

    @pytest.mark.parametrize("algo", list_available_algorithms())
    def test_params_warn_iff_declared_inert(self, algo):
        import warnings

        mod = load_algorithm_module(algo)
        inert = getattr(mod, "inert_params", {})
        declared = {p.name for p in mod.algo_params}
        assert set(inert) <= declared, "inert_params names unknown params"

        def hits(caught, name):
            return [
                w for w in caught
                if name in str(w.message) and "no effect" in str(w.message)
            ]

        for pdef in mod.algo_params:
            # a non-default value for a declared-inert param must warn;
            # for an honored param it must not (default values are used
            # for honored params so behavior stays on the tested path)
            value = (
                self._non_default(pdef) if pdef.name in inert
                else pdef.default_value
            )
            ad = AlgorithmDef(algo, {pdef.name: value})
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                solve_result(simple_chain(), ad, n_cycles=5, seed=0)
            if pdef.name in inert:
                assert hits(caught, pdef.name), (
                    algo, pdef.name, "inert param did not warn"
                )
            else:
                assert not hits(caught, pdef.name), (
                    algo, pdef.name, "honored param warned"
                )

    @pytest.mark.parametrize("algo", list_available_algorithms())
    def test_default_api_path_never_warns(self, algo):
        # the normal API path pre-fills every default into params
        # (AlgorithmDef.build_with_default_param); that must NOT trip the
        # inert-param warning — only asking for a non-default behavior
        # that will not happen does
        import warnings

        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            solve_result(simple_chain(), algo, n_cycles=5, seed=0)
        assert not [
            w for w in caught if "no effect" in str(w.message)
        ], algo


class TestFusedSolvePaths:
    """Edge paths of the one-dispatch run_cycles harness."""

    def test_large_domain_uses_int32_readback(self):
        # domains above 127 values take the int32 packing branch (small
        # domains ride int8); results must decode identically
        import numpy as np

        from pydcop_tpu.algorithms import dsa
        from pydcop_tpu.compile.direct import compile_from_edges

        d = 130
        rng = np.random.default_rng(0)
        edges = np.array([[0, 1], [1, 2], [2, 3]], dtype=np.int32)
        table = rng.uniform(0, 1, size=(d, d)).astype(np.float32)
        c = compile_from_edges(4, d, edges, table)
        r = dsa.solve(c, {}, n_cycles=30, seed=1)
        assert len(r.assignment) == 4
        vals = list(r.assignment.values())
        assert all(0 <= v <= d - 1 for v in vals)
        # some assignment index beyond int8 range should be reachable;
        # at minimum the decode round-trips through the compiled mapping
        idx = c.indices_from_assignment(r.assignment)
        assert (idx >= 0).all() and (idx < d).all()

    def test_noise_sweep_does_not_recompile(self):
        # the noise level is a traced operand of the fused solve (only the
        # zero/nonzero flag is a compile key): sweeping levels must reuse
        # one compiled program — a remote-TPU compile costs minutes
        from pydcop_tpu.algorithms import AlgorithmDef, base

        def algo(level):
            return AlgorithmDef.build_with_default_param(
                "maxsum", {"noise": level}
            )

        solve_result(simple_chain(), algo(0.01), n_cycles=10, seed=0)
        size_after_first = base._solve_fused._cache_size()
        for level in (0.02, 0.05, 0.1):
            r = solve_result(simple_chain(), algo(level), n_cycles=10, seed=0)
            assert r["violation"] == 0
        assert base._solve_fused._cache_size() == size_after_first

    def test_dpop_choice_flush_budget(self, monkeypatch):
        # force the between-level flush of device-resident argmin tables
        # and check the exact solve is unchanged
        from pydcop_tpu.algorithms import dpop
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_coloring_arrays,
        )

        c = generate_coloring_arrays(60, 3, graph="random", p_edge=0.06,
                                     seed=9)
        baseline = dpop.solve(c, {}, n_cycles=1, seed=0)
        monkeypatch.setattr(dpop, "CHOICE_FLUSH_ELEMS", 1)
        flushed = dpop.solve(c, {}, n_cycles=1, seed=0)
        assert flushed.cost == baseline.cost
        assert flushed.assignment == baseline.assignment

    def test_pallas_layout_matches_lanes(self):
        # the Pallas arity-2 min-plus kernel mirrors factor_step_lanes'
        # arithmetic add-for-add; under the interpreter (CPU) the whole
        # trajectory must match the lanes layout exactly
        from pydcop_tpu.algorithms import maxsum
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_coloring_arrays,
        )

        c = generate_coloring_arrays(120, 3, graph="scalefree", m_edge=2,
                                     seed=5)
        params = {"damping": 0.7}
        lanes = maxsum.solve(c, dict(params, layout="lanes"),
                             n_cycles=15, seed=2)
        pallas = maxsum.solve(c, dict(params, layout="pallas"),
                              n_cycles=15, seed=2)
        assert pallas.cost == lanes.cost
        assert pallas.assignment == lanes.assignment
        assert pallas.cycles == lanes.cycles

    def test_bf16_planes_quality(self):
        # bf16 message planes halve HBM traffic; quality must stay within
        # a small tolerance of f32 (BP is robust to message rounding)
        from pydcop_tpu.algorithms import maxsum
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_coloring_arrays,
        )

        c = generate_coloring_arrays(1000, 3, graph="random",
                                     p_edge=0.005, seed=11)
        f32 = maxsum.solve(c, {"damping": 0.5, "stop_cycle": 60},
                           n_cycles=60, seed=0)
        bf16 = maxsum.solve(
            c, {"damping": 0.5, "stop_cycle": 60, "precision": "bf16"},
            n_cycles=60, seed=0,
        )
        # different trajectories (the store rounds), comparable quality
        # (violations are vacuous on soft instances — the cost ratio is
        # the real check)
        assert bf16.cost <= f32.cost * 1.10 + 1.0

    def test_bf16_session_checkpoint_roundtrip(self, tmp_path):
        # bfloat16 planes must survive the npz checkpoint container
        # (stored as bit-preserving byte views with the dtype recorded)
        import numpy as np

        from pydcop_tpu.algorithms.maxsum_dynamic import DynamicMaxSum
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_graph_coloring,
        )

        dcop = generate_graph_coloring(8, 3, p_edge=0.3, seed=2)
        session = DynamicMaxSum(dcop, params={"precision": "bf16"}, seed=0)
        try:
            session.run(5)
            path = str(tmp_path / "ck.npz")
            session.save(path)
            planes_before = np.asarray(session.state.f2v)
            session2 = DynamicMaxSum(
                dcop, params={"precision": "bf16"}, seed=0
            )
            try:
                session2.restore(path)
                assert np.array_equal(
                    np.asarray(session2.state.f2v), planes_before
                )
                r = session2.run(5)
                assert len(r.assignment) == 8
            finally:
                session2.close()
        finally:
            session.close()


class TestEllLayout:
    """Round-5 TPU layout: degree-bucketed ELL edge order (kernels.py ELL
    section).  Same math as the lanes kernels — the on-device profile
    showed the lanes CSR gathers (~2 ms each on TPU v5e) WERE the cycle
    cost, so ELL replaces them with dense per-degree-class reshapes and a
    single partner-permutation gather."""

    @staticmethod
    def _instance(n=150, seed=13):
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_coloring_arrays,
        )

        return generate_coloring_arrays(
            n, 3, graph="scalefree", m_edge=2, seed=seed
        )

    @pytest.mark.parametrize("start", ["leafs", "leafs_vars", "all"])
    @pytest.mark.parametrize("dnodes", ["both", "vars", "none"])
    def test_matches_lanes_across_modes(self, start, dnodes):
        from pydcop_tpu.algorithms import maxsum

        c = self._instance()
        base = {
            "damping": 0.6, "start_messages": start,
            "damping_nodes": dnodes, "stop_cycle": 25,
        }
        lanes = maxsum.solve(c, dict(base, layout="lanes"),
                             n_cycles=25, seed=2)
        ell = maxsum.solve(c, dict(base, layout="ell"),
                           n_cycles=25, seed=2)
        assert ell.violations == lanes.violations
        # reduction order differs (reshape-sum vs segment-sum), so only
        # near-tied argmins may flip — cost parity, like the lanes/edges
        # cross-check above
        assert ell.cost == pytest.approx(lanes.cost, rel=1e-5)

    def test_convergence_early_exit_matches(self):
        # a chain's messages stabilize quickly; the stability early-exit
        # must fire at the same cycle in both layouts (padding slots carry
        # exact zeros, so they can never hold convergence open)
        from pydcop_tpu.algorithms import maxsum
        from pydcop_tpu.compile.core import compile_dcop

        c = compile_dcop(simple_chain())
        p = {"damping": 0.0, "noise": 0.0}
        lanes = maxsum.solve(c, dict(p, layout="lanes"), n_cycles=200,
                             seed=4)
        ell = maxsum.solve(c, dict(p, layout="ell"), n_cycles=200, seed=4)
        assert lanes.cycles < 200  # the instance converges
        assert ell.cycles == lanes.cycles
        assert ell.cost == pytest.approx(lanes.cost)

    def test_isolated_variable_and_hub(self):
        # a degree-0 variable must select its unary argmin; a hub variable
        # (star center) exercises a large degree class
        from pydcop_tpu.algorithms import maxsum
        from pydcop_tpu.compile.core import compile_dcop
        from pydcop_tpu.dcop import VariableWithCostDict

        d = Domain("d", "", ["a", "b", "c"])
        hub = Variable("hub", d)
        dcop = DCOP("star")
        for i in range(9):
            leaf = Variable(f"l{i}", d)
            dcop += constraint_from_str(
                f"c{i}", f"5 if hub == l{i} else 0", [hub, leaf]
            )
        lone = VariableWithCostDict(
            "lone", d, {"a": 3.0, "b": 1.0, "c": 2.0}
        )
        dcop.add_variable(lone)
        dcop.add_agents([])
        c = compile_dcop(dcop)
        # tie-breaking noise is load-bearing: with all-zero unaries BP
        # stays at the symmetric all-'a' fixpoint (lanes does too)
        r = maxsum.solve(c, {"layout": "ell", "noise": 0.01}, n_cycles=20,
                         seed=0)
        assert r.assignment["lone"] == "b"
        assert r.cost == pytest.approx(1.0)  # star colored + lone's unary
        assert r.violations == 0

    def test_bf16_precision_runs(self):
        from pydcop_tpu.algorithms import maxsum

        c = self._instance()
        f32 = maxsum.solve(c, {"layout": "ell", "noise": 0.0},
                           n_cycles=30, seed=1)
        bf16 = maxsum.solve(
            c, {"layout": "ell", "precision": "bf16", "noise": 0.0},
            n_cycles=30, seed=1,
        )
        assert bf16.violations == f32.violations
        assert bf16.cost == pytest.approx(f32.cost, rel=0.05)

    def test_falls_back_on_ternary(self):
        # arity-3 constraints: layout="ell" silently uses the lanes
        # kernels (documented) and must match them exactly
        from pydcop_tpu.algorithms import maxsum
        from pydcop_tpu.compile.core import compile_dcop

        d = Domain("d", "", [0, 1])
        x, y, z = (Variable(n, d) for n in "xyz")
        dcop = DCOP("tern")
        dcop += constraint_from_str("c1", "(x + y + z - 1) ** 2", [x, y, z])
        dcop.add_agents([])
        c = compile_dcop(dcop)
        lanes = maxsum.solve(c, {"layout": "lanes", "noise": 0.0},
                             n_cycles=15, seed=0)
        ell = maxsum.solve(c, {"layout": "ell", "noise": 0.0},
                           n_cycles=15, seed=0)
        assert ell.cost == lanes.cost
        assert ell.assignment == lanes.assignment

    def test_falls_back_on_padded_device(self):
        # a mesh-padded DeviceDCOP (row-sharded planes) is not ELL-able;
        # the fallback must still produce the lanes result
        from pydcop_tpu.algorithms import maxsum
        from pydcop_tpu.compile.kernels import to_device
        from pydcop_tpu.parallel.mesh import pad_device_dcop

        c = self._instance(n=64, seed=5)
        dev = pad_device_dcop(to_device(c), 8)
        plain = maxsum.solve(c, {"layout": "lanes", "noise": 0.0},
                             n_cycles=20, seed=1)
        padded = maxsum.solve(c, {"layout": "ell", "noise": 0.0},
                              n_cycles=20, seed=1, dev=dev)
        assert padded.cost == pytest.approx(plain.cost)

    def test_census_one_readback_zero_uploads(self, monkeypatch):
        import jax

        from pydcop_tpu.algorithms import base, maxsum
        from pydcop_tpu.compile.kernels import to_device

        c = self._instance(n=80, seed=9)
        dev = to_device(c)
        p = {"layout": "ell"}
        warm = maxsum.solve(c, dict(p), n_cycles=8, seed=0, dev=dev)
        readbacks = []
        orig = base.to_host
        monkeypatch.setattr(
            base, "to_host", lambda x: (readbacks.append(1), orig(x))[1]
        )
        with jax.transfer_guard_host_to_device("disallow_explicit"):
            again = maxsum.solve(c, dict(p), n_cycles=8, seed=0, dev=dev)
        assert len(readbacks) <= 1
        assert again.cost == warm.cost

    def test_dynamic_session_maps_ell_to_lanes(self):
        # maxsum_dynamic mutates per-edge state incrementally, which the
        # ELL order does not support: layout="ell" must run as lanes
        from pydcop_tpu.algorithms.maxsum_dynamic import DynamicMaxSum

        a = DynamicMaxSum(
            simple_chain(), {"layout": "ell", "noise": 0.0}, seed=3
        ).run(10)
        b = DynamicMaxSum(
            simple_chain(), {"layout": "lanes", "noise": 0.0}, seed=3
        ).run(10)
        assert a.assignment == b.assignment
        assert a.cost == b.cost

    def test_build_ell_invariants(self):
        from pydcop_tpu.compile.kernels import build_ell

        c = self._instance(n=200, seed=21)
        ell = build_ell(c)
        real = ell.edge_orig >= 0
        # every original edge appears exactly once
        assert sorted(ell.edge_orig[real].tolist()) == list(
            range(c.n_edges)
        )
        # pair permutation is an involution mapping real slots to real
        # slots of the SAME constraint
        pp = ell.pair_perm
        assert (pp[pp[real]] == np.flatnonzero(real)).all()
        assert (ell.edge_orig[pp[real]] >= 0).all()
        ec = np.asarray(c.edge_con)
        assert (
            ec[ell.edge_orig[real]] == ec[ell.edge_orig[pp[real]]]
        ).all()
        # spans tile the variable range and the padded edge range
        assert sum(nb for nb, _ in ell.spans) == c.n_vars
        assert sum(nb * db for nb, db in ell.spans) == ell.n_pad
        # var_perm and pos_of_var are inverse permutations
        assert (ell.var_perm[ell.pos_of_var] == np.arange(c.n_vars)).all()


class TestEllPallas:
    """Round-6 Pallas ELL kernel (pallas_kernels.ell_minplus): the fused
    min-plus marginalization hand-scheduled for the VPU, arithmetic
    identical op-for-op to the jnp ELL step — so the agreement bar is
    BITWISE, not approx.  Interpret mode on CPU runs the same kernel the
    TPU lowers (tools/validate_device.py re-runs these on hardware)."""

    # three degree distributions: multi-bucket scalefree (the bench
    # shape), a complete graph (ONE degree class — the (b,) = c.buckets
    # single-bucket edge hardened in PR 1), and a grid (two classes,
    # boundary-vs-interior)
    CASES = {
        "scalefree": dict(
            variables_count=150, graph="scalefree", m_edge=2, seed=13
        ),
        "clique": dict(
            variables_count=12, graph="random", p_edge=1.0, seed=3
        ),
        "grid": dict(variables_count=36, graph="grid", seed=4),
    }

    @classmethod
    def _case(cls, name):
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_coloring_arrays,
        )

        kw = dict(cls.CASES[name])
        n = kw.pop("variables_count")
        return generate_coloring_arrays(n, 3, **kw)

    @pytest.mark.parametrize("case", sorted(CASES))
    def test_factor_step_bitwise(self, case):
        import jax.numpy as jnp

        from pydcop_tpu.compile.kernels import build_ell, factor_step_ell

        c = self._case(case)
        ell = build_ell(c)
        d = int(c.max_domain)
        rng = np.random.default_rng(11)
        v2f = jnp.asarray(
            np.where(
                ell.real_row, rng.normal(size=(d, ell.n_pad)), 0.0
            ).astype(c.float_dtype)
        )
        tabs_t = jnp.asarray(ell.tabs_t)
        pair_perm = jnp.asarray(ell.pair_perm)
        real_row = jnp.asarray(ell.real_row)
        ref = factor_step_ell(tabs_t, pair_perm, real_row, v2f)
        pal = factor_step_ell(
            tabs_t, pair_perm, real_row, v2f, use_pallas=True
        )
        assert np.array_equal(np.asarray(ref), np.asarray(pal))

    @pytest.mark.parametrize("case", sorted(CASES))
    def test_three_way_solve_agreement(self, case):
        # ell-jnp <-> ell-pallas-interpret: bitwise (same ops, same
        # order); <-> lanes: cost/violation parity (different reduction
        # order, near-tied argmins may flip)
        from pydcop_tpu.algorithms import maxsum

        c = self._case(case)
        base = {"damping": 0.5, "noise": 0.0}
        ell = maxsum.solve(
            c, dict(base, layout="ell"), n_cycles=25, seed=5
        )
        pal = maxsum.solve(
            c, dict(base, layout="ell_pallas"), n_cycles=25, seed=5
        )
        lanes = maxsum.solve(
            c, dict(base, layout="lanes"), n_cycles=25, seed=5
        )
        assert pal.assignment == ell.assignment
        assert pal.cost == ell.cost
        assert lanes.violations == ell.violations
        assert lanes.cost == pytest.approx(ell.cost, rel=1e-5)

    def test_bf16_planes_bitwise(self):
        # bf16 message planes: the kernel's add promotes exactly like the
        # jnp path's explicit promotion, so bf16 trajectories are ALSO
        # bitwise identical between the two inner steps
        from pydcop_tpu.algorithms import maxsum

        c = self._case("scalefree")
        p = {"damping": 0.5, "noise": 0.0, "precision": "bf16"}
        ell = maxsum.solve(
            c, dict(p, layout="ell"), n_cycles=25, seed=5
        )
        pal = maxsum.solve(
            c, dict(p, layout="ell_pallas"), n_cycles=25, seed=5
        )
        assert pal.assignment == ell.assignment
        assert pal.cost == ell.cost

    def test_oversized_domain_runs_jnp_step(self):
        # domains past MAX_PALLAS_DOMAIN fall through to the XLA fusion
        # inside factor_step_ell — same result, no error
        import jax.numpy as jnp

        from pydcop_tpu.compile.kernels import build_ell, factor_step_ell
        from pydcop_tpu.compile.pallas_kernels import MAX_PALLAS_DOMAIN

        d_big = MAX_PALLAS_DOMAIN + 1
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_coloring_arrays,
        )

        c = generate_coloring_arrays(
            20, d_big, graph="random", p_edge=0.3, seed=2
        )
        ell = build_ell(c)
        rng = np.random.default_rng(5)
        v2f = jnp.asarray(
            np.where(
                ell.real_row,
                rng.normal(size=(d_big, ell.n_pad)),
                0.0,
            ).astype(c.float_dtype)
        )
        ref = factor_step_ell(
            jnp.asarray(ell.tabs_t), jnp.asarray(ell.pair_perm),
            jnp.asarray(ell.real_row), v2f,
        )
        fallback = factor_step_ell(
            jnp.asarray(ell.tabs_t), jnp.asarray(ell.pair_perm),
            jnp.asarray(ell.real_row), v2f, use_pallas=True,
        )
        assert np.array_equal(np.asarray(ref), np.asarray(fallback))


class TestDpopFusedWave:
    """Round-5: the whole UTIL wave as ONE jitted program (dpop.py
    _plan_fused_wave).  On the tunneled relay every jitted call pays a
    ~25-30 ms submission round trip; the streaming loop made ~194 of them
    on the bench-5 meetings instance (5.4 s of call overhead for 0.1 s of
    work).  The fused replay must be element-identical to the streaming
    path — same batching, same contribution order, same padding."""

    @staticmethod
    def _meetings():
        from pydcop_tpu.commands.generators.meetingscheduling import (
            generate_meeting_scheduling,
        )
        from pydcop_tpu.compile.core import compile_dcop

        return compile_dcop(generate_meeting_scheduling(
            slots_count=4, resources_count=10, events_count=10,
            max_resources_event=2, seed=5,
        ))

    def test_fused_matches_streaming(self, monkeypatch):
        from pydcop_tpu.algorithms import dpop

        def random_tree():
            from pydcop_tpu.compile.core import compile_dcop

            rng = np.random.default_rng(17)
            n = 200
            d = Domain("d", "", [0, 1, 2])
            vs = [Variable(f"v{i}", d) for i in range(n)]
            dcop = DCOP("tree")
            for i in range(1, n):
                p = int(rng.integers(0, i))
                w = rng.integers(0, 7, size=(3, 3))
                expr = "[" + ",".join(
                    "[" + ",".join(str(int(x)) for x in row) + "]"
                    for row in w
                ) + f"][v{p}][v{i}]"
                dcop += constraint_from_str(
                    f"c{i}", expr, [vs[p], vs[i]]
                )
            dcop.add_agents([])
            return compile_dcop(dcop)

        for make in (self._meetings, random_tree):
            c1, c2 = make(), make()
            fused = dpop.solve(c1, {})
            assert c1._device_consts[("dpop_fused_plan",)] is not None
            monkeypatch.setattr(dpop, "_plan_fused_wave", lambda *a: None)
            stream = dpop.solve(c2, {})
            monkeypatch.undo()
            assert fused.cost == stream.cost
            assert fused.assignment == stream.assignment

    def test_deep_chain_streams(self):
        # one batch per level on a chain: the descriptor cap routes deep
        # trees to the streaming path (huge single traces compile slowly)
        from pydcop_tpu.algorithms import dpop
        from pydcop_tpu.compile.core import compile_dcop

        n = dpop.FUSED_WAVE_MAX_BATCHES + 40
        d = Domain("d", "", [0, 1])
        vs = [Variable(f"v{i}", d) for i in range(n)]
        dcop = DCOP("chain")
        for i in range(n - 1):
            dcop += constraint_from_str(
                f"c{i}", f"1 if v{i} == v{i+1} else 0", [vs[i], vs[i + 1]]
            )
        dcop.add_agents([])
        c = compile_dcop(dcop)
        r = dpop.solve(c, {})
        assert c._device_consts[("dpop_fused_plan",)] is None
        assert r.cost == 0.0

    def test_warm_fused_zero_uploads(self):
        import jax

        from pydcop_tpu.algorithms import dpop

        c = self._meetings()
        warm = dpop.solve(c, {})
        with jax.transfer_guard_host_to_device("disallow_explicit"):
            again = dpop.solve(c, {})
        assert again.cost == warm.cost
        assert again.assignment == warm.assignment

    def test_elems_budget_routes_to_streaming(self, monkeypatch):
        # a wave over the element budget must stream (and still be exact)
        from pydcop_tpu.algorithms import dpop

        fused = dpop.solve(self._meetings(), {})
        monkeypatch.setattr(dpop, "FUSED_WAVE_MAX_ELEMS", 8)
        c = self._meetings()
        r = dpop.solve(c, {})
        assert c._device_consts[("dpop_fused_plan",)] is None
        assert r.cost == fused.cost  # exact either way


class TestGdbaModeSemantics:
    """Unit-level pins of GDBA's modifier machinery (reference
    test_algorithms_gdba.py covers each mode's micro-behavior; the
    24-variant chain test above cannot distinguish them).  A constant
    cost table makes every variable quasi-local-minimum immediately, so
    one step must bump exactly the entries each increase_mode selects."""

    @staticmethod
    def _stuck_step(violation, increase):
        import jax
        import jax.numpy as jnp

        from pydcop_tpu.algorithms import gdba
        from pydcop_tpu.algorithms.base import neighbor_pairs_dev
        from pydcop_tpu.compile.core import compile_dcop
        from pydcop_tpu.compile.kernels import to_device

        d = Domain("d", "", [0, 1])
        x, y = Variable("x", d), Variable("y", d)
        dcop = DCOP("t")
        # constant table: every joint assignment costs 1 -> nobody can
        # improve, everyone is stuck from cycle one
        dcop += constraint_from_str("c", "1 + 0 * (x + y)", [x, y])
        dcop.add_agents([])
        c = compile_dcop(dcop)
        dev = to_device(c)
        ns, nd = neighbor_pairs_dev(c)
        tmin, tmax = gdba._table_extrema(c)
        state = gdba.GdbaState(
            values=jnp.zeros(2, dtype=jnp.int32),
            modifiers=(jnp.zeros((1, 2, 4), dtype=dev.unary.dtype),),
        )
        step = gdba._make_step("A", violation, increase)
        new = step(
            dev, state, jax.random.PRNGKey(0), ns, nd,
            tuple(tmin), tuple(tmax),
        )
        return state, new

    # flat index = x*2 + y; current assignment (0, 0) -> flat 0
    @pytest.mark.parametrize("increase,slot0,slot1", [
        ("E", [1, 0, 0, 0], [1, 0, 0, 0]),     # exactly the current entry
        ("R", [1, 0, 1, 0], [1, 1, 0, 0]),     # own value free, y=0 / x=0
        ("C", [1, 1, 0, 0], [1, 0, 1, 0]),     # own value fixed, other free
        ("T", [1, 1, 1, 1], [1, 1, 1, 1]),     # whole table
    ])
    def test_increase_modes_bump_expected_entries(
        self, increase, slot0, slot1
    ):
        state, new = self._stuck_step("NZ", increase)
        assert new.values.tolist() == [0, 0]  # stuck: nobody moved
        mods = np.asarray(new.modifiers[0])
        assert mods[0, 0].tolist() == slot0
        assert mods[0, 1].tolist() == slot1

    def test_violation_nm_constant_table_never_bumps(self):
        # constant table: current cost == table minimum -> not violated
        _state, new = self._stuck_step("NM", "T")
        assert float(np.asarray(new.modifiers[0]).sum()) == 0.0

    def test_violation_mx_constant_table_bumps(self):
        # constant table: current cost == table maximum -> violated
        _state, new = self._stuck_step("MX", "E")
        assert float(np.asarray(new.modifiers[0]).sum()) == 2.0


class TestServeBatchBitIdentity:
    """graftserve bit-identity battery (ISSUE 9 satellite): a batch-of-K
    vmapped solve must produce assignments/costs BITWISE equal to the K
    sequential solves of the same requests with the same seeds
    (``serve.solve_one`` — the regular run_cycles fused path on the same
    bucket padding).  Includes a mixed-shape pair landing in two buckets,
    and exercises per-instance traced operands (PRNG keys, cycle budgets,
    and for maxsum the in-program tie-breaking noise)."""

    @staticmethod
    def _reqs(algo, params, sizes, cycles, seed0=700):
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_coloring_arrays,
        )
        from pydcop_tpu.serve import SolveRequest

        return [
            SolveRequest(
                f"{algo}{i}",
                generate_coloring_arrays(
                    n, 3, graph="grid", seed=seed0 + i
                ),
                algo, dict(params), cycles, seed0 + 3 * i,
            )
            for i, n in enumerate(sizes)
        ]

    def _pin(self, algo, params, sizes=(49, 49, 49, 25, 25), cycles=20):
        from pydcop_tpu.serve import bucket_key, solve_batched, solve_one

        reqs = self._reqs(algo, params, sizes, cycles)
        assert len({bucket_key(r) for r in reqs}) == 2  # two buckets
        out = solve_batched(reqs)
        for r in reqs:
            tr = out[r.tenant]
            seq = solve_one(r)
            assert tr.result.assignment == seq.result.assignment
            assert tr.result.cost == seq.result.cost  # bitwise host cost
            assert tr.extras["cycles"] == seq.extras["cycles"]
            assert tr.extras["best_cost"] == seq.extras["best_cost"]
            assert (
                tr.extras["cycles_to_best"] == seq.extras["cycles_to_best"]
            )

    def test_dsa_batch_bitwise_equals_sequential(self):
        self._pin("dsa", {})

    def test_dsa_variant_a_batch_bitwise(self):
        self._pin("dsa", {"variant": "A"}, sizes=(25, 25, 49), cycles=15)

    def test_mgm_batch_bitwise_equals_sequential(self):
        self._pin("mgm", {})

    def test_mgm2_batch_bitwise_equals_sequential(self):
        self._pin("mgm2", {}, sizes=(25, 25, 49), cycles=15)

    def test_maxsum_ell_batch_bitwise_equals_sequential(self):
        # default params: nonzero tie-breaking noise rides as a traced
        # per-instance operand inside the vmapped program
        self._pin("maxsum", {"damping": 0.5}, cycles=20)

    def test_maxsum_ell_noise_zero_batch_bitwise(self):
        self._pin(
            "maxsum", {"damping": 0.5, "noise": 0.0},
            sizes=(49, 25), cycles=15,
        )

    def test_mixed_cycle_budgets_stay_bitwise(self):
        # per-instance cycle budgets are traced: tenants with different
        # n_cycles share one executable AND keep solo trajectories.
        # Same scan-length bucket (pow2) so both land in one batch.
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_coloring_arrays,
        )
        from pydcop_tpu.serve import SolveRequest, solve_batched, solve_one

        reqs = [
            SolveRequest(
                f"t{i}",
                generate_coloring_arrays(25, 3, graph="grid", seed=800 + i),
                "dsa", {}, n_cycles, 800 + i,
            )
            for i, n_cycles in enumerate((9, 12, 16, 14))
        ]
        out = solve_batched(reqs)
        for r in reqs:
            seq = solve_one(r)
            tr = out[r.tenant]
            assert tr.result.assignment == seq.result.assignment
            assert tr.extras["cycles"] == seq.extras["cycles"] == r.n_cycles
