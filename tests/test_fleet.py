"""graftfleet: federated collector merge/labeling/counter-reset/staleness
against fake endpoints, fleet-SLO counter-source plumbing, the fleet HTTP
surface (/fleet/status + /metrics consistency under concurrent scrapes)
and manifest-as-target-source (pydcop_tpu/telemetry/federate.py,
commands/fleet.py, docs/observability.md graftfleet)."""

import json
import threading
import urllib.request

import pytest

from pydcop_tpu.telemetry import telemetry_off
from pydcop_tpu.telemetry.federate import (
    FleetCollector,
    FleetSlo,
    FleetTarget,
    clamped_rate,
    targets_from_args,
    targets_from_fleet_file,
    targets_from_manifest,
)
from pydcop_tpu.telemetry.prom import parse_prometheus_text
from pydcop_tpu.telemetry.slo import parse_objective


@pytest.fixture(autouse=True)
def _telemetry_reset():
    yield
    telemetry_off()


def _counter(value, **labels):
    return {"labels": labels, "value": float(value)}


class FakeFleet:
    """Injectable ``fetch``: a dict of worker docs the tests mutate
    between polls, plus a per-worker kill switch."""

    def __init__(self, workers):
        #: name -> {"metrics": {...}, "status": {...}}
        self.workers = dict(workers)
        self.dead = set()

    def targets(self):
        return [
            FleetTarget(name, f"http://fake/{name}")
            for name in sorted(self.workers)
        ]

    def fetch(self, url):
        name = url.split("/fake/", 1)[1].split("/", 1)[0]
        if name in self.dead:
            return None
        doc = self.workers[name]
        if url.endswith("/metrics.json"):
            return {"time": 0.0, "metrics": doc["metrics"]}
        if url.endswith("/status"):
            return dict(doc["status"])
        raise AssertionError(f"unexpected fetch {url}")


def _two_worker_fleet():
    fake = FakeFleet(
        {
            "w0": {
                "metrics": {
                    "serve.requests": {
                        "kind": "counter",
                        "help": "requests",
                        "values": [_counter(10, tenant="a")],
                    },
                    "serve.batch_occupancy_pct": {
                        "kind": "gauge",
                        "help": "occupancy",
                        "values": [_counter(75.0)],
                    },
                },
                "status": {
                    "state": "serving",
                    "solves": 5,
                    "queue_depth": 2,
                    "queue_depth_watermark": 4,
                    "dead_letters": 0,
                },
            },
            "w1": {
                "metrics": {
                    "serve.requests": {
                        "kind": "counter",
                        "help": "requests",
                        "values": [_counter(7, tenant="a")],
                    },
                },
                "status": {"state": "serving", "solves": 3,
                           "queue_depth": 1, "dead_letters": 1},
            },
        }
    )
    coll = FleetCollector(
        fake.targets(), stale_after_s=10.0, clock=lambda: 0.0,
        fetch=fake.fetch,
    )
    return fake, coll


def _series(snapshot, name):
    m = snapshot["metrics"].get(name) or {"values": []}
    return {
        tuple(sorted(e["labels"].items())): e["value"]
        for e in m["values"]
    }


# ---------------------------------------------------------------------------
# target sources
# ---------------------------------------------------------------------------


class TestTargetSources:
    def test_args_url_and_named(self):
        ts = targets_from_args(
            ["127.0.0.1:9010", "a=http://h:1/", "http://h:2"]
        )
        assert ts[0] == FleetTarget("127.0.0.1:9010",
                                    "http://127.0.0.1:9010")
        assert ts[1] == FleetTarget("a", "http://h:1")
        assert ts[2] == FleetTarget("h:2", "http://h:2")

    def test_fleet_file_mapping_and_list(self, tmp_path):
        f = tmp_path / "fleet.yaml"
        f.write_text(
            "workers:\n  w0: http://h:1\n  w1: {url: 'http://h:2'}\n"
        )
        assert targets_from_fleet_file(str(f)) == [
            FleetTarget("w0", "http://h:1"),
            FleetTarget("w1", "http://h:2"),
        ]
        f.write_text("workers:\n  - http://h:1\n  - {name: b, url: h:2}\n")
        assert targets_from_fleet_file(str(f)) == [
            FleetTarget("h:1", "http://h:1"),
            FleetTarget("b", "http://h:2"),
        ]

    def test_fleet_file_needs_workers(self, tmp_path):
        f = tmp_path / "fleet.yaml"
        f.write_text("targets: []\n")
        with pytest.raises(ValueError, match="workers"):
            targets_from_fleet_file(str(f))

    def test_manifest_file_and_directory(self, tmp_path):
        d0 = tmp_path / "state-w0"
        d0.mkdir()
        (d0 / "fleet-manifest.json").write_text(json.dumps(
            {"format": "graftdur-v1", "worker": "w0",
             "endpoint": "http://127.0.0.1:9010"}
        ))
        d1 = tmp_path / "state-w1"
        d1.mkdir()
        # pre-graftfleet manifest: no endpoint — skipped, not fatal
        (d1 / "fleet-manifest.json").write_text(
            json.dumps({"format": "graftdur-v1"})
        )
        ts = targets_from_manifest(str(tmp_path))
        assert ts == [FleetTarget("w0", "http://127.0.0.1:9010")]
        # a single manifest file works too
        assert targets_from_manifest(
            str(d0 / "fleet-manifest.json")
        ) == ts

    def test_manifest_without_endpoints_raises(self, tmp_path):
        (tmp_path / "fleet-manifest.json").write_text(json.dumps({}))
        with pytest.raises(ValueError, match="endpoint"):
            targets_from_manifest(str(tmp_path))

    def test_duplicate_worker_names_rejected(self):
        with pytest.raises(ValueError, match="duplicate"):
            FleetCollector(
                [FleetTarget("w", "http://h:1"),
                 FleetTarget("w", "http://h:2")]
            )


# ---------------------------------------------------------------------------
# the collector: merge, labeling, resets, staleness
# ---------------------------------------------------------------------------


class TestCollector:
    def test_clamped_rate(self):
        assert clamped_rate(10.0, 30.0, 2.0) == pytest.approx(10.0)
        # counter went backwards (restart): no negative rate, re-baseline
        assert clamped_rate(100.0, 5.0, 1.0) == 0.0
        assert clamped_rate(0.0, 1.0, 0.0) == 0.0

    def test_merge_relabels_every_series(self):
        fake, coll = _two_worker_fleet()
        coll.poll(now=0.0)
        snap = coll.snapshot(now=0.0)
        reqs = _series(snap, "serve.requests")
        assert reqs[(("tenant", "a"), ("worker", "w0"))] == 10.0
        assert reqs[(("tenant", "a"), ("worker", "w1"))] == 7.0
        up = _series(snap, "fleet.worker_up")
        assert up == {(("worker", "w0"),): 1.0, (("worker", "w1"),): 1.0}
        assert _series(snap, "fleet.workers_up")[()] == 2.0
        solves = _series(snap, "fleet.worker_solves_total")
        assert solves[(("worker", "w0"),)] == 5.0

    def test_counter_reset_keeps_federated_series_monotone(self):
        fake, coll = _two_worker_fleet()
        coll.poll(now=0.0)
        # w0 restarts: its counter falls 10 -> 3
        fake.workers["w0"]["metrics"]["serve.requests"]["values"] = [
            _counter(3, tenant="a")
        ]
        coll.poll(now=1.0)
        snap = coll.snapshot(now=1.0)
        reqs = _series(snap, "serve.requests")
        # pre-restart total folded into the offset: 10 + 3
        assert reqs[(("tenant", "a"), ("worker", "w0"))] == 13.0
        assert coll.counter_sum("serve.requests") == pytest.approx(20.0)
        assert coll.counter_sum(
            "serve.requests", worker="w0"
        ) == pytest.approx(13.0)
        resets = _series(snap, "fleet.counter_resets_total")
        assert resets[(("worker", "w0"),)] == 1.0
        assert resets[(("worker", "w1"),)] == 0.0

    def test_solves_reset_and_rate(self):
        fake, coll = _two_worker_fleet()
        coll.poll(now=0.0)
        fake.workers["w0"]["status"]["solves"] = 9
        coll.poll(now=2.0)
        st = coll.status(now=2.0)
        assert st["workers"]["w0"]["solves_s"] == pytest.approx(2.0)
        # restart: solve count falls 9 -> 1; monotone series keeps rising
        fake.workers["w0"]["status"]["solves"] = 1
        coll.poll(now=3.0)
        snap = coll.snapshot(now=3.0)
        solves = _series(snap, "fleet.worker_solves_total")
        assert solves[(("worker", "w0"),)] == 10.0  # 9 + 1
        st = coll.status(now=3.0)
        assert st["workers"]["w0"]["solves_s"] == pytest.approx(1.0)

    def test_histogram_reset_folds_offsets(self):
        fake = FakeFleet({
            "w0": {
                "metrics": {
                    "serve.latency": {
                        "kind": "histogram",
                        "help": "s",
                        "bucket_bounds": [0.1, 1.0, "+Inf"],
                        "values": [{
                            "labels": {},
                            "value": {"buckets": [4, 2, 1], "sum": 3.5,
                                      "count": 7},
                        }],
                    },
                },
                "status": {"state": "serving", "solves": 0},
            },
        })
        coll = FleetCollector(
            fake.targets(), clock=lambda: 0.0, fetch=fake.fetch
        )
        coll.poll(now=0.0)
        fake.workers["w0"]["metrics"]["serve.latency"]["values"] = [{
            "labels": {},
            "value": {"buckets": [1, 0, 0], "sum": 0.05, "count": 1},
        }]
        coll.poll(now=1.0)
        snap = coll.snapshot(now=1.0)
        entry = snap["metrics"]["serve.latency"]["values"][0]
        assert entry["labels"] == {"worker": "w0"}
        assert entry["value"]["buckets"] == [5.0, 2.0, 1.0]
        assert entry["value"]["count"] == 8.0
        assert entry["value"]["sum"] == pytest.approx(3.55)
        assert snap["metrics"]["serve.latency"]["bucket_bounds"] == [
            0.1, 1.0, "+Inf",
        ]

    def test_dead_worker_marked_down_then_stale_dropped(self):
        fake, coll = _two_worker_fleet()
        coll.poll(now=0.0)
        fake.dead.add("w1")
        coll.poll(now=1.0)
        snap = coll.snapshot(now=1.0)
        up = _series(snap, "fleet.worker_up")
        assert up[(("worker", "w1"),)] == 0.0  # down immediately
        # within stale_after_s the last-known series keep being served
        assert (("tenant", "a"), ("worker", "w1")) in _series(
            snap, "serve.requests"
        )
        age = _series(snap, "fleet.scrape_age_seconds")
        assert age[(("worker", "w1"),)] == pytest.approx(1.0)
        # ... but past it they are DROPPED, not served forever
        snap = coll.snapshot(now=30.0)
        assert (("tenant", "a"), ("worker", "w1")) not in _series(
            snap, "serve.requests"
        )
        # the meta-series survive as the worker's only trace
        assert _series(snap, "fleet.worker_up")[(("worker", "w1"),)] == 0.0
        st = coll.status(now=30.0)
        assert st["workers"]["w1"]["stale"] is True
        assert st["workers_up"] == 1
        fails = _series(snap, "fleet.scrape_failures_total")
        assert fails[(("worker", "w1"),)] == 1.0

    def test_status_table_rows(self):
        fake, coll = _two_worker_fleet()
        fake.workers["w0"]["status"]["tenants"] = {
            "a": {"pulse": {"diagnosis": "starvation"}},
            "b": {"pulse": {"diagnosis": "healthy"}},
        }
        fake.workers["w0"]["status"]["slo"] = {
            "objectives": {
                "avail": {"burn_fast": 20.0, "alert": "fast"},
            },
        }
        coll.poll(now=0.0)
        st = coll.status(now=0.0)
        row = st["workers"]["w0"]
        assert row["up"] and not row["stale"]
        assert row["queue_depth"] == 2
        assert row["queue_watermark"] == 4
        assert row["occupancy_pct"] == 75.0
        assert row["pulse"] == "starvation"
        assert row["burn_fast"] == 20.0
        assert row["alert"] == "avail:fast"
        assert st["fleet"]["solves"] == 8
        assert st["fleet"]["queue_depth"] == 3
        assert st["fleet"]["dead_letters"] == 1


# ---------------------------------------------------------------------------
# fleet SLOs over federated counters
# ---------------------------------------------------------------------------


def _slo_fleet(good_bad):
    """A fleet whose workers expose slo.events counters; ``good_bad`` is
    {worker: (good, bad)} and may be mutated between polls."""
    def worker_doc(name):
        return {
            "metrics": {
                "slo.events": {
                    "kind": "counter",
                    "help": "events",
                    "values": [
                        _counter(good_bad[name][0], objective="avail",
                                 outcome="good"),
                        _counter(good_bad[name][1], objective="avail",
                                 outcome="bad"),
                    ],
                },
            },
            "status": {"state": "serving", "solves": 0},
        }

    class _Fake(FakeFleet):
        def fetch(self, url):
            name = url.split("/fake/", 1)[1].split("/", 1)[0]
            self.workers[name] = worker_doc(name)
            return super().fetch(url)

    fake = _Fake({name: worker_doc(name) for name in good_bad})
    coll = FleetCollector(
        fake.targets(), clock=lambda: 0.0, fetch=fake.fetch
    )
    objectives = [parse_objective("avail=availability>=99%")]
    return fake, coll, FleetSlo(coll, objectives, clock=lambda: 0.0)


class TestFleetSlo:
    def test_counter_source_sums_and_filters(self):
        counts = {"w0": (90.0, 10.0), "w1": (100.0, 0.0)}
        fake, coll, fslo = _slo_fleet(counts)
        coll.poll(now=0.0)
        fleet_counts = fslo.fleet_engine._counts()
        assert fleet_counts["avail"] == (190.0, 10.0)
        assert fslo.worker_engines["w0"]._counts()["avail"] == (90.0, 10.0)
        assert fslo.worker_engines["w1"]._counts()["avail"] == (100.0, 0.0)

    def test_fleet_alert_names_worst_worker(self):
        counts = {"w0": (0.0, 0.0), "w1": (0.0, 0.0)}
        fake, coll, fslo = _slo_fleet(counts)
        coll.poll(now=0.0)
        fslo.evaluate(now=0.0)
        assert fslo.transitions == []
        # w0 burns hard (50% bad vs 1% budget), w1 stays clean
        counts["w0"] = (50.0, 50.0)
        counts["w1"] = (100.0, 0.0)
        coll.poll(now=30.0)
        fslo.evaluate(now=30.0)
        firing = [t for t in fslo.transitions if t["state"] == "firing"]
        assert firing and firing[0]["objective"] == "avail"
        assert firing[0]["worst_worker"] == "w0"
        block = fslo.status_block()
        assert block["fleet"]["objectives"]["avail"]["worst_worker"] == "w0"
        assert block["fleet"]["objectives"]["avail"]["alert"] is not None
        # per-worker budgets: w1's engine stays clean while w0 burns
        assert block["workers"]["w1"]["objectives"]["avail"]["alert"] is None
        assert block["workers"]["w0"]["objectives"]["avail"]["alert"]

    def test_metrics_block_series(self):
        counts = {"w0": (99.0, 1.0), "w1": (100.0, 0.0)}
        fake, coll, fslo = _slo_fleet(counts)
        coll.poll(now=0.0)
        fslo.evaluate(now=0.0)
        mb = fslo.metrics_block()
        budg = {
            tuple(sorted(e["labels"].items())): e["value"]
            for e in mb["fleet.slo.error_budget_remaining"]["values"]
        }
        # aggregate (no worker label) + one series per worker
        assert (("objective", "avail"),) in budg
        assert (("objective", "avail"), ("worker", "w0")) in budg
        assert (("objective", "avail"), ("worker", "w1")) in budg
        burns = mb["fleet.slo.burn_rate"]["values"]
        assert {e["labels"]["window"] for e in burns} == {
            "fast_long", "fast_short", "slow_long", "slow_short",
        }

    def test_engines_publish_no_local_gauges(self):
        from pydcop_tpu.telemetry.metrics import metrics_registry

        metrics_registry.enabled = True
        counts = {"w0": (50.0, 50.0)}
        fake, coll, fslo = _slo_fleet(counts)
        coll.poll(now=0.0)
        fslo.evaluate(now=30.0)
        snap = metrics_registry.snapshot()
        assert not snap["metrics"].get("slo.burn_rate", {}).get("values")
        assert not snap["metrics"].get("slo.alert_active", {}).get("values")


# ---------------------------------------------------------------------------
# the fleet HTTP surface
# ---------------------------------------------------------------------------


class TestFleetSurface:
    def _surface(self, coll, fslo=None):
        from pydcop_tpu.infrastructure.ui import MetricsHttpServer

        def _status():
            st = coll.status()
            if fslo is not None:
                st["slo"] = fslo.status_block()
            return st

        def _snapshot():
            snap = coll.snapshot()
            if fslo is not None:
                snap["metrics"].update(fslo.metrics_block())
            return snap

        return MetricsHttpServer(
            port=0,
            status_cb=_status,
            snapshot_cb=_snapshot,
            routes={("GET", "/fleet/status"):
                    lambda path, body: (200, _status())},
        )

    def test_federated_metrics_and_status_consistent_under_scrapes(self):
        fake, coll = _two_worker_fleet()
        coll._clock = lambda: 0.0
        coll.poll(now=0.0)
        srv = self._surface(coll)
        base = f"http://127.0.0.1:{srv.port}"
        stop = threading.Event()
        problems = []
        reqs = {"n": 10}

        def poll_loop():
            t = 0.0
            while not stop.is_set():
                t += 0.01
                reqs["n"] += 1
                fake.workers["w0"]["metrics"]["serve.requests"][
                    "values"
                ] = [_counter(reqs["n"], tenant="a")]
                coll.poll(now=t)

        def check(parsed, st):
            seen = {}
            for s in parsed["samples"]:
                if s["name"] == "serve_requests_total":
                    seen[s["labels"]["worker"]] = s["value"]
            if seen.get("w0", 0) < 10:
                problems.append(f"counter went backwards: {seen}")
            if not 0 <= st["workers_up"] <= st["workers_total"] == 2:
                problems.append(f"bad census: {st}")

        poller = threading.Thread(target=poll_loop, daemon=True)
        poller.start()
        try:
            last = 0.0
            for i in range(20):
                accept = (
                    "application/openmetrics-text" if i % 2 else
                    "text/plain"
                )
                req = urllib.request.Request(
                    base + "/metrics", headers={"Accept": accept}
                )
                with urllib.request.urlopen(req, timeout=5) as resp:
                    parsed = parse_prometheus_text(resp.read().decode())
                assert parsed["eof"] == bool(i % 2)
                with urllib.request.urlopen(
                    base + "/fleet/status", timeout=5
                ) as resp:
                    st = json.loads(resp.read())
                check(parsed, st)
                cur = [
                    s["value"] for s in parsed["samples"]
                    if s["name"] == "serve_requests_total"
                    and s["labels"].get("worker") == "w0"
                ][0]
                if cur < last:
                    problems.append(f"scrape not monotone: {cur} < {last}")
                last = cur
        finally:
            stop.set()
            poller.join(timeout=5)
            srv.shutdown()
        assert not problems, problems

    def test_fleet_verb_once_against_live_worker(self, tmp_path, capsys):
        """CLI wiring end to end: a real worker surface, the fleet verb
        in --once mode, a manifest as the target source."""
        from pydcop_tpu.dcop_cli import main
        from pydcop_tpu.infrastructure.ui import MetricsHttpServer

        worker = MetricsHttpServer(
            port=0,
            status_cb=lambda: {"state": "serving", "solves": 4},
            snapshot_cb=lambda: {
                "time": 0.0,
                "metrics": {
                    "serve.requests": {
                        "kind": "counter", "help": "r",
                        "values": [_counter(4)],
                    },
                },
            },
        )
        manifest_dir = tmp_path / "state"
        manifest_dir.mkdir()
        (manifest_dir / "fleet-manifest.json").write_text(json.dumps({
            "format": "graftdur-v1",
            "worker": "w0",
            "endpoint": f"http://127.0.0.1:{worker.port}",
        }))
        out = tmp_path / "fleet.json"
        try:
            rc = main([
                "--output", str(out), "fleet",
                "--manifest", str(manifest_dir), "--once",
            ])
        finally:
            worker.shutdown()
        assert rc == 0
        doc = json.loads(out.read_text())
        assert doc["workers_up"] == 1
        assert doc["workers"]["w0"]["solves"] == 4

    def test_watch_fleet_renders_worker_table(self, capsys):
        from pydcop_tpu.dcop_cli import main

        fake, coll = _two_worker_fleet()
        coll._clock = lambda: 0.0
        coll.poll(now=0.0)
        srv = self._surface(coll)
        try:
            rc = main([
                "watch", "--fleet", f"http://127.0.0.1:{srv.port}",
                "--once",
            ])
        finally:
            srv.shutdown()
        assert rc == 0
        out = capsys.readouterr().out
        assert "2/2 workers up" in out
        assert "w0" in out and "w1" in out
        assert "UP" in out

    def test_watch_fleet_down_worker_shown(self, capsys):
        from pydcop_tpu.dcop_cli import main

        fake, coll = _two_worker_fleet()
        coll._clock = lambda: 20.0
        coll.poll(now=0.0)
        fake.dead.add("w1")
        coll.poll(now=20.0)
        srv = self._surface(coll)
        try:
            rc = main([
                "watch", "--fleet", f"http://127.0.0.1:{srv.port}",
                "--once",
            ])
        finally:
            srv.shutdown()
        assert rc == 0
        out = capsys.readouterr().out
        assert "1/2 workers up" in out
        assert "DOWN" in out and "STALE" in out


# ---------------------------------------------------------------------------
# bounded scrape retry (graftha satellite): flap suppression vs death latency
# ---------------------------------------------------------------------------


class TestScrapeRetry:
    """One dropped connection is not a death: a failed scrape is retried
    (bounded, jittered) inside the same sweep before ``fleet.worker_up``
    flips — but a REAL death still lands at single-poll latency."""

    def _flaky_fleet(self, fail_first_n, **collector_kw):
        fake, _ = _two_worker_fleet()
        calls = {"n": 0}

        def flaky_fetch(url):
            if "/fake/w1/" in url and calls["n"] < fail_first_n:
                calls["n"] += 1
                return None
            return fake.fetch(url)

        coll = FleetCollector(
            fake.targets(), stale_after_s=10.0, clock=lambda: 0.0,
            fetch=flaky_fetch, **collector_kw,
        )
        return fake, coll, calls

    def test_flap_suppressed_within_one_sweep(self):
        fake, coll, calls = self._flaky_fleet(fail_first_n=1)
        coll.poll(now=0.0)
        snap = coll.snapshot(now=0.0)
        up = _series(snap, "fleet.worker_up")
        # the single dropped fetch never surfaced as a down transition
        assert up[(("worker", "w1"),)] == 1.0
        assert calls["n"] == 1
        retries = _series(snap, "fleet.scrape_retries_total")
        assert retries[(("worker", "w1"),)] == 1.0
        assert retries[(("worker", "w0"),)] == 0.0
        st = coll.status(now=0.0)
        assert st["workers"]["w1"]["retries"] == 1
        # a retried-but-successful sweep is NOT a failure
        assert st["workers"]["w1"]["failures"] == 0

    def test_real_death_detected_at_poll_latency(self):
        fake, coll = _two_worker_fleet()
        coll.poll(now=0.0)
        fetches = {"n": 0}
        real_fetch = coll._fetch

        def counting_fetch(url):
            if "/fake/w1/" in url:
                fetches["n"] += 1
            return real_fetch(url)

        coll._fetch = counting_fetch
        fake.dead.add("w1")
        coll.poll(now=1.0)
        # ONE poll is enough — the retry is in-sweep, not cross-poll
        up = _series(coll.snapshot(now=1.0), "fleet.worker_up")
        assert up[(("worker", "w1"),)] == 0.0
        # and the retry budget is bounded: default policy = 2 attempts,
        # metrics+status fetched per attempt
        assert fetches["n"] == 4
        st = coll.status(now=1.0)
        assert st["workers"]["w1"]["failures"] == 1
        assert st["workers"]["w1"]["retries"] == 1

    def test_scrape_retry_none_is_single_attempt(self):
        fake, coll, calls = self._flaky_fleet(
            fail_first_n=1, scrape_retry=None
        )
        coll.poll(now=0.0)
        up = _series(coll.snapshot(now=0.0), "fleet.worker_up")
        # no retry budget: the flap DOES flip the worker down
        assert up[(("worker", "w1"),)] == 0.0
        assert coll.status(now=0.0)["workers"]["w1"]["retries"] == 0

    def test_default_policy_is_bounded_and_jittered(self):
        from pydcop_tpu.telemetry.federate import default_scrape_retry

        policy = default_scrape_retry()
        assert policy.max_attempts == 2
        assert policy.jitter == "full"
        assert policy.max_delay <= 0.5  # a sweep never stalls long
