"""graftflow (pydcop_tpu.analysis.arrays): fixture-driven rule tests.

Mirrors test_analysis.py's shape: every flow-* rule gets a known-bad
sample (true positive) and a near-miss (true negative), linted from a
tmp dir in isolation.  A repo self-check asserts the arrays pass
produces nothing outside the checked-in baseline, wiring the graftflow
ratchet into tier-1 alongside the other passes.
"""

import json
import os
import textwrap

import pytest

from pydcop_tpu.analysis import (
    collect_findings,
    diff_against_baseline,
    iter_rules,
    load_baseline,
)
from pydcop_tpu.analysis.absval import (
    broadcast,
    canonical_dtype,
    join,
    promote,
    scalar,
)
from pydcop_tpu.analysis.arrays import EXPLAIN, RULES
from pydcop_tpu.analysis.cli import main as lint_main

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO_ROOT, "tools", "graftlint_baseline.json")

HEADER = """
from typing import NamedTuple
import jax
import jax.numpy as jnp
import numpy as np


class Dev(NamedTuple):
    n_vars: int  # static
    max_domain: int  # static
    unary: jnp.ndarray  # [n_vars, D] f32
    edge_var: jnp.ndarray  # [n_edges] i32
    msgs: jnp.ndarray  # [n_edges, D] bf16
    big_idx: jnp.ndarray  # [n_edges] i64
"""


def lint_source(tmp_path, source, name="sample.py", select=None):
    p = tmp_path / name
    p.write_text(textwrap.dedent(HEADER) + textwrap.dedent(source))
    return collect_findings([str(p)], select=select, passes=["arrays"])


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------
# the lattice itself
# ---------------------------------------------------------------------


class TestLattice:
    def test_weak_scalar_does_not_widen(self):
        # python float * f32 plane stays f32 (the property that makes
        # `x * 2.0` safe)
        assert promote("float32", False, "float32", True) == (
            "float32", False,
        )
        assert promote("bfloat16", False, "float32", True) == (
            "bfloat16", False,
        )

    def test_strong_widening(self):
        assert promote("float32", False, "float64", False)[0] == "float64"
        assert promote("int32", False, "int64", False)[0] == "int64"

    def test_int_meets_float(self):
        assert promote("int32", False, "bfloat16", False)[0] == "bfloat16"

    def test_broadcast_hard_and_soft(self):
        hard = broadcast((3, 4), (5, 4))
        assert hard.hard and not hard.soft
        soft = broadcast(("n_vars", "D"), ("n_edges",))
        assert soft.soft and not soft.hard
        ok = broadcast(("n_vars", "D"), ("n_vars", 1))
        assert not ok.hard and not ok.soft
        assert ok.shape == ("n_vars", "D")

    def test_canonical_dtype_tokens(self):
        assert canonical_dtype("f32") == "float32"
        assert canonical_dtype("jnp.bfloat16") == "bfloat16"
        assert canonical_dtype("i64") == "int64"
        assert canonical_dtype("SORTED") is None

    def test_join_merges_branches(self):
        a = scalar("int32", dim="n_vars")
        b = scalar("int32", dim="n_edges")
        assert join(a, a).dim == "n_vars"
        assert join(a, b).dim is None


# ---------------------------------------------------------------------
# dtype-flow family
# ---------------------------------------------------------------------


class TestDtypeFlow:
    def test_f64_widen_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def f(dev: Dev):
                return dev.unary.astype(jnp.float64)
            """,
        )
        assert "flow-f64-widen" in rules_of(fs)

    def test_f64_outside_jit_is_clean(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            def host_decode(x):
                return np.zeros(3, dtype=np.float64)
            """,
        )
        assert "flow-f64-widen" not in rules_of(fs)

    def test_int_promote_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def f(dev: Dev):
                return dev.edge_var + dev.big_idx
            """,
        )
        assert "flow-int-promote" in rules_of(fs)

    def test_float_index_flagged(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def f(dev: Dev):
                idx = dev.unary * 1
                return dev.edge_var[idx]
            """,
        )
        assert "flow-int-promote" in rules_of(fs)

    def test_arange_is_strong_int32(self, tmp_path):
        # the EXPLAIN text's own canonical case: an arange index array
        # meeting an int64 operand must fire (jnp.arange is strong)
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def f(dev: Dev, n: int):
                idx = jnp.arange(n)
                return idx + dev.big_idx
            """,
        )
        assert "flow-int-promote" in rules_of(fs)

    def test_int32_plus_constant_is_clean(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def f(dev: Dev):
                return dev.edge_var + 1
            """,
        )
        assert "flow-int-promote" not in rules_of(fs)

    def test_bf16_mixed_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def f(dev: Dev):
                return dev.msgs[:, 0] + dev.edge_var.astype(jnp.float32)
            """,
        )
        assert "flow-bf16-mixed" in rules_of(fs)

    def test_bf16_explicit_cast_is_clean(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def f(dev: Dev):
                lifted = dev.msgs.astype(jnp.float32)
                return lifted[:, 0] + dev.edge_var.astype(jnp.float32)
            """,
        )
        assert "flow-bf16-mixed" not in rules_of(fs)


# ---------------------------------------------------------------------
# shape/layout family
# ---------------------------------------------------------------------


class TestShapeLayout:
    def test_hard_mismatch_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def f(x: jnp.ndarray):
                return jnp.zeros((3, 4)) + jnp.ones((5, 4))
            """,
        )
        assert "flow-shape-mismatch" in rules_of(fs)

    def test_soft_symbol_mismatch_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def f(dev: Dev):
                return dev.unary + dev.edge_var
            """,
        )
        assert "flow-shape-mismatch" in rules_of(fs)

    def test_matching_symbols_clean(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def f(dev: Dev):
                return dev.unary + dev.unary * 2.0
            """,
        )
        assert "flow-shape-mismatch" not in rules_of(fs)

    def test_undocumented_symbols_do_not_soft_fire(self, tmp_path):
        # n_real is a parameter-derived extent, not part of the
        # documented vocabulary: slicing to it must stay silent
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def f(dev: Dev, n_real: int):
                head = dev.unary[:n_real]
                noise = jax.random.uniform(
                    jax.random.PRNGKey(0), (n_real, dev.max_domain)
                )
                return head + noise
            """,
        )
        assert "flow-shape-mismatch" not in rules_of(fs)

    def test_newaxis_broadcast_is_clean(self, tmp_path):
        # x[:, None] inserts a dim — the canonical broadcast idiom must
        # not read as consuming one and fire a bogus mismatch
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def f(dev: Dev):
                return dev.msgs * dev.edge_var[:, None]
            """,
        )
        assert "flow-shape-mismatch" not in rules_of(fs)

    def test_reshape_flatten_is_clean(self, tmp_path):
        # reshape(-1) is an unknown extent, not a concrete -1
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def f(dev: Dev):
                flat = dev.unary.reshape(-1)
                return flat + jnp.zeros((8,))
            """,
        )
        assert "flow-shape-mismatch" not in rules_of(fs)

    def test_valid_matmul_is_clean_and_bad_matmul_fires(self, tmp_path):
        # @ contracts — a valid matmul must not read as a broadcast
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def good(x: jnp.ndarray):
                return jnp.zeros((4, 7)) @ jnp.ones((7, 5))

            @jax.jit
            def bad(x: jnp.ndarray):
                return jnp.zeros((4, 7)) @ jnp.ones((5, 4))
            """,
        )
        mm = [f for f in fs if f.rule == "flow-shape-mismatch"]
        assert len(mm) == 1 and "contract" in mm[0].message

    def test_keepdims_normalize_is_clean(self, tmp_path):
        # x / x.sum(axis=-1, keepdims=True): the reduced axis stays
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def f(dev: Dev):
                return dev.unary / dev.unary.sum(axis=-1, keepdims=True)
            """,
        )
        assert "flow-shape-mismatch" not in rules_of(fs)

    def test_bounded_slices_never_guess_wrong_lengths(self, tmp_path):
        # x[1:4] has length 3; x[:-1] has unknown length — neither may
        # hard-fire against a correctly-sized operand
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def f(x: jnp.ndarray):
                a = jnp.zeros((9,))
                head = a[1:4] + jnp.ones((3,))
                tail = a[:-1] + jnp.ones((8,))
                return head, tail
            """,
        )
        assert "flow-shape-mismatch" not in rules_of(fs)

    def test_plane_reshape_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def f(dev: Dev):
                m = dev.msgs
                return m.reshape(m.shape[1], m.shape[0])
            """,
        )
        assert "flow-plane-reshape" in rules_of(fs)

    def test_transpose_is_clean(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def f(dev: Dev):
                return dev.msgs.T
            """,
        )
        assert "flow-plane-reshape" not in rules_of(fs)


# ---------------------------------------------------------------------
# batch-axis discipline family
# ---------------------------------------------------------------------


class TestBatchAxis:
    def test_marked_function_axis0_fires_all_forms(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            # graftflow: batchable
            def step(dev: Dev, values: jnp.ndarray):
                n = values.shape[0]
                first = values[0]
                pinned = values.at[0].set(1)
                tot = jnp.sum(values, axis=0)
                return n, first, pinned, tot
            """,
        )
        batch = [f for f in fs if f.rule == "flow-batch-axis"]
        assert len(batch) == 4

    def test_positional_axis_spellings_all_fire(self, tmp_path):
        # x.sum(0), jnp.sum(x, 0) and axis=0 are the same reduction;
        # the method form puts the axis at positional slot 0
        fs = lint_source(
            tmp_path,
            """
            # graftflow: batchable
            def step(values: jnp.ndarray):
                a = values.sum(0)
                b = jnp.sum(values, 0)
                return a, b
            """,
        )
        batch = [f for f in fs if f.rule == "flow-batch-axis"]
        assert len(batch) == 2

    def test_method_positional_axis_keeps_shape(self, tmp_path):
        # .sum(-1) is an axis reduction, not a full reduce: the result
        # still broadcasts like a plane, so a documented-symbol
        # mismatch downstream must still be visible
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def f(dev: Dev):
                rowsum = dev.unary.sum(-1)
                return rowsum + dev.edge_var
            """,
        )
        assert "flow-shape-mismatch" in rules_of(fs)

    def test_unmarked_function_is_clean(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            def step(dev: Dev, values: jnp.ndarray):
                return values[0]
            """,
        )
        assert "flow-batch-axis" not in rules_of(fs)

    def test_trailing_axis_usage_is_clean(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            # graftflow: batchable
            def step(dev: Dev, values: jnp.ndarray):
                best = jnp.argmin(values, axis=-1)
                tail = values[:, 0]
                return best, tail, values.shape[-1]
            """,
        )
        assert "flow-batch-axis" not in rules_of(fs)

    def test_marker_on_decorated_function(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            # graftflow: batchable
            @jax.jit
            def step(values: jnp.ndarray):
                return values[0]
            """,
        )
        assert "flow-batch-axis" in rules_of(fs)

    def test_suppression_with_justification(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            # graftflow: batchable
            def step(values: jnp.ndarray):
                return values[0]  # graftflow: disable=flow-batch-axis (stack axis, not batch)
            """,
        )
        assert "flow-batch-axis" not in rules_of(fs)


# ---------------------------------------------------------------------
# transfer/sharding family
# ---------------------------------------------------------------------


class TestTransferSharding:
    def test_host_transfer_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def f(x: jnp.ndarray):
                return float(np.asarray(x).sum())
            """,
        )
        assert "flow-host-transfer" in rules_of(fs)

    def test_item_method_flagged(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            @jax.jit
            def f(x: jnp.ndarray):
                return x.item()
            """,
        )
        assert "flow-host-transfer" in rules_of(fs)

    def test_host_code_is_clean(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            def to_host(x: jnp.ndarray):
                return np.asarray(x)
            """,
        )
        assert "flow-host-transfer" not in rules_of(fs)

    def test_undeclared_mesh_axis_fires(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            from jax.sharding import Mesh, PartitionSpec

            AXIS = "agents"

            def shard(x):
                return PartitionSpec("shards")
            """,
        )
        assert "flow-sharding-axis" in rules_of(fs)

    def test_declared_axis_is_clean(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            from jax.sharding import Mesh, PartitionSpec

            AXIS = "agents"

            def shard(x):
                return PartitionSpec("agents")
            """,
        )
        assert "flow-sharding-axis" not in rules_of(fs)

    def test_no_declarations_no_judgement(self, tmp_path):
        # a file set with no Mesh/axis declarations cannot know the
        # vocabulary, so PartitionSpec names pass
        fs = lint_source(
            tmp_path,
            """
            from jax.sharding import PartitionSpec

            def shard(x):
                return PartitionSpec("anything")
            """,
        )
        assert "flow-sharding-axis" not in rules_of(fs)


# ---------------------------------------------------------------------
# interprocedural propagation
# ---------------------------------------------------------------------


class TestInterprocedural:
    def test_callee_inherits_jit_reachability(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            def helper(x):
                return x.item()

            @jax.jit
            def f(x: jnp.ndarray):
                return helper(x)
            """,
        )
        assert "flow-host-transfer" in rules_of(fs)

    def test_shapes_flow_through_calls(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            def mix(a, b):
                return a + b

            @jax.jit
            def f(dev: Dev):
                return mix(dev.unary, dev.edge_var)
            """,
        )
        assert "flow-shape-mismatch" in rules_of(fs)

    def test_unsupplied_params_use_annotations(self, tmp_path):
        # a helper called with only some args still gets its other
        # params' documented types from annotations
        fs = lint_source(
            tmp_path,
            """
            def helper(dev: Dev, scale=1.0):
                return dev.unary + dev.edge_var

            @jax.jit
            def f(dev: Dev):
                return helper(scale=2.0, dev=dev)
            """,
        )
        assert "flow-shape-mismatch" in rules_of(fs)

    def test_combinator_callback_is_jit_reachable(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            def body(carry, x):
                return carry, float(x)

            def run(xs):
                return jax.lax.scan(body, 0.0, xs)
            """,
        )
        # body's params are unknown arrays -> float() not provable;
        # the seeding itself must at least not crash
        assert isinstance(fs, list)


# ---------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------


class TestCliSurface:
    def test_explain_prints_doc_and_example(self, capsys):
        rc = lint_main(["--explain", "flow-batch-axis"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "flow-batch-axis" in out
        assert "Minimal failing example" in out
        assert "batchable" in out

    def test_explain_unknown_rule_errors(self, capsys):
        rc = lint_main(["--explain", "flow-nope"])
        assert rc == 2

    def test_every_flow_rule_has_explain_entry(self):
        for rule in RULES:
            assert rule.id in EXPLAIN, rule.id

    def test_every_rule_everywhere_has_explain_entry(self):
        from pydcop_tpu.analysis.core import _passes

        documented = set()
        for mod in _passes().values():
            documented |= set(getattr(mod, "EXPLAIN", {}))
        assert {r.id for r in iter_rules()} <= documented

    def test_rule_count_table_in_output(self, tmp_path, capsys):
        p = tmp_path / "bad.py"
        p.write_text(
            textwrap.dedent(HEADER)
            + textwrap.dedent(
                """
                @jax.jit
                def f(dev: Dev):
                    return dev.unary + dev.edge_var
                """
            )
        )
        rc = lint_main([str(p)])
        out = capsys.readouterr().out
        assert rc == 1
        lines = out.splitlines()
        assert any(ln.startswith("rule") for ln in lines)
        assert any(
            ln.startswith("flow-shape-mismatch") and ln.split()[-2:]
            for ln in lines
        )


# ---------------------------------------------------------------------
# repo self-check: the graftflow ratchet is live in tier-1
# ---------------------------------------------------------------------


class TestRepoRatchet:
    def test_arrays_pass_matches_checked_in_baseline(self):
        os.chdir(REPO_ROOT)
        findings = collect_findings(["pydcop_tpu"], passes=["arrays"])
        baseline = load_baseline(BASELINE)
        diff = diff_against_baseline(findings, baseline)
        assert not diff.new, (
            "new graftflow finding(s); fix, suppress with a "
            "justification, or (deliberate accepts only) re-ratchet "
            "with make lint-baseline:\n"
            + "\n".join(f.format() for f in diff.new)
        )

    def test_batchable_markers_seeded_on_solve_path(self):
        # the ROADMAP-3 ratchet only works while the markers exist
        base = os.path.join(
            REPO_ROOT, "pydcop_tpu", "algorithms", "base.py"
        )
        with open(base, "r", encoding="utf-8") as f:
            src = f.read()
        assert src.count("# graftflow: batchable") >= 4
        kernels = os.path.join(
            REPO_ROOT, "pydcop_tpu", "compile", "kernels.py"
        )
        with open(kernels, "r", encoding="utf-8") as f:
            assert "# graftflow: batchable" in f.read()
