"""Tests for graftwatch (ISSUE 4): cross-agent trace flows and stitching,
the Prometheus formatter + live ``/metrics`` surface, the ``watch`` /
``telemetry stitch`` / ``telemetry --prom`` CLI verbs, and the anytime
convergence gauges published by the device solve.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from pydcop_tpu.infrastructure.communication import (
    InProcessCommunicationLayer,
    Messaging,
)
from pydcop_tpu.infrastructure.computations import Message
from pydcop_tpu.infrastructure.events import event_bus
from pydcop_tpu.telemetry import (
    flow_stats,
    metrics_registry,
    render_prometheus,
    stitch_traces,
    telemetry_off,
    tracer,
    validate_events,
)

ENV = dict(os.environ, JAX_PLATFORMS="cpu")
INSTANCE = os.path.join(
    os.path.dirname(__file__), "instances", "graph_coloring.yaml"
)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry_off()
    yield
    telemetry_off()
    event_bus.enabled = False
    event_bus.reset()


def run_cli(*args, timeout=120):
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=ENV,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )


# ---------------------------------------------------------------------------
# flow events: trace context stamping across the messaging path
# ---------------------------------------------------------------------------


class TestMessageFlows:
    def _pair(self):
        m1 = Messaging("a1", InProcessCommunicationLayer())
        m2 = Messaging("a2", InProcessCommunicationLayer())
        m2.register_computation("c2", object())
        m1.register_route("c2", "a2", m2.comm.address)
        return m1, m2

    def test_send_deliver_consume_share_one_flow_id(self):
        tracer.enabled = True
        m1, m2 = self._pair()
        m1.post_msg("c1", "c2", Message("ping", "x"))
        assert m2.next_msg(timeout=1) is not None
        flows = [e for e in tracer.events() if e.get("ph") in ("s", "t", "f")]
        assert [e["ph"] for e in flows] == ["s", "t", "f"]
        assert len({e["id"] for e in flows}) == 1
        # every flow event is anchored to a micro-slice at the same ts
        slices = {
            (e["name"], e["ts"])
            for e in tracer.events()
            if e.get("ph") == "X"
        }
        for e, name in zip(
            flows, ("comms.send", "comms.recv", "comms.delivery")
        ):
            assert (name, e["ts"]) in slices
        # finish events bind to their enclosing slice
        assert flows[2]["bp"] == "e"

    def test_consume_span_on_receiving_side_carries_latency(self):
        tracer.enabled = True
        m1, m2 = self._pair()
        m1.post_msg("c1", "c2", Message("ping", "x"))
        assert m2.next_msg(timeout=1) is not None
        delivery = [
            e for e in tracer.events() if e["name"] == "comms.delivery"
        ]
        assert len(delivery) == 1
        args = delivery[0]["args"]
        assert args["agent"] == "a2"
        assert args["latency_ms"] >= 0.0

    def test_parked_then_replayed_message_is_one_flow(self):
        tracer.enabled = True
        m1 = Messaging("a1", InProcessCommunicationLayer())
        m2 = Messaging("a2", InProcessCommunicationLayer())
        m2.register_computation("c2", object())
        m1.post_msg("c1", "c2", Message("ping", "x"))  # no route: parks
        m1.register_route("c2", "a2", m2.comm.address)  # flush re-posts
        assert m2.next_msg(timeout=1) is not None
        stats = flow_stats(tracer.events())
        assert stats == {
            "sends": 1, "delivered": 1, "consumed": 1, "matched": 1,
            "match_pct": 100.0,
        }

    def test_flow_ids_unique_across_messages(self):
        tracer.enabled = True
        m1, m2 = self._pair()
        for _ in range(10):
            m1.post_msg("c1", "c2", Message("ping", "x"))
        sends = [e for e in tracer.events() if e.get("ph") == "s"]
        assert len({e["id"] for e in sends}) == 10

    def test_flow_events_pass_schema_validation(self):
        tracer.enabled = True
        m1, m2 = self._pair()
        m1.post_msg("c1", "c2", Message("ping", "x"))
        assert m2.next_msg(timeout=1) is not None
        assert validate_events(tracer.events()) == []

    def test_disabled_tracer_stamps_nothing(self):
        m1, m2 = self._pair()
        msg = Message("ping", "x")
        m1.post_msg("c1", "c2", msg)
        assert not hasattr(msg, "_trace_ctx")
        assert tracer.events() == []

    @pytest.mark.slow
    def test_thread_mode_run_pairs_95pct_of_sends(self):
        # ISSUE 4 acceptance: a multi-agent thread-mode run yields >= 95%
        # of send flows paired with a delivery flow event on the
        # receiving agent's track (a different thread than the sender's)
        from pydcop_tpu.dcop.yamldcop import load_dcop_from_file
        from pydcop_tpu.infrastructure.run import run_local_thread_dcop

        tracer.enabled = True
        orchestrator = run_local_thread_dcop(
            "dsa", load_dcop_from_file([INSTANCE]), n_cycles=5
        )
        try:
            orchestrator.deploy_computations()
            orchestrator.run(timeout=60)
        finally:
            orchestrator.stop_agents()
            orchestrator.stop()
        events = tracer.events()
        stats = flow_stats(events)
        assert stats["sends"] > 0
        assert stats["match_pct"] >= 95.0
        # cross-thread arrows exist: at least one flow finishes on a
        # different thread than it started on
        start_tid = {e["id"]: e["tid"] for e in events if e.get("ph") == "s"}
        cross = [
            e for e in events
            if e.get("ph") == "f" and start_tid.get(e["id"]) != e["tid"]
        ]
        assert cross, "no cross-thread delivery flows recorded"


# ---------------------------------------------------------------------------
# tracer epoch hygiene (satellite fix)
# ---------------------------------------------------------------------------


class TestEpochRecapture:
    def test_reenable_recaptures_stale_epoch(self):
        stale_wall = tracer._epoch_wall - 3600.0
        tracer._epoch_wall = stale_wall
        tracer.enabled = True  # event-less enable: must re-capture
        assert tracer._epoch_wall != stale_wall
        assert abs(tracer._epoch_wall - time.time()) < 5.0

    def test_reenable_with_events_keeps_epoch(self):
        tracer.enabled = True
        tracer.instant("x")
        epoch = tracer._epoch_wall
        tracer.enabled = False
        tracer.enabled = True  # events recorded: their ts must stay valid
        assert tracer._epoch_wall == epoch

    def test_reset_recaptures_and_rotates_trace_id(self):
        old_id = tracer.trace_id
        tracer._epoch_wall -= 3600.0
        tracer.reset()
        assert abs(tracer._epoch_wall - time.time()) < 5.0
        assert tracer.trace_id != old_id


# ---------------------------------------------------------------------------
# Prometheus formatter
# ---------------------------------------------------------------------------


class TestPromFormatter:
    def test_counter_gauge_histogram_rendering(self):
        metrics_registry.enabled = True
        metrics_registry.counter("demo.requests", "reqs").inc(3, agent="a1")
        metrics_registry.gauge("demo.depth").set(2.5)
        h = metrics_registry.histogram(
            "demo.lat_seconds", "lat", buckets=(0.1, 1.0)
        )
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        text = render_prometheus(metrics_registry.snapshot())
        assert '# TYPE demo_requests_total counter' in text
        assert 'demo_requests_total{agent="a1"} 3' in text
        assert "demo_depth 2.5" in text
        # cumulative buckets: 1 under 0.1, 2 under 1.0, 3 under +Inf
        assert 'demo_lat_seconds_bucket{le="0.1"} 1' in text
        assert 'demo_lat_seconds_bucket{le="1"} 2' in text
        assert 'demo_lat_seconds_bucket{le="+Inf"} 3' in text
        assert "demo_lat_seconds_count 3" in text

    def test_label_values_escaped_and_names_sanitized(self):
        snapshot = {
            "metrics": {
                "weird.name-x": {
                    "kind": "gauge",
                    "help": "",
                    "values": [
                        {"labels": {"k": 'a"b\\c'}, "value": 1.0}
                    ],
                }
            }
        }
        text = render_prometheus(snapshot)
        assert 'weird_name_x{k="a\\"b\\\\c"} 1' in text

    def test_snapshot_file_roundtrip(self, tmp_path):
        metrics_registry.enabled = True
        metrics_registry.counter("demo.count").inc(7)
        path = tmp_path / "m.json"
        metrics_registry.dump(str(path))
        text = render_prometheus(json.loads(path.read_text()))
        assert "demo_count_total 7" in text


# ---------------------------------------------------------------------------
# stitching
# ---------------------------------------------------------------------------


def _mk_trace(path, pid, epoch, events, service=None):
    payload = {
        "traceEvents": events,
        "metadata": {"epoch_unix_s": epoch, "service": service},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f)


def _x(pid, ts, name="work", dur=5.0):
    return {
        "name": name, "cat": "t", "ph": "X", "ts": ts, "dur": dur,
        "pid": pid, "tid": pid,
    }


def _flow(pid, ph, fid, ts):
    e = {
        "name": "comms.msg", "cat": "comms", "ph": ph, "id": fid,
        "ts": ts, "pid": pid, "tid": pid,
    }
    if ph == "f":
        e["bp"] = "e"
    return e


class TestStitch:
    def test_epoch_alignment_and_symmetric_offset(self, tmp_path):
        # two processes; B's epoch is 1 s later AND its clock reads
        # 2000 us ahead.  Bidirectional flows let the symmetric-delay
        # estimator recover the 2000 us offset exactly (delay 100 us
        # both ways).
        skew = 2000.0
        a = str(tmp_path / "a.json")
        b = str(tmp_path / "b.json")
        fwd_send, rev_recv = 10_000.0, 40_000.0
        _mk_trace(a, 100, 1000.0, [
            _x(100, fwd_send), _flow(100, "s", 1, fwd_send),
            _x(100, rev_recv), _flow(100, "f", 2, rev_recv),
        ], service="orchestrator")
        # in B's (aligned) time: recv = send + delay + skew,
        # send = (true send) + skew where true reverse send lands at
        # rev_recv - delay in A time... expressed directly:
        b_recv = fwd_send - 1_000_000.0 + 100.0 + skew  # fid 1 arrives
        b_send = rev_recv - 1_000_000.0 - 100.0 + skew  # fid 2 departs
        _mk_trace(b, 200, 1001.0, [
            _x(200, b_recv), _flow(200, "t", 1, b_recv),
            _x(200, b_send), _flow(200, "s", 2, b_send),
        ], service="a1")
        trace, report = stitch_traces([a, b])
        offsets = trace["metadata"]["clock_offsets_us"]
        assert offsets[a] == 0.0
        assert offsets[b] == pytest.approx(skew, abs=1.0)
        # after stitching, both directions show the symmetric delay
        by_id = {}
        for e in trace["traceEvents"]:
            if e.get("ph") in ("s", "t", "f"):
                by_id.setdefault(e["id"], {})[e["ph"]] = e["ts"]
        assert by_id[1]["t"] - by_id[1]["s"] == pytest.approx(100.0, abs=1.0)
        assert by_id[2]["f"] - by_id[2]["s"] == pytest.approx(100.0, abs=1.0)
        assert report["flows"]["match_pct"] == 100.0

    def test_one_way_pair_clamped_to_causality(self, tmp_path):
        a = str(tmp_path / "a.json")
        b = str(tmp_path / "b.json")
        _mk_trace(a, 100, 1000.0, [
            _x(100, 5000.0), _flow(100, "s", 7, 5000.0),
        ])
        # receiver's clock is 3 ms behind: arrival would precede the send
        _mk_trace(b, 200, 1000.0, [
            _x(200, 2000.0), _flow(200, "f", 7, 2000.0),
        ])
        trace, _report = stitch_traces([a, b])
        by_ph = {
            e["ph"]: e["ts"]
            for e in trace["traceEvents"]
            if e.get("ph") in ("s", "f")
        }
        assert by_ph["f"] >= by_ph["s"]

    def test_pid_collision_remapped(self, tmp_path):
        a = str(tmp_path / "a.json")
        b = str(tmp_path / "b.json")
        _mk_trace(a, 100, 1000.0, [_x(100, 0.0)])
        _mk_trace(b, 100, 1000.0, [_x(100, 0.0)])
        trace, _ = stitch_traces([a, b])
        pids = {
            e["pid"] for e in trace["traceEvents"] if e.get("ph") == "X"
        }
        assert len(pids) == 2

    def test_stitched_trace_validates(self, tmp_path):
        a = str(tmp_path / "a.json")
        b = str(tmp_path / "b.json")
        _mk_trace(a, 100, 1000.0, [
            _x(100, 5000.0), _flow(100, "s", 7, 5000.0),
        ])
        _mk_trace(b, 200, 999.0, [
            _x(200, 9000.0), _flow(200, "f", 7, 9000.0),
        ])
        trace, _ = stitch_traces([a, b])
        assert validate_events(trace["traceEvents"]) == []
        assert all(
            e["ts"] >= 0
            for e in trace["traceEvents"]
            if isinstance(e.get("ts"), (int, float))
        )

    def test_flow_stats_counts(self):
        events = [
            _flow(1, "s", 1, 0.0), _flow(1, "s", 2, 1.0),
            _flow(1, "t", 1, 2.0), _flow(2, "f", 1, 3.0),
        ]
        stats = flow_stats(events)
        assert stats["sends"] == 2
        assert stats["matched"] == 1
        assert stats["match_pct"] == 50.0

    def test_stitch_cli_roundtrip(self, tmp_path):
        a = str(tmp_path / "a.json")
        b = str(tmp_path / "b.json")
        out = str(tmp_path / "merged.json")
        _mk_trace(a, 100, 1000.0, [
            _x(100, 5000.0), _flow(100, "s", 7, 5000.0),
        ], service="orchestrator")
        _mk_trace(b, 200, 1000.5, [
            _x(200, 1000.0), _flow(200, "f", 7, 1000.0),
        ], service="a0")
        r = run_cli("telemetry", "stitch", a, b, "-o", out, "--json")
        assert r.returncode == 0, r.stderr
        report = json.loads(r.stdout)
        assert report["flows"]["matched"] == 1
        merged = json.loads(open(out).read())
        assert len(merged["traceEvents"]) == 4
        # the merged file summarizes/validates like any single trace
        r2 = run_cli("telemetry", "--validate", out)
        assert r2.returncode == 0, r2.stderr

    def test_stitch_cli_requires_out(self, tmp_path):
        a = str(tmp_path / "a.json")
        _mk_trace(a, 1, 1.0, [_x(1, 0.0)])
        r = run_cli("telemetry", "stitch", a)
        assert r.returncode == 2
        assert "-o" in r.stderr


# ---------------------------------------------------------------------------
# live surface: MetricsHttpServer + watch verb
# ---------------------------------------------------------------------------


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=3
    ) as r:
        return r.status, r.headers.get("Content-Type"), r.read().decode()


class TestMetricsHttpServer:
    def test_endpoints(self):
        from pydcop_tpu.infrastructure.ui import MetricsHttpServer

        metrics_registry.enabled = True
        metrics_registry.counter("demo.hits").inc(4)
        server = MetricsHttpServer(0, status_cb=lambda: {"status": "RUNNING"})
        try:
            code, ctype, body = _get(server.port, "/metrics")
            assert code == 200 and "text/plain" in ctype
            assert "demo_hits_total 4" in body
            code, ctype, body = _get(server.port, "/metrics.json")
            assert code == 200
            snap = json.loads(body)
            assert snap["metrics"]["demo.hits"]["values"][0]["value"] == 4
            code, _, body = _get(server.port, "/status")
            assert code == 200 and json.loads(body)["status"] == "RUNNING"
            with pytest.raises(urllib.request.HTTPError):
                _get(server.port, "/nope")
        finally:
            server.shutdown()

    def test_broken_status_callback_answers_500_and_survives(self):
        from pydcop_tpu.infrastructure.ui import MetricsHttpServer

        def boom():
            raise RuntimeError("collector exploded")

        server = MetricsHttpServer(0, status_cb=boom)
        try:
            with pytest.raises(urllib.request.HTTPError) as exc:
                _get(server.port, "/status")
            assert exc.value.code == 500
            code, _, _ = _get(server.port, "/metrics")  # still serving
            assert code == 200
        finally:
            server.shutdown()


class TestWatchVerb:
    def test_sparkline(self):
        from pydcop_tpu.commands.watch import sparkline

        s = sparkline([5, 4, 3, 2, 1])
        assert len(s) == 5
        assert s[0] == "█" and s[-1] == "▁"
        assert sparkline([]) == ""
        assert len(sparkline(list(range(1000)), width=60)) <= 61

    def test_watch_once_against_live_server(self, capsys):
        from argparse import Namespace

        from pydcop_tpu.commands.watch import run_cmd
        from pydcop_tpu.infrastructure.ui import MetricsHttpServer

        metrics_registry.enabled = True
        metrics_registry.counter("comms.messages_sent").inc(12, agent="a1")
        status = {
            "status": "RUNNING", "cost": 3.5, "best_cost": 3.25,
            "cycles_to_best": 7, "cycle": 9, "time": 1.2,
            "cost_curve": [9.0, 5.0, 3.25],
            "agents": {"a1": {"queue": 2, "parked": 0, "dead_letters": 0}},
            "dead_letters": 0,
        }
        server = MetricsHttpServer(0, status_cb=lambda: status)
        try:
            rc = run_cmd(Namespace(
                url=None, host="127.0.0.1", port=server.port,
                interval=0.1, duration=None, once=True, as_json=False,
                output=None,
            ))
        finally:
            server.shutdown()
        out = capsys.readouterr().out
        assert rc == 0
        assert "RUNNING" in out and "best=3.25" in out
        assert "a1" in out and "anytime cost" in out

    def test_watch_unreachable_exits_nonzero(self, capsys):
        from argparse import Namespace

        from pydcop_tpu.commands.watch import run_cmd

        rc = run_cmd(Namespace(
            url="http://127.0.0.1:1", host="127.0.0.1", port=1,
            interval=0.1, duration=None, once=True, as_json=False,
            output=None,
        ))
        assert rc == 1

    def test_prom_cli_converts_snapshot(self, tmp_path):
        metrics_registry.enabled = True
        metrics_registry.counter("demo.total_things").inc(9)
        snap = tmp_path / "m.json"
        metrics_registry.dump(str(snap))
        r = run_cli("telemetry", "--prom", str(snap))
        assert r.returncode == 0, r.stderr
        assert "demo_total_things_total 9" in r.stdout


# ---------------------------------------------------------------------------
# convergence gauges (tentpole layer 3)
# ---------------------------------------------------------------------------


class TestConvergenceGauges:
    def _compiled(self):
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_coloring_arrays,
        )

        return generate_coloring_arrays(
            30, 3, graph="random", p_edge=0.15, seed=3
        )

    def test_chunked_path_publishes_incremental_nonincreasing(self):
        from unittest import mock

        from pydcop_tpu.algorithms import dsa

        metrics_registry.enabled = True
        series = []
        g = metrics_registry.gauge("solve.best_cost")
        orig = g.set
        with mock.patch.object(
            g, "set",
            side_effect=lambda v, **kw: (series.append(v), orig(v, **kw)),
        ):
            dsa.solve(self._compiled(), {}, n_cycles=100, seed=0, timeout=60)
        # 100 cycles = chunks of 16/32/52: >= 2 incremental publications
        assert len(series) >= 2
        assert all(b <= a + 1e-9 for a, b in zip(series, series[1:]))
        assert metrics_registry.gauge("solve.cycles_to_best").value() >= 1

    def test_fused_path_publishes_final_best_and_argmin(self):
        import numpy as np

        from pydcop_tpu.algorithms import dsa

        metrics_registry.enabled = True
        r = dsa.solve(
            self._compiled(), {}, n_cycles=40, seed=0, collect_curve=True
        )
        best = metrics_registry.gauge("solve.best_cost").value()
        c2b = metrics_registry.gauge("solve.cycles_to_best").value()
        assert best == pytest.approx(min(r.cost_curve), rel=1e-5)
        assert int(c2b) == int(np.argmin(r.cost_curve)) + 1

    def test_gauges_untouched_when_metrics_off(self):
        from pydcop_tpu.algorithms import dsa

        dsa.solve(self._compiled(), {}, n_cycles=20, seed=0, timeout=60)
        assert metrics_registry.gauge("solve.best_cost").labels() == []


# ---------------------------------------------------------------------------
# process-mode trace files + stitch (ISSUE 4 two-process acceptance)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestProcessModeStitch:
    def test_process_run_traces_stitch_into_one_timeline(self, tmp_path):
        trace = str(tmp_path / "trace.json")
        r = run_cli(
            "--output", str(tmp_path / "result.json"),
            "solve", "-a", "dsa", "-m", "process", "-n", "5",
            "--trace-out", trace, INSTANCE,
            timeout=300,
        )
        assert r.returncode == 0, r.stderr
        agent_traces = sorted(
            str(p) for p in tmp_path.glob("trace.json.*.json")
        )
        assert len(agent_traces) >= 2  # one per agent process
        merged_path = str(tmp_path / "merged.json")
        merged, report = stitch_traces([trace] + agent_traces)
        with open(merged_path, "w") as f:
            json.dump(merged, f)
        assert validate_events(merged["traceEvents"]) == []
        flows = report["flows"]
        assert flows["sends"] > 0
        assert flows["match_pct"] >= 95.0
        # the stitched timeline spans multiple processes
        pids = {
            e["pid"]
            for e in merged["traceEvents"]
            if isinstance(e.get("pid"), int)
        }
        assert len(pids) >= 3  # orchestrator + >= 2 agents
