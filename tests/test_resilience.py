"""Resilience tests: replication, repair DCOP, scenario-driven agent removal
(SURVEY.md §2.6, §5.3) and the HTTP/process topology."""

import time

import pytest

pytest.importorskip("jax")

from pydcop_tpu.dcop import (  # noqa: E402
    DCOP,
    AgentDef,
    Domain,
    Variable,
    constraint_from_str,
)
from pydcop_tpu.dcop.scenario import DcopEvent, EventAction, Scenario  # noqa: E402
from pydcop_tpu.infrastructure.run import run_local_thread_dcop  # noqa: E402
from pydcop_tpu.reparation import repair_dcop, repair_distribution  # noqa: E402
from pydcop_tpu.reparation.removal import (  # noqa: E402
    removal_candidate_agents,
    removal_orphaned_computations,
)
from pydcop_tpu.replication.path_utils import (  # noqa: E402
    affordable_path_from,
    cheapest_path_to,
    filter_missing_agents_paths,
    ucs_paths,
)


def coloring_dcop(n_agents=3):
    d = Domain("colors", "", ["R", "G", "B"])
    x, y, z = Variable("x", d), Variable("y", d), Variable("z", d)
    dcop = DCOP("chain")
    dcop += constraint_from_str("c1", "10 if x == y else 0", [x, y])
    dcop += constraint_from_str("c2", "10 if y == z else 0", [y, z])
    dcop.add_agents(
        [AgentDef(f"a{i}", capacity=100) for i in range(n_agents)]
    )
    return dcop


class TestPathUtils:
    def test_cheapest_path_to(self):
        paths = {("a", "b"): 3.0, ("a", "c", "b"): 2.0, ("a", "c"): 1.0}
        p, c = cheapest_path_to("b", paths)
        assert p == ("a", "c", "b") and c == 2.0

    def test_affordable_path_from(self):
        paths = {("a", "b"): 3.0, ("a", "c"): 1.0, ("b", "c"): 1.0}
        out = affordable_path_from(("a",), 2.0, paths)
        assert out == {("a", "c"): 1.0}

    def test_filter_missing_agents(self):
        paths = {("a", "b"): 3.0, ("a", "c"): 1.0}
        out = filter_missing_agents_paths(paths, ["a", "c"])
        assert out == {("a", "c"): 1.0}

    def test_ucs_paths_uses_cheapest_route(self):
        costs = {("a", "b"): 10.0, ("a", "c"): 1.0, ("c", "b"): 2.0}

        def route(x, y):
            return costs.get((x, y), costs.get((y, x), 100.0))

        dist = ucs_paths("a", route, ["a", "b", "c"])
        assert dist["c"] == 1.0
        assert dist["b"] == 3.0  # through c, not the direct 10.0 hop


class TestRemovalAnalysis:
    def test_orphans_and_candidates(self):
        from pydcop_tpu.distribution.objects import Distribution

        dist = Distribution({"a0": ["x"], "a1": ["y", "z"]})
        orphans = removal_orphaned_computations(dist, "a1")
        assert sorted(orphans) == ["y", "z"]
        survivors = {"a0": AgentDef("a0")}
        cands = removal_candidate_agents(
            orphans, survivors, {"y": ["a0"], "z": []}
        )
        assert cands["y"] == ["a0"]
        assert cands["z"] == ["a0"]  # fallback: all survivors


class TestRepairDcop:
    def _setup(self):
        from pydcop_tpu.computations_graph import constraints_hypergraph
        from pydcop_tpu.distribution.objects import Distribution

        dcop = coloring_dcop()
        cg = constraints_hypergraph.build_computation_graph(dcop)
        dist = Distribution({"a0": ["x"], "a1": ["y"], "a2": ["z"]})
        from pydcop_tpu.algorithms import AlgorithmDef

        algo = AlgorithmDef.build_with_default_param("dsa")
        return dcop, cg, dist, algo

    def test_repair_dcop_structure(self):
        dcop, cg, dist, algo = self._setup()
        agents = list(dcop.agents.values())
        rdcop, cand = repair_dcop(cg, agents, dist, "a2", algo)
        # one binary var per (orphan, candidate agent)
        assert set(cand) == {"z"}
        assert set(cand["z"]) == {"a0", "a1"}
        assert "hosted_z" in rdcop.constraints
        assert "capacity_a0" in rdcop.constraints
        assert "hosting_a1" in rdcop.constraints

    def test_repair_distribution_rehosts_orphan(self):
        dcop, cg, dist, algo = self._setup()
        agents = list(dcop.agents.values())
        new_dist, metrics = repair_distribution(
            cg, agents, dist, "a2", algo
        )
        assert "a2" not in new_dist.agents
        host = new_dist.agent_for("z")
        assert host in ("a0", "a1")
        assert metrics["migrated"] == {"z": host}
        assert metrics["repair_violation"] == 0

    def test_repair_greedy_fallback_on_huge_tabulation(self, monkeypatch):
        # with many orphan candidates per agent the dense tabulation of the
        # capacity constraint explodes (compile/core.py MAX_TABLE_ELEMS);
        # the repair must fall back to greedy placement, not fail
        import pydcop_tpu.api as api

        def boom(*a, **kw):
            raise NotImplementedError("table too large")

        monkeypatch.setattr(api, "solve_result", boom)
        dcop, cg, dist, algo = self._setup()
        agents = list(dcop.agents.values())
        new_dist, metrics = repair_distribution(
            cg, agents, dist, "a2", algo
        )
        assert metrics["repair_status"] == "GREEDY"
        host = new_dist.agent_for("z")
        assert host in ("a0", "a1")
        assert metrics["migrated"] == {"z": host}

    def test_repair_respects_replica_candidates(self):
        dcop, cg, dist, algo = self._setup()
        agents = list(dcop.agents.values())
        new_dist, _ = repair_distribution(
            cg, agents, dist, "a2", algo, replica_hosts={"z": ["a1"]}
        )
        assert new_dist.agent_for("z") == "a1"


class TestReplicationProtocol:
    def test_start_replication_places_replicas(self):
        dcop = coloring_dcop()
        orchestrator = run_local_thread_dcop(
            "dsa", dcop, "oneagent", n_cycles=10
        )
        try:
            orchestrator.deploy_computations()
            orchestrator.start_replication(k=1, timeout=10)
            # every computation has one replica host recorded
            assert set(orchestrator.mgt.replica_hosts) == {"x", "y", "z"}
            for comp, hosts in orchestrator.mgt.replica_hosts.items():
                assert len(hosts) == 1
                assert hosts[0] != orchestrator.distribution.agent_for(comp)
            # directory knows the replicas too
            reps = orchestrator.directory.directory.replicas
            assert set(reps) == {"x", "y", "z"}
        finally:
            orchestrator.stop_agents()
            orchestrator.stop()


class TestScenarioRepair:
    def test_two_simultaneous_removals_with_k2_replication(self):
        # round-3 verdict item 5: two agents die in the SAME scenario
        # event while computations carry k=2 replicas; every orphan must
        # be re-hosted on a surviving agent and the solve still finishes
        # with a complete assignment
        d = Domain("colors", "", ["R", "G", "B"])
        vs = [Variable(f"v{i}", d) for i in range(5)]
        dcop = DCOP("ring5")
        for i in range(5):
            a, b = vs[i], vs[(i + 1) % 5]
            dcop += constraint_from_str(
                f"c{i}", f"10 if {a.name} == {b.name} else 0", [a, b]
            )
        dcop.add_agents(
            [AgentDef(f"a{i}", capacity=100) for i in range(5)]
        )
        scenario = Scenario(
            [
                DcopEvent("e1", delay=0.1),
                DcopEvent(
                    "e2",
                    actions=[
                        EventAction("remove_agent", agent="a2"),
                        EventAction("remove_agent", agent="a3"),
                    ],
                ),
            ]
        )
        orchestrator = run_local_thread_dcop(
            "dsa", dcop, "oneagent", n_cycles=30, seed=0
        )
        try:
            orchestrator.deploy_computations()
            orphans = orchestrator.distribution.computations_hosted(
                "a2"
            ) + orchestrator.distribution.computations_hosted("a3")
            assert orphans
            orchestrator.start_replication(k=2, timeout=15)
            for comp, hosts in orchestrator.mgt.replica_hosts.items():
                assert len(hosts) == 2, (comp, hosts)
            orchestrator.run(scenario=scenario, timeout=60)
            assert orchestrator.status == "FINISHED"
            survivors = {"a0", "a1", "a4"}
            assert set(orchestrator.distribution.agents) <= survivors
            for comp in orphans:
                assert orchestrator.distribution.agent_for(comp) in survivors
            # both repairs recorded, and the final solution is complete
            metrics = orchestrator.end_metrics()
            repaired = {
                o for r in metrics["repair_metrics"] for o in r["orphans"]
            }
            assert repaired == set(orphans)
            assignment, _ = orchestrator.current_solution()
            assert set(assignment) == {v.name for v in vs}
        finally:
            orchestrator.stop_agents()
            orchestrator.stop()

    def test_remove_agent_scenario_rehosts_computations(self):
        dcop = coloring_dcop()
        scenario = Scenario(
            [
                DcopEvent("e1", delay=0.1),
                DcopEvent(
                    "e2",
                    actions=[EventAction("remove_agent", agent="a2")],
                ),
            ]
        )
        orchestrator = run_local_thread_dcop(
            "dsa", dcop, "oneagent", n_cycles=30, seed=0
        )
        try:
            orchestrator.deploy_computations()
            removed_comp = orchestrator.distribution.computations_hosted(
                "a2"
            )
            assert len(removed_comp) == 1
            orchestrator.run(scenario=scenario, timeout=30)
            assert orchestrator.status == "FINISHED"
            # the orphan was rehosted on a survivor
            assert "a2" not in orchestrator.distribution.agents
            new_host = orchestrator.distribution.agent_for(removed_comp[0])
            assert new_host in ("a0", "a1")
            metrics = orchestrator.end_metrics()
            assert metrics["repair_metrics"]
            assert metrics["repair_metrics"][0]["orphans"] == removed_comp
            # solution is still complete after the repair
            assignment, _ = orchestrator.current_solution()
            assert set(assignment) == {"x", "y", "z"}
        finally:
            orchestrator.stop_agents()
            orchestrator.stop()


@pytest.mark.slow
class TestProcessTopology:
    def test_http_process_run(self):
        from pydcop_tpu.infrastructure.run import run_local_process_dcop

        dcop = coloring_dcop()
        orchestrator = run_local_process_dcop(
            "dpop", dcop, "oneagent", port=19300
        )
        try:
            orchestrator.deploy_computations(timeout=60)
            orchestrator.run(timeout=60)
            assignment, cost = orchestrator.current_solution()
            assert set(assignment) == {"x", "y", "z"}
            assert assignment["x"] != assignment["y"]
        finally:
            orchestrator.stop_agents(timeout=10)
            orchestrator.stop()
            for p in getattr(orchestrator, "_agent_processes", []):
                p.join(5)
                if p.is_alive():
                    p.terminate()


class TestReplicaObjects:
    def test_mapping_and_queries(self):
        from pydcop_tpu.replication.objects import ReplicaDistribution

        rd = ReplicaDistribution({"c1": ["a1", "a2"], "c2": ["a2"]})
        assert rd.replica_count("c1") == 2
        assert rd.agents_for_computation("c2") == ["a2"]
        assert sorted(rd.computations_for_agent("a2")) == ["c1", "c2"]

    def test_yaml_roundtrip(self):
        from pydcop_tpu.replication.objects import ReplicaDistribution
        from pydcop_tpu.replication.yamlformat import (
            load_replica_dist,
            yaml_replica_dist,
        )

        rd = ReplicaDistribution({"c1": ["a1"], "c2": ["a2", "a3"]})
        assert load_replica_dist(yaml_replica_dist(rd)) == rd


class TestStatsTracing:
    def test_trace_rows_written(self, tmp_path):
        from pydcop_tpu.infrastructure import stats

        p = str(tmp_path / "trace.csv")
        stats.set_stats_file(p)
        try:
            assert stats.stats_enabled()
            stats.trace_computation("comp_a", 1, 0.01, 5, 120, 300, 40)
            stats.trace_computation("comp_b", 2, 0.02)
        finally:
            stats.set_stats_file(None)
        lines = open(p).read().splitlines()
        assert lines[0].startswith("time,computation,cycle,duration")
        assert len(lines) == 3
        assert "comp_a,1," in lines[1]

    def test_disabled_by_default(self, tmp_path):
        from pydcop_tpu.infrastructure import stats

        assert not stats.stats_enabled()
        stats.trace_computation("x", 0, 0.0)  # no-op, must not raise


class TestChaosSoak:
    """graftchaos seeded soak (ISSUE 3): replication → abrupt kill →
    repair under message delays and a transient device fault, asserting
    the run converges to the SAME assignment as a fault-free solve with
    the same seed (the device solve is deterministic; resilience must
    only re-host, never change the answer)."""

    def _ring_dcop(self, n=5):
        d = Domain("colors", "", ["R", "G", "B"])
        vs = [Variable(f"v{i}", d) for i in range(n)]
        dcop = DCOP(f"ring{n}")
        for i in range(n):
            a, b = vs[i], vs[(i + 1) % n]
            dcop += constraint_from_str(
                f"c{i}", f"10 if {a.name} == {b.name} else 0", [a, b]
            )
        dcop.add_agents(
            [AgentDef(f"a{i}", capacity=100) for i in range(n)]
        )
        return dcop, vs

    def test_seeded_kill_repair_converges_to_fault_free_solution(self):
        from pydcop_tpu.algorithms import AlgorithmDef
        from pydcop_tpu.api import solve_result
        from pydcop_tpu.chaos import (
            ChaosController,
            DeviceFault,
            FaultSchedule,
            KillEvent,
            MessageRule,
        )

        dcop, vs = self._ring_dcop()
        algo = AlgorithmDef.build_with_default_param(
            "dsa", mode=dcop.objective
        )
        baseline = solve_result(dcop, algo, n_cycles=30, seed=0)[
            "assignment"
        ]

        schedule = FaultSchedule(
            seed=11,
            events=[
                KillEvent("a2", at=0.15),
                # jitter the control plane: delays reorder racing
                # senders, duplicated deploy acks probe idempotency
                MessageRule(
                    action="delay", pattern="*", p=0.15, seconds=0.02
                ),
                MessageRule(action="duplicate", pattern="deployed", p=0.2),
                # and one transient device failure the solve must absorb
                DeviceFault(count=1),
            ],
        )
        controller = ChaosController(schedule)
        orchestrator = run_local_thread_dcop(
            "dsa", dcop, "oneagent", n_cycles=30, seed=0, chaos=controller
        )
        try:
            orchestrator.deploy_computations()
            orphans = orchestrator.distribution.computations_hosted("a2")
            assert orphans
            orchestrator.start_replication(k=2, timeout=15)
            orchestrator.run(timeout=60)
            assert orchestrator.status == "FINISHED"
            # the kill really was abrupt
            assert orchestrator._local_agents["a2"]._crashed
            # every orphan re-hosted on a survivor
            assert "a2" not in orchestrator.distribution.agents
            for comp in orphans:
                host = orchestrator.distribution.agent_for(comp)
                assert host != "a2"
                assert host in orchestrator.mgt.registered_agents
            # convergence: same assignment as the fault-free run
            assignment, _ = orchestrator.current_solution()
            assert assignment == baseline
            # nothing was silently lost
            assert orchestrator.dead_letter_total() == 0
            # the log records the kill and the injected device fault
            log = controller.event_log()
            assert {
                "stream": "_timeline", "n": 0, "action": "kill",
                "agent": "a2", "at": 0.15,
            } in log
            assert {
                "stream": "_device", "n": 0, "action": "device_fault",
            } in log
        finally:
            orchestrator.stop_agents()
            orchestrator.stop()


class TestScenarioArrival:
    """Agent ARRIVAL elasticity — beyond the reference, where add_agent
    is an explicit TODO (its orchestrator.py:1032-1037): a scenario can
    grow the running system; the newcomer registers, is routable, and
    participates in the candidate filter of later repairs.  (Orphans of
    THIS removal can only go to surviving replica HOLDERS — replication
    predates the arrival — so hosting by the newcomer comes via the
    re-replication that follows repairs, not this one.)"""

    def test_added_agent_joins_running_system(self):
        d = Domain("colors", "", ["R", "G", "B"])
        vs = [Variable(f"v{i}", d) for i in range(4)]
        dcop = DCOP("ring4")
        for i in range(4):
            a, b = vs[i], vs[(i + 1) % 4]
            dcop += constraint_from_str(
                f"c{i}", f"10 if {a.name} == {b.name} else 0", [a, b]
            )
        dcop.add_agents(
            [AgentDef(f"a{i}", capacity=100) for i in range(4)]
        )
        scenario = Scenario(
            [
                DcopEvent("e1", delay=0.1),
                DcopEvent(
                    "e2", actions=[EventAction("add_agent", agent="a_new")]
                ),
                DcopEvent("e3", delay=0.2),
                DcopEvent(
                    "e4", actions=[EventAction("remove_agent", agent="a1")]
                ),
            ]
        )
        orchestrator = run_local_thread_dcop(
            "dsa", dcop, "oneagent", n_cycles=40, seed=0
        )
        try:
            orchestrator.deploy_computations()
            orphans = orchestrator.distribution.computations_hosted("a1")
            assert orphans
            orchestrator.start_replication(k=2, timeout=15)
            orchestrator.run(scenario=scenario, timeout=60)
            assert orchestrator.status == "FINISHED"
            # the newcomer registered with the control plane
            assert "a_new" in orchestrator.mgt.registered_agents
            assert "a_new" in orchestrator.directory.directory.agents
            # the newcomer is routable from the orchestrator
            assert "a_new" in orchestrator.mgt.agent_addresses
            # the failed agent's computations all moved OFF it
            for comp in orphans:
                host = orchestrator.distribution.agent_for(comp)
                assert host != "a1"
                assert host in orchestrator.mgt.registered_agents
            assignment, _ = orchestrator.current_solution()
            assert set(assignment) == {v.name for v in vs}
        finally:
            orchestrator.stop_agents()
            orchestrator.stop()
