"""Worker for the 2-process DCN test (tests/test_parallel.py): joins the
distributed mesh, runs a sharded MaxSum solve spanning both processes, and
prints one parseable result line.  Not a test module."""

import os
import sys


def main() -> None:
    port, pid, num = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    os.environ["JAX_PLATFORMS"] = "cpu"
    from pydcop_tpu.parallel.mesh import init_distributed

    init_distributed(
        f"127.0.0.1:{port}", num, pid, local_device_count=4
    )

    from pydcop_tpu.algorithms import maxsum
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_coloring_arrays,
    )
    from pydcop_tpu.compile.kernels import to_device
    from pydcop_tpu.parallel.mesh import (
        make_mesh,
        pad_device_dcop,
        shard_device_dcop,
    )

    compiled = generate_coloring_arrays(
        64, 3, graph="scalefree", m_edge=2, seed=5
    )
    mesh = make_mesh(4 * num)
    dev = shard_device_dcop(
        pad_device_dcop(to_device(compiled), mesh.size), mesh
    )
    r = maxsum.solve(
        compiled, {"noise": 0.0, "stop_cycle": 10, "layout": "lanes"},
        n_cycles=10, seed=0, dev=dev,
    )
    vals = ",".join(str(r.assignment[n]) for n in sorted(r.assignment))
    print(f"DISTRESULT {pid} {r.cost:.6f} {r.violations} {vals}", flush=True)

    # second flagship over the SAME distributed mesh: exact inference with
    # the UTIL joints partitioned across both processes
    import numpy as np

    from pydcop_tpu.algorithms import dpop
    from pydcop_tpu.compile.direct import compile_from_edges

    rng = np.random.default_rng(3)
    n = 200
    parents = np.array(
        [rng.integers(max(0, i - 4), i) for i in range(1, n)]
    )
    edges = np.stack([parents, np.arange(1, n)], axis=1)
    tables = rng.uniform(0, 10, size=(len(edges), 3, 3)).astype(np.float32)
    tree_problem = compile_from_edges(n, 3, edges, tables)
    rd = dpop.solve(tree_problem, {}, mesh=mesh)
    dvals = ",".join(str(rd.assignment[k]) for k in sorted(rd.assignment))
    print(f"DPOPRESULT {pid} {rd.cost:.6f} {dvals}", flush=True)


if __name__ == "__main__":
    main()
