"""Worker for the 2-process DCN test (tests/test_parallel.py): joins the
distributed mesh, runs a sharded MaxSum solve spanning both processes, and
prints one parseable result line.  Not a test module."""

import os
import sys


def main() -> None:
    port, pid, num = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    os.environ["JAX_PLATFORMS"] = "cpu"
    from pydcop_tpu.parallel.mesh import init_distributed

    init_distributed(
        f"127.0.0.1:{port}", num, pid, local_device_count=4
    )

    from pydcop_tpu.algorithms import maxsum
    from pydcop_tpu.commands.generators.graphcoloring import (
        generate_coloring_arrays,
    )
    from pydcop_tpu.compile.kernels import to_device
    from pydcop_tpu.parallel.mesh import (
        make_mesh,
        pad_device_dcop,
        shard_device_dcop,
    )

    compiled = generate_coloring_arrays(
        64, 3, graph="scalefree", m_edge=2, seed=5
    )
    mesh = make_mesh(4 * num)
    dev = shard_device_dcop(
        pad_device_dcop(to_device(compiled), mesh.size), mesh
    )
    r = maxsum.solve(
        compiled, {"noise": 0.0, "stop_cycle": 10},
        n_cycles=10, seed=0, dev=dev,
    )
    vals = ",".join(str(r.assignment[n]) for n in sorted(r.assignment))
    print(f"DISTRESULT {pid} {r.cost:.6f} {r.violations} {vals}", flush=True)


if __name__ == "__main__":
    main()
