"""Variable-family depth tests, modeled on the reference's coverage
(/root/reference/tests/unit/test_dcop_variables.py, ~490 LoC): domains,
every Variable subclass (cost dict/func/noisy, binary, external),
clone semantics, simple_repr round-trips and hashing."""

import pytest

pytest.importorskip("jax")

from pydcop_tpu.dcop.objects import (  # noqa: E402
    AgentDef,
    BinaryVariable,
    Domain,
    ExternalVariable,
    Variable,
    VariableNoisyCostFunc,
    VariableWithCostDict,
    VariableWithCostFunc,
)
from pydcop_tpu.utils.expressions import ExpressionFunction  # noqa: E402
from pydcop_tpu.utils.simple_repr import from_repr, simple_repr  # noqa: E402


class TestDomain:
    def test_repr_roundtrip(self):
        d = Domain("colors", "color", ["R", "G", "B"])
        d2 = from_repr(simple_repr(d))
        assert d2 == d
        assert list(d2.values) == ["R", "G", "B"]
        assert d2.type == "color"

    def test_hash_distinguishes_values(self):
        assert hash(Domain("d", "", [0, 1])) != hash(Domain("d", "", [0, 2]))
        assert hash(Domain("d", "", [0, 1])) == hash(Domain("d", "", [0, 1]))

    def test_membership_and_index(self):
        d = Domain("d", "", [5, 7, 9])
        assert 7 in d
        assert 8 not in d
        assert d.index(9) == 2
        assert len(d) == 3


class TestVariable:
    def test_initial_value_kept(self):
        d = Domain("d", "", [0, 1, 2])
        assert Variable("v", d).initial_value is None
        assert Variable("v", d, 2).initial_value == 2

    def test_repr_roundtrip_with_initial(self):
        d = Domain("d", "", [0, 1, 2])
        v = Variable("v", d, 1)
        v2 = from_repr(simple_repr(v))
        assert v2 == v
        assert v2.initial_value == 1

    def test_clone_is_equal_not_same(self):
        d = Domain("d", "", [0, 1])
        v = Variable("v", d, 1)
        c = v.clone()
        assert c == v and c is not v

    def test_hash_covers_initial_value(self):
        d = Domain("d", "", [0, 1])
        assert hash(Variable("v", d, 0)) != hash(Variable("v", d, 1))


class TestBinaryVariable:
    def test_fixed_domain(self):
        b = BinaryVariable("b")
        assert list(b.domain.values) == [0, 1]
        assert b.clone() == b


class TestVariableWithCostDict:
    def test_costs_and_roundtrip(self):
        d = Domain("d", "", ["a", "b"])
        v = VariableWithCostDict("v", d, {"a": 1.5, "b": 0.5})
        assert v.cost_for_val("a") == 1.5
        v2 = from_repr(simple_repr(v))
        assert v2 == v
        assert v2.cost_for_val("b") == 0.5


class TestVariableWithCostFunc:
    def test_expression_cost(self):
        d = Domain("d", "", [0, 1, 2])
        v = VariableWithCostFunc("v", d, ExpressionFunction("v * 2 + 1"))
        assert v.cost_for_val(2) == 5

    def test_expression_must_use_own_name(self):
        d = Domain("d", "", [0, 1])
        with pytest.raises(ValueError):
            VariableWithCostFunc("v", d, ExpressionFunction("w * 2"))

    def test_lambda_cost_not_serializable(self):
        d = Domain("d", "", [0, 1])
        v = VariableWithCostFunc("v", d, lambda v: v * 3)
        assert v.cost_for_val(1) == 3
        with pytest.raises((TypeError, ValueError)):
            simple_repr(v)

    def test_expression_roundtrip(self):
        d = Domain("d", "", [0, 1, 2])
        v = VariableWithCostFunc("v", d, ExpressionFunction("v * 2"))
        v2 = from_repr(simple_repr(v))
        assert [v2.cost_for_val(x) for x in (0, 1, 2)] == [0, 2, 4]


class TestVariableNoisyCostFunc:
    def test_noise_bounded_and_deterministic(self):
        d = Domain("d", "", [0, 1, 2])
        v = VariableNoisyCostFunc(
            "v", d, ExpressionFunction("v * 2"), noise_level=0.2
        )
        for val in (0, 1, 2):
            base = val * 2
            c = v.cost_for_val(val)
            assert base <= c < base + 0.2
            assert v.cost_for_val(val) == c  # stable per value

    def test_roundtrip_keeps_noise_level(self):
        d = Domain("d", "", [0, 1])
        v = VariableNoisyCostFunc(
            "v", d, ExpressionFunction("v"), noise_level=0.1
        )
        v2 = from_repr(simple_repr(v))
        assert isinstance(v2, VariableNoisyCostFunc)
        assert v2.noise_level == 0.1

    def test_clone_same_costs(self):
        d = Domain("d", "", [0, 1, 2])
        v = VariableNoisyCostFunc(
            "v", d, ExpressionFunction("v"), noise_level=0.3
        )
        c = v.clone()
        assert [c.cost_for_val(x) for x in (0, 1, 2)] == [
            v.cost_for_val(x) for x in (0, 1, 2)
        ]


class TestExternalVariable:
    def test_value_must_stay_in_domain(self):
        d = Domain("d", "", [0, 1])
        e = ExternalVariable("e", d, 0)
        e.value = 1
        assert e.value == 1
        with pytest.raises(ValueError):
            e.value = 9

    def test_subscription_fires_on_change_only(self):
        d = Domain("d", "", [0, 1])
        e = ExternalVariable("e", d, 0)
        seen = []
        e.subscribe(seen.append)
        e.value = 1
        e.value = 1  # no change: no callback
        e.value = 0
        assert seen == [1, 0]

    def test_clone_detaches_subscribers(self):
        d = Domain("d", "", [0, 1])
        e = ExternalVariable("e", d, 0)
        seen = []
        e.subscribe(seen.append)
        c = e.clone()
        c.value = 1
        assert seen == []  # clone's changes don't reach original's subs
        assert e.value == 0


class TestAgentDef:
    def test_default_and_pair_routes(self):
        a = AgentDef("a1", default_route=2.5, routes={"a2": 7})
        assert a.route("a2") == 7
        assert a.route("a3") == 2.5
        assert a.route("a1") == 0  # self route is free

    def test_hosting_cost_levels(self):
        a = AgentDef(
            "a1", default_hosting_cost=9, hosting_costs={"c1": 0}
        )
        assert a.hosting_cost("c1") == 0
        assert a.hosting_cost("other") == 9

    def test_extras_and_roundtrip(self):
        a = AgentDef("a1", capacity=42, zone="roof")
        a2 = from_repr(simple_repr(a))
        assert a2.capacity == 42
        assert a2.zone == "roof"
