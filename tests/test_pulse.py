"""graftpulse tests: health-vector schema + device hooks, diagnosis
taxonomy, fused-vs-chunked bit-stability, the unified cycles_to_best
definition, and the postmortem flight recorder (docs/observability.md).

The device fixtures are tiny DCOPs whose dynamics are forced regardless
of the seeded random init, so the expected flip/residual values are
hand-computable:

- unary-only pull: every variable moves to its unary argmin in cycle 1
  and never again — flips nonzero only in cycle 1, residual (available
  gain) exactly 0 from cycle 1 on, cost exactly 0 from cycle 1 on.
- equality-seeking pair under parallel best response (DSA p=1): from a
  mismatched init both variables copy each other simultaneously forever —
  churn 1.0 and flipback 1.0 every cycle, the canonical period-2
  oscillation.
- tree MaxSum: messages converge exactly in finite time, so the v2f/f2v
  residual fields hit 0.0 exactly.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

pytest.importorskip("jax")

from pydcop_tpu.dcop import (  # noqa: E402
    DCOP,
    Domain,
    Variable,
    constraint_from_str,
)
from pydcop_tpu.telemetry.pulse import (  # noqa: E402
    HEALTH_FIELDS,
    HEALTH_WIDTH,
    POSTMORTEM_FORMAT,
    FlightRecorder,
    analyze,
    flip_summary,
    load_postmortem,
    pulse,
    render_postmortem,
)

F = {name: i for i, name in enumerate(HEALTH_FIELDS)}


def row(cost=0.0, best=0.0, flips=0.0, churn=0.0, flipback=0.0,
        residual=0.0, aux=0.0, violations=0.0):
    r = [0.0] * HEALTH_WIDTH
    r[F["cost"]], r[F["best_cost"]], r[F["flips"]] = cost, best, flips
    r[F["churn"]], r[F["flipback"]] = churn, flipback
    r[F["residual"]], r[F["aux"]], r[F["violations"]] = (
        residual, aux, violations,
    )
    return r


@pytest.fixture
def pulse_on():
    """Enable the pulse monitor for one test, fully reset both ways."""
    pulse.reset()
    pulse.enabled = True
    yield pulse
    pulse.enabled = False
    pulse.reset()


def compiled(dcop):
    from pydcop_tpu.compile.core import compile_dcop

    return compile_dcop(dcop)


def unary_pull(n=3):
    """n independent variables, 3 colors, unary cost 0 only on 'R'."""
    d = Domain("c", "", ["R", "G", "B"])
    dcop = DCOP("unary_pull")
    for i in range(n):
        v = Variable(f"v{i}", d)
        dcop += constraint_from_str(
            f"u{i}", f"0 if v{i} == 'R' else 5", [v]
        )
    dcop.add_agents([])
    return dcop


def equality_pair():
    """x, y want to be equal: parallel best response swaps forever."""
    d = Domain("c", "", ["R", "G"])
    x, y = Variable("x", d), Variable("y", d)
    dcop = DCOP("pair")
    dcop += constraint_from_str("c1", "10 if x != y else 0", [x, y])
    dcop.add_agents([])
    return dcop


def chain():
    d = Domain("c", "", ["R", "G"])
    x, y, z = Variable("x", d), Variable("y", d), Variable("z", d)
    dcop = DCOP("chain")
    dcop += constraint_from_str("c1", "10 if x == y else 0", [x, y])
    dcop += constraint_from_str("c2", "10 if y == z else 0", [y, z])
    dcop.add_agents([])
    return dcop


# ---------------------------------------------------------------------------
# schema + analyzer (pure host)
# ---------------------------------------------------------------------------


class TestSchema:
    def test_field_order_pinned(self):
        # the device pack in algorithms/base.py:_health_vec emits exactly
        # this order; renaming or reordering is a postmortem format break
        assert HEALTH_FIELDS == (
            "cost", "best_cost", "flips", "churn", "flipback",
            "residual", "aux", "violations",
        )
        assert HEALTH_WIDTH == 8


class TestAnalyze:
    def test_no_data(self):
        assert analyze([])["diagnosis"] == "no-data"

    def test_still_improving(self):
        rows = [row(cost=10 - i, best=10 - i) for i in range(10)]
        a = analyze(rows)
        assert a["diagnosis"] == "still-improving"
        assert a["best_delta"] == pytest.approx(9.0)

    def test_converged(self):
        rows = [row(cost=3.0, best=3.0)] * 10
        assert analyze(rows)["diagnosis"] == "converged"

    def test_converged_after_early_churn(self):
        # settled runs keep their transient in the window: cycle 1
        # churned, everything after is quiet — that is converged, not a
        # stalled plateau
        rows = [row(cost=5.0, best=0.0, flips=3, churn=1.0)] + [
            row(cost=0.0, best=0.0)
        ] * 15
        assert analyze(rows)["diagnosis"] == "converged"

    def test_oscillating_cost_period(self):
        costs = [4.0, 7.0, 5.0] * 8  # period 3
        rows = [
            row(cost=c, best=4.0, flips=2, churn=0.5) for c in costs
        ]
        a = analyze(rows)
        assert a["diagnosis"] == "oscillating"
        assert a["period"] == 3
        assert a["diagnosis_full"] == "oscillating(period=3)"

    def test_oscillating_flipback_symmetric_swap(self):
        # cost series flat (symmetric swap), but the device flipback
        # indicator says values return to their 2-cycles-ago state
        rows = [
            row(cost=10.0, best=10.0, flips=2, churn=1.0, flipback=1.0)
        ] * 12
        a = analyze(rows)
        assert a["diagnosis"] == "oscillating"
        assert a["period"] == 2

    def test_big_cost_base_does_not_blind_the_tolerances(self):
        # tolerances anchor on the window's cost dynamic range, not
        # |cost|: soft-cost dynamics of ~10/cycle on a ~1e9 BIG base
        # (one unsatisfiable hard constraint) must still register
        big = 1.0e9
        rows = [
            row(cost=big - 10.0 * i, best=big - 10.0 * i,
                flips=1, churn=0.1)
            for i in range(32)
        ]
        assert analyze(rows)["diagnosis"] == "still-improving"
        rows = [
            row(cost=big + (10.0 if i % 2 else -10.0), best=big - 10.0,
                flips=2, churn=1.0)
            for i in range(32)
        ]
        a = analyze(rows)
        assert a["diagnosis"] == "oscillating"
        assert a["period"] == 2

    def test_one_flipper_on_a_huge_problem_is_not_converged(self):
        # churn is flips/n_live: on a 100k-variable solve one variable
        # flipping every cycle reads churn 1e-5 — inside any fixed
        # fractional tolerance, yet the run has not settled.  converged
        # must demand literally zero flips in the recent tail.
        rows = [
            row(cost=5.0, best=5.0, flips=1.0, churn=1e-5)
            for _ in range(32)
        ]
        assert analyze(rows)["diagnosis"] == "stalled-plateau"

    def test_old_flipback_does_not_mask_a_stall(self):
        # oscillated EARLIER in the window (flipback 1.0 for the first
        # 3/4) but the recent tail thrashes aperiodically (flipback 0):
        # the whole-window flipback mean is 0.75, yet the CURRENT
        # behavior is a stalled plateau — the fallback must judge the
        # same recent tail as churn_now, or the operator is told to
        # raise damping when the run needs noise/restart
        rows = [
            row(cost=10.0, best=10.0, flips=2, churn=1.0, flipback=1.0)
        ] * 24
        rows += [
            row(cost=10.0, best=10.0, flips=2, churn=1.0, flipback=0.0)
        ] * 8
        a = analyze(rows, tail=32)
        assert a["diagnosis"] == "stalled-plateau"

    def test_stalled_plateau(self):
        # best flat, churning, aperiodic cost series
        costs = [5.0, 6.0, 5.5, 7.0, 5.3, 6.6, 5.9, 7.1, 5.2, 6.1,
                 5.7, 7.3, 5.6, 6.9, 5.8, 6.3]
        rows = [
            row(cost=c, best=5.0, flips=1, churn=0.3) for c in costs
        ]
        assert analyze(rows)["diagnosis"] == "stalled-plateau"

    def test_window_limits_lookback(self):
        # improvement older than the tail window must not count
        rows = [row(cost=10.0 - i, best=10.0 - i) for i in range(10)]
        rows += [row(cost=1.0, best=1.0)] * 40
        assert analyze(rows, tail=32)["diagnosis"] == "converged"


class TestFlipSummary:
    def test_counts(self):
        s = flip_summary([0, 0, 5, 1, 9], cycles=10)
        assert s["n_vars"] == 5
        assert s["frozen"] == 2
        assert s["frozen_frac"] == pytest.approx(0.4)
        assert s["churning"] == 1  # only the 9/10 flipper crosses 50%
        assert s["top_churners"][0] == {"var": 4, "flips": 9}

    def test_empty(self):
        s = flip_summary([], cycles=0)
        assert s["n_vars"] == 0 and s["frozen_frac"] == 1.0


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_keeps_tail(self):
        r = FlightRecorder(capacity=4)
        r.reset({"algo": "t"})
        r.record([row(cost=float(i)) for i in range(10)], start_cycle=0)
        doc = r.snapshot()
        assert len(doc["rows"]) == 4
        assert doc["start_cycle"] == 6
        assert [x[F["cost"]] for x in doc["rows"]] == [6.0, 7.0, 8.0, 9.0]
        assert doc["format"] == POSTMORTEM_FORMAT

    def test_dump_once_per_reason(self, pulse_on, tmp_path):
        rec = pulse_on.recorder
        rec.reset({"algo": "t", "seed": 3})
        rec.record([row(cost=1.0)], 0)
        p = str(tmp_path / "pm.json")
        assert rec.maybe_dump("solve-timeout", p) == p
        assert rec.maybe_dump("solve-timeout", p) is None  # once
        # same reason CLASS: a cascade keeps the first agent's context
        assert rec.maybe_dump("agent-crash:a1", p) == p
        assert rec.maybe_dump("agent-crash:a2", p) is None
        assert rec.maybe_dump("chaos-divergence", p) == p  # new reason
        doc = load_postmortem(p)
        assert doc["reason"] == "chaos-divergence"
        assert doc["fields"] == list(HEALTH_FIELDS)
        assert doc["meta"]["seed"] == 3

    def test_failed_dump_releases_the_slot(self, pulse_on, tmp_path):
        # a transient write failure (full disk, vanished state dir) must
        # not consume the once-per-class slot: the NEXT failure of that
        # class still dumps
        rec = pulse_on.recorder
        rec.reset({"algo": "t"})
        rec.record([row(cost=1.0)], 0)
        bad = str(tmp_path / "is_a_dir")
        os.makedirs(bad)
        assert rec.maybe_dump("agent-crash:a1", bad) is None
        good = str(tmp_path / "pm.json")
        assert rec.maybe_dump("agent-crash:a2", good) == good
        assert load_postmortem(good)["reason"] == "agent-crash:a2"

    def test_dump_noop_when_disabled(self, tmp_path):
        pulse.reset()
        assert pulse.enabled is False
        rec = pulse.recorder
        rec.record([row()], 0)
        assert rec.maybe_dump("x", str(tmp_path / "no.json")) is None
        assert not (tmp_path / "no.json").exists()

    def test_load_rejects_foreign_json(self, tmp_path):
        p = tmp_path / "other.json"
        p.write_text('{"hello": 1}')
        with pytest.raises(ValueError, match="not a pydcop_tpu postmortem"):
            load_postmortem(str(p))
        # non-object JSON must raise the same clean ValueError (the verb
        # turns it into an error line), not an AttributeError traceback
        p.write_text('[1, 2, 3]')
        with pytest.raises(ValueError, match="not a pydcop_tpu postmortem"):
            load_postmortem(str(p))

    def test_render_timeline(self):
        doc = {
            "format": POSTMORTEM_FORMAT,
            "reason": "solve-timeout",
            "fingerprint": "abc",
            "meta": {"algo": "dsa"},
            "start_cycle": 0,
            "rows": [row(cost=3.0, best=3.0)] * 20,
            "flip_summary": flip_summary([0, 4], cycles=20),
        }
        text = render_postmortem(doc, window=8)
        assert "solve-timeout" in text
        assert "converged" in text
        assert "1/2 frozen" in text


# ---------------------------------------------------------------------------
# device hooks: hand-computed health vectors per algorithm family
# ---------------------------------------------------------------------------


def recorded_rows():
    return np.asarray(pulse.recorder.snapshot()["rows"], dtype=np.float32)


class TestLocalSearchHealth:
    def test_mgm_unary_pull_exact(self, pulse_on):
        # every variable moves to its argmin in cycle 1; nothing ever
        # moves again: flips nonzero only in row 1, residual (available
        # gain) and cost exactly 0.0 from row 1 on
        from pydcop_tpu.algorithms import mgm

        mgm.solve(compiled(unary_pull(3)), {}, n_cycles=12, seed=0)
        rows = recorded_rows()
        assert rows.shape[1] == HEALTH_WIDTH
        assert rows[0, F["cost"]] == 0.0
        assert rows[0, F["best_cost"]] == 0.0
        assert rows[0, F["residual"]] == 0.0  # computed on the new state
        assert rows[0, F["violations"]] == 0.0
        k = rows[0, F["flips"]]
        assert k in (0.0, 1.0, 2.0, 3.0)
        assert rows[0, F["churn"]] == pytest.approx(k / 3.0)
        # cycles 2..n: fully settled, exactly zero everywhere
        assert np.all(rows[1:, F["flips"]] == 0.0)
        assert np.all(rows[1:, F["churn"]] == 0.0)
        assert np.all(rows[1:, F["residual"]] == 0.0)
        assert np.all(rows[:, F["cost"]] == 0.0)
        report = pulse.last_report
        assert report["diagnosis"] == "converged"
        fs = report["flip_summary"]
        assert fs["n_vars"] == 3
        assert fs["frozen"] == 3 - int(k)
        assert sum(t["flips"] for t in fs["top_churners"]) == int(k)

    def test_dsa_equality_pair_oscillates(self, pulse_on):
        # parallel best response on an equality pair: from a mismatched
        # init both copy each other forever — churn 1, flipback 1, the
        # canonical period-2 swap.  The seeded init is deterministic;
        # probe a few seeds for one starting mismatched (each seed is
        # mismatched with probability 1/2).
        from pydcop_tpu.algorithms import dsa

        c = compiled(equality_pair())
        for seed in range(12):
            pulse.reset()
            dsa.solve(
                c, {"probability": 1.0}, n_cycles=16, seed=seed
            )
            rows = recorded_rows()
            if rows[0, F["cost"]] == 10.0:
                break
        else:
            pytest.fail("no seed produced a mismatched init in 12 tries")
        assert np.all(rows[:, F["cost"]] == 10.0)
        assert np.all(rows[:, F["churn"]] == 1.0)
        assert np.all(rows[:, F["flips"]] == 2.0)
        # from cycle 2 on every flip returns to the 2-cycles-ago value
        assert np.all(rows[1:, F["flipback"]] == 1.0)
        report = pulse.last_report
        assert report["diagnosis"] == "oscillating(period=2)"
        assert report["flip_summary"]["churning"] == 2

    def test_mesh_padding_does_not_dilute_churn(self, pulse_on):
        # pad_device_dcop pads with 1-value dead domains: those rows can
        # never flip, so they must not count as live — an oscillating
        # pair padded 2 -> 8 rows still reads churn 1.0, not 2/8
        from pydcop_tpu.algorithms import dsa
        from pydcop_tpu.compile.kernels import to_device
        from pydcop_tpu.parallel.mesh import pad_device_dcop

        c = compiled(equality_pair())
        dev = pad_device_dcop(to_device(c), 8)
        for seed in range(12):
            pulse.reset()
            dsa.solve(
                c, {"probability": 1.0}, n_cycles=8, seed=seed, dev=dev
            )
            rows = recorded_rows()
            if rows[0, F["cost"]] == 10.0:
                break
        else:
            pytest.fail("no seed produced a mismatched init in 12 tries")
        assert np.all(rows[:, F["churn"]] == 1.0)
        assert np.all(rows[:, F["flips"]] == 2.0)

    def test_dsa_converging_run(self, pulse_on):
        from pydcop_tpu.algorithms import dsa

        dsa.solve(compiled(chain()), {}, n_cycles=40, seed=0)
        rows = recorded_rows()
        assert len(rows) == 40
        assert pulse.last_report["analysis"]["violations"] == 0.0
        # the anytime best series in the rows is non-increasing
        best = rows[:, F["best_cost"]]
        assert np.all(np.diff(best) <= 0.0)


class TestMessagePassingHealth:
    def test_maxsum_tree_residual_hits_zero(self, pulse_on):
        # undamped BP on a tree converges exactly: both message-plane
        # residual fields reach 0.0, and the diagnosis is converged
        from pydcop_tpu.algorithms import maxsum

        maxsum.solve(
            compiled(chain()),
            {"damping": 0.0, "stop_cycle": 40},
            n_cycles=40,
            seed=0,
        )
        rows = recorded_rows()
        assert rows[-1, F["residual"]] == 0.0  # v2f plane
        assert rows[-1, F["aux"]] == 0.0  # f2v plane
        assert rows[-1, F["churn"]] == 0.0
        assert pulse.last_report["diagnosis"] == "converged"

    def test_dba_and_gdba_emit(self, pulse_on):
        from pydcop_tpu.algorithms import dba, gdba

        for mod in (dba, gdba):
            pulse.reset()
            mod.solve(compiled(chain()), {}, n_cycles=10, seed=0)
            rows = recorded_rows()
            assert rows.shape == (10, HEALTH_WIDTH)
            assert np.all(np.isfinite(rows))
            assert np.all(rows[:, F["churn"]] <= 1.0)

    def test_adsa_and_mgm2_emit(self, pulse_on):
        from pydcop_tpu.algorithms import adsa, mgm2

        for mod in (adsa, mgm2):
            pulse.reset()
            mod.solve(compiled(chain()), {}, n_cycles=10, seed=0)
            rows = recorded_rows()
            assert rows.shape[1] == HEALTH_WIDTH
            assert np.all(np.isfinite(rows))

    def test_amaxsum_mixeddsa_dsatuto_emit(self, pulse_on):
        # the remaining scan-loop solvers are wired too — algo_ref's
        # "every scan-loop algorithm exports a health hook" is a promise
        from pydcop_tpu.algorithms import amaxsum, dsatuto, mixeddsa

        for mod in (amaxsum, mixeddsa, dsatuto):
            pulse.reset()
            mod.solve(compiled(chain()), {}, n_cycles=10, seed=0)
            rows = recorded_rows()
            assert rows.shape == (10, HEALTH_WIDTH), mod.__name__
            assert np.all(np.isfinite(rows)), mod.__name__


# ---------------------------------------------------------------------------
# fused vs chunked bit-stability + the one cycles_to_best definition
# ---------------------------------------------------------------------------


class TestPathStability:
    def _run(self, timeout, collect_curve=False, n_cycles=40):
        from pydcop_tpu.algorithms import dsa
        from pydcop_tpu.telemetry import metrics_registry

        pulse.reset()
        metrics_registry.reset()
        metrics_registry.enabled = True
        try:
            r = dsa.solve(
                compiled(chain()), {}, n_cycles=n_cycles, seed=3,
                timeout=timeout, collect_curve=collect_curve,
            )
        finally:
            metrics_registry.enabled = False
        c2b = metrics_registry.gauge("solve.cycles_to_best").value()
        return r, recorded_rows(), int(c2b)

    def test_health_rows_bit_identical_across_paths(self, pulse_on):
        # same seed => same trajectory (keys by absolute cycle index);
        # the health reductions must agree BITWISE between the fused
        # single-dispatch path and the chunked timeout path (chunks 16+)
        _, fused, c2b_fused = self._run(timeout=None)
        _, chunked, c2b_chunked = self._run(timeout=3600)
        assert fused.shape == chunked.shape == (40, HEALTH_WIDTH)
        np.testing.assert_array_equal(fused, chunked)
        assert c2b_fused == c2b_chunked

    def test_cycles_to_best_matches_curve_argmin(self, pulse_on):
        # satellite: the device-tracked best_cycle IS argmin(curve) + 1
        # whenever the curve improves on the initial assignment — on
        # every path (fused, chunked+curve)
        r1, _, c2b1 = self._run(timeout=None, collect_curve=True)
        assert r1.cost_curve is not None
        curve = np.asarray(r1.cost_curve)
        assert c2b1 == int(np.argmin(curve)) + 1
        r2, _, c2b2 = self._run(timeout=3600, collect_curve=True)
        np.testing.assert_allclose(r2.cost_curve, r1.cost_curve)
        assert c2b2 == c2b1

    def test_trajectory_unchanged_by_pulse(self):
        # the health hook consumes no PRNG keys: assignments and costs
        # are identical with pulse on and off
        from pydcop_tpu.algorithms import dsa

        c = compiled(chain())
        pulse.reset()
        pulse.enabled = False
        r_off = dsa.solve(c, {}, n_cycles=20, seed=5)
        pulse.enabled = True
        try:
            r_on = dsa.solve(c, {}, n_cycles=20, seed=5)
        finally:
            pulse.enabled = False
            pulse.reset()
        assert r_on.assignment == r_off.assignment
        assert r_on.cost == r_off.cost


# ---------------------------------------------------------------------------
# postmortem end-to-end: chaos-triggered dump + CLI render
# ---------------------------------------------------------------------------


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestPostmortemEndToEnd:
    def test_chaos_kill_dumps_postmortem(self, tmp_path):
        # a chaos run with pulse armed: the kill event drives
        # Agent.crash(), which must leave a parseable postmortem.json in
        # the cwd that the postmortem verb renders
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO
        r = subprocess.run(
            [
                sys.executable, "-m", "pydcop_tpu",
                "--output", str(tmp_path / "chaos.json"),
                "chaos", "-a", "dsa", "-n", "10", "--seed", "0",
                "-k", "1",
                "--fault-schedule",
                os.path.join(
                    REPO, "tests", "instances", "chaos_kill_repair.yaml"
                ),
                "--pulse-out", str(tmp_path / "pulse.jsonl"),
                os.path.join(
                    REPO, "tests", "instances", "graph_coloring.yaml"
                ),
            ],
            capture_output=True, text=True, timeout=300,
            cwd=str(tmp_path), env=env,
        )
        assert r.returncode == 0, r.stderr
        pm = tmp_path / "postmortem.json"
        assert pm.exists(), "chaos kill did not dump a postmortem"
        doc = load_postmortem(str(pm))
        assert doc["reason"].startswith("agent-crash:")
        assert doc["fields"] == list(HEALTH_FIELDS)
        # the --pulse-out stream carries begin + per-cycle rows + diagnosis
        lines = [
            json.loads(l)
            for l in (tmp_path / "pulse.jsonl").read_text().splitlines()
        ]
        assert lines[0]["event"] == "begin"
        assert lines[-1]["event"] == "diagnosis"
        # and the verb renders it
        r2 = subprocess.run(
            [
                sys.executable, "-m", "pydcop_tpu",
                "postmortem", str(pm),
            ],
            capture_output=True, text=True, timeout=120,
            cwd=str(tmp_path), env=env,
        )
        assert r2.returncode == 0, r2.stderr
        assert "postmortem: agent-crash:" in r2.stdout
        # the kill can fire before the device solve published anything
        # (compile wall >> fault time): the recorder then reports the
        # empty ring explicitly instead of inventing a diagnosis
        assert (
            "overall:" in r2.stdout
            or "no health rows recorded" in r2.stdout
        )


# ---------------------------------------------------------------------------
# live surface: /status pulse block + watch rendering
# ---------------------------------------------------------------------------


class TestStatusSurface:
    def test_status_block_lifecycle(self, pulse_on):
        # no block until a run publishes (the orchestrator omits the
        # "pulse" key from /status in that case)
        assert pulse.status_block() is None
        pulse.begin_run({"algo": "dsa", "n_vars": 4})
        assert pulse.status_block() is None
        # start_cycle is the count of cycles completed BEFORE the batch
        # (0 for the first chunk), so 12 rows land on cycles 1..12
        rows = [row(cost=5.0, best=5.0, churn=0.25) for _ in range(12)]
        pulse.publish(rows, start_cycle=0)
        blk = pulse.status_block()
        assert blk is not None
        assert blk["cycle"] == 12
        assert blk["churn"] == pytest.approx(0.25)
        assert blk["best_cost"] == pytest.approx(5.0)
        assert blk["diagnosis"] in (
            "converged", "stalled-plateau", "still-improving",
        ) or blk["diagnosis"].startswith("oscillating")
        assert len(blk["churn_series"]) == 12

    def test_watch_renders_pulse_block(self, pulse_on):
        from pydcop_tpu.commands.watch import _render_frame

        pulse.begin_run({"algo": "dsa", "n_vars": 4})
        pulse.publish(
            [row(cost=5.0, best=5.0, churn=0.5) for _ in range(8)],
            start_cycle=0,
        )
        status = {"status": "running", "pulse": pulse.status_block()}
        frame = _render_frame(status, {}, {})
        pulse_lines = [l for l in frame.splitlines() if "pulse:" in l]
        assert len(pulse_lines) == 1
        assert "churn=0.500" in pulse_lines[0]
        assert "cycle=8" in pulse_lines[0]
        # the churn sparkline rides on its own line
        assert any(
            l.startswith("churn") for l in frame.splitlines()
        )
        # no pulse key -> no pulse line (watch degrades cleanly)
        frame2 = _render_frame({"status": "running"}, {}, {})
        assert "pulse:" not in frame2
