"""graftlint (pydcop_tpu.analysis): fixture-driven rule tests.

Every rule gets one known-bad sample (true positive) and one near-miss
(true negative), written to a tmp dir and linted in isolation.  The
suite also self-checks the repo: the live finding set must match
``tools/graftlint_baseline.json`` exactly — a new finding fails here,
which is what wires the ratchet into the tier-1 flow.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from pydcop_tpu.analysis import (
    collect_findings,
    diff_against_baseline,
    iter_rules,
    load_baseline,
)
from pydcop_tpu.analysis.cli import main as lint_main

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO_ROOT, "tools", "graftlint_baseline.json")


def lint_source(tmp_path, source, name="sample.py", select=None):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return collect_findings([str(p)], select=select)


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------
# pass 1: lock discipline
# ---------------------------------------------------------------------


class TestLockDiscipline:
    def test_unguarded_write_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import threading

            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}

                def put(self, k, v):
                    with self._lock:
                        self._items[k] = v

                def clear_fast(self):
                    self._items = {}
            """,
        )
        assert "lock-unguarded-write" in rules_of(fs)
        (f,) = [f for f in fs if f.rule == "lock-unguarded-write"]
        assert "clear_fast" in f.message and f.line == 14

    def test_unguarded_write_negative_when_locked(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import threading

            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}

                def put(self, k, v):
                    with self._lock:
                        self._items[k] = v

                def clear(self):
                    with self._lock:
                        self._items = {}
            """,
        )
        assert "lock-unguarded-write" not in rules_of(fs)

    def test_init_writes_are_not_flagged(self, tmp_path):
        # construction happens before any concurrency: a near-miss the
        # rule must not fire on
        fs = lint_source(
            tmp_path,
            """
            import threading

            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}
                    self._items["warm"] = 1

                def put(self, k, v):
                    with self._lock:
                        self._items[k] = v
            """,
        )
        assert rules_of(fs) == set()

    def test_unguarded_read_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import threading

            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}

                def put(self, k, v):
                    with self._lock:
                        self._items[k] = v

                def peek(self, k):
                    return self._items.get(k)
            """,
        )
        assert "lock-unguarded-read" in rules_of(fs)

    def test_unguarded_read_negative_for_unshared_attr(self, tmp_path):
        # `name` is never written under the lock, so reading it without
        # the lock is fine
        fs = lint_source(
            tmp_path,
            """
            import threading

            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}
                    self.name = "cache"

                def put(self, k, v):
                    with self._lock:
                        self._items[k] = v

                def label(self):
                    return self.name
            """,
        )
        assert "lock-unguarded-read" not in rules_of(fs)

    # the exact pre-fix discovery.py shape (ADVICE round 5, fixed this
    # PR): `emptied` decided under the lock, the directory unsubscribe
    # posted after release
    PRE_FIX_DISCOVERY = """
        import threading

        class Discovery:
            def __init__(self):
                self._lock = threading.RLock()
                self._agent_cbs = []

            def subscribe(self, cb):
                with self._lock:
                    self._agent_cbs.append((cb, False))
                self.post_msg("_directory", "subscribe")

            def unsubscribe_all_agents(self, cb=None):
                with self._lock:
                    self._agent_cbs = (
                        [] if cb is None
                        else [r for r in self._agent_cbs if r[0] is not cb]
                    )
                    emptied = not self._agent_cbs
                if emptied:
                    self.post_msg("_directory", "unsubscribe")

            def post_msg(self, target, msg):
                pass
        """

    def test_post_outside_catches_prefix_discovery_shape(self, tmp_path):
        fs = lint_source(tmp_path, self.PRE_FIX_DISCOVERY)
        hits = [f for f in fs if f.rule == "lock-post-outside"]
        assert len(hits) == 1
        assert "unsubscribe_all_agents" in hits[0].message
        assert "'emptied'" in hits[0].message

    def test_post_inside_lock_is_clean(self, tmp_path):
        # the fixed shape: decision and post serialized under the lock
        fs = lint_source(
            tmp_path,
            """
            import threading

            class Discovery:
                def __init__(self):
                    self._lock = threading.RLock()
                    self._agent_cbs = []

                def unsubscribe_all_agents(self, cb=None):
                    with self._lock:
                        existed = bool(self._agent_cbs)
                        self._agent_cbs = []
                        if existed and not self._agent_cbs:
                            self.post_msg("_directory", "unsubscribe")

                def post_msg(self, target, msg):
                    pass
            """,
        )
        assert "lock-post-outside" not in rules_of(fs)

    def test_rebind_outside_lock_clears_taint(self, tmp_path):
        # the sent name was recomputed after the lock released: no
        # longer lock-derived, must not be flagged
        fs = lint_source(
            tmp_path,
            """
            import threading

            class Router:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._routes = {}

                def lookup(self, k):
                    with self._lock:
                        self._routes[k] = k
                        route = self._routes.get(k)
                    route = "default"
                    self.post_msg("peer", route)

                def post_msg(self, target, msg):
                    pass
            """,
        )
        assert "lock-post-outside" not in rules_of(fs)

    def test_post_of_parameter_outside_lock_is_clean(self, tmp_path):
        # near miss: the post argument is a plain parameter, not state
        # computed under the lock
        fs = lint_source(
            tmp_path,
            """
            import threading

            class Registry:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._agents = {}

                def register(self, agent, address):
                    with self._lock:
                        self._agents[agent] = address
                    self.post_msg("_directory", (agent, address))

                def post_msg(self, target, msg):
                    pass
            """,
        )
        assert "lock-post-outside" not in rules_of(fs)

    def test_lock_order_cycle_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import threading

            class TwoLocks:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def forward(self):
                    with self._a:
                        with self._b:
                            pass

                def backward(self):
                    with self._b:
                        with self._a:
                            pass
            """,
        )
        hits = [f for f in fs if f.rule == "lock-order-cycle"]
        assert len(hits) == 1
        assert "_a" in hits[0].message and "_b" in hits[0].message

    def test_lock_order_cycle_via_method_call(self, tmp_path):
        # the cycle closes through a call made while holding a lock
        fs = lint_source(
            tmp_path,
            """
            import threading

            class TwoLocks:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def forward(self):
                    with self._a:
                        self._grab_b()

                def _grab_b(self):
                    with self._b:
                        pass

                def backward(self):
                    with self._b:
                        with self._a:
                            pass
            """,
        )
        assert "lock-order-cycle" in rules_of(fs)

    def test_lock_order_cycle_multi_item_with(self, tmp_path):
        # `with self._a, self._b:` orders exactly like nested blocks
        fs = lint_source(
            tmp_path,
            """
            import threading

            class TwoLocks:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def forward(self):
                    with self._a, self._b:
                        pass

                def backward(self):
                    with self._b:
                        with self._a:
                            pass
            """,
        )
        assert "lock-order-cycle" in rules_of(fs)

    def test_consistent_order_is_clean(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import threading

            class TwoLocks:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def forward(self):
                    with self._a:
                        with self._b:
                            pass

                def also_forward(self):
                    with self._a:
                        with self._b:
                            pass
            """,
        )
        assert "lock-order-cycle" not in rules_of(fs)


# ---------------------------------------------------------------------
# pass 2: JAX tracing hazards
# ---------------------------------------------------------------------


class TestTracingHazards:
    def test_python_branch_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def step(x):
                if x > 0:
                    return x + 1
                return x - 1
            """,
        )
        hits = [f for f in fs if f.rule == "trace-python-branch"]
        assert len(hits) == 1 and hits[0].line == 7

    def test_python_branch_static_argnames_negative(self, tmp_path):
        # branches on a static arg, an is-None test, and a shape
        # attribute are all legal at trace time
        fs = lint_source(
            tmp_path,
            """
            from functools import partial

            import jax
            import jax.numpy as jnp

            @partial(jax.jit, static_argnames=("flag",))
            def step(x, flag, mask=None):
                if flag:
                    x = x + 1
                if mask is not None:
                    x = x * mask
                if x.shape[0] > 4:
                    x = x[:4]
                return x
            """,
        )
        assert "trace-python-branch" not in rules_of(fs)

    def test_branch_inside_scan_body_closure(self, tmp_path):
        # traced via being passed to lax.scan, not via a decorator
        fs = lint_source(
            tmp_path,
            """
            import jax
            import jax.numpy as jnp

            def outer(xs):
                def body(carry, x):
                    if x > 0:
                        carry = carry + x
                    return carry, x

                return jax.lax.scan(body, jnp.zeros(()), xs)
            """,
        )
        assert "trace-python-branch" in rules_of(fs)

    def test_host_sync_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def bad(x):
                total = float(x.sum())
                peak = x.max().item()
                return total + peak
            """,
        )
        hits = [f for f in fs if f.rule == "trace-host-sync"]
        assert len(hits) == 2

    def test_host_sync_on_static_shape_negative(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def fine(x):
                n = int(x.shape[0])
                scale = float(1.5)
                return x * scale + n
            """,
        )
        assert "trace-host-sync" not in rules_of(fs)

    def test_impure_call_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import time

            import jax
            import jax.numpy as jnp

            @jax.jit
            def bad(x):
                stamp = time.time()
                return x * stamp
            """,
        )
        assert "trace-impure-call" in rules_of(fs)

    def test_impure_call_in_host_code_negative(self, tmp_path):
        # same call in an undecorated host function: fine
        fs = lint_source(
            tmp_path,
            """
            import time

            import jax.numpy as jnp

            def benchmark(fn, x):
                t0 = time.time()
                y = fn(x)
                return y, time.time() - t0
            """,
        )
        assert "trace-impure-call" not in rules_of(fs)

    def test_shape_loop_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def bad(x):
                acc = jnp.zeros(())
                for i in range(x.shape[0]):
                    acc = acc + x[i]
                return acc
            """,
        )
        assert "trace-shape-loop" in rules_of(fs)

    def test_enumerate_over_traced_array_is_flagged(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def bad(x):
                acc = jnp.zeros(())
                for i, row in enumerate(x):
                    acc = acc + row.sum()
                return acc
            """,
        )
        assert "trace-shape-loop" in rules_of(fs)

    def test_zip_of_untraced_containers_negative(self, tmp_path):
        # the idiomatic static unroll over tuples of operands
        fs = lint_source(
            tmp_path,
            """
            from functools import partial

            import jax
            import jax.numpy as jnp

            @partial(jax.jit, static_argnames=("names",))
            def fine(x, names):
                acc = jnp.zeros(())
                for name, w in zip(names, (1.0, 2.0)):
                    acc = acc + w
                return x + acc
            """,
        )
        assert "trace-shape-loop" not in rules_of(fs)

    def test_constant_range_loop_negative(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def fine(x):
                acc = jnp.zeros(())
                for i in range(4):
                    acc = acc + x[i]
                return acc
            """,
        )
        assert "trace-shape-loop" not in rules_of(fs)


# ---------------------------------------------------------------------
# pass 3: message-protocol consistency
# ---------------------------------------------------------------------


class TestProtocolConsistency:
    def test_unhandled_message_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            from pydcop_tpu.infrastructure.computations import (
                MessagePassingComputation, message_type, register,
            )

            PingMessage = message_type("ping", ["value"])
            PongMessage = message_type("pong", ["value"])

            class Player(MessagePassingComputation):
                @register("pong")
                def _on_pong(self, sender, msg, t):
                    pass
            """,
        )
        hits = [f for f in fs if f.rule == "proto-unhandled-message"]
        assert len(hits) == 1 and "'ping'" in hits[0].message

    def test_handled_everywhere_negative(self, tmp_path):
        # declared, handled AND sent: a complete conversation (pass 5's
        # proto-unsent-message fires when nothing ever constructs it)
        fs = lint_source(
            tmp_path,
            """
            from pydcop_tpu.infrastructure.computations import (
                MessagePassingComputation, message_type, register,
            )

            PingMessage = message_type("ping", ["value"])

            class Player(MessagePassingComputation):
                @register("ping")
                def _on_ping(self, sender, msg, t):
                    self.post_msg(sender, PingMessage(value=msg.value))
            """,
        )
        assert rules_of(fs) == set()

    def test_cross_file_handling_is_seen(self, tmp_path):
        # declaration + send in one module, handler in another: the
        # pass is whole-file-set, so this is clean
        (tmp_path / "decl.py").write_text(
            textwrap.dedent(
                """
                from pydcop_tpu.infrastructure.computations import (
                    message_type,
                )

                PingMessage = message_type("ping", ["value"])

                def send(comp):
                    comp.post_msg("player", PingMessage(value=1))
                """
            )
        )
        (tmp_path / "hand.py").write_text(
            textwrap.dedent(
                """
                from pydcop_tpu.infrastructure.computations import (
                    MessagePassingComputation, register,
                )

                class Player(MessagePassingComputation):
                    @register("ping")
                    def _on_ping(self, sender, msg, t):
                        pass
                """
            )
        )
        fs = collect_findings([str(tmp_path)])
        assert rules_of(fs) == set()

    def test_dead_handler_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            from pydcop_tpu.infrastructure.computations import (
                MessagePassingComputation, register,
            )

            class Player(MessagePassingComputation):
                @register("renamed_long_ago")
                def _on_old(self, sender, msg, t):
                    pass
            """,
        )
        assert "proto-dead-handler" in rules_of(fs)

    def test_raw_message_construction_is_declaration(self, tmp_path):
        # Message("probe", ...) puts the type on the wire, so its
        # handler is NOT dead — the orchestration readback idiom
        fs = lint_source(
            tmp_path,
            """
            from pydcop_tpu.infrastructure.computations import (
                Message, MessagePassingComputation, register,
            )

            def poke(comp):
                comp.deliver_msg("x", Message("probe", 1), 0.0)

            class Player(MessagePassingComputation):
                @register("probe")
                def _on_probe(self, sender, msg, t):
                    pass
            """,
        )
        assert "proto-dead-handler" not in rules_of(fs)

    def test_duplicate_handler_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            from pydcop_tpu.infrastructure.computations import (
                MessagePassingComputation, message_type, register,
            )

            PingMessage = message_type("ping", ["value"])

            class Player(MessagePassingComputation):
                @register("ping")
                def _on_ping(self, sender, msg, t):
                    pass

                @register("ping")
                def _on_ping_again(self, sender, msg, t):
                    pass
            """,
        )
        hits = [f for f in fs if f.rule == "proto-duplicate-handler"]
        assert len(hits) == 1

    def test_same_type_in_two_classes_negative(self, tmp_path):
        # two different computations handling the same type is the
        # normal fan-out (directory + client), not a duplicate
        fs = lint_source(
            tmp_path,
            """
            from pydcop_tpu.infrastructure.computations import (
                MessagePassingComputation, message_type, register,
            )

            PingMessage = message_type("ping", ["value"])

            class Server(MessagePassingComputation):
                @register("ping")
                def _on_ping(self, sender, msg, t):
                    pass

            class Client(MessagePassingComputation):
                @register("ping")
                def _on_ping(self, sender, msg, t):
                    pass
            """,
        )
        assert "proto-duplicate-handler" not in rules_of(fs)

    def test_handler_signature_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            from pydcop_tpu.infrastructure.computations import (
                MessagePassingComputation, message_type, register,
            )

            PingMessage = message_type("ping", ["value"])

            class Player(MessagePassingComputation):
                @register("ping")
                def _on_ping(self, msg):
                    pass
            """,
        )
        assert "proto-handler-signature" in rules_of(fs)

    def test_handler_required_kwonly_is_flagged(self, tmp_path):
        # positional dispatch can never satisfy a required keyword-only
        # parameter, even with *args present
        fs = lint_source(
            tmp_path,
            """
            from pydcop_tpu.infrastructure.computations import (
                MessagePassingComputation, message_type, register,
            )

            PingMessage = message_type("ping", ["value"])

            class Player(MessagePassingComputation):
                @register("ping")
                def _on_ping(self, *args, strict):
                    pass
            """,
        )
        assert "proto-handler-signature" in rules_of(fs)

    def test_handler_signature_with_defaults_negative(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            from pydcop_tpu.infrastructure.computations import (
                MessagePassingComputation, message_type, register,
            )

            PingMessage = message_type("ping", ["value"])

            class Player(MessagePassingComputation):
                @register("ping")
                def _on_ping(self, sender, msg, t, extra=None):
                    pass
            """,
        )
        assert "proto-handler-signature" not in rules_of(fs)


# ---------------------------------------------------------------------
# suppressions, fingerprints, baseline
# ---------------------------------------------------------------------


class TestSuppressionAndBaseline:
    def test_inline_suppression(self, tmp_path):
        src = """
            import threading

            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}

                def put(self, k, v):
                    with self._lock:
                        self._items[k] = v

                def peek(self, k):
                    return self._items.get(k)  # graftlint: disable=lock-unguarded-read
            """
        fs = lint_source(tmp_path, src)
        assert "lock-unguarded-read" not in rules_of(fs)

    def test_suppression_of_other_rule_does_not_hide(self, tmp_path):
        src = """
            import threading

            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}

                def put(self, k, v):
                    with self._lock:
                        self._items[k] = v

                def peek(self, k):
                    return self._items.get(k)  # graftlint: disable=trace-host-sync
            """
        fs = lint_source(tmp_path, src)
        assert "lock-unguarded-read" in rules_of(fs)

    def test_fingerprints_stable_across_line_shift(self, tmp_path):
        src = """
            import threading

            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}

                def put(self, k, v):
                    with self._lock:
                        self._items[k] = v

                def peek(self, k):
                    return self._items.get(k)
            """
        f1 = lint_source(tmp_path, src, name="a.py")
        # unrelated edit above the finding shifts every line number
        shifted = "# a new leading comment\n# another one\n" + textwrap.dedent(src)
        p = tmp_path / "a.py"
        p.write_text(shifted)
        f2 = collect_findings([str(p)])
        assert {f.fingerprint for f in f1} == {f.fingerprint for f in f2}

    def test_repo_matches_checked_in_baseline(self, monkeypatch):
        """The ratchet: the repo at HEAD must produce exactly the
        baselined finding set — any new finding fails tier-1 here."""
        monkeypatch.chdir(REPO_ROOT)
        findings = collect_findings(["pydcop_tpu"])
        baseline = load_baseline(BASELINE)
        diff = diff_against_baseline(findings, baseline)
        assert not diff.new, "new graftlint findings:\n" + "\n".join(
            f.format() for f in diff.new
        )
        assert not diff.fixed, (
            "stale baseline entries (re-ratchet with --write-baseline):\n"
            + json.dumps(diff.fixed, indent=2)
        )
        assert len(findings) == len(baseline)


# ---------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------


class TestCli:
    def test_clean_repo_exits_zero(self, monkeypatch, capsys):
        monkeypatch.chdir(REPO_ROOT)
        rc = lint_main(
            ["--baseline", BASELINE, "--quiet", "pydcop_tpu"]
        )
        assert rc == 0
        assert "0 new" in capsys.readouterr().out

    def test_introduced_bug_exits_nonzero(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text(
            textwrap.dedent(TestLockDiscipline.PRE_FIX_DISCOVERY)
        )
        rc = lint_main(["--baseline", BASELINE, str(bad)])
        assert rc == 1
        assert "lock-post-outside" in capsys.readouterr().out

    def test_write_baseline_roundtrip(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text(
            textwrap.dedent(TestLockDiscipline.PRE_FIX_DISCOVERY)
        )
        bl = tmp_path / "bl.json"
        assert lint_main(
            ["--baseline", str(bl), "--write-baseline", str(bad)]
        ) == 0
        assert lint_main(["--baseline", str(bl), str(bad)]) == 0
        capsys.readouterr()

    def test_select_restricts_rules(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            textwrap.dedent(TestLockDiscipline.PRE_FIX_DISCOVERY)
        )
        fs = collect_findings([str(bad)], select=["lock-order-cycle"])
        assert fs == []

    def test_unknown_rule_is_an_error(self, tmp_path):
        with pytest.raises(ValueError, match="unknown rule"):
            collect_findings([str(tmp_path)], select=["no-such-rule"])

    def test_nonexistent_path_is_an_error(self, tmp_path, capsys):
        # a typo'd path must not be vacuously green: that would
        # silently disable the whole ratchet in CI
        with pytest.raises(ValueError, match="no such file"):
            collect_findings([str(tmp_path / "nope")])
        rc = lint_main(
            ["--baseline", BASELINE, str(tmp_path / "nope")]
        )
        assert rc == 2
        capsys.readouterr()

    def test_write_baseline_refuses_filters(self, tmp_path, capsys):
        # a filtered write would erase the other rules' accepted
        # findings from the baseline
        bl = tmp_path / "bl.json"
        rc = lint_main(
            [
                "--baseline", str(bl), "--write-baseline",
                "--passes", "locks", str(tmp_path),
            ]
        )
        assert rc == 2
        assert not bl.exists()
        capsys.readouterr()

    def test_list_rules_has_three_per_pass(self, capsys):
        rules = iter_rules()
        by_prefix = {}
        for r in rules:
            by_prefix.setdefault(r.id.split("-")[0], []).append(r)
        # the "proto" prefix is shared by pass 3 (registrations) and
        # pass 5 (graftproto conversations): 4 + 7 rules
        assert set(by_prefix) == {
            "lock", "trace", "proto", "flow", "perf"
        }
        for prefix, rs in by_prefix.items():
            assert len(rs) >= 3, f"pass {prefix} has < 3 rules"
        assert len(by_prefix["proto"]) == 11
        assert len(by_prefix["perf"]) == 6
        from pydcop_tpu.analysis.core import PASS_NAMES

        assert PASS_NAMES == (
            "locks", "tracing", "protocol", "arrays", "proto", "perf"
        )

    def test_module_entry_point(self, monkeypatch):
        # the acceptance-criteria invocation, end to end
        proc = subprocess.run(
            [
                sys.executable, "-m", "pydcop_tpu.analysis",
                "--baseline", "tools/graftlint_baseline.json",
                "--quiet", "pydcop_tpu/",
            ],
            cwd=REPO_ROOT,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_cli_lint_subcommand(self, monkeypatch, capsys):
        from pydcop_tpu.dcop_cli import main as cli_main

        monkeypatch.chdir(REPO_ROOT)
        rc = cli_main(
            ["lint", "--baseline", BASELINE, "--quiet", "pydcop_tpu"]
        )
        assert rc == 0
