"""graftproto (graftlint pass 5): conversation-level protocol
verification — fixture-driven rule tests, the pinned PR-10
stale-epoch-ack shape, the incremental lint cache and the SARIF export.

Every rule gets at least one known-bad sample (true positive) and one
near-miss (true negative); the repo self-check asserts the live tree is
clean under the pass, which — with ``tools/graftlint_baseline.json``
required to stay EMPTY — is what wires the fifth pass into the tier-1
ratchet."""

import json
import os
import textwrap

import pytest

from pydcop_tpu.analysis import collect_findings
from pydcop_tpu.analysis.cli import main as lint_main
from pydcop_tpu.analysis.core import PASS_NAMES, iter_rules, pass_versions

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO_ROOT, "tools", "graftlint_baseline.json")

PROTO_RULES = {
    "proto-reply-gap",
    "proto-stale-guard",
    "proto-handler-blocking",
    "proto-send-under-lock",
    "proto-field-mismatch",
    "proto-unsent-message",
    "proto-wait-unbounded",
}

PRELUDE = """
    import threading

    from pydcop_tpu.infrastructure.computations import (
        Message, MessagePassingComputation, message_type, register,
    )
"""


def lint_source(tmp_path, source, name="sample.py", passes=("proto",)):
    p = tmp_path / name
    p.write_text(textwrap.dedent(PRELUDE) + textwrap.dedent(source))
    return collect_findings([str(p)], passes=list(passes))


def rules_of(findings):
    return {f.rule for f in findings}


def only(findings, rule):
    hits = [f for f in findings if f.rule == rule]
    assert hits, f"no {rule} finding in {[f.format() for f in findings]}"
    return hits


# ---------------------------------------------------------------------
# proto-reply-gap
# ---------------------------------------------------------------------


class TestReplyGap:
    def test_silent_return_is_flagged(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            AcceptMsg = message_type("accept", ["comp"])
            RefuseMsg = message_type("refuse", ["comp"])
            VisitMsg = message_type("visit", ["comp"])

            def send(c):
                c.post_msg("h", VisitMsg(comp="x"))
                c.post_msg("h", RefuseMsg(comp="x"))

            class Host(MessagePassingComputation):
                full = False

                @register("visit")  # graftproto: replies=accept,refuse
                def _on_visit(self, sender, msg, t):
                    if self.full:
                        return
                    self.post_msg(sender, AcceptMsg(comp=msg.comp))
            """,
        )
        (hit,) = only(fs, "proto-reply-gap")
        assert "_on_visit" in hit.message
        # the finding anchors on the silent `return`
        lines = (tmp_path / "sample.py").read_text().splitlines()
        assert lines[hit.line - 1].strip() == "return"

    def test_fall_through_without_reply_is_flagged(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            AckMsg = message_type("ack", ["comp"])
            ReqMsg = message_type("req", ["comp"])

            def send(c):
                c.post_msg("h", ReqMsg(comp="x"))

            class Host(MessagePassingComputation):
                ok = False

                @register("req")  # graftproto: replies=ack
                def _on_req(self, sender, msg, t):
                    if self.ok:
                        self.post_msg(sender, AckMsg(comp=msg.comp))
            """,
        )
        (hit,) = only(fs, "proto-reply-gap")
        assert "fall through" in hit.message

    def test_reply_on_every_path_is_clean(self, tmp_path):
        # the negotiation shape: accept inline, refuse via a helper
        fs = lint_source(
            tmp_path,
            """
            AcceptMsg = message_type("accept", ["comp"])
            RefuseMsg = message_type("refuse", ["comp"])
            VisitMsg = message_type("visit", ["comp"])

            def send(c):
                c.post_msg("h", VisitMsg(comp="x"))

            class Host(MessagePassingComputation):
                full = False

                @register("visit")  # graftproto: replies=accept,refuse
                def _on_visit(self, sender, msg, t):
                    if self.full:
                        self._refuse(sender, msg.comp)
                        return
                    self.post_msg(sender, AcceptMsg(comp=msg.comp))

                def _refuse(self, owner, comp):
                    self.post_msg(owner, RefuseMsg(comp=comp))
            """,
        )
        assert "proto-reply-gap" not in rules_of(fs)

    def test_raise_exit_is_not_a_gap(self, tmp_path):
        # an exception is a loud failure, not a silent hang
        fs = lint_source(
            tmp_path,
            """
            AckMsg = message_type("ack", ["comp"])
            ReqMsg = message_type("req", ["comp"])

            def send(c):
                c.post_msg("h", ReqMsg(comp="x"))

            class Host(MessagePassingComputation):
                @register("req")  # graftproto: replies=ack
                def _on_req(self, sender, msg, t):
                    if msg.comp is None:
                        raise ValueError("bad request")
                    self.post_msg(sender, AckMsg(comp=msg.comp))
            """,
        )
        assert "proto-reply-gap" not in rules_of(fs)

    def test_unannotated_handler_is_not_checked(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            ReqMsg = message_type("req", ["comp"])

            def send(c):
                c.post_msg("h", ReqMsg(comp="x"))

            class Host(MessagePassingComputation):
                @register("req")
                def _on_req(self, sender, msg, t):
                    return
            """,
        )
        assert "proto-reply-gap" not in rules_of(fs)

    def test_graftproto_suppression_prefix(self, tmp_path):
        # the async-ack idiom: the reply is posted later by another
        # conversation turn — the justified suppression documents it
        fs = lint_source(
            tmp_path,
            """
            AckMsg = message_type("ack", ["comp"])
            ReqMsg = message_type("req", ["comp"])

            def send(c):
                c.post_msg("h", ReqMsg(comp="x"))
                c.post_msg("h", AckMsg(comp="x"))

            class Host(MessagePassingComputation):
                @register("req")  # graftproto: replies=ack
                def _on_req(self, sender, msg, t):
                    self.start_round(msg.comp)
                    return  # graftproto: disable=proto-reply-gap (acked asynchronously)

                def start_round(self, comp):
                    pass
            """,
        )
        assert "proto-reply-gap" not in rules_of(fs)


# ---------------------------------------------------------------------
# proto-stale-guard (the pinned PR-10 bug shape)
# ---------------------------------------------------------------------


class TestStaleGuard:
    # the exact graftucs review bug: a replication ack carries a round
    # epoch, but the pre-fix handler released the barrier without ever
    # comparing it — a stale/duplicated round-1 ack could release
    # round 2's barrier while that agent's negotiation still ran
    PR10_PRE_FIX = """
        ReplicatedMsg = message_type(
            "replicated", ["agent", "replica_hosts", "round"]
        )

        def ack(c, rnd):
            c.post_msg(
                "_mgt", ReplicatedMsg(agent="a1", replica_hosts={}, round=rnd)
            )

        class AgentsMgt(MessagePassingComputation):
            def __init__(self):
                super().__init__("_mgt")
                self.replica_hosts = {}
                self.replicated_agents = set()
                self.expected = set()
                self.all_replicated = threading.Event()

            @register("replicated")
            def _on_replicated(self, sender, msg, t):
                for comp, hosts in (msg.replica_hosts or {}).items():
                    self.replica_hosts[comp] = hosts
                self.replicated_agents.add(msg.agent)
                if self.replicated_agents >= self.expected:
                    self.all_replicated.set()
        """

    def test_pr10_stale_epoch_ack_shape_is_flagged(self, tmp_path):
        fs = lint_source(tmp_path, self.PR10_PRE_FIX)
        (hit,) = only(fs, "proto-stale-guard")
        assert "_on_replicated" in hit.message
        assert "'round'" in hit.message

    def test_epoch_comparison_guard_is_clean(self, tmp_path):
        # the shipped fix: the ack's round is compared to the live one
        fs = lint_source(
            tmp_path,
            """
            ReplicatedMsg = message_type(
                "replicated", ["agent", "round"]
            )

            def ack(c, rnd):
                c.post_msg("_mgt", ReplicatedMsg(agent="a1", round=rnd))

            class AgentsMgt(MessagePassingComputation):
                def __init__(self):
                    super().__init__("_mgt")
                    self.replication_round = 0
                    self.replicated_agents = set()
                    self.all_replicated = threading.Event()

                @register("replicated")
                def _on_replicated(self, sender, msg, t):
                    ack_round = getattr(msg, "round", None)
                    if ack_round is not None and (
                        ack_round != self.replication_round
                    ):
                        return
                    self.replicated_agents.add(msg.agent)
                    self.all_replicated.set()
            """,
        )
        assert "proto-stale-guard" not in rules_of(fs)

    def test_delegating_the_message_is_clean(self, tmp_path):
        # the sync-mixin shape: the whole message is handed to a method
        # that does the cycle_id bookkeeping
        fs = lint_source(
            tmp_path,
            """
            SyncMsg = message_type("syncpad", ["cycle_id"])

            def pad(c):
                c.post_msg("n", SyncMsg(cycle_id=0))

            class Comp(MessagePassingComputation):
                def __init__(self):
                    super().__init__("c")
                    self.buffered = []

                @register("syncpad")
                def _on_pad(self, sender, msg, t):
                    self.buffered.append(sender)
                    self.on_sync_message(sender, msg, t)

                def on_sync_message(self, sender, msg, t):
                    pass
            """,
        )
        assert "proto-stale-guard" not in rules_of(fs)

    def test_storing_epoch_without_check_is_flagged(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            TickMsg = message_type("tick", ["epoch"])

            def send(c):
                c.post_msg("h", TickMsg(epoch=1))

            class Host(MessagePassingComputation):
                last_epoch = 0

                @register("tick")
                def _on_tick(self, sender, msg, t):
                    self.last_epoch = msg.epoch
            """,
        )
        assert "proto-stale-guard" in rules_of(fs)

    def test_no_epoch_field_no_check(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            PingMsg = message_type("ping", ["value"])

            def send(c):
                c.post_msg("h", PingMsg(value=1))

            class Host(MessagePassingComputation):
                def __init__(self):
                    super().__init__("h")
                    self.seen = set()

                @register("ping")
                def _on_ping(self, sender, msg, t):
                    self.seen.add(msg.value)
            """,
        )
        assert "proto-stale-guard" not in rules_of(fs)


# ---------------------------------------------------------------------
# proto-handler-blocking
# ---------------------------------------------------------------------


class TestHandlerBlocking:
    def test_bare_wait_in_handler_is_flagged(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            GoMsg = message_type("go", ["x"])

            def send(c):
                c.post_msg("h", GoMsg(x=1))

            class Host(MessagePassingComputation):
                def __init__(self):
                    super().__init__("h")
                    self.ready = threading.Event()

                @register("go")
                def _on_go(self, sender, msg, t):
                    self.ready.wait()
            """,
        )
        (hit,) = only(fs, "proto-handler-blocking")
        assert ".wait()" in hit.message

    def test_bounded_wait_is_clean(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            GoMsg = message_type("go", ["x"])

            def send(c):
                c.post_msg("h", GoMsg(x=1))

            class Host(MessagePassingComputation):
                def __init__(self):
                    super().__init__("h")
                    self.ready = threading.Event()

                @register("go")
                def _on_go(self, sender, msg, t):
                    if not self.ready.wait(2.0):
                        return
            """,
        )
        assert "proto-handler-blocking" not in rules_of(fs)

    def test_blocking_helper_is_followed(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            GoMsg = message_type("go", ["x"])

            def send(c):
                c.post_msg("h", GoMsg(x=1))

            class Host(MessagePassingComputation):
                def __init__(self):
                    super().__init__("h")
                    self.ready = threading.Event()

                @register("go")
                def _on_go(self, sender, msg, t):
                    self._sync()

                def _sync(self):
                    self.ready.wait()
            """,
        )
        hits = only(fs, "proto-handler-blocking")
        assert any("_sync" in h.message for h in hits)

    def test_http_without_timeout_in_handler_is_flagged(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import urllib.request

            GoMsg = message_type("go", ["x"])

            def send(c):
                c.post_msg("h", GoMsg(x=1))

            class Host(MessagePassingComputation):
                @register("go")
                def _on_go(self, sender, msg, t):
                    urllib.request.urlopen("http://peer/status")
            """,
        )
        assert "proto-handler-blocking" in rules_of(fs)

    def test_http_with_timeout_is_clean(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import urllib.request

            GoMsg = message_type("go", ["x"])

            def send(c):
                c.post_msg("h", GoMsg(x=1))

            class Host(MessagePassingComputation):
                @register("go")
                def _on_go(self, sender, msg, t):
                    urllib.request.urlopen("http://peer/status", timeout=2.0)
            """,
        )
        assert "proto-handler-blocking" not in rules_of(fs)


# ---------------------------------------------------------------------
# proto-send-under-lock
# ---------------------------------------------------------------------


class TestSendUnderLock:
    def test_post_under_lock_in_handler_class_is_flagged(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            TickMsg = message_type("tick", ["n"])

            class Comp(MessagePassingComputation):
                def __init__(self):
                    super().__init__("c")
                    self._lock = threading.Lock()
                    self.n = 0

                @register("tick")
                def _on_tick(self, sender, msg, t):
                    with self._lock:
                        self.n += 1

                def kick(self):
                    with self._lock:
                        self.post_msg("peer", TickMsg(n=self.n))
            """,
        )
        (hit,) = only(fs, "proto-send-under-lock")
        assert "kick" in hit.message and "_lock" in hit.message

    def test_post_after_release_is_clean(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            TickMsg = message_type("tick", ["n"])

            class Comp(MessagePassingComputation):
                def __init__(self):
                    super().__init__("c")
                    self._lock = threading.Lock()
                    self.n = 0

                @register("tick")
                def _on_tick(self, sender, msg, t):
                    with self._lock:
                        self.n += 1

                def kick(self):
                    with self._lock:
                        n = self.n
                    self.post_msg("peer", TickMsg(n=n))
            """,
        )
        assert "proto-send-under-lock" not in rules_of(fs)

    def test_handler_free_class_is_not_checked(self, tmp_path):
        # the sanctioned Discovery idiom: posts serialized under the
        # lock in a class that registers NO handlers (so in-process
        # delivery can never re-enter it)
        fs = lint_source(
            tmp_path,
            """
            SubMsg = message_type("subpost", ["kind"])

            class Cache:
                def __init__(self):
                    self._lock = threading.RLock()
                    self._cbs = []
                    self.post_msg = print

                def subscribe(self, cb):
                    with self._lock:
                        self._cbs.append(cb)
                        self.post_msg("_directory", SubMsg(kind="agent"))
            """,
        )
        assert "proto-send-under-lock" not in rules_of(fs)


# ---------------------------------------------------------------------
# proto-field-mismatch
# ---------------------------------------------------------------------


class TestFieldMismatch:
    def test_unknown_and_missing_fields_flagged(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            AckMsg = message_type("ack", ["agent", "round"])

            class Host(MessagePassingComputation):
                @register("ack")
                def _on_ack(self, sender, msg, t):
                    pass

            def bad_epoch(c):
                c.post_msg("h", AckMsg(agent="a1", epoch=3))

            def bad_missing(c):
                c.post_msg("h", AckMsg(agent="a1"))
            """,
        )
        hits = only(fs, "proto-field-mismatch")
        msgs = " | ".join(h.message for h in hits)
        assert "'epoch'" in msgs and "missing field" in msgs

    def test_too_many_positionals_flagged(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            AckMsg = message_type("ack", ["agent"])

            class Host(MessagePassingComputation):
                @register("ack")
                def _on_ack(self, sender, msg, t):
                    pass

            def bad(c):
                c.post_msg("h", AckMsg("a1", 3))
            """,
        )
        assert "proto-field-mismatch" in rules_of(fs)

    def test_correct_constructions_clean(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            AckMsg = message_type("ack", ["agent", "round"])

            class Host(MessagePassingComputation):
                @register("ack")
                def _on_ack(self, sender, msg, t):
                    pass

            def good(c, extras):
                c.post_msg("h", AckMsg(agent="a1", round=3))
                c.post_msg("h", AckMsg("a1", round=3))
                c.post_msg("h", AckMsg(**extras))
            """,
        )
        assert "proto-field-mismatch" not in rules_of(fs)


# ---------------------------------------------------------------------
# proto-unsent-message
# ---------------------------------------------------------------------


class TestUnsentMessage:
    def test_declared_and_handled_but_never_sent(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            DeadMsg = message_type("dead_conv", ["x"])

            class Host(MessagePassingComputation):
                @register("dead_conv")
                def _on_dead(self, sender, msg, t):
                    pass
            """,
        )
        (hit,) = only(fs, "proto-unsent-message")
        assert "'dead_conv'" in hit.message

    def test_constructed_type_is_clean(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            LiveMsg = message_type("live_conv", ["x"])

            def send(c):
                c.post_msg("h", LiveMsg(x=1))

            class Host(MessagePassingComputation):
                @register("live_conv")
                def _on_live(self, sender, msg, t):
                    pass
            """,
        )
        assert "proto-unsent-message" not in rules_of(fs)

    def test_raw_message_construction_counts(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            ProbeMsg = message_type("probe", ["x"])

            def poke(comp):
                comp.deliver_msg("x", Message("probe", 1), 0.0)

            class Host(MessagePassingComputation):
                @register("probe")
                def _on_probe(self, sender, msg, t):
                    pass
            """,
        )
        assert "proto-unsent-message" not in rules_of(fs)

    def test_unhandled_type_is_pass3_territory(self, tmp_path):
        # declared but unhandled: proto-unhandled-message (pass 3), not
        # a dead conversation — this rule needs BOTH halves present
        fs = lint_source(
            tmp_path,
            """
            OrphanMsg = message_type("orphan", ["x"])
            """,
        )
        assert "proto-unsent-message" not in rules_of(fs)

    def test_cross_file_construction_is_seen(self, tmp_path):
        (tmp_path / "decl.py").write_text(
            textwrap.dedent(PRELUDE)
            + textwrap.dedent(
                """
                PingMsg = message_type("xping", ["x"])

                class Host(MessagePassingComputation):
                    @register("xping")
                    def _on_ping(self, sender, msg, t):
                        pass
                """
            )
        )
        (tmp_path / "send.py").write_text(
            textwrap.dedent(
                """
                from decl import PingMsg

                def go(c):
                    c.post_msg("h", PingMsg(x=1))
                """
            )
        )
        fs = collect_findings([str(tmp_path)], passes=["proto"])
        assert "proto-unsent-message" not in rules_of(fs)


# ---------------------------------------------------------------------
# proto-wait-unbounded
# ---------------------------------------------------------------------


class TestWaitUnbounded:
    def test_unbounded_event_wait_is_flagged(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            class Barrier:
                def __init__(self):
                    self.all_ready = threading.Event()

                def sync(self):
                    self.all_ready.wait()
            """,
        )
        (hit,) = only(fs, "proto-wait-unbounded")
        assert "'all_ready'" in hit.message

    def test_bounded_wait_is_clean(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            class Barrier:
                def __init__(self):
                    self.all_ready = threading.Event()

                def sync(self, timeout):
                    return self.all_ready.wait(timeout)
            """,
        )
        assert "proto-wait-unbounded" not in rules_of(fs)

    def test_local_event_variable_is_tracked(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            def sync():
                evt = threading.Event()
                evt.wait()
            """,
        )
        assert "proto-wait-unbounded" in rules_of(fs)

    def test_cross_object_event_attr_is_tracked(self, tmp_path):
        # the orchestrator idiom: self.mgt.all_replicated.wait() — the
        # Event lives on another object, recognised via the attr census
        fs = lint_source(
            tmp_path,
            """
            class Mgt:
                def __init__(self):
                    self.all_replicated = threading.Event()

            class Orchestrator:
                def __init__(self):
                    self.mgt = Mgt()

                def start_replication(self):
                    self.mgt.all_replicated.wait()
            """,
        )
        assert "proto-wait-unbounded" in rules_of(fs)

    def test_handler_waits_are_blocking_rule_territory(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            GoMsg = message_type("go2", ["x"])

            def send(c):
                c.post_msg("h", GoMsg(x=1))

            class Host(MessagePassingComputation):
                def __init__(self):
                    super().__init__("h")
                    self.ready = threading.Event()

                @register("go2")
                def _on_go(self, sender, msg, t):
                    self.ready.wait()
            """,
        )
        assert "proto-handler-blocking" in rules_of(fs)
        assert "proto-wait-unbounded" not in rules_of(fs)

    def test_non_event_wait_is_not_guessed(self, tmp_path):
        # a subprocess-style .wait() on an attr never assigned an Event
        fs = lint_source(
            tmp_path,
            """
            class Runner:
                def __init__(self, proc):
                    self.proc = proc

                def finish(self):
                    self.proc.wait()
            """,
        )
        assert "proto-wait-unbounded" not in rules_of(fs)


# ---------------------------------------------------------------------
# the live tree: pass 5 clean, annotations present
# ---------------------------------------------------------------------


class TestRepoRatchet:
    def test_repo_proto_pass_has_zero_findings(self, monkeypatch):
        """The fifth pass on the live tree, against the EMPTY baseline:
        every conversation defect it can see is either fixed or carries
        a justified inline suppression."""
        monkeypatch.chdir(REPO_ROOT)
        findings = collect_findings(["pydcop_tpu"], passes=["proto"])
        assert findings == [], "\n".join(f.format() for f in findings)

    def test_baseline_is_empty(self):
        data = json.load(open(BASELINE))
        assert data["findings"] == [], (
            "the graftlint baseline must stay EMPTY: fix or suppress "
            "instead of baselining"
        )

    def test_proto_pass_registered_fifth(self):
        assert PASS_NAMES == (
            "locks", "tracing", "protocol", "arrays", "proto", "perf"
        )
        assert PASS_NAMES.index("proto") == 4  # pass 5, 0-indexed
        proto_rules = {
            r.id for r in iter_rules() if r.id in PROTO_RULES
        }
        assert proto_rules == PROTO_RULES
        assert pass_versions()["proto"] >= 1

    def test_reply_annotations_present_on_live_handlers(self):
        """The replies= contracts are load-bearing: without the marker
        the reply-gap rule checks nothing, so a refactor dropping the
        comment silently disables the check."""
        neg = open(
            os.path.join(
                REPO_ROOT, "pydcop_tpu", "resilience", "negotiation.py"
            )
        ).read()
        assert "# graftproto: replies=ucs_accept,ucs_refuse" in neg
        oa = open(
            os.path.join(
                REPO_ROOT, "pydcop_tpu", "infrastructure",
                "orchestratedagents.py",
            )
        ).read()
        for marker in (
            "replies=deployed",
            "replies=agent_stopped",
            "replies=metrics",
            "replies=replicated",
            "replies=repair_ready",
            "replies=repair_done",
        ):
            assert f"# graftproto: {marker}" in oa, marker

    def test_explain_covers_every_proto_rule(self, capsys):
        for rule in sorted(PROTO_RULES):
            assert lint_main(["--explain", rule]) == 0
            out = capsys.readouterr().out
            assert rule in out and "Minimal failing example" in out


# ---------------------------------------------------------------------
# incremental cache
# ---------------------------------------------------------------------


SAMPLE_WITH_FINDING = (
    textwrap.dedent(PRELUDE)
    + textwrap.dedent(
        """
        DeadMsg = message_type("dead_conv", ["x"])

        class Host(MessagePassingComputation):
            @register("dead_conv")
            def _on_dead(self, sender, msg, t):
                pass
        """
    )
)


class TestCache:
    @pytest.fixture(autouse=True)
    def _state_dir(self, tmp_path, monkeypatch):
        self.state = tmp_path / "state"
        monkeypatch.setenv("PYDCOP_TPU_STATE_DIR", str(self.state))

    def test_warm_run_skips_the_passes(self, tmp_path, monkeypatch):
        p = tmp_path / "s.py"
        p.write_text(SAMPLE_WITH_FINDING)
        cold = collect_findings([str(p)], use_cache=True)
        assert rules_of(cold) == {"proto-unsent-message"}
        from pydcop_tpu.analysis import cache as cache_mod
        assert os.path.exists(cache_mod.cache_path())

        # a warm run must not even parse: poison the parse entry point
        from pydcop_tpu.analysis import core as core_mod

        def boom(text, rpath):
            raise AssertionError("cache miss: source was parsed")

        monkeypatch.setattr(core_mod, "source_from_text", boom)
        warm = collect_findings([str(p)], use_cache=True)
        assert [f.as_dict() for f in warm] == [
            f.as_dict() for f in cold
        ]

    def test_content_change_invalidates(self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text(SAMPLE_WITH_FINDING)
        assert rules_of(collect_findings([str(p)], use_cache=True))
        # wire the send half: the finding must disappear despite the cache
        p.write_text(
            SAMPLE_WITH_FINDING
            + "\ndef send(c):\n    c.post_msg('h', DeadMsg(x=1))\n"
        )
        assert (
            rules_of(collect_findings([str(p)], use_cache=True)) == set()
        )

    def test_pass_version_bump_invalidates(self, tmp_path, monkeypatch):
        p = tmp_path / "s.py"
        p.write_text(SAMPLE_WITH_FINDING)
        collect_findings([str(p)], use_cache=True)
        from pydcop_tpu.analysis import core as core_mod, proto

        monkeypatch.setattr(proto, "VERSION", proto.VERSION + 1)

        def boom(text, rpath):
            raise RuntimeError("re-ran after version bump")

        monkeypatch.setattr(core_mod, "source_from_text", boom)
        with pytest.raises(RuntimeError, match="version bump"):
            collect_findings([str(p)], use_cache=True)

    def test_select_and_passes_partition_the_cache(self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text(SAMPLE_WITH_FINDING)
        all_f = collect_findings([str(p)], use_cache=True)
        none_f = collect_findings(
            [str(p)], passes=["locks"], use_cache=True
        )
        assert rules_of(all_f) == {"proto-unsent-message"}
        assert none_f == []
        # and the full-config entry still answers correctly
        again = collect_findings([str(p)], use_cache=True)
        assert rules_of(again) == {"proto-unsent-message"}

    def test_corrupt_cache_file_is_ignored(self, tmp_path):
        from pydcop_tpu.analysis import cache as cache_mod

        p = tmp_path / "s.py"
        p.write_text(SAMPLE_WITH_FINDING)
        os.makedirs(self.state, exist_ok=True)
        with open(cache_mod.cache_path(), "w") as f:
            f.write("{not json")
        fs = collect_findings([str(p)], use_cache=True)
        assert rules_of(fs) == {"proto-unsent-message"}

    def test_no_cache_flag_writes_nothing(self, tmp_path, capsys):
        from pydcop_tpu.analysis import cache as cache_mod

        p = tmp_path / "s.py"
        p.write_text(SAMPLE_WITH_FINDING)
        rc = lint_main(["--no-cache", str(p)])
        assert rc == 1
        assert not os.path.exists(cache_mod.cache_path())
        capsys.readouterr()

    def test_cli_default_uses_cache(self, tmp_path, capsys):
        from pydcop_tpu.analysis import cache as cache_mod

        p = tmp_path / "s.py"
        p.write_text(SAMPLE_WITH_FINDING)
        assert lint_main([str(p)]) == 1
        assert os.path.exists(cache_mod.cache_path())
        assert lint_main([str(p)]) == 1  # warm, same verdict
        capsys.readouterr()


# ---------------------------------------------------------------------
# SARIF export
# ---------------------------------------------------------------------


def validate_sarif(doc):
    """Structural SARIF 2.1.0 validation (the subset CI annotators and
    editors rely on)."""
    assert doc["version"] == "2.1.0"
    assert "sarif-schema-2.1.0" in doc["$schema"]
    assert isinstance(doc["runs"], list) and doc["runs"]
    for run in doc["runs"]:
        driver = run["tool"]["driver"]
        assert driver["name"] == "graftlint"
        ids = set()
        for rule in driver["rules"]:
            assert rule["id"] and rule["shortDescription"]["text"]
            assert rule["defaultConfiguration"]["level"] in (
                "none", "note", "warning", "error"
            )
            ids.add(rule["id"])
        for res in run["results"]:
            assert res["ruleId"] in ids
            assert res["level"] in ("none", "note", "warning", "error")
            assert res["message"]["text"]
            loc = res["locations"][0]["physicalLocation"]
            assert loc["artifactLocation"]["uri"]
            assert loc["region"]["startLine"] >= 1
            assert loc["region"]["startColumn"] >= 1
            if "ruleIndex" in res:
                assert (
                    driver["rules"][res["ruleIndex"]]["id"]
                    == res["ruleId"]
                )


class TestSarif:
    def test_sarif_output_validates(self, tmp_path, capsys):
        p = tmp_path / "s.py"
        p.write_text(SAMPLE_WITH_FINDING)
        rc = lint_main(["--no-cache", "--format", "sarif", str(p)])
        out = capsys.readouterr().out
        assert rc == 1  # exit codes unchanged across formats
        doc = json.loads(out)
        validate_sarif(doc)
        results = doc["runs"][0]["results"]
        assert any(
            r["ruleId"] == "proto-unsent-message" for r in results
        )
        # rule metadata came from the EXPLAIN dicts
        rules = {
            r["id"]: r for r in doc["runs"][0]["tool"]["driver"]["rules"]
        }
        assert "fullDescription" in rules["proto-unsent-message"]
        assert "help" in rules["proto-unsent-message"]

    def test_sarif_baseline_state(self, tmp_path, capsys):
        p = tmp_path / "s.py"
        p.write_text(SAMPLE_WITH_FINDING)
        bl = tmp_path / "bl.json"
        assert lint_main(
            ["--no-cache", "--baseline", str(bl), "--write-baseline",
             str(p)]
        ) == 0
        capsys.readouterr()
        rc = lint_main(
            ["--no-cache", "--baseline", str(bl), "--format", "sarif",
             str(p)]
        )
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0  # everything baselined
        validate_sarif(doc)
        states = [
            r["baselineState"] for r in doc["runs"][0]["results"]
        ]
        assert states and set(states) == {"unchanged"}
        # fingerprints exported for cross-commit tracking
        assert all(
            r["partialFingerprints"]["graftlint/v1"]
            for r in doc["runs"][0]["results"]
        )

    def test_repo_sarif_run_is_clean_and_valid(
        self, monkeypatch, capsys
    ):
        """The acceptance invocation: `lint --format sarif` over the
        repo validates as SARIF 2.1.0 and carries zero new results."""
        monkeypatch.chdir(REPO_ROOT)
        rc = lint_main(
            ["--baseline", BASELINE, "--format", "sarif", "pydcop_tpu"]
        )
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        validate_sarif(doc)
        assert doc["runs"][0]["results"] == []
