"""YAML serialization depth tests, modeled on the reference's coverage map
(/root/reference/tests/unit/test_dcop_serialization.py, ~1050 LoC):
header validation, every domain flavor, variable cost forms, external
variables, constraint forms, agent routes/hosting-costs variants,
distribution hints, and scenario round-trips."""

import pytest

from pydcop_tpu.dcop.yamldcop import (
    DcopInvalidFormatError,
    dcop_yaml,
    load_dcop,
    load_scenario,
    yaml_scenario,
)


def _load(body: str):
    return load_dcop("name: t\nobjective: min\n" + body)


class TestHeader:
    def test_name_and_description(self):
        d = load_dcop(
            "name: my_dcop\nobjective: max\ndescription: a thing\n"
            "domains: {d: {values: [0]}}\n"
        )
        assert d.name == "my_dcop"
        assert d.objective == "max"
        assert d.description == "a thing"

    def test_raises_when_no_name(self):
        with pytest.raises(DcopInvalidFormatError, match="name"):
            load_dcop("objective: min\ndomains: {d: {values: [0]}}\n")

    def test_raises_when_no_objective(self):
        with pytest.raises(DcopInvalidFormatError, match="objective"):
            load_dcop("name: t\ndomains: {d: {values: [0]}}\n")

    def test_raises_when_invalid_objective(self):
        with pytest.raises(ValueError, match="min.*max|max.*min"):
            load_dcop(
                "name: t\nobjective: neither\n"
                "domains: {d: {values: [0]}}\n"
            )


class TestDomains:
    def test_int_values_and_type(self):
        d = _load("domains: {d1: {values: [0, 1, 2], type: level}}\n")
        dom = d.domains["d1"]
        assert list(dom.values) == [0, 1, 2]
        assert dom.type == "level"

    def test_range_expansion(self):
        d = _load("domains: {d1: {values: [1 .. 4]}}\n")
        assert list(d.domains["d1"].values) == [1, 2, 3, 4]

    def test_string_domain(self):
        d = _load("domains: {c: {values: [red, green, blue]}}\n")
        assert list(d.domains["c"].values) == ["red", "green", "blue"]

    def test_boolean_domain(self):
        d = _load("domains: {b: {values: [true, false]}}\n")
        assert list(d.domains["b"].values) == [True, False]

    def test_several_domains(self):
        d = _load(
            "domains:\n"
            "  d1: {values: [0, 1]}\n"
            "  d2: {values: [a, b, c]}\n"
        )
        assert set(d.domains) == {"d1", "d2"}


VARS_PREAMBLE = "domains: {d: {values: [0, 1, 2]}}\n"


class TestVariables:
    def test_initial_value(self):
        d = _load(
            VARS_PREAMBLE
            + "variables: {v: {domain: d, initial_value: 2}}\n"
        )
        assert d.variables["v"].initial_value == 2

    def test_invalid_initial_value_raises(self):
        with pytest.raises(DcopInvalidFormatError, match="initial"):
            _load(
                VARS_PREAMBLE
                + "variables: {v: {domain: d, initial_value: 9}}\n"
            )

    def test_cost_function(self):
        d = _load(
            VARS_PREAMBLE
            + "variables: {v: {domain: d, cost_function: v * 2}}\n"
        )
        assert d.variables["v"].cost_for_val(2) == 4

    def test_noisy_cost_function(self):
        d = _load(
            VARS_PREAMBLE
            + "variables:\n"
            "  v: {domain: d, cost_function: v * 2, noise_level: 0.1}\n"
        )
        v = d.variables["v"]
        base = v.cost_for_val(1)
        assert 2 <= base <= 2.1  # noise in [0, noise_level)
        assert v.cost_for_val(1) == base  # deterministic per value

    def test_external_variable_requires_initial(self):
        with pytest.raises(DcopInvalidFormatError, match="initial"):
            _load(
                VARS_PREAMBLE + "external_variables: {e: {domain: d}}\n"
            )

    def test_external_variable(self):
        d = _load(
            VARS_PREAMBLE
            + "external_variables: {e: {domain: d, initial_value: 1}}\n"
        )
        assert d.external_variables["e"].value == 1


CONS_PREAMBLE = (
    "domains: {d: {values: [0, 1, 2]}}\n"
    "variables: {v1: {domain: d}, v2: {domain: d}, v3: {domain: d}}\n"
)


class TestConstraints:
    def test_intention_one_var(self):
        d = _load(
            CONS_PREAMBLE
            + "constraints: {c: {type: intention, function: v1 * 3}}\n"
            "agents: [a]\n"
        )
        c = d.constraints["c"]
        assert [v.name for v in c.dimensions] == ["v1"]
        assert c(v1=2) == 6

    def test_intention_multiline_function(self):
        d = _load(
            CONS_PREAMBLE
            + "constraints:\n"
            "  c:\n"
            "    type: intention\n"
            "    function: |\n"
            "      if v1 == v2:\n"
            "          return 10\n"
            "      return 0\n"
            "agents: [a]\n"
        )
        c = d.constraints["c"]
        assert c(v1=1, v2=1) == 10
        assert c(v1=1, v2=2) == 0

    def test_extensional_one_var(self):
        d = _load(
            CONS_PREAMBLE
            + "constraints:\n"
            "  c:\n"
            "    type: extensional\n"
            "    variables: v1\n"
            "    default: 9\n"
            "    values: {3: 0 | 2, 1: 1}\n"
            "agents: [a]\n"
        )
        c = d.constraints["c"]
        assert c(v1=0) == 3 and c(v1=2) == 3
        assert c(v1=1) == 1

    def test_extensional_two_var(self):
        d = _load(
            CONS_PREAMBLE
            + "constraints:\n"
            "  c:\n"
            "    type: extensional\n"
            "    variables: [v1, v2]\n"
            "    default: 0\n"
            "    values: {7: 1 2 | 2 1}\n"
            "agents: [a]\n"
        )
        c = d.constraints["c"]
        assert c(v1=1, v2=2) == 7 and c(v1=2, v2=1) == 7
        assert c(v1=0, v2=0) == 0

    def test_constraint_with_external_variable(self):
        d = _load(
            CONS_PREAMBLE
            + "external_variables: {e: {domain: d, initial_value: 0}}\n"
            "constraints:\n"
            "  c: {type: intention, function: v1 * 10 if e else v1}\n"
            "agents: [a]\n"
        )
        c = d.constraints["c"]
        assert c(v1=2, e=0) == 2
        assert c(v1=2, e=1) == 20


AGENTS_PREAMBLE = (
    "domains: {d: {values: [0, 1]}}\n"
    "variables: {v: {domain: d}}\n"
)


class TestAgents:
    def test_agent_with_capacity_and_extras(self):
        d = _load(
            AGENTS_PREAMBLE
            + "agents:\n  a1: {capacity: 42, foo: bar}\n"
        )
        a = d.agents["a1"]
        assert a.capacity == 42
        assert a.foo == "bar"

    def test_default_route(self):
        d = _load(
            AGENTS_PREAMBLE
            + "agents: [a1, a2]\nroutes: {default: 3}\n"
        )
        assert d.agents["a1"].route("a2") == 3

    def test_pair_routes_are_symmetric(self):
        d = _load(
            AGENTS_PREAMBLE
            + "agents: [a1, a2, a3]\n"
            "routes: {default: 1, a1: {a2: 5}}\n"
        )
        assert d.agents["a1"].route("a2") == 5
        assert d.agents["a2"].route("a1") == 5
        assert d.agents["a1"].route("a3") == 1

    def test_duplicate_route_with_different_cost_raises(self):
        with pytest.raises(DcopInvalidFormatError, match="route"):
            _load(
                AGENTS_PREAMBLE
                + "agents: [a1, a2]\n"
                "routes: {a1: {a2: 5}, a2: {a1: 6}}\n"
            )

    def test_hosting_costs_levels(self):
        d = _load(
            AGENTS_PREAMBLE
            + "agents: [a1, a2]\n"
            "hosting_costs:\n"
            "  default: 100\n"
            "  a1:\n"
            "    default: 10\n"
            "    computations: {v: 0}\n"
        )
        assert d.agents["a1"].hosting_cost("v") == 0
        assert d.agents["a1"].hosting_cost("other") == 10
        assert d.agents["a2"].hosting_cost("v") == 100


class TestDistributionHints:
    def test_no_hints(self):
        d = _load(AGENTS_PREAMBLE + "agents: [a1]\n")
        assert d.dist_hints is None or not d.dist_hints.must_host

    def test_must_host_and_host_with(self):
        d = _load(
            AGENTS_PREAMBLE
            + "agents: [a1, a2]\n"
            "distribution_hints:\n"
            "  must_host: {a1: [v]}\n"
            "  host_with: {v: [w]}\n"
        )
        assert d.dist_hints.must_host_on("a1") == ["v"]
        assert "w" in d.dist_hints.host_with_computation("v")


class TestRoundTrip:
    def test_dump_and_reload_preserves_everything(self):
        src = (
            "name: t\nobjective: max\n"
            "domains: {d: {values: [0, 1, 2], type: lvl}}\n"
            "variables:\n"
            "  v1: {domain: d, initial_value: 1}\n"
            "  v2: {domain: d, cost_function: v2 * 2}\n"
            "constraints:\n"
            "  c: {type: intention, function: v1 + v2}\n"
            "agents:\n  a1: {capacity: 11}\n  a2: {capacity: 22}\n"
            "routes: {default: 2, a1: {a2: 7}}\n"
            "hosting_costs: {default: 5}\n"
        )
        d1 = load_dcop(src)
        d2 = load_dcop(dcop_yaml(d1))
        assert d2.objective == "max"
        assert list(d2.domains["d"].values) == [0, 1, 2]
        assert d2.variables["v1"].initial_value == 1
        assert d2.variables["v2"].cost_for_val(2) == 4
        assert d2.constraints["c"](v1=1, v2=2) == 3
        assert d2.agents["a1"].capacity == 11
        assert d2.agents["a1"].route("a2") == 7
        assert d2.agents["a1"].route("unknown") == 2
        assert d2.agents["a2"].hosting_cost("anything") == 5

    def test_scenario_roundtrip(self):
        src = (
            "events:\n"
            "  - id: w1\n    delay: 0.5\n"
            "  - id: e1\n"
            "    actions:\n"
            "      - type: remove_agent\n        agent: a2\n"
            "      - type: remove_agent\n        agent: a3\n"
        )
        s1 = load_scenario(src)
        s2 = load_scenario(yaml_scenario(s1))
        assert len(s2.events) == 2
        assert s2.events[0].is_delay and s2.events[0].delay == 0.5
        assert [a.type for a in s2.events[1].actions] == [
            "remove_agent", "remove_agent",
        ]
        assert s2.events[1].actions[1].args["agent"] == "a3"


class TestAdversarialInputs:
    """Malformed-input paths, mirroring the error-path breadth of the
    reference's test_dcop_serialization.py (round-4 verdict item 9):
    every DcopInvalidFormatError raise site in yamldcop is exercised."""

    BASE = (
        "domains: {d: {values: [0, 1, 2]}}\n"
        "variables: {v1: {domain: d}, v2: {domain: d}}\n"
    )

    def test_non_mapping_document(self):
        with pytest.raises(DcopInvalidFormatError, match="mapping"):
            load_dcop("- just\n- a\n- list\n")

    def test_non_mapping_file_in_multi_file_merge(self, tmp_path):
        from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

        ok = tmp_path / "main.yaml"
        ok.write_text(
            "name: t\nobjective: min\ndomains: {d: {values: [0]}}\n"
        )
        bad = tmp_path / "extra.yaml"
        bad.write_text("- not\n- a\n- mapping\n")
        with pytest.raises(DcopInvalidFormatError, match="mapping"):
            load_dcop_from_file([str(ok), str(bad)])

    def test_bad_range_syntax(self):
        with pytest.raises(DcopInvalidFormatError, match="range"):
            _load("domains: {d: {values: 1 ... x}}\n")

    def test_domain_without_values(self):
        with pytest.raises(DcopInvalidFormatError, match="values"):
            _load("domains: {d: {type: level}}\n")

    def test_variable_with_unknown_domain(self):
        with pytest.raises(DcopInvalidFormatError, match="domain"):
            _load(
                "domains: {d: {values: [0]}}\n"
                "variables: {v1: {domain: nope}}\n"
            )

    def test_external_variable_without_initial_value(self):
        with pytest.raises(DcopInvalidFormatError, match="initial_value"):
            _load(
                "domains: {d: {values: [0, 1]}}\n"
                "external_variables: {e1: {domain: d}}\n"
            )

    def test_unknown_constraint_type(self):
        with pytest.raises(DcopInvalidFormatError, match="unknown type"):
            _load(
                self.BASE
                + "constraints: {c1: {type: bogus, function: v1 + v2}}\n"
            )

    def test_intension_with_invalid_expression(self):
        # names the offending constraint instead of a bare SyntaxError
        with pytest.raises(DcopInvalidFormatError, match="c1"):
            _load(
                self.BASE
                + "constraints: {c1: {type: intention, function: 'v1 +* v2'}}\n"
            )

    def test_extensional_with_unknown_variable(self):
        with pytest.raises(DcopInvalidFormatError, match="unknown variable"):
            _load(
                self.BASE
                + "constraints:\n"
                + "  c1:\n    type: extensional\n    variables: [v1, ghost]\n"
                + "    values: {1: 0 0}\n"
            )

    def test_extensional_with_wrong_arity_assignment(self):
        # a 3-value row against a 2-variable scope (ref
        # test_dcop_serialization.py extensional error paths)
        with pytest.raises(DcopInvalidFormatError, match="arity"):
            _load(
                self.BASE
                + "constraints:\n"
                + "  c1:\n    type: extensional\n    variables: [v1, v2]\n"
                + "    values: {1: 0 0 0}\n"
            )

    def test_duplicate_route_with_conflicting_costs(self):
        with pytest.raises(DcopInvalidFormatError, match="route"):
            _load(
                "domains: {d: {values: [0]}}\n"
                "agents: {a1: {}, a2: {}}\n"
                "routes: {a1: {a2: 3}, a2: {a1: 4}}\n"
            )

    def test_must_host_with_unknown_agent(self):
        # ref tests/unit/test_dcop_serialization.py:889
        with pytest.raises(ValueError, match="unknown agent"):
            _load(
                self.BASE
                + "agents: {a1: {}}\n"
                + "distribution_hints:\n  must_host: {a99: [v1]}\n"
            )

    def test_must_host_with_unknown_computation(self):
        # ref tests/unit/test_dcop_serialization.py:897
        with pytest.raises(ValueError, match="unknown computation"):
            _load(
                self.BASE
                + "agents: {a1: {}}\n"
                + "distribution_hints:\n  must_host: {a1: [ghost]}\n"
            )

    def test_valid_must_host_still_loads(self):
        d = _load(
            self.BASE
            + "constraints: {c1: {type: intention, function: v1 + v2}}\n"
            + "agents: {a1: {}}\n"
            + "distribution_hints:\n  must_host: {a1: [v1, c1]}\n"
        )
        assert d.dist_hints.must_host["a1"] == ["v1", "c1"]

    def test_leading_space_expression_still_an_expression(self):
        # ' v1 + v2' used to fall through to the statement path and
        # build a constraint that returned None for every assignment
        d = _load(
            self.BASE
            + "constraints: {c1: {type: intention, function: ' v1 + v2'}}\n"
        )
        assert d.constraints["c1"](v1=1, v2=2) == 3

    def test_multiline_function_without_return_rejected(self):
        with pytest.raises(DcopInvalidFormatError, match="return"):
            _load(
                self.BASE
                + 'constraints:\n  c1:\n    type: intention\n'
                + '    function: "x = v1 + v2\\nx"\n'
            )

    def test_return_inside_nested_def_does_not_count(self):
        with pytest.raises(DcopInvalidFormatError, match="return"):
            _load(
                self.BASE
                + 'constraints:\n  c1:\n    type: intention\n'
                + '    function: "def g():\\n    return v1\\ng()"\n'
            )

    def test_invalid_cost_function_names_the_variable(self):
        with pytest.raises(DcopInvalidFormatError, match="v1"):
            _load(
                "domains: {d: {values: [0, 1]}}\n"
                + 'variables:\n  v1:\n    domain: d\n'
                + '    cost_function: "x = v1\\nx"\n'
            )
