"""graftcap tests: capture bundles + the per-op regression diff.

Golden mini-bundle fixtures — two synthetic captures with a known
per-op delta, a dispatch-count change and a recompile injection — pin
the ranked attribution output and the diff JSON schema; the gate
integration test pins that bench_gate failure output carries the
attribution table.  All host-side: perfdiff is stdlib-only by contract.
"""

import copy
import importlib.util
import json
import os

import pytest

from pydcop_tpu.telemetry import perfdiff

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
)


def _ell_record(metric="maxsum_1k_random_wall", value=0.10, device="cpu",
                config="2"):
    """A synthetic bench_all-shaped record with the full observability
    surface (compile / census / roofline / kernel blocks)."""
    return {
        "metric": metric,
        "value": value,
        "unit": "s",
        "cost": 42.0,
        "violations": 0,
        "cycles": 60,
        "device": device,
        "config": config,
        "telemetry": {"windows": 1, "readback_bytes": 2012},
        "compile": {"jit_compiles": 2, "jit_cache_hits": 5},
        "census": {
            "jit": {
                "solve._solve_fused": {
                    "compiles": 0, "hits": 1, "dispatches": 1,
                },
            },
            "readback": {"windows": 1, "readbacks": 1},
        },
        "roofline": {
            "traffic_bytes_per_cycle": 773400,
            "achieved_gbps": 1.68,
        },
        "kernel": {
            "layout": "ell",
            "step_ms": 0.46,
            "attributed_pct": 98.5,
            "ops": {
                "pair_gather": {"ms": 0.017, "share_pct": 3.8,
                                "gbps": 10.2},
                "minplus": {"ms": 0.218, "share_pct": 47.4, "gbps": 1.8},
                "variable_step": {"ms": 0.218, "share_pct": 47.4,
                                  "gbps": 1.0},
            },
        },
    }


def _mgm2_record(value=0.20):
    rec = _ell_record(
        metric="mgm2_ising10k_wall", value=value, config="3"
    )
    rec["kernel"] = {
        "algo": "mgm2",
        "step_ms": 6.0,
        "attributed_pct": 95.0,
        "phases": {
            "value": {"ms": 1.0, "share_pct": 16.7},
            "offer": {"ms": 2.0, "share_pct": 33.3},
            "gain": {"ms": 3.0, "share_pct": 50.0},
        },
    }
    return rec


@pytest.fixture()
def golden_bundles(tmp_path):
    """Two mini-bundles: ``fresh`` carries a known per-op regression
    (ell.minplus x4, wall x2), a dispatch-count change on the mgm2
    config, and a recompile injection on the dpop config."""
    base_recs = [
        _ell_record(),
        _mgm2_record(),
        _ell_record(metric="dpop_meetings_wall", value=0.05, config="5"),
    ]
    fresh_recs = copy.deepcopy(base_recs)
    # per-op delta: minplus x4 dominates; wall follows
    fresh_recs[0]["value"] = 0.20
    fresh_recs[0]["kernel"]["ops"]["minplus"]["ms"] = 0.872
    # dispatch-count change: one warm solve now dispatches twice
    fresh_recs[1]["value"] = 0.40
    fresh_recs[1]["census"]["jit"]["solve._solve_fused"].update(
        {"hits": 2, "dispatches": 2}
    )
    # recompile injection: the timed run rebuilt its executable
    fresh_recs[2]["value"] = 0.11
    fresh_recs[2]["census"]["jit"]["solve._solve_fused"].update(
        {"compiles": 1, "dispatches": 2}
    )
    dirs = {}
    for name, recs in (("base", base_recs), ("fresh", fresh_recs)):
        out = str(tmp_path / name)
        manifest = perfdiff.new_manifest(
            environment={"device": "cpu"}, created="2026-08-07T00:00:00"
        )
        perfdiff.write_manifest(out, manifest)
        for rec in recs:
            perfdiff.append_record(out, rec, manifest)
        dirs[name] = out
    return dirs


# -- bundle IO ---------------------------------------------------------


def test_bundle_roundtrip_and_manifest_index(golden_bundles):
    side = perfdiff.load_side(golden_bundles["base"])
    assert side["kind"] == "bundle"
    assert set(side["records"]) == {
        "maxsum_1k_random_wall", "mgm2_ising10k_wall",
        "dpop_meetings_wall",
    }
    manifest = side["manifest"]
    assert manifest["format"] == perfdiff.BUNDLE_FORMAT
    assert manifest["configs"]["2"]["metric"] == "maxsum_1k_random_wall"
    assert manifest["configs"]["2"]["attribution"] == "ok"
    assert manifest["configs"]["2"]["file"] == os.path.join(
        "records", "config_2.json"
    )


def test_attribution_state_degradations():
    rec = _ell_record()
    assert perfdiff.attribution_state(rec) == "ok"
    rec["kernel"] = {"layout": "ell", "skipped": "no edges"}
    assert perfdiff.attribution_state(rec).startswith("skipped: no edges")
    rec["kernel"] = {"error": "RuntimeError: boom"}
    assert perfdiff.attribution_state(rec).startswith("error:")
    del rec["kernel"]
    assert perfdiff.attribution_state(rec) == "missing"


def test_op_rows_prefix_layout_and_algo():
    assert set(perfdiff.op_rows(_ell_record())) == {
        "ell.pair_gather", "ell.minplus", "ell.variable_step",
    }
    assert set(perfdiff.op_rows(_mgm2_record())) == {
        "mgm2.value", "mgm2.offer", "mgm2.gain",
    }


# -- the golden diff ---------------------------------------------------


def test_golden_diff_ranks_injected_op_first(golden_bundles):
    diff = perfdiff.diff_sides(
        perfdiff.load_side(golden_bundles["base"]),
        perfdiff.load_side(golden_bundles["fresh"]),
    )
    assert diff["significant"] == 3
    # worst regression ranks first (mgm2 +100% over maxsum +100%?
    # both 100% — ranked among the significant set); the injected op
    # must lead ITS metric's table
    md = next(
        d for d in diff["metrics"]
        if d["metric"] == "maxsum_1k_random_wall"
    )
    assert md["significant"]
    assert md["ops"][0]["op"] == "ell.minplus"
    assert md["ops"][0]["significant"]
    assert md["verdict"].startswith("op-level shift: ell.minplus")
    # the human table names the op on its top row, with the marker
    table = perfdiff.format_attribution(md)
    lines = [ln for ln in table.splitlines() if ln.startswith("  ell.")]
    assert lines[0].lstrip().startswith("ell.minplus")
    assert "<--" in lines[0]


def test_golden_diff_schema(golden_bundles):
    diff = perfdiff.diff_sides(
        perfdiff.load_side(golden_bundles["base"]),
        perfdiff.load_side(golden_bundles["fresh"]),
    )
    assert diff["format"] == perfdiff.DIFF_FORMAT
    assert set(diff) == {
        "format", "base", "fresh", "metrics", "significant", "flags",
        "only_in_base", "only_in_fresh",
    }
    for md in diff["metrics"]:
        assert set(md) == {
            "metric", "base_value", "fresh_value", "unit", "delta_pct",
            "significant", "device", "attribution", "ops", "census",
            "roofline", "memory", "flags", "verdict",
        }
        for row in md["ops"]:
            assert set(row) == {
                "op", "base_ms", "fresh_ms", "delta_ms", "delta_pct",
                "base_share_pct", "fresh_share_pct", "significant",
            }
    # machine JSON is json-serializable as-is
    json.dumps(diff)


def test_dispatch_count_change_flagged_and_veredicted(golden_bundles):
    diff = perfdiff.diff_sides(
        perfdiff.load_side(golden_bundles["base"]),
        perfdiff.load_side(golden_bundles["fresh"]),
    )
    md = next(
        d for d in diff["metrics"] if d["metric"] == "mgm2_ising10k_wall"
    )
    assert any(
        f.startswith("dispatches: solve._solve_fused 1 -> 2")
        for f in md["flags"]
    )
    assert md["verdict"].startswith("dispatch-count change")


def test_recompile_injection_wins_verdict_priority(golden_bundles):
    diff = perfdiff.diff_sides(
        perfdiff.load_side(golden_bundles["base"]),
        perfdiff.load_side(golden_bundles["fresh"]),
    )
    md = next(
        d for d in diff["metrics"] if d["metric"] == "dpop_meetings_wall"
    )
    assert any(
        f.startswith("recompile in timed run: solve._solve_fused")
        for f in md["flags"]
    )
    assert md["verdict"].startswith("recompile drift")


def test_self_diff_is_clean(golden_bundles):
    side = perfdiff.load_side(golden_bundles["base"])
    diff = perfdiff.diff_sides(side, side)
    assert diff["significant"] == 0
    assert diff["flags"] == []
    assert all(not d["significant"] for d in diff["metrics"])


def test_memory_bound_drift_verdict():
    base = _ell_record()
    fresh = copy.deepcopy(base)
    fresh["value"] = 0.20
    fresh["roofline"]["achieved_gbps"] = 0.84  # halved, traffic same
    md = perfdiff.diff_records(base, fresh)
    assert md["significant"]
    assert md["verdict"].startswith("memory-bound drift")


def test_device_change_flagged_first():
    base = _ell_record(device="tpu")
    fresh = _ell_record(device="cpu", value=0.9)
    md = perfdiff.diff_records(base, fresh)
    assert md["flags"][0].startswith("device changed: tpu -> cpu")


# -- comparand resolution ----------------------------------------------


def test_load_side_records_file_and_driver_wrapper(tmp_path):
    raw = tmp_path / "BENCH_a.json"
    raw.write_text(json.dumps(_ell_record()) + "\n")
    side = perfdiff.load_side(str(raw))
    assert side["kind"] == "records"
    assert "maxsum_1k_random_wall" in side["records"]
    wrapped = tmp_path / "BENCH_b.json"
    wrapped.write_text(json.dumps({
        "tail": json.dumps(_ell_record(value=0.3)),
        "driver": "bench.py",
    }))
    side = perfdiff.load_side(str(wrapped))
    assert side["records"]["maxsum_1k_random_wall"]["value"] == 0.3


def test_trajectory_median_same_device(tmp_path):
    for i, (value, device) in enumerate(
        [(0.1, "cpu"), (0.2, "cpu"), (0.3, "cpu"), (9.9, "tpu")]
    ):
        (tmp_path / f"BENCH_r{i}.json").write_text(
            json.dumps(_ell_record(value=value, device=device)) + "\n"
        )
    side = perfdiff.load_side(
        str(tmp_path / "BENCH_*.json"), device="cpu"
    )
    assert side["kind"] == "trajectory"
    assert side["records"]["maxsum_1k_random_wall"]["value"] == 0.2


def test_load_side_missing_raises():
    with pytest.raises(FileNotFoundError):
        perfdiff.load_side("/nonexistent/BENCH_*.json")


# -- budget site flags -------------------------------------------------


def test_budget_site_change_flagged(golden_bundles):
    for name, sites in (("base", 1), ("fresh", 2)):
        mpath = os.path.join(golden_bundles[name], "manifest.json")
        with open(mpath) as fh:
            manifest = json.load(fh)
        manifest["budget"] = {
            "census": {
                "solve._solve_fused": {
                    "region": "solve.py:_solve_fused",
                    "dispatch_sites": sites,
                    "readback_sites": 1,
                },
            },
            "problems": [],
        }
        with open(mpath, "w") as fh:
            json.dump(manifest, fh)
    diff = perfdiff.diff_sides(
        perfdiff.load_side(golden_bundles["base"]),
        perfdiff.load_side(golden_bundles["fresh"]),
    )
    assert any(
        f == "budget: solve._solve_fused.dispatch_sites 1 -> 2"
        for f in diff["flags"]
    )


# -- gate integration --------------------------------------------------


@pytest.fixture()
def bench_gate():
    spec = importlib.util.spec_from_file_location(
        "bench_gate_perfdiff_test",
        os.path.join(REPO_ROOT, "tools", "bench_gate.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_gate_failure_output_includes_attribution(
    bench_gate, tmp_path, capsys
):
    """bench_gate.main on a regressing fresh set must print the per-op
    attribution table in the SAME failure output."""
    hist = tmp_path / "BENCH_h1.json"
    hist.write_text(
        "\n".join(json.dumps(_ell_record()) for _ in range(3)) + "\n"
    )
    fresh_rec = copy.deepcopy(_ell_record())
    fresh_rec["value"] = 0.50
    fresh_rec["kernel"]["ops"]["minplus"]["ms"] = 1.2
    fresh = tmp_path / "fresh.json"
    fresh.write_text(json.dumps(fresh_rec) + "\n")
    rc = bench_gate.main([
        "--fresh", str(fresh),
        "--history", str(tmp_path / "BENCH_h*.json"),
        "--no-waivers", "--no-normalize",
    ])
    out = capsys.readouterr().out
    assert rc == 1
    assert "REGRESSION" in out
    assert "per-op attribution (graftcap" in out
    assert "ell.minplus" in out
    assert "<--" in out


def test_gate_waiver_output_includes_attribution(
    bench_gate, tmp_path, capsys
):
    hist = tmp_path / "BENCH_h1.json"
    hist.write_text(
        "\n".join(json.dumps(_ell_record()) for _ in range(3)) + "\n"
    )
    fresh_rec = copy.deepcopy(_ell_record())
    fresh_rec["value"] = 0.50
    fresh = tmp_path / "fresh.json"
    fresh.write_text(json.dumps(fresh_rec) + "\n")
    waivers = tmp_path / "waivers.json"
    waivers.write_text(json.dumps({
        "version": 1,
        "waivers": [{
            "metric": "maxsum_1k_random_wall",
            "reason": "synthetic drift for the test",
        }],
    }))
    rc = bench_gate.main([
        "--fresh", str(fresh),
        "--history", str(tmp_path / "BENCH_h*.json"),
        "--known-drift", str(waivers), "--no-normalize",
    ])
    out = capsys.readouterr().out
    assert rc == 0  # waived: the gate passes...
    assert "WAIVED" in out
    # ...but the attribution table still prints, so the waiver stays
    # explainable instead of becoming a blind spot
    assert "per-op attribution (graftcap" in out


def test_gate_json_output_carries_attribution(
    bench_gate, tmp_path, capsys
):
    hist = tmp_path / "BENCH_h1.json"
    hist.write_text(
        "\n".join(json.dumps(_ell_record()) for _ in range(3)) + "\n"
    )
    fresh_rec = copy.deepcopy(_ell_record())
    fresh_rec["value"] = 0.50
    fresh = tmp_path / "fresh.json"
    fresh.write_text(json.dumps(fresh_rec) + "\n")
    rc = bench_gate.main([
        "--fresh", str(fresh),
        "--history", str(tmp_path / "BENCH_h*.json"),
        "--no-waivers", "--no-normalize", "--json",
    ])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    md = payload["attribution"]["maxsum_1k_random_wall"]
    assert md["significant"]
    assert md["ops"][0]["op"] == "ell.minplus"


# -- kernelprof degraded counter ---------------------------------------


def test_kernelprof_skip_counts_degraded():
    from pydcop_tpu.telemetry import metrics_registry
    from pydcop_tpu.telemetry.kernelprof import ell_kernel_block

    class _NoEdges:
        n_edges = 0
        buckets = ()

    metrics_registry.reset()
    metrics_registry.enabled = True
    try:
        block = ell_kernel_block(_NoEdges())
    finally:
        metrics_registry.enabled = False
    assert block == {"layout": "ell", "skipped": "no edges"}
    counter = metrics_registry.get("kernelprof.degraded")
    assert counter is not None
    assert counter.value(reason="no edges") == 1.0
    metrics_registry.reset()
