"""Tests for the graftscope telemetry subsystem (pydcop_tpu/telemetry/):
metric types under concurrency, span nesting/ordering in the Chrome trace
output, the event-bus -> metrics bridge, the instrumented runtime paths,
and the CLI round-trip (``solve --trace-out`` -> ``pydcop_tpu telemetry``).
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from pydcop_tpu.infrastructure.communication import (
    InProcessCommunicationLayer,
    Messaging,
)
from pydcop_tpu.infrastructure.computations import Message
from pydcop_tpu.infrastructure.events import EventDispatcher, event_bus
from pydcop_tpu.infrastructure import stats
from pydcop_tpu.telemetry import (
    EventBusBridge,
    attach_event_bridge,
    load_trace,
    metrics_registry,
    summarize_events,
    telemetry_off,
    traced,
    tracer,
    validate_events,
)

ENV = dict(os.environ, JAX_PLATFORMS="cpu")
INSTANCE = os.path.join(
    os.path.dirname(__file__), "instances", "graph_coloring.yaml"
)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry_off()
    yield
    telemetry_off()
    event_bus.enabled = False
    event_bus.reset()


# ---------------------------------------------------------------------------
# metric types
# ---------------------------------------------------------------------------


class TestMetrics:
    def test_disabled_registry_writes_nothing(self):
        c = metrics_registry.counter("t.off", "x")
        c.inc(5)
        assert c.value() == 0.0
        assert "t.off" not in metrics_registry.snapshot()["metrics"]

    def test_counter_labels_and_values(self):
        metrics_registry.enabled = True
        c = metrics_registry.counter("t.c", "x")
        c.inc(agent="a1")
        c.inc(2.5, agent="a1")
        c.inc(agent="a2")
        c.inc()
        assert c.value(agent="a1") == 3.5
        assert c.value(agent="a2") == 1.0
        assert c.value() == 1.0
        snap = metrics_registry.snapshot()["metrics"]["t.c"]
        assert snap["kind"] == "counter"
        assert {"labels": {"agent": "a1"}, "value": 3.5} in snap["values"]

    def test_gauge_set_and_add(self):
        metrics_registry.enabled = True
        g = metrics_registry.gauge("t.g", "x")
        g.set(7)
        g.set(3, q="depth")
        g.add(2, q="depth")
        assert g.value() == 7.0
        assert g.value(q="depth") == 5.0

    def test_histogram_buckets_sum_count(self):
        metrics_registry.enabled = True
        h = metrics_registry.histogram("t.h", "x", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 0.5, 5.0):
            h.observe(v)
        assert h.count() == 4
        assert h.sum() == pytest.approx(6.05)
        snap = metrics_registry.snapshot()["metrics"]["t.h"]
        assert snap["bucket_bounds"] == [0.1, 1.0, "+Inf"]
        assert snap["values"][0]["value"]["buckets"] == [1, 2, 1]

    def test_kind_conflict_rejected(self):
        metrics_registry.counter("t.kind", "x")
        with pytest.raises(TypeError):
            metrics_registry.gauge("t.kind", "x")

    def test_snapshot_is_json_serializable(self):
        metrics_registry.enabled = True
        metrics_registry.counter("t.js", "x").inc(n=3)
        metrics_registry.histogram("t.jh", "x").observe(0.2)
        text = metrics_registry.to_json()
        assert json.loads(text)["metrics"]["t.js"]["values"]

    def test_concurrent_increments_from_threads(self):
        # the acceptance bar: >= 4 threads hammering the same counter and
        # histogram must lose no update
        metrics_registry.enabled = True
        c = metrics_registry.counter("t.conc", "x")
        h = metrics_registry.histogram("t.conch", "x")
        n_threads, n_iter = 6, 5000
        barrier = threading.Barrier(n_threads)

        def worker(i):
            barrier.wait()
            for _ in range(n_iter):
                c.inc(worker=str(i % 2))
                h.observe(0.001)

        threads = [
            threading.Thread(target=worker, args=(i,))
            for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        total = c.value(worker="0") + c.value(worker="1")
        assert total == n_threads * n_iter
        assert h.count() == n_threads * n_iter

    def test_reset_keeps_handles_live(self):
        metrics_registry.enabled = True
        c = metrics_registry.counter("t.reset", "x")
        c.inc(4)
        metrics_registry.reset()
        assert c.value() == 0.0
        c.inc()
        assert c.value() == 1.0
        assert metrics_registry.get("t.reset") is c


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------


class TestTracer:
    def test_disabled_span_is_shared_noop(self):
        s1 = tracer.span("a")
        s2 = tracer.span("b", key="value")
        assert s1 is s2  # one shared object: no allocation when off
        with s1:
            pass
        assert tracer.events() == []

    def test_span_nesting_and_ordering(self):
        tracer.enabled = True
        with tracer.span("outer", phase="demo"):
            with tracer.span("inner"):
                pass
            with tracer.span("inner2"):
                pass
        events = tracer.events()
        # spans close innermost-first
        assert [e["name"] for e in events] == ["inner", "inner2", "outer"]
        inner, inner2, outer = events
        assert inner["args"]["parent"] == "outer"
        assert inner2["args"]["parent"] == "outer"
        assert "parent" not in outer["args"]
        # containment: children start after and end before the parent
        for child in (inner, inner2):
            assert child["ts"] >= outer["ts"]
            assert child["ts"] + child["dur"] <= (
                outer["ts"] + outer["dur"] + 1e-6
            )
        assert inner2["ts"] >= inner["ts"] + inner["dur"] - 1e-6
        assert outer["args"]["phase"] == "demo"

    def test_chrome_trace_validates_and_summarizes(self):
        tracer.enabled = True
        with tracer.span("work", cat="test"):
            tracer.instant("tick", n=1)
        trace = tracer.chrome_trace()
        assert validate_events(trace["traceEvents"]) == []
        summary = summarize_events(trace["traceEvents"])
        assert summary["spans"]["work"]["count"] == 1
        assert summary["instants"]["tick"] == 1

    def test_complete_records_explicit_timings(self):
        import time

        tracer.enabled = True
        t0 = time.perf_counter()
        tracer.complete("post.hoc", t0, 0.25, cat="test", bytes=42)
        (e,) = tracer.events()
        assert e["ph"] == "X"
        assert e["dur"] == pytest.approx(0.25e6)
        assert e["args"]["bytes"] == 42

    def test_traced_decorator(self):
        calls = []

        @traced("deco.fn", cat="test")
        def fn(x):
            calls.append(x)
            return x + 1

        assert fn(1) == 2  # disabled: no event
        assert tracer.events() == []
        tracer.enabled = True
        assert fn(2) == 3
        (e,) = tracer.events()
        assert e["name"] == "deco.fn"
        assert calls == [1, 2]

    def test_jsonl_export_roundtrip(self, tmp_path):
        tracer.enabled = True
        with tracer.span("jsonl.span"):
            pass
        p = tmp_path / "trace.jsonl"
        tracer.export_jsonl(str(p))
        events = load_trace(str(p))
        assert [e["name"] for e in events] == ["jsonl.span"]
        assert validate_events(events) == []

    def test_spans_from_multiple_threads_keep_own_stacks(self):
        tracer.enabled = True
        done = threading.Barrier(3)

        def worker(name):
            with tracer.span(f"outer.{name}"):
                done.wait()  # both threads inside their outer span
                with tracer.span(f"inner.{name}"):
                    pass

        threads = [
            threading.Thread(target=worker, args=(n,)) for n in ("a", "b")
        ]
        for t in threads:
            t.start()
        done.wait()
        for t in threads:
            t.join()
        by_name = {e["name"]: e for e in tracer.events()}
        # nesting is per-thread: inner.a under outer.a, never outer.b
        assert by_name["inner.a"]["args"]["parent"] == "outer.a"
        assert by_name["inner.b"]["args"]["parent"] == "outer.b"


# ---------------------------------------------------------------------------
# event-bus bridge + dispatch re-entrancy
# ---------------------------------------------------------------------------


class TestEventBusBridge:
    def test_topics_become_metrics(self):
        metrics_registry.enabled = True
        bridge = attach_event_bridge()
        try:
            event_bus.send("computations.message_snd.c1", ("c2", "ping"))
            event_bus.send("computations.message_snd.c1", ("c3", "ping"))
            event_bus.send("computations.message_rcv.c2", ("c1", "ping"))
            event_bus.send("computations.cycle.c1", 3)
            event_bus.send("computations.value.c1", ("a", 0.5))
            event_bus.send("agents.add_computation.a1", "c1")
            event_bus.send("orchestrator.scenario.remove_agent", "a2")
            reg = metrics_registry
            assert reg.counter("computations.messages_sent").value(
                computation="c1"
            ) == 2
            assert reg.counter("computations.messages_received").value(
                computation="c2"
            ) == 1
            assert reg.counter("computations.cycles").value(
                computation="c1"
            ) == 1
            assert reg.counter("computations.value_changes").value(
                computation="c1"
            ) == 1
            assert reg.counter("agents.computations_added").value(
                agent="a1"
            ) == 1
            assert reg.counter("orchestrator.events").value(
                event="scenario.remove_agent"
            ) == 1
        finally:
            bridge.detach()

    def test_attach_enables_bus_detach_restores(self):
        assert not event_bus.enabled
        bridge = attach_event_bridge()
        assert event_bus.enabled
        bridge.detach()
        assert not event_bus.enabled

    def test_raising_callback_keeps_dispatching_and_counts(self):
        # satellite: a callback that raises must not kill the sender's
        # thread nor starve later subscribers
        metrics_registry.enabled = True
        bus = EventDispatcher(enabled=True)
        seen = []

        def bad(topic, evt):
            raise RuntimeError("boom")

        bus.subscribe("computations.cycle.*", bad)
        bus.subscribe("computations.cycle.*", lambda t, e: seen.append(e))
        bus.send("computations.cycle.c1", 7)  # must not raise
        assert seen == [7]
        assert metrics_registry.counter(
            "telemetry.dispatch_errors"
        ).value(topic="computations.cycle.c1") == 1


# ---------------------------------------------------------------------------
# messaging instrumentation (satellite: message_snd / message_rcv topics)
# ---------------------------------------------------------------------------


class TestMessagingTelemetry:
    def _pair(self):
        """Two wired Messaging endpoints (a1 -> a2 route registered)."""
        m1 = Messaging("a1", InProcessCommunicationLayer())
        m2 = Messaging("a2", InProcessCommunicationLayer())
        m2.register_computation("c2", object())
        m1.register_route("c2", "a2", m2.comm.address)
        return m1, m2

    def test_snd_rcv_topics_published_from_messaging(self):
        topics = []
        event_bus.enabled = True
        event_bus.subscribe(
            "computations.message_snd.*", lambda t, e: topics.append((t, e))
        )
        event_bus.subscribe(
            "computations.message_rcv.*", lambda t, e: topics.append((t, e))
        )
        m1, m2 = self._pair()
        m1.post_msg("c1", "c2", Message("ping", "hello"))
        assert (
            "computations.message_snd.c1", ("c2", "ping")
        ) in topics
        assert (
            "computations.message_rcv.c2", ("c1", "ping")
        ) in topics

    def test_comms_counters_match_traffic(self):
        metrics_registry.enabled = True
        m1, m2 = self._pair()
        msg = Message("ping", "hello")
        for _ in range(5):
            m1.post_msg("c1", "c2", msg)
        reg = metrics_registry
        assert reg.counter("comms.messages_sent").value(agent="a1") == 5
        assert reg.counter("comms.messages_received").value(agent="a2") == 5
        assert reg.counter("comms.payload_bytes_sent").value(
            agent="a1"
        ) == 5 * msg.size
        assert reg.counter("comms.payload_bytes_received").value(
            agent="a2"
        ) == 5 * msg.size
        assert reg.gauge("comms.queue_depth").value(agent="a2") >= 1
        # consuming records delivery latency
        assert m2.next_msg(timeout=1) is not None
        assert reg.histogram("comms.delivery_seconds").count(agent="a2") == 1

    def test_parked_then_flushed_message_counted_once(self):
        # a message posted before its destination has a route parks, and
        # register_route's flush re-posts it: the telemetry sinks must see
        # ONE logical message, not two
        metrics_registry.enabled = True
        tracer.enabled = True
        topics = []
        event_bus.enabled = True
        event_bus.subscribe(
            "computations.message_snd.*", lambda t, e: topics.append(t)
        )
        m1 = Messaging("a1", InProcessCommunicationLayer())
        m2 = Messaging("a2", InProcessCommunicationLayer())
        m2.register_computation("c2", object())
        m1.post_msg("c1", "c2", Message("ping", "x"))  # no route: parks
        m1.register_route("c2", "a2", m2.comm.address)  # flush re-posts
        assert m2.next_msg(timeout=1) is not None  # delivered exactly once
        reg = metrics_registry
        assert reg.counter("comms.messages_sent").value(agent="a1") == 1
        assert reg.counter("comms.messages_received").value(agent="a2") == 1
        assert topics == ["computations.message_snd.c1"]
        names = [e["name"] for e in tracer.events()]
        assert names.count("comms.send") == 1

    def test_trace_instants_for_send_recv(self):
        tracer.enabled = True
        m1, m2 = self._pair()
        m1.post_msg("c1", "c2", Message("ping", "x"))
        names = [e["name"] for e in tracer.events()]
        assert names.count("comms.send") == 1
        assert names.count("comms.recv") == 1

    def test_404_repark_counts_ext_msg_once(self):
        # a send answered with the reference's 404 re-parks the message;
        # the register_route replay is its one successful send and must
        # be the one count in count_ext_msg/size_ext_msg
        from pydcop_tpu.infrastructure.communication import (
            CommunicationLayer,
            UnknownComputation,
        )

        class Flaky404Layer(CommunicationLayer):
            def __init__(self):
                super().__init__()
                self.calls = 0

            @property
            def address(self):
                return self

            def send_msg(self, *a, **kw):
                self.calls += 1
                if self.calls == 1:
                    raise UnknownComputation("c2")
                return True

        m1 = Messaging("a1", Flaky404Layer())
        m1.register_route("c2", "a2", "addr")
        m1.post_msg("c1", "c2", Message("ping", "x"))  # 404 -> re-parked
        assert m1.count_ext_msg.get("c1", 0) == 0
        m1.register_route("c2", "a2", "addr")  # flush: succeeds now
        assert m1.comm.calls == 2
        assert m1.count_ext_msg["c1"] == 1
        assert m1.size_ext_msg["c1"] == Message("ping", "x").size


# ---------------------------------------------------------------------------
# stats.py routing (satellite)
# ---------------------------------------------------------------------------


class TestStatsTelemetry:
    def test_set_stats_file_none_closes_and_disables(self, tmp_path):
        p = str(tmp_path / "trace.csv")
        stats.set_stats_file(p)
        stats.trace_computation("comp_a", 1, 0.25, 2, 64, 10, 3)
        handle = stats._file
        stats.set_stats_file(None)
        assert not stats.stats_enabled()
        assert stats._file is None
        assert handle.closed
        stats.trace_computation("comp_b", 2, 0.5)  # no-op after close
        with open(p, encoding="utf-8") as f:
            lines = f.read().splitlines()
        assert lines[0] == ",".join(stats.columns)
        assert len(lines) == 2 and "comp_a" in lines[1]

    def test_rows_routed_to_registry_and_csv_identical(self, tmp_path):
        p = str(tmp_path / "trace.csv")
        # CSV written with metrics OFF, the pre-telemetry format...
        stats.set_stats_file(p)
        stats.trace_computation("comp_a", 1, 0.25, 2, 64, 10, 3)
        stats.set_stats_file(None)
        with open(p, encoding="utf-8") as f:
            baseline_row = f.read().splitlines()[1].split(",")[1:]
        # ...must be byte-identical (time column aside) with metrics ON
        metrics_registry.enabled = True
        stats.set_stats_file(p)
        stats.trace_computation("comp_a", 1, 0.25, 2, 64, 10, 3)
        stats.set_stats_file(None)
        with open(p, encoding="utf-8") as f:
            row = f.read().splitlines()[1].split(",")[1:]
        assert row == baseline_row
        reg = metrics_registry
        assert reg.counter("stats.steps").value(computation="comp_a") == 1
        assert reg.counter("stats.msg_count").value(
            computation="comp_a"
        ) == 2
        assert reg.counter("stats.msg_size").value(
            computation="comp_a"
        ) == 64
        assert reg.counter("stats.op_count").value(
            computation="comp_a"
        ) == 10
        assert reg.histogram("stats.step_seconds").sum(
            computation="comp_a"
        ) == pytest.approx(0.25)

    def test_registry_only_routing_without_csv(self):
        metrics_registry.enabled = True
        stats.trace_computation("comp_x", 0, 0.1)
        assert metrics_registry.counter("stats.steps").value(
            computation="comp_x"
        ) == 1


# ---------------------------------------------------------------------------
# solver-path instrumentation (in-process, CPU)
# ---------------------------------------------------------------------------


class TestSolvePathTelemetry:
    def test_direct_solve_records_windows_and_readbacks(self):
        from pydcop_tpu.api import solve_result
        from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

        metrics_registry.enabled = True
        tracer.enabled = True
        dcop = load_dcop_from_file([INSTANCE])
        r = solve_result(dcop, "dsa", n_cycles=6, seed=0)
        assert r["status"] == "FINISHED"
        reg = metrics_registry
        assert reg.counter("solve.windows").value() >= 1
        assert reg.counter("solve.device_cycles").value() == 6
        assert reg.counter("solve.readback_bytes").value() > 0
        assert reg.histogram("solve.readback_seconds").count() >= 1
        assert reg.counter("compile.runs").value() == 1
        assert reg.gauge("compile.n_vars").value() == 10
        names = {e["name"] for e in tracer.events()}
        assert {
            "compile.compile_dcop", "solve.algorithm",
            "solve.window", "solve.readback",
        } <= names

    def test_timeout_path_records_chunk_windows(self):
        from pydcop_tpu.api import solve_result
        from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

        metrics_registry.enabled = True
        tracer.enabled = True
        dcop = load_dcop_from_file([INSTANCE])
        r = solve_result(dcop, "dsa", n_cycles=40, seed=0, timeout=60)
        assert r["status"] in ("FINISHED", "TIMEOUT")
        windows = [
            e for e in tracer.events() if e["name"] == "solve.window"
        ]
        assert windows and all(
            w["args"]["kind"] == "chunk" for w in windows
        )
        assert metrics_registry.counter("solve.device_cycles").value() == 40


# ---------------------------------------------------------------------------
# CLI round-trip (subprocess, like tests/test_cli.py)
# ---------------------------------------------------------------------------


def run_cli(*args, timeout=120):
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=ENV,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )


class TestCliRoundTrip:
    def test_solve_trace_out_then_telemetry_summarize(self, tmp_path):
        trace = str(tmp_path / "trace.json")
        metrics = str(tmp_path / "metrics.json")
        r = run_cli(
            "solve", "-a", "dsa", "-n", "5",
            "--trace-out", trace, "--metrics-out", metrics, INSTANCE,
        )
        assert r.returncode == 0, r.stderr
        # the trace file is a valid Chrome trace the verb can summarize
        s = run_cli("telemetry", "--validate", "--json", trace)
        assert s.returncode == 0, s.stderr
        payload = json.loads(s.stdout)
        assert payload["schema_errors"] == []
        spans = payload["summary"]["spans"]
        assert "compile.compile_dcop" in spans
        assert "solve.window" in spans
        assert "solve.readback" in spans

    def test_telemetry_validate_rejects_garbage(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text('{"traceEvents": [{"ph": "Q"}]}')
        r = run_cli("telemetry", "--validate", str(bad))
        assert r.returncode == 1

    def test_telemetry_malformed_known_phase_reported_not_fatal(
        self, tmp_path
    ):
        # an X event missing ts/dur (and a nameless instant) must produce
        # schema errors + exit 1, never a traceback
        bad = tmp_path / "bad.json"
        bad.write_text(
            '{"traceEvents": [{"ph": "X", "name": "a"}, {"ph": "i"}]}'
        )
        r = run_cli("telemetry", "--validate", "--json", str(bad))
        assert r.returncode == 1
        assert "Traceback" not in r.stderr
        payload = json.loads(r.stdout)
        assert payload["schema_errors"]

    def test_truncated_jsonl_stream_still_loads(self, tmp_path):
        # a streaming process that died mid-write leaves a partial final
        # line; the intact events before it must still summarize
        p = tmp_path / "crash.jsonl"
        p.write_text(
            '{"ph": "X", "name": "a", "ts": 1, "dur": 2, '
            '"pid": 1, "tid": 1}\n'
            '{"ph": "X", "name": "b", "ts"'  # truncated mid-write
        )
        events = load_trace(str(p))
        assert [e["name"] for e in events] == ["a"]

    @pytest.mark.slow
    def test_thread_mode_demo_covers_acceptance(self, tmp_path):
        # acceptance criterion: a demo solve whose trace covers compile,
        # >= 1 readback window and message send/recv, with metrics
        # counters matching the run's actual traffic
        trace = str(tmp_path / "trace.json")
        metrics = str(tmp_path / "metrics.json")
        r = run_cli(
            "solve", "-a", "dsa", "-m", "thread", "-n", "5",
            "--trace-out", trace, "--metrics-out", metrics, INSTANCE,
            timeout=180,
        )
        assert r.returncode == 0, r.stderr
        events = json.load(open(trace))["traceEvents"]
        names = [e["name"] for e in events if e.get("ph") in ("X", "i")]
        assert "compile.compile_dcop" in names
        assert "solve.window" in names and "solve.readback" in names
        n_send = names.count("comms.send")
        n_recv = names.count("comms.recv")
        assert n_send > 0 and n_recv > 0
        m = json.load(open(metrics))["metrics"]

        def total(name):
            return sum(v["value"] for v in m[name]["values"])

        # counters match the run's actual traffic: every posted message
        # was delivered in-process (sent == received), and each one was
        # also recorded as a trace instant and a bus-bridge count
        assert total("comms.messages_sent") == total(
            "comms.messages_received"
        ) == n_send == n_recv
        assert total("comms.payload_bytes_sent") == total(
            "comms.payload_bytes_received"
        ) > 0
        assert total("computations.messages_sent") == n_send
