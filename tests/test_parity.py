"""Reference-parity harness (round-2 verdict item 6): run the ACTUAL
reference implementation from /root/reference and this framework on
identical instances, and assert solution-quality parity.

The reference is unseeded (thread-timing nondeterminism — SURVEY.md §4), so
parity is on FINAL QUALITY, not trajectories: for complete algorithms the
costs must be equal; for local search this framework's best-of-N-seeds must
be at least as good as the reference's run, within a small tolerance
(institutionalizing BASELINE.md's hand-run method; reference test analog
/root/reference/tests/api/test_api_solve.py:30-110).

The local-search asserts are TWO-SIDED (round-4 verdict item 10): besides
">= the reference's run" — which a degenerate reference run would make
vacuous — each instance also has an ABSOLUTE ceiling in CEILINGS below,
derived from its exact optimum (computed once with this framework's DPOP,
which the cross-solver fuzz suite pins against brute force) plus documented
slack.

Run with ``pytest -m parity``.
"""

import sys
import types
from unittest.mock import MagicMock

import numpy as np
import pytest

pytestmark = pytest.mark.parity

REF_ROOT = "/root/reference"

# (max violations, max cost) per instance.  Optima measured via DPOP on
# 2026-07-30 (deterministic: the instances are seeded): coloring10vars
# optimum = 1 violation / cost 0.0 (graph is not 2-colorable) — reached by
# maxsum/dsa/mgm best-of-seeds exactly; ising4x4 optimum -17.1555 and
# arity3 optimum 6.0 — both reached by mgm2 exactly (ceiling adds ~10-25%
# range slack for platform variation); gdba12 optimum 0.0777, gdba
# best-of-3 measures 0.1225 (breakout weights distort the landscape —
# ceiling 0.25 still rules out any degenerate outcome).
CEILINGS = {
    "coloring10vars": (1, 1e-6),
    "ising4x4": (0, -15.4),
    "arity3": (0, 7.5),
    "gdba12": (0, 0.25),
    # PEAV meeting scheduling with hard 4-ary all-equal constraints:
    # optimum 10.0 (DPOP), which our mgm2 best-of-6 reaches exactly (the
    # reference's unseeded runs land at 10.0 or 12.0) — the ceiling rules
    # out any leftover 100-point meeting penalty (binary-only
    # coordination used to land at 114)
    "meetings4": (0, 15.0),
}


def assert_ceiling(instance: str, cost: float, viol: int) -> None:
    max_viol, max_cost = CEILINGS[instance]
    assert viol <= max_viol, (instance, viol, max_viol)
    assert cost <= max_cost, (instance, cost, max_cost)


@pytest.fixture(scope="module")
def ref():
    """Import the reference with the py3.12 + missing-optional-deps shims
    (collections ABCs; websocket_server and pulp are unused on the solve
    paths exercised here but imported at module scope by the reference)."""
    import collections
    import collections.abc

    for n in (
        "Iterable", "Mapping", "Sequence", "Callable",
        "Hashable", "Sized", "Container", "Iterator",
    ):
        setattr(collections, n, getattr(collections.abc, n))
    ws = types.ModuleType("websocket_server")
    wsi = types.ModuleType("websocket_server.websocket_server")
    wsi.WebsocketServer = MagicMock()
    ws.websocket_server = wsi
    sys.modules.setdefault("websocket_server", ws)
    sys.modules.setdefault("websocket_server.websocket_server", wsi)
    sys.modules.setdefault("pulp", MagicMock())
    if REF_ROOT not in sys.path:
        sys.path.insert(0, REF_ROOT)
    mod = types.SimpleNamespace()
    from pydcop.dcop.dcop import solution_cost as ref_solution_cost
    from pydcop.dcop.relations import NAryMatrixRelation
    from pydcop.dcop.yamldcop import load_dcop_from_file as ref_load
    from pydcop.infrastructure.run import solve as ref_solve

    # numpy>=2 removed ndarray.itemset, which the reference's DPOP UTIL
    # message construction uses (relations.py:857) — patch the one method
    _orig_set = NAryMatrixRelation.set_value_for_assignment

    def _set_value(self, var_values, rel_value):
        if isinstance(var_values, dict):
            values = [var_values[v.name] for v in self._variables]
            _, s = self._slice_matrix(
                [v.name for v in self._variables], values
            )
            matrix = np.copy(self._m)
            matrix[s] = rel_value
            return NAryMatrixRelation(
                self._variables, matrix, name=self.name
            )
        return _orig_set(self, var_values, rel_value)

    NAryMatrixRelation.set_value_for_assignment = _set_value

    mod.load = ref_load
    mod.solve = ref_solve
    mod.solution_cost = ref_solution_cost
    return mod


def _ref_quality(ref, yaml_path, algo, timeout=15, distribution="adhoc"):
    # dpop: the reference's adhoc distribution needs computation_memory,
    # which its dpop module raises NotImplementedError for — use oneagent
    dcop = ref.load([yaml_path])
    assignment = ref.solve(dcop, algo, distribution, timeout=timeout)
    assert assignment, f"reference {algo} returned no assignment"
    viol, cost = ref.solution_cost(
        list(dcop.constraints.values()),
        list(dcop.variables.values()),
        assignment,
        10000,
    )
    return float(cost), int(viol)


def _our_quality(yaml_path, algo, n_cycles=80, seeds=(0, 1, 2), params=None):
    from pydcop_tpu.algorithms import AlgorithmDef
    from pydcop_tpu.api import solve_result
    from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

    best = (np.inf, np.inf)
    for seed in seeds:
        dcop = load_dcop_from_file([yaml_path])
        ad = (
            AlgorithmDef(algo, dict(params), mode="min") if params else algo
        )
        r = solve_result(dcop, ad, n_cycles=n_cycles, seed=seed)
        best = min(best, (r["violation"], r["cost"]))
    return best[1], best[0]  # (cost, violations)


def _write_instance(tmp_path_factory, dcop, name):
    from pydcop_tpu.dcop.yamldcop import dcop_yaml

    path = tmp_path_factory.mktemp("parity") / f"{name}.yaml"
    path.write_text(dcop_yaml(dcop))
    return str(path)


class TestParity:
    def test_maxsum_coloring(self, ref):
        path = f"{REF_ROOT}/tests/instances/graph_coloring_3agts_10vars.yaml"
        ref_cost, ref_viol = _ref_quality(ref, path, "maxsum")
        cost, viol = _our_quality(path, "maxsum")
        assert (viol, cost) <= (ref_viol, ref_cost + 1e-6)
        assert_ceiling("coloring10vars", cost, viol)

    def test_dsa_coloring(self, ref):
        path = f"{REF_ROOT}/tests/instances/graph_coloring_3agts_10vars.yaml"
        ref_cost, ref_viol = _ref_quality(ref, path, "dsa")
        cost, viol = _our_quality(path, "dsa", seeds=(0, 1, 2, 3))
        assert (viol, cost) <= (ref_viol, ref_cost + 1e-6)
        assert_ceiling("coloring10vars", cost, viol)

    def test_mgm2_ising_grid(self, ref, tmp_path_factory):
        # round-2 weak item 3: MGM-2 coordination coverage on an Ising grid
        # (parallel unary+binary structure) measured head-to-head
        from pydcop_tpu.commands.generators.ising import generate_ising

        dcop = generate_ising(4, 4, seed=3)
        path = _write_instance(tmp_path_factory, dcop, "ising4x4")
        ref_cost, ref_viol = _ref_quality(ref, path, "mgm2", timeout=20)
        cost, viol = _our_quality(path, "mgm2", n_cycles=100)
        # ising is min-form with negative costs; parity = at least as good,
        # within 5% of the cost RANGE as float tolerance
        tol = 0.05 * max(1.0, abs(ref_cost))
        assert viol <= ref_viol
        assert cost <= ref_cost + tol
        assert_ceiling("ising4x4", cost, viol)

    def test_mgm2_arity3(self, ref, tmp_path_factory):
        # round-2 weak item 3, arity>2 side: pairs coupled through ternary
        # constraints fall back to unilateral moves; quality must still
        # match the reference's mgm2 on the same instance
        from pydcop_tpu.dcop.dcop import DCOP
        from pydcop_tpu.dcop.objects import (
            AgentDef,
            Domain,
            Variable,
        )
        from pydcop_tpu.dcop.relations import constraint_from_str

        rng = np.random.default_rng(5)
        d = Domain("d", "", [0, 1, 2])
        vs = [Variable(f"v{i}", d) for i in range(9)]
        dcop = DCOP("arity3")
        for k in range(7):
            i, j, l = rng.choice(9, size=3, replace=False)
            coeffs = rng.integers(0, 9, size=27)
            expr = (
                f"[{','.join(map(str, coeffs))}]"
                f"[v{i}*9 + v{j}*3 + v{l}]"
            )
            dcop += constraint_from_str(
                f"c{k}", expr, [vs[i], vs[j], vs[l]]
            )
        dcop.add_agents([AgentDef(f"a{i}") for i in range(9)])
        path = _write_instance(tmp_path_factory, dcop, "arity3")
        ref_cost, ref_viol = _ref_quality(ref, path, "mgm2", timeout=20)
        cost, viol = _our_quality(path, "mgm2", n_cycles=100)
        tol = 0.05 * max(1.0, abs(ref_cost))
        assert viol <= ref_viol
        assert cost <= ref_cost + tol
        assert_ceiling("arity3", cost, viol)

    def test_mgm2_meeting_scheduling_arity4(self, ref, tmp_path_factory):
        # round-4 verdict item 6: higher-arity coordination quality where
        # binary-only pair moves are most likely to bite — PEAV meeting
        # scheduling, hard 4-ary all-equal constraint per meeting, slot
        # preferences, binary exclusion for shared participants.  The
        # reference coordinates pairs over any shared constraint
        # (ref mgm2.py:399); ours over per-cycle sliced 4-ary tables.
        from pydcop_tpu.dcop.dcop import DCOP
        from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
        from pydcop_tpu.dcop.relations import constraint_from_str

        rng = np.random.default_rng(3)
        slots = Domain("slots", "", list(range(5)))
        meetings = [rng.choice(6, size=4, replace=False) for _ in range(3)]
        dcop = DCOP("meetings4")
        vars_by = {}
        for m, parts in enumerate(meetings):
            for p in parts:
                v = Variable(f"m{m}_p{p}", slots)
                vars_by[(m, int(p))] = v
                prefs = rng.integers(0, 4, size=5)
                dcop += constraint_from_str(
                    f"pref_m{m}_p{p}",
                    f"[{','.join(map(str, prefs))}][{v.name}]",
                    [v],
                )
        for m, parts in enumerate(meetings):
            vs = [vars_by[(m, int(p))] for p in parts]
            names = [v.name for v in vs]
            cond = " and ".join(f"{names[0]} == {n}" for n in names[1:])
            dcop += constraint_from_str(
                f"meet_m{m}", f"0 if ({cond}) else 100", vs
            )
        for (m1, p1), v1 in vars_by.items():
            for (m2, p2), v2 in vars_by.items():
                if p1 == p2 and m1 < m2:
                    dcop += constraint_from_str(
                        f"ex_p{p1}_m{m1}m{m2}",
                        f"100 if {v1.name} == {v2.name} else 0",
                        [v1, v2],
                    )
        dcop.add_agents([AgentDef(f"a{i}") for i in range(6)])
        path = _write_instance(tmp_path_factory, dcop, "meetings4")
        ref_cost, ref_viol = _ref_quality(ref, path, "mgm2", timeout=20)
        # best-of-6 seeds reaches the exact optimum 10.0 (seed 5), so the
        # one-sided assert cannot lose to a lucky unseeded reference run
        # (its observed outcomes on this instance: 10.0 and 12.0)
        cost, viol = _our_quality(
            path, "mgm2", n_cycles=150, seeds=tuple(range(6))
        )
        tol = 0.05 * max(1.0, abs(ref_cost))
        assert viol <= ref_viol
        assert cost <= ref_cost + tol
        assert_ceiling("meetings4", cost, viol)

    def test_dpop_exact_equality(self, ref, tmp_path_factory):
        # complete algorithm: equal optimal cost, no tolerance
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_graph_coloring,
        )

        dcop = generate_graph_coloring(
            10, 3, graph="random", p_edge=0.25, seed=2, n_agents=10
        )
        path = _write_instance(tmp_path_factory, dcop, "coloring10")
        ref_cost, ref_viol = _ref_quality(
            ref, path, "dpop", timeout=20, distribution="oneagent"
        )
        cost, viol = _our_quality(path, "dpop", n_cycles=1, seeds=(0,))
        assert viol == ref_viol
        assert cost == pytest.approx(ref_cost, abs=1e-5)

    def _secp_instance(self, tmp_path_factory):
        from pydcop_tpu.commands.generators.secp import generate_secp

        dcop = generate_secp(
            lights=6, models=3, rules=3, capacity=1000, seed=4
        )
        return dcop, _write_instance(
            tmp_path_factory, dcop, "secp_dist"
        )

    @staticmethod
    def _as_sets(mapping):
        return {
            a: frozenset(cs) for a, cs in mapping.items() if cs
        }

    def test_gh_secp_cgdp_placement_parity(self, ref, tmp_path_factory):
        # round-3 verdict item 7: the greedy SECP placements must MATCH the
        # reference's actuator-affinity heuristic agent for agent — both
        # sides run on the same instance with the same footprint function
        from pydcop.computations_graph import constraints_hypergraph as rch
        from pydcop.distribution import gh_secp_cgdp as ref_dist

        from pydcop_tpu.computations_graph import (
            constraints_hypergraph as och,
        )
        from pydcop_tpu.dcop.yamldcop import load_dcop_from_file
        from pydcop_tpu.distribution import gh_secp_cgdp as our_dist

        _, path = self._secp_instance(tmp_path_factory)
        mem = lambda node: 10.0  # noqa: E731 — same footprint both sides

        ref_dcop = ref.load([path])
        ref_graph = rch.build_computation_graph(ref_dcop)
        ref_mapping = ref_dist.distribute(
            ref_graph, ref_dcop.agents.values(), computation_memory=mem
        ).mapping()

        our_dcop = load_dcop_from_file([path])
        our_graph = och.build_computation_graph(our_dcop)
        ours = our_dist.distribute(
            our_graph, our_dcop.agents.values(), computation_memory=mem
        ).mapping

        assert self._as_sets(ours) == self._as_sets(ref_mapping)

    def test_gh_secp_fgdp_placement_parity(self, ref, tmp_path_factory):
        from pydcop.computations_graph import factor_graph as rfg
        from pydcop.distribution import gh_secp_fgdp as ref_dist

        from pydcop_tpu.computations_graph import factor_graph as ofg
        from pydcop_tpu.dcop.yamldcop import load_dcop_from_file
        from pydcop_tpu.distribution import gh_secp_fgdp as our_dist

        _, path = self._secp_instance(tmp_path_factory)
        mem = lambda node: 10.0  # noqa: E731

        ref_dcop = ref.load([path])
        ref_graph = rfg.build_computation_graph(ref_dcop)
        ref_mapping = ref_dist.distribute(
            ref_graph, ref_dcop.agents.values(), computation_memory=mem
        ).mapping()

        our_dcop = load_dcop_from_file([path])
        our_graph = ofg.build_computation_graph(our_dcop)
        ours = our_dist.distribute(
            our_graph, our_dcop.agents.values(), computation_memory=mem
        ).mapping

        assert self._as_sets(ours) == self._as_sets(ref_mapping)

    def test_gdba_coloring(self, ref, tmp_path_factory):
        # breakout family head-to-head on a soft-colored random graph
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_graph_coloring,
        )

        dcop = generate_graph_coloring(
            12, 3, graph="random", p_edge=0.3, seed=6, n_agents=12,
            soft=True,
        )
        path = _write_instance(tmp_path_factory, dcop, "gdba12")
        # oneagent: the reference's gdba computation_memory crashes under
        # adhoc (its neighbor-link arithmetic, gdba.py:95)
        ref_cost, ref_viol = _ref_quality(
            ref, path, "gdba", timeout=20, distribution="oneagent"
        )
        cost, viol = _our_quality(path, "gdba", n_cycles=100)
        tol = 0.05 * max(1.0, abs(ref_cost))
        assert viol <= ref_viol
        assert cost <= ref_cost + tol
        assert_ceiling("gdba12", cost, viol)

    def test_mgm_coloring(self, ref):
        path = f"{REF_ROOT}/tests/instances/graph_coloring_3agts_10vars.yaml"
        ref_cost, ref_viol = _ref_quality(ref, path, "mgm")
        cost, viol = _our_quality(path, "mgm", seeds=(0, 1, 2, 3))
        assert (viol, cost) <= (ref_viol, ref_cost + 1e-6)
        assert_ceiling("coloring10vars", cost, viol)
