"""graftslo: objective grammar, burn-rate engine, exemplar histograms,
OpenMetrics round-trip, the serve request lifecycle (trace ids, phase
metrics, chaos-delay determinism) and mid-batch scrape consistency
(pydcop_tpu/telemetry/slo.py, docs/observability.md)."""

import json
import os
import threading
import time
import urllib.request

import pytest

from pydcop_tpu.telemetry import telemetry_off
from pydcop_tpu.telemetry.metrics import metrics_registry
from pydcop_tpu.telemetry.prom import (
    parse_prometheus_text,
    render_prometheus,
)
from pydcop_tpu.telemetry.pulse import load_postmortem, render_postmortem
from pydcop_tpu.telemetry.slo import (
    Objective,
    SloEngine,
    load_slo_file,
    parse_objective,
)
from pydcop_tpu.telemetry.tracing import tracer


@pytest.fixture(autouse=True)
def _telemetry_reset():
    yield
    telemetry_off()


# ---------------------------------------------------------------------------
# objective grammar
# ---------------------------------------------------------------------------


class TestObjectiveGrammar:
    def test_latency_spec(self):
        o = parse_objective("p99<250ms")
        assert o.kind == "latency"
        assert o.target == pytest.approx(0.99)
        assert o.threshold_s == pytest.approx(0.25)
        assert o.window_s == 3600.0
        assert o.name == "p99_latency"

    def test_latency_seconds_and_window(self):
        o = parse_objective("p95<=2s@30m")
        assert o.target == pytest.approx(0.95)
        assert o.threshold_s == pytest.approx(2.0)
        assert o.window_s == 1800.0

    def test_named_objective(self):
        o = parse_objective("lat=p99<500ms@2h")
        assert o.name == "lat"
        assert o.window_s == 7200.0

    def test_availability_percent_and_fraction(self):
        assert parse_objective(
            "availability>=99.9%"
        ).target == pytest.approx(0.999)
        assert parse_objective(
            "availability>=0.95"
        ).target == pytest.approx(0.95)

    def test_dead_letter_rate(self):
        o = parse_objective("dead_letter_rate<=0.5%")
        assert o.kind == "dead_letters"
        assert o.budget == pytest.approx(0.005)

    @pytest.mark.parametrize("bad", [
        "p99", "latency<1s", "p99<", "availability>=150%", "p0<1s",
        "p100<1s", "p99<1s@", "nonsense",
    ])
    def test_bad_specs_rejected(self, bad):
        with pytest.raises(ValueError):
            parse_objective(bad)

    def test_objective_validation(self):
        with pytest.raises(ValueError):
            Objective("x", "latency", target=0.99, threshold_s=0.0)
        with pytest.raises(ValueError):
            Objective("x", "availability", target=1.0)
        with pytest.raises(ValueError):
            Objective("x", "weird", target=0.9)

    def test_classification(self):
        lat = parse_objective("p99<100ms")
        assert lat.is_good("done", 0.05, False)
        assert not lat.is_good("done", 0.2, False)
        assert not lat.is_good("failed", 0.01, True)
        avail = parse_objective("availability>=99%")
        assert avail.is_good("done", 99.0, False)
        assert not avail.is_good("killed", 0.0, True)
        dl = parse_objective("dead_letter_rate<=1%")
        assert dl.is_good("done", 0.0, False)
        assert not dl.is_good("killed", 0.0, True)

    def test_yaml_file(self, tmp_path):
        p = tmp_path / "slo.yaml"
        p.write_text(
            "objectives:\n"
            "  - p99<250ms\n"
            "  - name: avail\n"
            "    kind: availability\n"
            "    target: 0.999\n"
            "    window_s: 600\n"
            "fast_burn: 10\n"
            "eval_interval_s: 0.5\n"
        )
        objectives, options = load_slo_file(str(p))
        assert [o.name for o in objectives] == ["p99_latency", "avail"]
        assert objectives[1].window_s == 600.0
        assert options == {"fast_burn": 10.0, "eval_interval_s": 0.5}

    def test_yaml_rejects_non_mapping(self, tmp_path):
        p = tmp_path / "bad.yaml"
        p.write_text("- just\n- a list\n")
        with pytest.raises(ValueError):
            load_slo_file(str(p))

    def test_duplicate_names_rejected(self):
        with pytest.raises(ValueError):
            SloEngine([parse_objective("p99<1s"),
                       parse_objective("p99<2s")])


# ---------------------------------------------------------------------------
# the burn engine (driven by a fake clock: fully deterministic)
# ---------------------------------------------------------------------------


def _engine(tmp_path, specs=("p99<100ms", "availability>=99%"), **kw):
    t = [0.0]
    eng = SloEngine(
        [parse_objective(s) for s in specs],
        clock=lambda: t[0],
        postmortem_path=str(tmp_path / "slo_pm.json"),
        **kw,
    )
    return eng, t


class TestPostmortemDefaultPath:
    """The default postmortem path must land in the bench state dir,
    NEVER the cwd — a bare SloEngine used to litter (and get committed
    as) a root-level slo_postmortem.json (PRs 17–18)."""

    def test_default_routes_into_state_dir_env(self, monkeypatch, tmp_path):
        monkeypatch.setenv("PYDCOP_TPU_STATE_DIR", str(tmp_path / "state"))
        eng = SloEngine([parse_objective("availability>=99%")])
        assert eng.postmortem_path == str(
            tmp_path / "state" / "slo_postmortem.json"
        )

    def test_default_without_env_is_bench_state_not_cwd(self, monkeypatch):
        monkeypatch.delenv("PYDCOP_TPU_STATE_DIR", raising=False)
        eng = SloEngine([parse_objective("availability>=99%")])
        assert eng.postmortem_path == os.path.join(
            ".bench_state", "slo_postmortem.json"
        )

    def test_explicit_path_still_wins(self, tmp_path):
        eng = SloEngine(
            [parse_objective("availability>=99%")],
            postmortem_path=str(tmp_path / "pm.json"),
        )
        assert eng.postmortem_path == str(tmp_path / "pm.json")


class TestBurnEngine:
    def test_quiet_traffic_no_alerts_full_budget(self, tmp_path):
        metrics_registry.enabled = True
        eng, t = _engine(tmp_path)
        for i in range(40):
            eng.record_request(f"t{i}", "done", 0.01, trace=f"tr{i}")
        t[0] = 5.0
        eng.evaluate()
        assert eng.alerts_active() == []
        assert eng.transitions == []
        rep = eng.report()
        for ob in rep["objectives"]:
            assert ob["bad"] == 0
            assert ob["budget_remaining"] == pytest.approx(1.0)

    def test_fast_burn_fires_and_resolves(self, tmp_path):
        metrics_registry.enabled = True
        eng, t = _engine(tmp_path)
        for i in range(10):
            eng.record_request(f"ok{i}", "done", 0.01)
        t[0] = 1.0
        eng.evaluate()
        assert eng.alerts_active() == []
        for i in range(10):
            eng.record_request(f"slow{i}", "done", 0.5)
        t[0] = 2.0
        eng.evaluate()
        active = eng.alerts_active()
        assert ("p99_latency", "fast") in active
        # availability saw only 'done' requests: silent
        assert not any(o == "availability" for o, _ in active)
        # long after the burst slid out of every alert window, with
        # fresh healthy traffic, the alert resolves
        t[0] = 500.0
        eng.evaluate()
        for i in range(20):
            eng.record_request(f"again{i}", "done", 0.01)
        t[0] = 501.0
        eng.evaluate()
        assert eng.alerts_active() == []
        states = [
            (x["objective"], x["severity"], x["state"])
            for x in eng.transitions
        ]
        assert states[0] == ("p99_latency", "fast", "firing")
        assert ("p99_latency", "fast", "resolved") in states

    def test_slo_metrics_published(self, tmp_path):
        metrics_registry.enabled = True
        eng, t = _engine(tmp_path)
        eng.record_request("a", "done", 0.01)
        eng.record_request("b", "failed", 0.01, dead_letter=True)
        t[0] = 1.0
        eng.evaluate()
        snap = metrics_registry.snapshot()["metrics"]
        assert "slo.events" in snap
        assert "slo.burn_rate" in snap
        assert "slo.error_budget_remaining" in snap
        # four burn windows per objective
        windows = {
            (v["labels"]["objective"], v["labels"]["window"])
            for v in snap["slo.burn_rate"]["values"]
        }
        assert windows == {
            (obj, w)
            for obj in ("p99_latency", "availability")
            for w in ("fast_long", "fast_short", "slow_long", "slow_short")
        }

    def test_budget_consumption_counted(self, tmp_path):
        metrics_registry.enabled = True
        eng, t = _engine(tmp_path, specs=("availability>=90%@100s",))
        for i in range(8):
            eng.record_request(f"ok{i}", "done", 0.0)
        for i in range(2):
            eng.record_request(f"bad{i}", "failed", 0.0, dead_letter=True)
        t[0] = 100.0  # a full window elapsed
        eng.evaluate()
        rep = eng.report()
        (ob,) = rep["objectives"]
        # 20% bad on a 10% budget over the whole window: budget is gone
        assert ob["budget_remaining"] <= 0.0

    def test_postmortem_written_once_and_renders(self, tmp_path):
        metrics_registry.enabled = True
        eng, t = _engine(tmp_path)
        for i in range(10):
            eng.record_request(f"s{i}", "done", 0.5, trace=f"tr{i}")
        t[0] = 1.0
        eng.evaluate()
        pm = tmp_path / "slo_pm.json"
        assert pm.exists()
        doc = load_postmortem(str(pm))
        assert doc["reason"] == "slo-alert:p99_latency"
        assert doc["slo"]["objective"] == "p99_latency"
        assert doc["slo"]["bad_requests"], "bad requests missing"
        assert doc["slo"]["bad_requests"][0]["trace"].startswith("tr")
        rendered = render_postmortem(doc)
        assert "slo violated: p99_latency" in rendered
        assert "trace=tr" in rendered
        # the dump is once-per-objective: wipe it, re-evaluate, still gone
        pm.unlink()
        t[0] = 1.5
        eng.evaluate()
        assert not pm.exists()

    def test_background_thread_lifecycle(self, tmp_path):
        metrics_registry.enabled = True
        eng = SloEngine(
            [parse_objective("availability>=99%")],
            eval_interval_s=0.05,
            postmortem_path=str(tmp_path / "pm.json"),
        )
        eng.start()
        eng.start()  # idempotent
        eng.record_request("a", "done", 0.01)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if eng.report()["objectives"][0]["good"] == 1:
                break
            time.sleep(0.02)
        eng.stop()
        assert eng.report()["objectives"][0]["good"] == 1

    def test_phase_percentiles(self, tmp_path):
        eng, _t = _engine(tmp_path)
        for i in range(10):
            eng.record_request(
                f"t{i}", "done", 0.01 * (i + 1),
                phases={"queue": 0.001 * (i + 1), "solve": 0.002},
            )
        pct = eng.phase_percentiles()
        assert pct["request"]["p50"] == pytest.approx(0.05, abs=0.02)
        assert pct["queue"]["p99"] == pytest.approx(0.01, abs=0.005)
        assert "solve" in pct


# ---------------------------------------------------------------------------
# exemplars + OpenMetrics round-trip (satellite: prom.py)
# ---------------------------------------------------------------------------


class TestOpenMetrics:
    def _snapshot(self):
        metrics_registry.enabled = True
        metrics_registry.counter("om.requests", "reqs").inc(3, agent="a1")
        metrics_registry.gauge("om.depth").set(2.5)
        h = metrics_registry.histogram(
            "om.lat_seconds", "lat", buckets=(0.1, 1.0)
        )
        h.observe(0.05, exemplar_="trace-a")
        h.observe(0.5, exemplar_="trace-b")
        h.observe(5.0)
        return metrics_registry.snapshot()

    def test_exemplar_stored_last_wins(self):
        metrics_registry.enabled = True
        h = metrics_registry.histogram(
            "om.ex_seconds", "x", buckets=(1.0,)
        )
        h.observe(0.5, exemplar_="first")
        h.observe(0.6, exemplar_="second")
        (entry,) = h.snapshot()["values"]
        assert entry["value"]["exemplars"]["0"]["trace_id"] == "second"
        assert entry["value"]["exemplars"]["0"]["value"] == 0.6

    def test_classic_output_has_no_exemplars_or_eof(self):
        text = render_prometheus(self._snapshot())
        assert "# EOF" not in text
        assert "trace-a" not in text
        assert "# TYPE om_requests_total counter" in text

    def test_openmetrics_output(self):
        text = render_prometheus(self._snapshot(), openmetrics=True)
        assert text.rstrip().endswith("# EOF")
        # counter FAMILY drops _total, the sample keeps it
        assert "# TYPE om_requests counter" in text
        assert 'om_requests_total{agent="a1"} 3' in text
        assert '# {trace_id="trace-a"} 0.05' in text

    def test_round_trip_classic(self):
        snap = self._snapshot()
        parsed = parse_prometheus_text(render_prometheus(snap))
        assert not parsed["eof"]
        by_name = {}
        for s in parsed["samples"]:
            by_name.setdefault(s["name"], []).append(s)
        assert by_name["om_requests_total"][0]["value"] == 3.0
        assert by_name["om_depth"][0]["value"] == 2.5
        buckets = {
            s["labels"]["le"]: s["value"]
            for s in by_name["om_lat_seconds_bucket"]
        }
        assert buckets == {"0.1": 1.0, "1": 2.0, "+Inf": 3.0}
        assert by_name["om_lat_seconds_count"][0]["value"] == 3.0
        assert by_name["om_lat_seconds_sum"][0]["value"] == pytest.approx(
            5.55
        )

    def test_round_trip_openmetrics_exemplars(self):
        snap = self._snapshot()
        parsed = parse_prometheus_text(
            render_prometheus(snap, openmetrics=True)
        )
        assert parsed["eof"]
        assert parsed["types"]["om_requests"] == "counter"
        ex = {
            s["labels"]["le"]: s["exemplar"]
            for s in parsed["samples"]
            if s["name"] == "om_lat_seconds_bucket" and s["exemplar"]
        }
        assert ex["0.1"]["labels"]["trace_id"] == "trace-a"
        assert ex["0.1"]["value"] == pytest.approx(0.05)
        assert ex["1"]["labels"]["trace_id"] == "trace-b"
        # values identical to the classic rendering: the format changes,
        # the series must not
        classic = parse_prometheus_text(render_prometheus(snap))
        def values(p):
            return sorted(
                (s["name"], tuple(sorted(s["labels"].items())), s["value"])
                for s in p["samples"]
            )
        assert values(parsed) == values(classic)

    def test_label_escapes_round_trip(self):
        snap = {
            "metrics": {
                "esc.gauge": {
                    "kind": "gauge",
                    "help": "",
                    "values": [
                        {"labels": {"k": 'a"b\\c\nd'}, "value": 1.0}
                    ],
                }
            }
        }
        for om in (False, True):
            parsed = parse_prometheus_text(
                render_prometheus(snap, openmetrics=om)
            )
            (s,) = parsed["samples"]
            assert s["labels"]["k"] == 'a"b\\c\nd'

    @pytest.mark.parametrize(
        "om", (False, True), ids=("classic", "openmetrics")
    )
    @pytest.mark.parametrize(
        "value",
        (
            "back\\slash",
            "trailing\\",
            "\\\\leading_double",
            "new\nline",
            "\n",
            'embedded"quote',
            '"',
            "literal\\n stays two chars",
            '\\"escaped-quote-literal',
            'every "kind"\\of\nescape\\n at once',
        ),
        ids=(
            "backslash", "trailing-backslash", "double-backslash",
            "newline", "bare-newline", "quote", "bare-quote",
            "literal-backslash-n", "backslash-quote", "combined",
        ),
    )
    def test_label_value_escape_round_trip(self, value, om):
        """Every escape class the exposition format defines survives a
        render -> parse round-trip byte-for-byte, in both formats, on
        counters (name gains _total) and gauges alike."""
        snap = {
            "metrics": {
                "esc.count": {
                    "kind": "counter",
                    "help": "c",
                    "values": [
                        {"labels": {"k": value, "other": "plain"},
                         "value": 2.0},
                    ],
                },
                "esc.gauge": {
                    "kind": "gauge",
                    "help": "g",
                    "values": [{"labels": {"k": value}, "value": 1.0}],
                },
            }
        }
        text = render_prometheus(snap, openmetrics=om)
        # the rendered text itself must stay line-oriented: a raw
        # newline inside a label value would fork the sample line
        for line in text.splitlines():
            if line.startswith("esc_"):
                assert line.count('"') % 2 == 0 or "\\" in line
        parsed = parse_prometheus_text(text)
        by_name = {s["name"]: s for s in parsed["samples"]}
        assert by_name["esc_count_total"]["labels"]["k"] == value
        assert by_name["esc_count_total"]["labels"]["other"] == "plain"
        assert by_name["esc_count_total"]["value"] == 2.0
        assert by_name["esc_gauge"]["labels"]["k"] == value
        assert parsed["eof"] is om

    def test_watch_renders_slo_line(self):
        # the watch verb's burn-rate/budget line (host-only render)
        from pydcop_tpu.commands.watch import _render_frame

        status = {
            "status": "serve",
            "slo": {
                "objectives": {
                    "p99_latency": {
                        "describe": "p99 latency <= 250 ms",
                        "good": 90, "bad": 10,
                        "budget_remaining": 0.42,
                        "burn_fast": 18.7,
                        "alert": "fast",
                    },
                    "availability": {
                        "describe": "availability >= 99.9%",
                        "good": 100, "bad": 0,
                        "budget_remaining": 1.0,
                        "burn_fast": 0.0,
                        "alert": None,
                    },
                },
                "transitions": 1,
            },
        }
        frame = _render_frame(status, {}, {})
        assert "slo: p99_latency" in frame
        assert "ALERT[fast]" in frame
        assert "42.0%" in frame
        assert "slo: availability" in frame
        assert "ALERT" not in frame.split("availability")[1].split("\n")[0]

    def test_histogram_snapshot_is_deep_copied(self):
        metrics_registry.enabled = True
        h = metrics_registry.histogram("om.deep", "x", buckets=(1.0,))
        h.observe(0.5)
        snap = h.snapshot()
        h.observe(0.6)
        h.observe(2.0)
        # the earlier snapshot must not have moved
        (entry,) = snap["values"]
        assert entry["value"]["count"] == 1
        assert entry["value"]["buckets"] == [1, 0]
