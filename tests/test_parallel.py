"""Mesh sharding: padding neutrality + sharded-step equivalence.

Runs on the virtual 8-device CPU mesh (conftest.py), per SURVEY.md §4's
"fake mesh" strategy.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pydcop_tpu.commands.generators.graphcoloring import (
    generate_coloring_arrays,
    generate_graph_coloring,
)
from pydcop_tpu.compile.core import compile_dcop
from pydcop_tpu.compile.kernels import (
    evaluate,
    factor_step,
    local_costs,
    select_values,
    to_device,
    variable_step,
)
from pydcop_tpu.parallel.mesh import (
    AXIS,
    make_mesh,
    pad_device_dcop,
    shard_device_dcop,
)


@pytest.fixture(scope="module")
def problem():
    return generate_coloring_arrays(
        50, 3, graph="scalefree", m_edge=2, seed=3
    )


def _run_steps(dev, n_edges, n_steps=4):
    v2f = jnp.zeros((n_edges, dev.max_domain), dtype=dev.unary.dtype)
    f2v = jnp.zeros_like(v2f)
    for _ in range(n_steps):
        f2v = factor_step(dev, v2f)
        v2f = variable_step(dev, f2v, damping=0.5, prev_v2f=v2f)
    return select_values(dev, f2v)


def test_padding_is_cost_neutral(problem):
    dev = to_device(problem)
    padded = pad_device_dcop(dev, 8)
    assert padded.n_edges % 8 == 0
    assert padded.n_vars % 8 == 0
    for b in padded.buckets:
        assert b.tables_flat.shape[0] % 8 == 0

    vals = jnp.zeros(dev.n_vars, dtype=jnp.int32)
    vals_p = jnp.zeros(padded.n_vars, dtype=jnp.int32)
    np.testing.assert_allclose(
        float(evaluate(dev, vals)), float(evaluate(padded, vals_p)), rtol=1e-6
    )
    # local costs on real variables unchanged
    lc = local_costs(dev, vals)
    lc_p = local_costs(padded, vals_p)[: dev.n_vars]
    np.testing.assert_allclose(np.asarray(lc), np.asarray(lc_p), rtol=1e-5)


def test_padded_maxsum_matches_unpadded(problem):
    dev = to_device(problem)
    padded = pad_device_dcop(dev, 8)
    vals = _run_steps(dev, dev.n_edges)
    vals_p = _run_steps(padded, padded.n_edges)[: dev.n_vars]
    np.testing.assert_array_equal(np.asarray(vals), np.asarray(vals_p))


def test_sharded_step_matches_single_device(problem):
    dev = to_device(problem)
    ref_vals = _run_steps(dev, dev.n_edges)

    mesh = make_mesh(8)
    padded = pad_device_dcop(dev, mesh.size)
    sharded = shard_device_dcop(padded, mesh)
    vals = _run_steps(sharded, sharded.n_edges)[: dev.n_vars]
    np.testing.assert_array_equal(np.asarray(ref_vals), np.asarray(vals))


class TestPlacement:
    """Placement-aware layout (parallel/placement.py): the TPU analog of the
    reference's communication-minimizing distribution (oilp_cgdp objective).
    """

    def _ising(self):
        from pydcop_tpu.commands.generators.ising import generate_ising_arrays

        return generate_ising_arrays(16, 16, seed=2)

    def test_bfs_order_is_permutation(self):
        from pydcop_tpu.parallel.placement import bfs_order

        c = self._ising()
        order = bfs_order(c)
        assert np.array_equal(np.sort(order), np.arange(c.n_vars))

    def test_reorder_preserves_semantics(self):
        from pydcop_tpu.algorithms import maxsum
        from pydcop_tpu.parallel.placement import partition_compiled

        c = generate_coloring_arrays(36, 3, graph="grid", seed=4)
        r = partition_compiled(c)
        assert sorted(r.var_names) == sorted(c.var_names)
        # identical global cost for the same NAMED assignment
        a = {n: c.domains[i].values[0] for i, n in enumerate(c.var_names)}
        cost_c, _ = c.host_cost(c.indices_from_assignment(a))
        cost_r, _ = r.host_cost(r.indices_from_assignment(a))
        assert cost_c == pytest.approx(cost_r)
        # deterministic solver, noise off: identical named assignment
        params = {"noise": 0.0, "stop_cycle": 8}
        res_c = maxsum.solve(c, dict(params), n_cycles=8, seed=0)
        res_r = maxsum.solve(r, dict(params), n_cycles=8, seed=0)
        assert res_c.assignment == res_r.assignment

    def test_partition_reduces_cross_shard_edges_on_grid(self):
        from pydcop_tpu.parallel.placement import (
            cross_shard_edges,
            partition_compiled,
        )

        c = self._ising()  # ising generator numbers vars row-major already;
        # shuffle to a blind layout first to model an arbitrary ordering
        from pydcop_tpu.parallel.placement import reorder_compiled

        rng = np.random.default_rng(0)
        blind = reorder_compiled(c, rng.permutation(c.n_vars))
        placed = partition_compiled(blind)
        before = cross_shard_edges(blind, 8)
        after = cross_shard_edges(placed, 8)
        assert after < before / 2, (before, after)

    def test_partitioned_sharded_solve_matches(self):
        from pydcop_tpu.algorithms import maxsum
        from pydcop_tpu.parallel.placement import partition_compiled

        c = generate_coloring_arrays(64, 3, graph="scalefree", m_edge=2, seed=5)
        placed = partition_compiled(c)
        mesh = make_mesh(8)
        sharded = shard_device_dcop(
            pad_device_dcop(to_device(placed), mesh.size), mesh
        )
        # noise off: row-indexed noise would differ across layouts.
        # layout pinned: this test isolates SHARDING identity, and the
        # auto default resolves differently on sharded (lanes fallback)
        # vs unsharded (ell) devices
        params = {"noise": 0.0, "stop_cycle": 10, "layout": "lanes"}
        res_single = maxsum.solve(c, dict(params), n_cycles=10, seed=0)
        res_sharded = maxsum.solve(
            placed, dict(params), n_cycles=10, seed=0, dev=sharded
        )
        assert res_sharded.assignment == res_single.assignment
        assert res_sharded.cost == pytest.approx(res_single.cost, rel=1e-4)
        assert res_sharded.violations == res_single.violations


@pytest.mark.slow
def test_two_process_dcn_solve_matches_single_process():
    """Round-2 verdict item 5: a REAL multi-process sharded solve — two OS
    processes join one mesh via jax.distributed (the DCN path; Gloo
    collectives on CPU), each holding 4 of the 8 devices, and the solve
    result must equal the single-process run exactly."""
    import os
    import socket
    import subprocess
    import sys

    from pydcop_tpu.algorithms import maxsum

    # single-process reference result (this process, virtual 8-device mesh)
    compiled = generate_coloring_arrays(
        64, 3, graph="scalefree", m_edge=2, seed=5
    )
    ref = maxsum.solve(
        compiled,
        {"noise": 0.0, "stop_cycle": 10, "layout": "lanes"},
        n_cycles=10, seed=0,
    )

    with socket.socket() as s:  # free port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    # a bare PYTHONPATH: the axon TPU plugin (sitecustomize) must not load
    # in the workers — jax.distributed would probe its backend and hang
    # whenever the TPU relay is down
    env["PYTHONPATH"] = repo_root
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    worker = os.path.join(repo_root, "tests", "dist_worker.py")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(port), str(i), "2"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=240)[0] for p in procs]
    results = {}
    dpop_results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("DISTRESULT"):
                _, pid, cost, viol, vals = line.split(" ", 4)
                results[int(pid)] = (float(cost), int(viol), vals)
            elif line.startswith("DPOPRESULT"):
                _, pid, cost, vals = line.split(" ", 3)
                dpop_results[int(pid)] = (float(cost), vals)
    assert set(results) == {0, 1}, outs
    ref_vals = ",".join(str(ref.assignment[n]) for n in sorted(ref.assignment))
    for pid in (0, 1):
        cost, viol, vals = results[pid]
        assert cost == pytest.approx(ref.cost, rel=1e-5)
        assert viol == ref.violations
        assert vals == ref_vals

    # the mesh-sharded DPOP ran across both processes: identical exact
    # result on each, equal to this process's single-device solve
    assert set(dpop_results) == {0, 1}, outs
    from pydcop_tpu.algorithms import dpop
    from pydcop_tpu.compile.direct import compile_from_edges

    rng = np.random.default_rng(3)
    n = 200
    parents = np.array(
        [rng.integers(max(0, i - 4), i) for i in range(1, n)]
    )
    edges = np.stack([parents, np.arange(1, n)], axis=1)
    tables = rng.uniform(0, 10, size=(len(edges), 3, 3)).astype(np.float32)
    ref_dpop = dpop.solve(compile_from_edges(n, 3, edges, tables), {})
    ref_dvals = ",".join(
        str(ref_dpop.assignment[k]) for k in sorted(ref_dpop.assignment)
    )
    for pid in (0, 1):
        cost, vals = dpop_results[pid]
        assert cost == pytest.approx(ref_dpop.cost, rel=1e-5)
        assert vals == ref_dvals


class TestDpopMesh:
    """DPOP's UTIL joints partitioned over the mesh (round-3 verdict item
    3): the separator-hypercube axis is sharded, the own-value reduction
    stays device-local, and the result must match single-device exactly."""

    def _tree_problem(self, n, seed=3, span=4):
        from pydcop_tpu.compile.direct import compile_from_edges

        rng = np.random.default_rng(seed)
        parents = np.array(
            [rng.integers(max(0, i - span), i) for i in range(1, n)]
        )
        edges = np.stack([parents, np.arange(1, n)], axis=1)
        tables = rng.uniform(0, 10, size=(len(edges), 3, 3)).astype(
            np.float32
        )
        return compile_from_edges(n, 3, edges, tables), parents, tables

    def test_sharded_5k_tree_matches_single_device(self):
        from pydcop_tpu.algorithms import dpop

        c, parents, tables = self._tree_problem(5000)
        single = dpop.solve(c, {})
        sharded = dpop.solve(c, {}, mesh=make_mesh(8))
        assert sharded.cost == single.cost  # exact, not approx
        assert sharded.assignment == single.assignment
        # independent bottom-up float64 DP pins both to the true optimum
        n = c.n_vars
        util = np.zeros((n, 3))
        for i in range(n - 1, 0, -1):
            p = parents[i - 1]
            util[p] += (tables[i - 1].astype(np.float64) + util[i]).min(
                axis=1
            )
        assert single.cost == pytest.approx(float(util[0].min()), rel=1e-5)

    def test_sharded_chunked_path_matches(self, monkeypatch):
        # force the big-node chunked path and shard its chunks too
        import random

        from pydcop_tpu.algorithms import dpop
        from pydcop_tpu.compile.core import compile_dcop
        from pydcop_tpu.dcop import DCOP, Domain, Variable, constraint_from_str

        random.seed(11)
        d = Domain("d", "", list(range(3)))
        vs = [Variable(f"v{i}", d) for i in range(7)]
        dcop = DCOP("wide")
        for k in range(10):
            i, j = random.sample(range(7), 2)
            coeffs = [random.randint(0, 9) for _ in range(9)]
            expr = f"[{','.join(map(str, coeffs))}][v{i}*3+v{j}]"
            dcop += constraint_from_str(f"c{k}", expr, [vs[i], vs[j]])
        dcop.add_agents([])
        c = compile_dcop(dcop)
        baseline = dpop.solve(c, {})
        monkeypatch.setattr(dpop, "MAX_JOINT_ELEMS", 9)
        monkeypatch.setattr(dpop, "CHUNK_ELEMS", 27)
        sharded = dpop.solve(c, {}, mesh=make_mesh(8))
        assert sharded.cost == pytest.approx(baseline.cost)
        assert sharded.assignment == baseline.assignment


class TestShardedEll:
    """Round-6 mesh-composable ELL (build_ell(n_shards)): shard-major
    degree-bucketed planes whose only cross-shard op is the pair
    gather.  The layout is slot-for-slot the same math as single-shard
    ELL, so sharded solves must be COST-BIT-IDENTICAL, not approx."""

    @staticmethod
    def _problem(n=96, seed=5):
        return generate_coloring_arrays(
            n, 3, graph="scalefree", m_edge=2, seed=seed
        )

    def test_sharded_ell_cost_bit_identical(self):
        from pydcop_tpu.algorithms import maxsum

        compiled = self._problem()
        dev = to_device(compiled)
        mesh = make_mesh(8)
        sharded = shard_device_dcop(
            pad_device_dcop(dev, mesh.size), mesh
        )
        p = {"layout": "ell", "noise": 0.0, "damping": 0.5}
        single = maxsum.solve(
            compiled, dict(p), n_cycles=15, seed=0, dev=dev
        )
        multi = maxsum.solve(
            compiled, dict(p), n_cycles=15, seed=0, dev=sharded
        )
        assert multi.cost == single.cost  # bitwise, not approx
        assert multi.assignment == single.assignment
        assert multi.violations == single.violations

    def test_auto_resolves_to_ell_on_sharded_mesh(self):
        # the acceptance bar that deletes the old ~6x lanes fallback:
        # layout="auto" on a sharded DeviceDCOP must take the ELL path —
        # observable as the mesh.ell_cross_frac gauge the ELL-on-mesh
        # branch (and only it) publishes
        from pydcop_tpu.algorithms import maxsum
        from pydcop_tpu.telemetry import metrics_registry

        compiled = self._problem(seed=7)
        dev = to_device(compiled)
        mesh = make_mesh(8)
        sharded = shard_device_dcop(
            pad_device_dcop(dev, mesh.size), mesh
        )
        metrics_registry.reset()
        metrics_registry.enabled = True
        try:
            auto = maxsum.solve(
                compiled, {"noise": 0.0}, n_cycles=10, seed=0,
                dev=sharded,
            )
            gauge = metrics_registry.get("mesh.ell_cross_frac")
            frac = gauge.value() if gauge is not None else None
        finally:
            metrics_registry.enabled = False
            metrics_registry.reset()
        assert gauge is not None
        assert 0.0 < frac <= 1.0
        ell = maxsum.solve(
            compiled, {"noise": 0.0, "layout": "ell"}, n_cycles=10,
            seed=0, dev=to_device(compiled),
        )
        assert auto.cost == ell.cost

    def test_build_ell_sharded_invariants(self):
        from pydcop_tpu.compile.kernels import (
            build_ell,
            ell_cross_shard_frac,
        )
        from pydcop_tpu.parallel.placement import cross_shard_incidence

        compiled = self._problem()
        n_shards = 8
        ell = build_ell(compiled, n_shards=n_shards)
        assert ell.n_shards == n_shards
        # every shardable axis splits into equal mesh chunks
        assert ell.n_pad % n_shards == 0
        v_ell = len(ell.var_perm)
        assert v_ell % n_shards == 0
        assert ell.valid_ell_t.shape[1] == v_ell
        # span boundaries never straddle a lane chunk: walking the spans
        # accumulates slot counts that hit each chunk boundary exactly
        lane_chunk = ell.n_pad // n_shards
        var_chunk = v_ell // n_shards
        slot, var, slot_marks, var_marks = 0, 0, set(), set()
        for nb, db in ell.spans:
            slot += nb * db
            var += nb
            slot_marks.add(slot)
            var_marks.add(var)
        assert all(
            lane_chunk * (k + 1) in slot_marks for k in range(n_shards)
        )
        assert all(
            var_chunk * (k + 1) in var_marks for k in range(n_shards)
        )
        # every real edge appears exactly once; pair_perm pairs real
        # slots of the same constraint
        real = ell.edge_orig >= 0
        assert sorted(ell.edge_orig[real].tolist()) == list(
            range(compiled.n_edges)
        )
        assert (ell.pair_perm[ell.pair_perm] == np.arange(
            ell.n_pad
        )).all()
        # the layout's measured cross-shard fraction equals the
        # graph-level predictor computed without building the layout
        frac = ell_cross_shard_frac(ell)
        pred = cross_shard_incidence(compiled, n_shards)
        assert frac == pytest.approx(pred)
        assert 0.0 < frac < 1.0
        # single-shard layouts report zero
        assert ell_cross_shard_frac(build_ell(compiled)) == 0.0


@pytest.mark.parametrize("algo_name", ["maxsum", "dsa"])
def test_sharded_solve_end_to_end(algo_name):
    from pydcop_tpu.algorithms import dsa, maxsum

    algo = {"maxsum": maxsum, "dsa": dsa}[algo_name]
    compiled = generate_coloring_arrays(
        64, 3, graph="scalefree", m_edge=2, seed=5
    )
    dev = to_device(compiled)
    mesh = make_mesh(8)
    sharded = shard_device_dcop(pad_device_dcop(dev, mesh.size), mesh)

    res_single = algo.solve(compiled, n_cycles=10, seed=0, dev=dev)
    res_sharded = algo.solve(compiled, n_cycles=10, seed=0, dev=sharded)
    assert res_sharded.assignment == res_single.assignment
    assert res_sharded.violations == res_single.violations == 0
    assert res_sharded.cost == pytest.approx(res_single.cost, rel=1e-4)
