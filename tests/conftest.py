"""Test configuration: force a local 8-device virtual CPU platform.

Real TPU hardware in CI is a single chip behind the axon relay; multi-device
sharding tests run on a virtual CPU mesh instead (SURVEY.md §4: "fake mesh"
strategy).  The axon plugin (activated by a sitecustomize before this file
runs) routes backend selection to the relay, so we must (a) set the XLA
device-count flag before the first backend is built and (b) override the
platform selection via jax.config — env vars alone are overridden by the
plugin's registration.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402

REFERENCE_INSTANCES = "/root/reference/tests/instances"


@pytest.fixture
def instance_path():
    def _path(name: str) -> str:
        import os.path

        local = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "instances", name
        )
        if os.path.exists(local):
            return local
        return os.path.join(REFERENCE_INSTANCES, name)

    return _path
