"""Test configuration: force a local 8-device virtual CPU platform.

Real TPU hardware in CI is a single chip behind the axon relay; multi-device
sharding tests run on a virtual CPU mesh instead (SURVEY.md §4: "fake mesh"
strategy).  The axon plugin (activated by a sitecustomize before this file
runs) routes backend selection to the relay, so we must (a) set the XLA
device-count flag before the first backend is built and (b) override the
platform selection via jax.config — env vars alone are overridden by the
plugin's registration.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pydcop_tpu.utils.platform import pin_cpu  # noqa: E402

# keep an externally-forced device count if the caller set one
flags = os.environ.get("XLA_FLAGS", "")
pin_cpu(None if "xla_force_host_platform_device_count" in flags else 8)

import pytest  # noqa: E402

REFERENCE_INSTANCES = "/root/reference/tests/instances"


@pytest.fixture
def instance_path():
    def _path(name: str) -> str:
        import os.path

        local = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "instances", name
        )
        if os.path.exists(local):
            return local
        return os.path.join(REFERENCE_INSTANCES, name)

    return _path
