"""graftprof tests: compile/device observability (telemetry/profiling.py).

Covers the ISSUE-5 acceptance surface:

- ``profiled_jit`` hit/miss counting and cost/memory analyses, including
  the graceful-degradation paths (lowering API absent, profiler absent —
  the CPU backend in CI IS the no-device-profiler environment for the
  chunk_ms fallback assertions);
- compile-cache hit/miss counting across repeated ``compile_dcop`` calls
  on an identical DCOP (host repeat census + jit cache hits);
- phase attribution of solver readback windows (``solve.window`` spans
  carry ``phase``; ``device.chunk_ms`` observes every window);
- the ``telemetry`` verb's compile section;
- zero-cost-when-off: the disabled path records nothing.
"""

import json
import os

import numpy as np
import pytest

from pydcop_tpu.telemetry import (
    metrics_registry,
    profiled_jit,
    profiling,
    start_profiling,
    stop_profiling,
    telemetry_off,
    tracer,
)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry_off()
    yield
    telemetry_off()


def _fresh_jit(label):
    """A profiled jit over a unique lambda (its own jit cache)."""
    import jax.numpy as jnp

    return profiled_jit(lambda x: x * 2 + 1, name=label)


def _values(name, **labels):
    m = metrics_registry.get(name)
    if m is None:
        return 0.0
    return m.value(**labels)


class TestProfiledJit:
    def test_miss_then_hits_per_shape_bucket(self):
        import jax.numpy as jnp

        f = _fresh_jit("t.hitmiss")
        metrics_registry.enabled = True
        f(jnp.ones(4))
        f(jnp.ones(4))
        f(jnp.ones(4))
        assert _values("compile.jit_compiles", fn="t.hitmiss") == 1
        assert _values("compile.jit_cache_hits", fn="t.hitmiss") == 2
        # a new shape bucket is a fresh compile
        f(jnp.ones(8))
        assert _values("compile.jit_compiles", fn="t.hitmiss") == 2

    def test_cost_analysis_published_on_compile(self):
        import jax.numpy as jnp

        f = _fresh_jit("t.cost")
        metrics_registry.enabled = True
        out = f(jnp.ones(16))
        np.testing.assert_allclose(np.asarray(out), np.full(16, 3.0))
        assert _values("compile.flops", fn="t.cost") > 0
        assert _values("compile.bytes_accessed", fn="t.cost") > 0
        assert metrics_registry.get("compile.flops_total").value() > 0
        assert (
            metrics_registry.get("compile.jit_seconds").count(fn="t.cost")
            == 1
        )

    def test_compile_span_recorded(self):
        import jax.numpy as jnp

        f = _fresh_jit("t.span")
        tracer.enabled = True
        f(jnp.ones(4))
        spans = [
            e for e in tracer.events() if e["name"] == "compile.jit"
        ]
        assert len(spans) == 1
        assert spans[0]["args"]["fn"] == "t.span"

    def test_disabled_path_records_nothing(self):
        import jax.numpy as jnp

        f = _fresh_jit("t.off")
        out = f(jnp.ones(4))
        np.testing.assert_allclose(np.asarray(out), np.full(4, 3.0))
        assert _values("compile.jit_compiles", fn="t.off") == 0
        assert _values("compile.jit_cache_hits", fn="t.off") == 0

    def test_lower_failure_degrades_gracefully(self):
        import jax.numpy as jnp

        f = _fresh_jit("t.nolower")

        class _Broken:
            def __init__(self, inner):
                self._inner = inner

            def __call__(self, *a, **k):
                return self._inner(*a, **k)

            def _cache_size(self):
                return self._inner._cache_size()

            def lower(self, *a, **k):
                raise NotImplementedError("no lowering on this backend")

        f._jitted = _Broken(f._jitted)
        metrics_registry.enabled = True
        out = f(jnp.ones(4))  # the call itself must be unaffected
        np.testing.assert_allclose(np.asarray(out), np.full(4, 3.0))
        assert _values("compile.jit_compiles", fn="t.nolower") == 1
        assert (
            _values(
                "compile.analysis_unavailable", fn="t.nolower", api="lower"
            )
            == 1
        )
        assert _values("compile.flops", fn="t.nolower") == 0

    def test_cache_size_passthrough_for_transfer_census(self):
        # test_algorithms.TestTransferCensus pokes _cache_size() on the
        # wrapped solver entry points — the wrapper must forward it
        from pydcop_tpu.algorithms import base

        assert isinstance(base._solve_fused._cache_size(), int)

    def test_full_mode_memory_analysis_and_hlo_dump(self, tmp_path):
        import jax.numpy as jnp

        f = _fresh_jit("t.full")
        metrics_registry.enabled = True
        start_profiling(hlo_dir=str(tmp_path))
        try:
            f(jnp.ones(4))
        finally:
            stop_profiling()
        files = os.listdir(tmp_path)
        assert len(files) == 1 and files[0].endswith(".hlo.txt")
        text = (tmp_path / files[0]).read_text()
        assert "module" in text
        # memory_analysis ran (CPU backend supports it)
        for kind in ("argument", "output", "peak"):
            assert (
                metrics_registry.get("compile.memory_bytes").value(
                    fn="t.full", kind=kind
                )
                >= 0
            )
        assert _values("compile.hlo_dumps", fn="t.full") == 1


class TestProfilerSession:
    def test_profiler_absent_falls_back(self, monkeypatch, tmp_path):
        import jax.profiler

        def _boom(*a, **k):
            raise RuntimeError("profiler not supported here")

        monkeypatch.setattr(jax.profiler, "start_trace", _boom)
        metrics_registry.enabled = True
        start_profiling(profile_dir=str(tmp_path / "prof"))
        try:
            assert profiling.enabled
            assert not profiling.profiler_active
            assert "profiler not supported" in profiling.profiler_error
            assert (
                metrics_registry.get("device.profiler_unavailable").value()
                == 1
            )
            from pydcop_tpu.telemetry import device_annotation

            # annotation must be a no-op context, not a crash
            with device_annotation("solve.x.fused"):
                pass
        finally:
            stop_profiling()

    def test_start_stop_roundtrip(self, tmp_path):
        from pydcop_tpu.telemetry import device_annotation

        start_profiling(profile_dir=str(tmp_path / "prof"))
        try:
            if profiling.profiler_active:  # CPU backend supports it
                with device_annotation("solve.test.fused"):
                    pass
        finally:
            stop_profiling()
        assert not profiling.profiler_active
        assert not profiling.enabled

    def test_stop_is_idempotent(self):
        stop_profiling()
        stop_profiling()
        assert not profiling.enabled


class TestCompileCacheCensus:
    def _dcop(self):
        from pydcop_tpu.dcop.yamldcop import load_dcop

        return load_dcop(
            """
            name: prof_test
            objective: min
            domains:
              colors: {values: [R, G, B]}
            variables:
              v1: {domain: colors}
              v2: {domain: colors}
            constraints:
              c1:
                type: intention
                function: "10 if v1 == v2 else 0"
            agents: [a1, a2]
            """
        )

    def test_repeat_compile_dcop_counted(self):
        from pydcop_tpu.compile.core import compile_dcop

        metrics_registry.enabled = True
        compile_dcop(self._dcop())
        before = _values("compile.host_repeat_compiles")
        compile_dcop(self._dcop())
        assert _values("compile.host_repeat_compiles") == before + 1
        assert (
            metrics_registry.get("compile.host_seconds").count() >= 2
        )

    def test_jit_cache_hit_across_identical_compiles(self):
        """Two compile_dcop calls on an identical DCOP feed two solves:
        the second solve's fused program is a jit cache HIT (same shapes,
        same static step function), not a recompile."""
        from pydcop_tpu.algorithms import dsa
        from pydcop_tpu.compile.core import compile_dcop

        # warm everything OUTSIDE the census so jit compiles triggered by
        # other tests' leftovers don't pollute the counts
        dsa.solve(compile_dcop(self._dcop()), {}, n_cycles=3, seed=0)
        metrics_registry.enabled = True
        dsa.solve(compile_dcop(self._dcop()), {}, n_cycles=3, seed=0)
        compiles = _values(
            "compile.jit_compiles", fn="solve._solve_fused"
        )
        hits = _values("compile.jit_cache_hits", fn="solve._solve_fused")
        assert compiles == 0
        assert hits == 1

    def test_compile_from_edges_publishes_compile_stats(self):
        from pydcop_tpu.compile.direct import compile_from_edges

        metrics_registry.enabled = True
        tracer.enabled = True
        edges = np.array([[0, 1], [1, 2]], dtype=np.int32)
        table = np.ones((3, 3), dtype=np.float32)
        compile_from_edges(3, 3, edges, table)
        assert _values("compile.runs") == 1
        assert metrics_registry.get("compile.host_seconds").count() == 1
        spans = [
            e for e in tracer.events()
            if e["name"] == "compile.compile_from_edges"
        ]
        assert len(spans) == 1
        assert spans[0]["args"]["n_edges"] == 4


class TestPhaseAttribution:
    def _compiled(self):
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_coloring_arrays,
        )

        return generate_coloring_arrays(
            20, 3, graph="random", p_edge=0.2, seed=3
        )

    def test_fused_window_carries_phase(self):
        from pydcop_tpu.algorithms import maxsum

        compiled = self._compiled()
        tracer.enabled = True
        metrics_registry.enabled = True
        maxsum.solve(compiled, {"damping": 0.5}, n_cycles=5, seed=0)
        windows = [
            e for e in tracer.events() if e["name"] == "solve.window"
        ]
        assert windows
        assert all(w["args"]["phase"] == "maxsum" for w in windows)
        # 100% of window time is phase-attributed (the >=90% bar)
        total = sum(w["dur"] for w in windows)
        named = sum(w["dur"] for w in windows if w["args"].get("phase"))
        assert total > 0 and named == total

    def test_timeout_chunks_observe_chunk_ms(self):
        from pydcop_tpu.algorithms import dsa

        compiled = self._compiled()
        metrics_registry.enabled = True
        dsa.solve(compiled, {}, n_cycles=40, seed=0, timeout=120)
        h = metrics_registry.get("device.chunk_ms")
        assert h.count(phase="dsa", kind="chunk") >= 1

    def test_phase_of_derives_module_tail(self):
        from pydcop_tpu.algorithms import base
        from pydcop_tpu.algorithms.maxsum import solve as ms_solve

        assert base._phase_of(ms_solve) == "maxsum"
        assert base._phase_of(lambda: None) == "test_profiling"


class TestTelemetryVerbCompileSection:
    def test_compile_section_rows(self, tmp_path, capsys):
        import jax.numpy as jnp

        from pydcop_tpu.commands.telemetry import run_cmd

        f = _fresh_jit("t.verb")
        metrics_registry.enabled = True
        f(jnp.ones(4))
        metrics_registry.enabled = False
        snap_file = tmp_path / "metrics.json"
        metrics_registry.dump(str(snap_file))

        class _Args:
            trace_file = []
            prom = None
            metrics = str(snap_file)
            top = 20
            as_json = True
            validate = False
            out = None
            output = None

        assert run_cmd(_Args()) == 0
        out = json.loads(capsys.readouterr().out)
        names = {r["metric"] for r in out["compile"]}
        assert "compile.jit_compiles" in names
        assert any(
            r["metric"] == "compile.jit_seconds" and "total" in r
            for r in out["compile"]
        )


class TestBenchCompileBlock:
    def test_bench_record_carries_compile_and_roofline(self):
        import bench_all
        from pydcop_tpu.algorithms import dsa

        compiled = TestPhaseAttribution()._compiled()

        record = bench_all._bench(
            "prof_test_metric",
            lambda **kw: dsa.solve(
                compiled, {}, n_cycles=5, seed=0, **kw
            ),
            5,
            traffic_bytes=10**9,
        )
        assert record["compile"]["jit_compiles"] >= 0
        assert "compile_s" in record["compile"]
        assert record["roofline"]["traffic_bytes_per_cycle"] == 10**9
        assert record["roofline"]["achieved_gbps"] > 0


class TestKernelProf:
    """graftkern (telemetry/kernelprof.py): the per-op `kernel` block of
    BENCH records — ELL cycle decomposition + MGM-2 phase walls."""

    def _compiled(self, n=120):
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_coloring_arrays,
        )

        return generate_coloring_arrays(
            n, 3, graph="scalefree", m_edge=2, seed=7
        )

    def test_ell_block_schema_and_attribution(self):
        from pydcop_tpu.telemetry import ell_kernel_block

        block = ell_kernel_block(self._compiled(), reps=3)
        assert block["layout"] == "ell"
        ops = block["ops"]
        for op in ("pair_gather", "minplus", "variable_step"):
            assert ops[op]["ms"] >= 0
            assert ops[op]["bytes"] > 0
            assert ops[op]["share_pct"] is not None
        assert ops["readback"]["per_solve"] is True
        # the acceptance bar: the three ops account for the step (>100%
        # is dispatch overhead of timing them separately, never a miss)
        assert block["attributed_pct"] is not None
        assert block["traffic_bytes_per_cycle"] == sum(
            ops[o]["bytes"]
            for o in ("pair_gather", "minplus", "variable_step")
        )
        # CPU runs must never carry a fake HBM roofline
        assert block["peak_gbps"] is None
        pallas = block["pallas"]
        assert pallas["supported"] is True
        assert "factor_ms" in pallas and "jnp_factor_ms" in pallas

    def test_ell_block_skips_unrepresentable(self):
        from pydcop_tpu.compile.core import compile_dcop
        from pydcop_tpu.dcop import (
            DCOP,
            Domain,
            Variable,
            constraint_from_str,
        )
        from pydcop_tpu.telemetry import ell_kernel_block

        # an arity-3 constraint: the ELL layout cannot represent it
        d = Domain("d", "", [0, 1])
        x, y, z = (Variable(n, d) for n in "xyz")
        dcop = DCOP("tern")
        dcop += constraint_from_str(
            "c1", "(x + y + z - 1) ** 2", [x, y, z]
        )
        dcop.add_agents([])
        block = ell_kernel_block(compile_dcop(dcop), reps=1)
        assert "skipped" in block

    def test_mgm2_phase_block_attributes_all_phases(self):
        from pydcop_tpu.algorithms.mgm2 import MGM2_PHASES
        from pydcop_tpu.telemetry import mgm2_phase_block

        metrics_registry.enabled = True
        block = mgm2_phase_block(self._compiled(), reps=2)
        assert block["algo"] == "mgm2"
        assert set(block["phases"]) == set(MGM2_PHASES)
        for entry in block["phases"].values():
            assert entry["ms"] >= 0
        assert block["attributed_pct"] is not None
        # each phase landed one device.chunk_ms{phase="mgm2.<p>"} row
        hist = metrics_registry.get("device.chunk_ms")
        assert hist is not None
        for name in MGM2_PHASES:
            assert hist.count(phase=f"mgm2.{name}", kind="phase") >= 1

    def test_bench_record_carries_kernel_block(self):
        import bench_all
        from pydcop_tpu.algorithms import maxsum
        from pydcop_tpu.telemetry import ell_kernel_block

        compiled = self._compiled(n=40)
        record = bench_all._bench(
            "kernelprof_test_metric",
            lambda **kw: maxsum.solve(
                compiled, {"layout": "ell"}, n_cycles=5, seed=0, **kw
            ),
            5,
            kernel_fn=lambda: ell_kernel_block(compiled, reps=2),
        )
        assert record["kernel"]["layout"] == "ell"
        assert "ops" in record["kernel"]

    def test_bench_kernel_failure_degrades_to_error(self):
        import bench_all
        from pydcop_tpu.algorithms import maxsum

        compiled = self._compiled(n=40)

        def boom():
            raise RuntimeError("kernel prof exploded")

        record = bench_all._bench(
            "kernelprof_err_metric",
            lambda **kw: maxsum.solve(
                compiled, {"layout": "ell"}, n_cycles=3, seed=0, **kw
            ),
            3,
            kernel_fn=boom,
        )
        assert "kernel prof exploded" in record["kernel"]["error"]
        assert record["value"] is not None


class TestServeBucketCensus:
    """graftserve executable sharing, asserted through the profiled_jit
    census (ISSUE 9 satellite): two DIFFERENT problems mapping to the
    same shape bucket share one compiled program — the second tenant in a
    warm bucket registers jit cache hits and ZERO fresh compiles — while
    a bucket-boundary miss (different padded dims) compiles fresh."""

    @staticmethod
    def _census():
        def tot(name):
            m = metrics_registry.get(name)
            if m is None:
                return 0
            return int(
                sum(
                    float(e.get("value") or 0)
                    for e in m.snapshot().get("values", [])
                )
            )

        return tot("compile.jit_compiles"), tot("compile.jit_cache_hits")

    def test_warm_bucket_zero_fresh_compiles(self):
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_coloring_arrays,
        )
        from pydcop_tpu.serve import SolveRequest, bucket_key, solve_batched

        a = generate_coloring_arrays(49, 3, graph="grid", seed=31)
        b = generate_coloring_arrays(49, 3, graph="grid", seed=32)
        c = generate_coloring_arrays(25, 3, graph="grid", seed=33)
        ka = bucket_key(SolveRequest("a", a, "dsa", {}, 20, 0))
        kb = bucket_key(SolveRequest("b", b, "dsa", {}, 20, 5))
        kc = bucket_key(SolveRequest("c", c, "dsa", {}, 20, 0))
        assert ka == kb  # same topology class -> same bucket
        assert kc != ka  # boundary miss: different padded dims

        metrics_registry.enabled = True
        solve_batched([SolveRequest("a", a, "dsa", {}, 20, 0)])
        cold_compiles, _ = self._census()
        assert cold_compiles >= 1  # the bucket's executable was built

        # second tenant, DIFFERENT problem, same bucket: 0 fresh compiles
        before = self._census()
        solve_batched([SolveRequest("b", b, "dsa", {}, 20, 5)])
        after = self._census()
        assert after[0] - before[0] == 0, "warm bucket recompiled"
        assert after[1] - before[1] >= 1  # served from the jit cache

        # negative case: the bucket-boundary miss compiles fresh
        before = self._census()
        solve_batched([SolveRequest("c", c, "dsa", {}, 20, 0)])
        after = self._census()
        assert after[0] - before[0] >= 1

    def test_warm_bucket_survives_batch_size_class(self):
        # K rounds to powers of two: a batch of 3 pads to the K=4
        # executable, so a later batch of 4 in the same bucket hits it
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_coloring_arrays,
        )
        from pydcop_tpu.serve import SolveRequest, solve_batched

        def reqs(n_reqs, seed0):
            return [
                SolveRequest(
                    f"t{seed0}-{i}",
                    generate_coloring_arrays(
                        49, 3, graph="grid", seed=seed0 + i
                    ),
                    "dsa", {}, 20, i,
                )
                for i in range(n_reqs)
            ]

        metrics_registry.enabled = True
        solve_batched(reqs(3, 40))  # compiles the K=4 executable
        before = self._census()
        solve_batched(reqs(4, 60))
        after = self._census()
        assert after[0] - before[0] == 0
