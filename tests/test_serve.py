"""graftserve: shape buckets, the vmapped batch engine, fleet fusion and
the micro-batching server (pydcop_tpu/serve/, docs/serving.md)."""

import threading
import time

import numpy as np
import pytest

from pydcop_tpu.commands.generators.graphcoloring import (
    generate_coloring_arrays,
)
from pydcop_tpu.compile.kernels import to_device
from pydcop_tpu.serve import (
    ServeServer,
    ServeUnsupported,
    SolveRequest,
    bucket_dims_of,
    bucket_key,
    pad_dev_to_bucket,
    solve_batched,
    solve_one,
)
from pydcop_tpu.telemetry import metrics_registry, pulse, telemetry_off


def _coloring(n, seed, graph="grid"):
    return generate_coloring_arrays(n, 3, graph=graph, seed=seed)


def _reqs(n, count, algo="dsa", params=None, cycles=20, seed0=50):
    return [
        SolveRequest(
            f"{algo}-{n}-{i}", _coloring(n, seed0 + i), algo,
            dict(params or {}), cycles, i,
        )
        for i in range(count)
    ]


@pytest.fixture(autouse=True)
def _telemetry_reset():
    yield
    telemetry_off()


# ---------------------------------------------------------------------------
# shape buckets
# ---------------------------------------------------------------------------


class TestBuckets:
    def test_dims_power_of_two_and_shared(self):
        a, b = _coloring(49, 1), _coloring(49, 2)
        da, db = bucket_dims_of(a), bucket_dims_of(b)
        assert da == db  # same topology class -> same bucket
        for dim in (da.n_vars, da.n_edges, da.n_constraints):
            assert dim & (dim - 1) == 0  # powers of two
        assert da.n_vars > a.n_vars  # the dead row is reserved

    def test_different_sizes_different_buckets(self):
        assert bucket_dims_of(_coloring(49, 1)) != bucket_dims_of(
            _coloring(25, 1)
        )

    def test_pad_to_bucket_matches_dims(self):
        c = _coloring(49, 3)
        dims = bucket_dims_of(c)
        dev = pad_dev_to_bucket(to_device(c), dims)
        assert dev.n_vars == dims.n_vars
        assert dev.n_edges == dims.n_edges
        assert dev.n_constraints == dims.n_constraints

    def test_pad_to_bucket_is_cost_neutral(self):
        from pydcop_tpu.compile.kernels import evaluate

        c = _coloring(25, 3)
        dims = bucket_dims_of(c)
        dev = to_device(c)
        dev_p = pad_dev_to_bucket(dev, dims)
        vals = np.zeros(c.n_vars, dtype=np.int32)
        vals_p = np.zeros(dims.n_vars, dtype=np.int32)
        assert float(evaluate(dev, vals)) == pytest.approx(
            float(evaluate(dev_p, vals_p)), abs=1e-5
        )

    def test_pad_ell_classes_spans_pow2(self):
        from pydcop_tpu.compile.kernels import build_ell
        from pydcop_tpu.serve.bucket import pad_ell_classes

        c = _coloring(64, 5, graph="scalefree")
        ell = build_ell(c)
        padded = pad_ell_classes(ell)
        for nb, _db in padded.spans:
            assert nb & (nb - 1) == 0
        # every real variable still maps to a live column
        assert np.array_equal(
            padded.var_perm[padded.pos_of_var], np.arange(c.n_vars)
        )
        # pad slots are dead and self-paired
        pad_slots = np.flatnonzero(padded.edge_orig < 0)
        assert not padded.real_row[0, pad_slots].any()


# ---------------------------------------------------------------------------
# fleet fusion (mode="fused")
# ---------------------------------------------------------------------------


class TestFleetFusion:
    def test_union_compiled_blocks(self):
        from pydcop_tpu.serve.union import union_compiled

        parts = [_coloring(9, 1), _coloring(16, 2), _coloring(9, 3)]
        union, blocks = union_compiled(parts)
        assert union.n_vars == sum(p.n_vars for p in parts)
        assert union.n_edges == sum(p.n_edges for p in parts)
        assert blocks[1] == (9, 25)
        # edge list stays var-sorted (the to_device contract)
        assert np.all(np.diff(union.edge_var) >= 0)
        # block-diagonal: each constraint's scope stays inside its block
        for b in union.buckets:
            for (lo, hi), p in zip(blocks, parts):
                rows = (b.var_slots >= lo).all(axis=1) & (
                    b.var_slots < hi
                ).all(axis=1)
                assert rows.sum() * 1  # slicing sanity (no crash)
        inside = np.zeros(len(union.edge_var), dtype=bool)
        for lo, hi in blocks:
            inside |= (union.edge_var >= lo) & (union.edge_var < hi)
        assert inside.all()

    def test_fused_mode_solves_every_tenant(self):
        reqs = _reqs(9, 3) + _reqs(16, 2, seed0=80)
        out = solve_batched(reqs, mode="fused")
        assert len(out) == 5
        for r in reqs:
            tr = out[r.tenant]
            assert tr.result is not None
            assert tr.result.violations == 0
            assert tr.extras["mode"] == "fused"
        # cross-bucket fusion: ONE union dispatch for both sizes
        sizes = {out[r.tenant].extras["batch_size"] for r in reqs}
        assert sizes == {5}

    def test_fused_quality_matches_sequential_family(self):
        # fused trajectories are not seed-reproducible (one fleet key),
        # and DSA tenants may settle in different local optima than
        # their solo runs — but the FLEET must land in the same cost
        # family: zero violations everywhere, and a total cost within
        # two soft conflicts of the solo total (each edge conflict costs
        # 1.0 on these instances)
        reqs = _reqs(9, 4, cycles=100)
        out = solve_batched(reqs, mode="fused")
        fused_total = 0.0
        solo_total = 0.0
        for r in reqs:
            tr = out[r.tenant]
            assert tr.result.violations == 0
            fused_total += tr.result.cost
            solo_total += solve_one(r).result.cost
        assert fused_total <= solo_total + 2.0


# ---------------------------------------------------------------------------
# the serving front-end
# ---------------------------------------------------------------------------


class TestServeServer:
    def test_submit_wait_status_drain(self):
        pulse.reset()
        pulse.enabled = True
        srv = ServeServer(port=None, window_ms=20, max_batch=8)
        try:
            reqs = _reqs(9, 3) + _reqs(16, 2, seed0=90)
            for r in reqs:
                srv.submit(r)
            for r in reqs:
                rec = srv.wait(r.tenant, timeout=120)
                assert rec["status"] == "done", rec
                assert rec["cost"] == solve_one(r).result.cost
            st = srv.status()
            assert st["dead_letters"] == 0
            assert st["solves"] == 5
            assert st["batches"] < 5  # micro-batching actually batched
            # per-tenant pulse rows on the status surface
            with_pulse = [
                t for t, row in st["tenants"].items() if "pulse" in row
            ]
            assert len(with_pulse) == 5
            assert st["queue_ms"]["p50"] is not None
        finally:
            assert srv.shutdown(drain=True)
        assert srv.status()["state"] == "drained"

    def test_submit_rejected_while_draining(self):
        srv = ServeServer(port=None, window_ms=1)
        srv.drain(timeout=30)
        with pytest.raises(RuntimeError):
            srv.submit(_reqs(9, 1)[0])
        srv.shutdown(drain=False)

    def test_unsupported_algo_fails_only_that_tenant(self):
        srv = ServeServer(port=None, window_ms=20)
        try:
            good = _reqs(9, 2)
            bad = SolveRequest(
                "bad", _coloring(9, 77), "dpop", {}, 10, 0
            )
            for r in good:
                srv.submit(r)
            srv.submit(bad)
            assert srv.wait("bad", timeout=120)["status"] == "failed"
            for r in good:
                assert srv.wait(r.tenant, timeout=120)["status"] == "done"
            assert srv.status()["dead_letters"] == 1
        finally:
            srv.shutdown(drain=True)


class TestServeChaos:
    """ISSUE satellite: chaos fault schedules compose with the serve loop
    — a tenant killed mid-batch degrades that tenant only (dead-letter
    accounted), never the co-batched tenants."""

    def test_kill_degrades_only_the_victim(self):
        from pydcop_tpu.chaos.schedule import FaultSchedule, KillEvent

        sched = FaultSchedule(
            seed=0, events=[KillEvent(agent="victim", at=0.0)]
        )
        srv = ServeServer(
            port=None, window_ms=30, max_batch=8, fault_schedule=sched
        )
        try:
            reqs = _reqs(9, 4)
            victim = SolveRequest(
                "victim", _coloring(9, 99), "dsa", {}, 20, 7
            )
            for r in reqs:
                srv.submit(r)
            srv.submit(victim)
            v = srv.wait("victim", timeout=120)
            assert v["status"] == "killed"
            # every co-batched tenant finished with its EXACT sequential
            # cost — the batch math never depended on the victim
            for r in reqs:
                rec = srv.wait(r.tenant, timeout=120)
                assert rec["status"] == "done"
                assert rec["cost"] == solve_one(r).result.cost
            st = srv.status()
            assert st["dead_letters"] == 1
            assert st["tenant_counts"]["killed"] == 1
            assert st["tenant_counts"]["done"] == 4
        finally:
            srv.shutdown(drain=True)

    def test_telemetry_off_composes_with_serve_loop(self):
        # ISSUE satellite bugfix: telemetry_off() mid-serve only stops
        # the streams; later tenants still solve
        pulse.reset()
        pulse.enabled = True
        metrics_registry.enabled = True
        srv = ServeServer(port=None, window_ms=10)
        try:
            r0 = _reqs(9, 1)[0]
            srv.submit(r0)
            assert srv.wait(r0.tenant, timeout=120)["status"] == "done"
            telemetry_off()
            r1 = SolveRequest("after", _coloring(9, 101), "dsa", {}, 15, 3)
            srv.submit(r1)
            rec = srv.wait("after", timeout=120)
            assert rec["status"] == "done"
            # pulse off -> no pulse row for the later tenant, no crash
            assert "pulse" not in srv.status()["tenants"]["after"]
        finally:
            srv.shutdown(drain=True)


class TestServeHttp:
    def test_http_solve_result_status_shutdown(self):
        import json
        import urllib.request

        from pydcop_tpu.dcop.yamldcop import dcop_yaml, load_dcop
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_graph_coloring,
        )

        srv = ServeServer(port=0, window_ms=10)
        base = f"http://127.0.0.1:{srv.http.port}"
        try:
            doc = dcop_yaml(
                generate_graph_coloring(
                    9, 3, graph="grid", seed=5, extensive=True
                )
            )
            body = json.dumps(
                {
                    "dcop_yaml": doc, "algo": "dsa", "n_cycles": 15,
                    "seed": 2, "tenant": "web",
                }
            ).encode()
            req = urllib.request.Request(
                base + "/solve", data=body, method="POST"
            )
            r = json.loads(urllib.request.urlopen(req, timeout=30).read())
            assert r["tenant"] == "web"
            deadline = time.time() + 120
            while time.time() < deadline:
                rec = json.loads(
                    urllib.request.urlopen(
                        base + "/result/web", timeout=30
                    ).read()
                )
                if rec["status"] in ("done", "failed"):
                    break
                time.sleep(0.05)
            assert rec["status"] == "done"
            st = json.loads(
                urllib.request.urlopen(base + "/status", timeout=30).read()
            )
            assert st["status"] == "serve"
            assert "web" in st["tenants"]
            # unknown tenant answers 404
            with pytest.raises(Exception):
                urllib.request.urlopen(base + "/result/nope", timeout=30)
        finally:
            srv.shutdown(drain=True)


class TestBatchEngine:
    def test_pad_batch_to_pow2_discards_pads(self):
        # 3 tenants pad to a batch of 4; results are per-tenant exact
        reqs = _reqs(9, 3)
        out = solve_batched(reqs)
        for r in reqs:
            assert out[r.tenant].result.cost == solve_one(r).result.cost

    def test_batch_path_actually_taken(self):
        # the sequential fallback produces BITWISE identical results, so
        # cost asserts alone cannot catch an engine that silently
        # degrades — pin the batch-path-only extras (the serve-smoke
        # gate asserts the same end-to-end via /status bucket labels)
        reqs = _reqs(9, 3)
        out = solve_batched(reqs)
        for r in reqs:
            extras = out[r.tenant].extras
            assert "bucket" in extras, "vmap dispatch fell back"
            assert extras["batch_size"] == 3

    def test_solve_one_equals_plain_solve_for_dsa(self):
        # DSA consts are shaped purely by the dev, so solve_one on the
        # bucket-padded dev IS the plain API solve on that dev
        from pydcop_tpu.algorithms import dsa

        r = _reqs(25, 1, cycles=25)[0]
        dims = bucket_dims_of(r.compiled)
        dev = pad_dev_to_bucket(to_device(r.compiled), dims)
        api = dsa.solve(
            r.compiled, {}, n_cycles=25, seed=r.seed, dev=dev
        )
        assert solve_one(r).result.assignment == api.assignment

    def test_unhashable_params_fail_only_that_tenant(self):
        # a malformed tenant (list-valued param hits the key caches with
        # a TypeError) must fail alone, never the whole call
        good = _reqs(9, 2)
        bad = SolveRequest(
            "bad", _coloring(9, 55), "dsa",
            {"probability": [0.7]}, 10, 0,
        )
        out = solve_batched(good + [bad])
        assert out["bad"].result is None
        assert "TypeError" in out["bad"].extras["error"]
        for r in good:
            assert out[r.tenant].result.cost == solve_one(r).result.cost

    def test_mixed_algos_grouped_separately(self):
        reqs = _reqs(9, 2) + _reqs(9, 2, algo="mgm", seed0=70)
        keys = {bucket_key(r) for r in reqs}
        assert len(keys) == 2
        out = solve_batched(reqs)
        for r in reqs:
            assert out[r.tenant].result.cost == solve_one(r).result.cost

    def test_maxsum_non_binary_unsupported(self):
        from pydcop_tpu.commands.generators.ising import (
            generate_ising_arrays,
        )

        c = generate_ising_arrays(3, 3, seed=1)
        # ELL needs at least one edge: a 1-variable coloring has none
        with pytest.raises(ServeUnsupported):
            bucket_key(
                SolveRequest(
                    "t",
                    generate_coloring_arrays(
                        1, 3, graph="random", p_edge=0.0, seed=1
                    ),
                    "maxsum", {}, 10, 0,
                )
            )
        # sanity: the binary ising case IS supported
        bucket_key(SolveRequest("t2", c, "maxsum", {}, 10, 0))


# ---------------------------------------------------------------------------
# graftslo: request lifecycle tracing + SLO wiring + scrape consistency
# ---------------------------------------------------------------------------


class TestRequestLifecycle:
    def test_trace_ids_phases_and_span_tree(self):
        from pydcop_tpu.telemetry.tracing import tracer

        metrics_registry.enabled = True
        tracer.reset()
        tracer.enabled = True
        srv = ServeServer(port=None, window_ms=20, max_batch=8)
        try:
            # a shape no other test in this file dispatches (6x6 grid):
            # the first batch MUST compile, so the cold-compile stall
            # attribution below is deterministic whatever ran before
            reqs = _reqs(36, 3)
            tids = [srv.submit(r) for r in reqs]
            for t in tids:
                assert srv.wait(t, timeout=120)["status"] == "done"
            recs = [srv.result(t) for t in tids]
            # every tenant got a trace id and a full phase decomposition
            for rec in recs:
                assert rec["trace"]
                assert set(rec["phases"]) == {
                    "queue", "assemble", "dispatch", "solve", "readback",
                }
                assert rec["batch_seq"] >= 1
                assert "bucket" in rec
            # the span tree: one serve.request root per tenant carrying
            # its trace id, bucket and batch; phase slices + flows exist
            events = tracer.events()
            by_name = {}
            for e in events:
                by_name.setdefault(e["name"], []).append(e)
            roots = {
                e["args"]["trace"]: e["args"]
                for e in by_name["serve.request"]
                if e.get("ph") == "X"  # the flow events share the name
            }
            for rec in recs:
                args = roots[rec["trace"]]
                assert args["bucket"] == rec["bucket"]
                assert args["batch"] == rec["batch_seq"]
                assert args["status"] == "done"
            for name in (
                "serve.submit", "serve.queued", "serve.batch",
                "serve.assemble", "serve.dispatch", "serve.solve",
                "serve.readback", "serve.result",
            ):
                assert by_name.get(name), f"missing {name} spans"
            # the first (cold) batch paid a compile: attributed by span
            # and on the tenants that rode it
            assert by_name.get("serve.cold_compile")
            assert any(r.get("cold_compile") for r in recs)
            # flows pair: one s + one f per tenant
            flows = [e for e in events if e.get("ph") in ("s", "t", "f")]
            assert sum(1 for e in flows if e["ph"] == "s") == len(reqs)
            assert sum(1 for e in flows if e["ph"] == "f") == len(reqs)
            # exemplar trace ids on the request histogram resolve to a
            # recorded root span
            h = metrics_registry.get("serve.request_seconds")
            (entry,) = h.snapshot()["values"]
            exemplars = {
                ex["trace_id"]
                for ex in entry["value"].get("exemplars", {}).values()
            }
            assert exemplars
            assert exemplars <= set(roots)
        finally:
            srv.shutdown(drain=True)

    def test_resubmit_accepts_trace_id(self):
        srv = ServeServer(port=None, window_ms=5)
        try:
            (r,) = _reqs(9, 1)
            tid = srv.submit(r)
            rid = srv.result(tid)["trace"]
            assert rid
            srv.wait(tid, timeout=120)
            # resubmit (new tenant id, same trace): the id is accepted
            # verbatim, keeping both attempts on one flow timeline
            tid2 = srv.submit(r._replace(tenant="retry-0"), trace=rid)
            assert srv.result(tid2)["trace"] == rid
            assert srv.wait(tid2, timeout=120)["status"] == "done"
        finally:
            srv.shutdown(drain=True)

    def test_disabled_telemetry_records_nothing(self):
        # the overhead contract: telemetry off + no engine -> no trace
        # ids beyond the record field, no metrics, no phases
        from pydcop_tpu.telemetry.tracing import tracer

        assert not metrics_registry.enabled and not tracer.enabled
        srv = ServeServer(port=None, window_ms=5)
        try:
            (r,) = _reqs(9, 1, seed0=91)
            tid = srv.submit(r)
            rec = srv.wait(tid, timeout=120)
            assert rec["status"] == "done"
            assert "phases" not in rec
            assert metrics_registry.get("serve.request_seconds") is None \
                or not metrics_registry.get(
                    "serve.request_seconds"
                ).snapshot()["values"]
        finally:
            srv.shutdown(drain=True)

    def test_chaos_delay_holds_only_victims_deterministically(self):
        from pydcop_tpu.chaos.schedule import FaultSchedule, MessageRule

        schedule = FaultSchedule(seed=3, events=[
            MessageRule(
                action="delay", pattern="solve", dest="lag*",
                seconds=0.6,
            ),
        ])
        latencies = []
        for _run in range(2):
            srv = ServeServer(
                port=None, window_ms=10, max_batch=8,
                fault_schedule=schedule,
            )
            try:
                ok = _reqs(9, 2, seed0=60)
                lag = [
                    r._replace(tenant=f"lag-{i}")
                    for i, r in enumerate(_reqs(9, 2, seed0=60))
                ]
                t0 = time.monotonic()
                for r in ok + lag:
                    srv.submit(r)
                out = {}
                for r in ok + lag:
                    rec = srv.wait(r.tenant, timeout=120)
                    assert rec["status"] == "done"
                    out[r.tenant] = rec["queue_ms"]
                # victims held past the injected delay; the co-submitted
                # ok tenants dispatched well before it
                for t, q_ms in out.items():
                    if t.startswith("lag-"):
                        assert q_ms >= 600.0, (t, q_ms)
                    else:
                        assert q_ms < 600.0, (t, q_ms)
                latencies.append(
                    {t: q >= 600.0 for t, q in out.items()}
                )
                del t0
            finally:
                srv.shutdown(drain=True)
        # same schedule, same victims: deterministic by seed
        assert latencies[0] == latencies[1]

    def test_slo_route_and_status_block(self):
        import json as _json
        import urllib.request

        from pydcop_tpu.telemetry.slo import SloEngine, parse_objective

        metrics_registry.enabled = True
        eng = SloEngine(
            [parse_objective("p99<60s"), parse_objective(
                "availability>=99%"
            )],
            eval_interval_s=0.1,
        )
        srv = ServeServer(port=0, window_ms=10, max_batch=8, slo=eng)
        try:
            reqs = _reqs(9, 3, seed0=95)
            for r in reqs:
                srv.submit(r)
            for r in reqs:
                assert srv.wait(r.tenant, timeout=120)["status"] == "done"
            base = f"http://127.0.0.1:{srv.http.port}"
            with urllib.request.urlopen(base + "/slo", timeout=5) as resp:
                rep = _json.loads(resp.read())
            assert {o["name"] for o in rep["objectives"]} == {
                "p99_latency", "availability",
            }
            assert rep["phase_percentiles"]["request"]
            st = srv.status()
            assert st["slo"]["objectives"]["availability"]["alert"] is None
            assert st["queue_depth_watermark"] >= 1
            assert st["buckets"] >= 1
        finally:
            srv.shutdown(drain=True)
        # the drain ran the engine's final tick: every request counted
        for ob in eng.report()["objectives"]:
            assert ob["good"] == 3


class TestScrapeConsistency:
    """Satellite: /metrics + /status scraped mid-batch under concurrent
    serve load must be internally consistent — no torn counter/gauge/
    histogram reads, tenant states summing to the census."""

    def test_mid_batch_scrapes_consistent(self):
        import json as _json
        import urllib.request

        from pydcop_tpu.telemetry.prom import parse_prometheus_text

        metrics_registry.enabled = True
        srv = ServeServer(port=0, window_ms=10, max_batch=4)
        base = f"http://127.0.0.1:{srv.http.port}"
        stop = threading.Event()
        problems = []

        def scrape_loop():
            while not stop.is_set():
                try:
                    with urllib.request.urlopen(
                        base + "/metrics", timeout=5
                    ) as resp:
                        parsed = parse_prometheus_text(
                            resp.read().decode()
                        )
                    with urllib.request.urlopen(
                        base + "/status", timeout=5
                    ) as resp:
                        st = _json.loads(resp.read())
                except OSError as e:  # server busy: retry
                    problems.append(f"scrape error: {e}")
                    continue
                # histogram internal consistency: cumulative buckets
                # non-decreasing, +Inf bucket == count (a torn read
                # breaks one of these)
                hists = {}
                for s in parsed["samples"]:
                    if s["name"].endswith("_bucket"):
                        key = (
                            s["name"][:-7],
                            tuple(sorted(
                                (k, v) for k, v in s["labels"].items()
                                if k != "le"
                            )),
                        )
                        hists.setdefault(key, []).append(
                            (s["labels"]["le"], s["value"])
                        )
                counts = {
                    (s["name"][:-6], tuple(sorted(s["labels"].items()))):
                        s["value"]
                    for s in parsed["samples"]
                    if s["name"].endswith("_count")
                }
                for (name, lbl), rows in hists.items():
                    vals = [v for _le, v in rows]
                    if vals != sorted(vals):
                        problems.append(
                            f"non-monotone buckets {name}{lbl}: {rows}"
                        )
                    total = counts.get((name, lbl))
                    if total is not None and vals and vals[-1] != total:
                        problems.append(
                            f"bucket/count torn {name}{lbl}: "
                            f"{vals[-1]} != {total}"
                        )
                # /status census: every known tenant is in exactly one
                # state, terminal accounting matches the counters
                census = st["tenant_counts"]
                if sum(census.values()) > 16:
                    problems.append(f"census overflow: {census}")
                if census.get("done", 0) != st["solves"]:
                    problems.append(
                        f"done {census.get('done')} != solves "
                        f"{st['solves']}"
                    )
                dead = census.get("failed", 0) + census.get("killed", 0)
                if dead != st["dead_letters"]:
                    problems.append(
                        f"failed+killed {dead} != dead_letters "
                        f"{st['dead_letters']}"
                    )

        threads = [
            threading.Thread(target=scrape_loop, daemon=True)
            for _ in range(2)
        ]
        for t in threads:
            t.start()
        try:
            reqs = _reqs(9, 8, seed0=40) + _reqs(16, 8, seed0=140)
            for r in reqs:
                srv.submit(r)
            for r in reqs:
                assert srv.wait(r.tenant, timeout=180)["status"] == "done"
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
            srv.shutdown(drain=True)
        assert not problems, problems[:5]
        final = srv.status()
        assert sum(final["tenant_counts"].values()) == 16
        assert final["tenant_counts"]["done"] == 16

    def test_openmetrics_negotiation_on_live_endpoint(self):
        import urllib.request

        metrics_registry.enabled = True
        srv = ServeServer(port=0, window_ms=5)
        try:
            (r,) = _reqs(9, 1, seed0=42)
            srv.submit(r)
            assert srv.wait(r.tenant, timeout=120)["status"] == "done"
            base = f"http://127.0.0.1:{srv.http.port}"
            with urllib.request.urlopen(
                base + "/metrics", timeout=5
            ) as resp:
                classic = resp.read().decode()
                ctype = resp.headers.get("Content-Type", "")
            assert "# EOF" not in classic
            assert "0.0.4" in ctype
            req = urllib.request.Request(
                base + "/metrics",
                headers={"Accept": "application/openmetrics-text"},
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                om = resp.read().decode()
                ctype = resp.headers.get("Content-Type", "")
            assert om.rstrip().endswith("# EOF")
            assert "openmetrics-text" in ctype
            # query-param opt-in works without the header
            with urllib.request.urlopen(
                base + "/metrics?format=openmetrics", timeout=5
            ) as resp:
                assert resp.read().decode().rstrip().endswith("# EOF")
        finally:
            srv.shutdown(drain=True)


class TestHaSurface:
    """graftha worker-side satellites: /healthz readiness transitions,
    the draining worker's structured 503 (Retry-After + peer list) and
    the router-tunable /window endpoint (docs/serving.md "HA fleet")."""

    @staticmethod
    def _solve_body(tenant):
        import json

        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_graph_coloring,
        )
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        doc = dcop_yaml(
            generate_graph_coloring(
                9, 3, graph="grid", seed=5, extensive=True
            )
        )
        return json.dumps(
            {
                "dcop_yaml": doc, "algo": "dsa", "n_cycles": 10,
                "seed": 0, "tenant": tenant,
            }
        ).encode()

    def test_healthz_readiness_transitions(self):
        import json
        import urllib.error
        import urllib.request

        srv = ServeServer(port=0, window_ms=1)
        base = f"http://127.0.0.1:{srv.http.port}"
        try:
            with urllib.request.urlopen(
                base + "/healthz", timeout=10
            ) as resp:
                assert resp.getcode() == 200
                doc = json.loads(resp.read())
            assert doc["state"] == "serving"
            assert doc["queue_depth"] == 0
            assert srv.drain(timeout=60)
            # draining/drained answers NOT READY — the body still says
            # which, so a probe can tell a drain from a crash loop
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(base + "/healthz", timeout=10)
            assert exc.value.code == 503
            body = json.loads(exc.value.read())
            assert body["state"] in ("draining", "drained")
        finally:
            srv.shutdown(drain=False)

    def test_draining_solve_rejected_with_structured_503(self):
        import json
        import urllib.error
        import urllib.request

        srv = ServeServer(
            port=0, window_ms=1, peers=["http://peer-a:9010/"]
        )
        base = f"http://127.0.0.1:{srv.http.port}"
        try:
            assert srv.drain(timeout=60)
            req = urllib.request.Request(
                base + "/solve", data=self._solve_body("late"),
                method="POST",
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=30)
            assert exc.value.code == 503
            # the machine-actionable parts: when to retry, where to go
            assert exc.value.headers["Retry-After"] == "2"
            body = json.loads(exc.value.read())
            assert body["state"] in ("draining", "drained")
            assert body["retry_after_s"] == 2
            assert body["peers"] == ["http://peer-a:9010"]
        finally:
            srv.shutdown(drain=False)

    def test_window_retune_endpoint(self):
        import json
        import urllib.error
        import urllib.request

        srv = ServeServer(port=0, window_ms=25)
        base = f"http://127.0.0.1:{srv.http.port}"
        try:
            req = urllib.request.Request(
                base + "/window",
                data=json.dumps({"window_ms": 80.0}).encode(),
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert json.loads(resp.read())["window_ms"] == 80.0
            assert srv.window_s == pytest.approx(0.08)
            # clamped, not rejected: a wild router can't park the loop
            req = urllib.request.Request(
                base + "/window",
                data=json.dumps({"window_ms": 9e9}).encode(),
                method="POST",
            )
            urllib.request.urlopen(req, timeout=10).read()
            assert srv.window_s == pytest.approx(10.0)
            # garbage answers 400 and changes nothing
            req = urllib.request.Request(
                base + "/window",
                data=json.dumps({"window_ms": None}).encode(),
                method="POST",
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=10)
            assert exc.value.code == 400
            assert srv.window_s == pytest.approx(10.0)
        finally:
            srv.shutdown(drain=True)

    def test_peers_config_plus_manifest_discovery(self, tmp_path):
        import json

        (tmp_path / "w1").mkdir()
        (tmp_path / "w1" / "fleet-manifest.json").write_text(
            json.dumps(
                {"kind": "fleet", "endpoint": "http://127.0.0.1:7001/"}
            )
        )
        srv = ServeServer(
            port=0,
            window_ms=1,
            checkpoint_dir=str(tmp_path / "me"),
            peers=["http://cfg:1", "http://cfg:1/"],  # dupes collapse
        )
        try:
            own = f"http://127.0.0.1:{srv.http.port}"
            # a sibling manifest recording OUR endpoint is not a peer
            (tmp_path / "w9").mkdir()
            (tmp_path / "w9" / "fleet-manifest.json").write_text(
                json.dumps({"kind": "fleet", "endpoint": own})
            )
            assert srv.peers() == ["http://cfg:1", "http://127.0.0.1:7001"]
        finally:
            srv.shutdown(drain=False)
