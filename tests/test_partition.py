"""graftpart: multilevel mesh-aware partitioning (pydcop_tpu/partition/).

Seeded-corpus property tests over four graph families and several shard
counts, pinning the contracts the subsystem sells:

- multilevel never loses to the BFS baseline on cross_shard_incidence;
- the balance bound: partition blocks are EXACTLY the padded
  DeviceDCOP's GSPMD row chunks;
- permutation validity: a reordered problem decodes identically
  (named assignments, costs);
- the analytic ICI model equals the measured layout
  (``ell_cross_shard_frac``) slot for slot, bytes for bytes;
- the tpu_part distribution method places every computation under
  capacity with the shared distribution_cost accounting.
"""

import numpy as np
import pytest

from pydcop_tpu.commands.generators.graphcoloring import (
    generate_coloring_arrays,
)
from pydcop_tpu.compile.direct import compile_from_edges


def _clique(n=24, d=3, seed=0):
    rng = np.random.default_rng(seed)
    ii, jj = np.triu_indices(n, k=1)
    edges = np.stack([ii, jj], axis=1)
    tables = rng.uniform(0, 10, size=(len(edges), d, d)).astype(
        np.float32
    )
    return compile_from_edges(n, d, edges, tables)


def _corpus():
    return [
        (
            "scalefree",
            generate_coloring_arrays(
                600, 3, graph="scalefree", m_edge=2, seed=11
            ),
        ),
        (
            "grid",
            generate_coloring_arrays(256, 3, graph="grid", seed=12),
        ),
        (
            "random",
            generate_coloring_arrays(
                400, 3, graph="random", p_edge=0.02, seed=13
            ),
        ),
        ("clique", _clique()),
    ]


class TestMultilevelPartition:
    @pytest.mark.parametrize("k", [2, 4, 8])
    def test_corpus_beats_bfs_and_holds_balance(self, k):
        from pydcop_tpu.parallel.placement import (
            bfs_order,
            cross_shard_incidence,
            partition_compiled,
            reorder_compiled,
        )

        for name, c in _corpus():
            placed = partition_compiled(
                c, strategy="multilevel", n_shards=k
            )
            bfs = reorder_compiled(c, bfs_order(c))
            inc_ml = cross_shard_incidence(placed, k)
            inc_bfs = cross_shard_incidence(bfs, k)
            # never worse than the baseline (clique is tight: every
            # balanced partition of K_n cuts the same edge count)
            assert inc_ml <= inc_bfs + 1e-9, (name, k, inc_ml, inc_bfs)

    @pytest.mark.parametrize("k", [2, 8])
    def test_partition_order_is_chunk_blocked(self, k):
        from pydcop_tpu.partition import chunk_targets, partition_order

        for name, c in _corpus():
            order, assign, info = partition_order(c, k)
            n = c.n_vars
            assert np.array_equal(np.sort(order), np.arange(n)), name
            targets = chunk_targets(n, k)
            sizes = np.bincount(assign, minlength=k)
            assert np.array_equal(sizes, targets), (name, sizes, targets)
            # the permutation lays part p exactly on block p
            chunk = (n + k) // k
            assert np.array_equal(
                assign[order],
                np.minimum(np.arange(n) // chunk, k - 1),
            ), name

    def test_reorder_decodes_identically(self):
        from pydcop_tpu.parallel.placement import partition_compiled

        c = generate_coloring_arrays(
            300, 3, graph="scalefree", m_edge=2, seed=5
        )
        placed = partition_compiled(c, strategy="multilevel", n_shards=4)
        assert sorted(placed.var_names) == sorted(c.var_names)
        a = {n: c.domains[i].values[-1] for i, n in enumerate(c.var_names)}
        cost_c, viol_c = c.host_cost(c.indices_from_assignment(a))
        cost_p, viol_p = placed.host_cost(
            placed.indices_from_assignment(a)
        )
        assert cost_c == pytest.approx(cost_p)
        assert viol_c == viol_p

    def test_strategy_dispatch_and_meta(self):
        from pydcop_tpu.parallel.placement import partition_compiled

        c = generate_coloring_arrays(
            200, 3, graph="scalefree", m_edge=2, seed=6
        )
        # auto without a shard count falls back to BFS (no meta stamp)
        auto = partition_compiled(c)
        assert getattr(auto, "_partition_meta", None) is None
        # auto with shards resolves to multilevel and stamps meta
        placed = partition_compiled(c, strategy="auto", n_shards=4)
        meta = getattr(placed, "_partition_meta", None)
        assert meta and meta["n_shards"] == 4
        assert meta["strategy"] == "multilevel"
        with pytest.raises(ValueError):
            partition_compiled(c, strategy="multilevel")  # no n_shards
        with pytest.raises(ValueError):
            partition_compiled(c, strategy="zigzag")

    @pytest.mark.parametrize("k", [2, 8])
    def test_icimodel_matches_measured_layout(self, k):
        from pydcop_tpu.compile.kernels import (
            build_ell,
            ell_cross_shard_frac,
        )
        from pydcop_tpu.partition import (
            ell_shard_assignment,
            ici_model,
            plane_itemsize,
        )

        for name, c in _corpus():
            shard_of, tag = ell_shard_assignment(c, k, None, "multilevel")
            assert tag == "multilevel"
            ell = build_ell(c, n_shards=k, shard_of=shard_of)
            frac = ell_cross_shard_frac(ell)
            model = ici_model(c, shard_of, k)
            assert model["incidence"] == pytest.approx(frac), name
            # bytes: measured frac x real slots x D x itemsize == model
            measured_bytes = (
                frac
                * c.n_edges
                * c.max_domain
                * plane_itemsize(c)
            )
            assert model["bytes_per_cycle"] == pytest.approx(
                measured_bytes
            ), name

    def test_ell_shard_assignment_resolution(self):
        from pydcop_tpu.parallel.placement import partition_compiled
        from pydcop_tpu.partition import ell_shard_assignment

        c = generate_coloring_arrays(
            200, 3, graph="scalefree", m_edge=2, seed=6
        )
        assert ell_shard_assignment(c, 1, None, "auto") == (None, "none")
        assert ell_shard_assignment(c, 4, None, "none") == (None, "none")
        shard_of, tag = ell_shard_assignment(c, 4, None, "auto")
        assert tag == "multilevel" and shard_of is not None
        assert shard_of.shape == (c.n_vars,)
        assert set(np.unique(shard_of)) <= set(range(4))
        bfs_of, tag = ell_shard_assignment(c, 4, None, "bfs")
        assert tag == "bfs" and bfs_of is not None
        # a pre-partitioned problem resolves auto to contiguous chunks
        placed = partition_compiled(c, strategy="multilevel", n_shards=4)
        pre, tag = ell_shard_assignment(placed, 4, None, "auto")
        assert pre is None and tag.startswith("pre:")
        with pytest.raises(ValueError):
            ell_shard_assignment(c, 4, None, "zigzag")

    def test_multilevel_assign_validates_targets(self):
        from pydcop_tpu.partition import multilevel_assign

        with pytest.raises(ValueError):
            multilevel_assign(
                np.zeros(5, dtype=np.int64),
                np.zeros(0, dtype=np.int64),
                np.zeros(0),
                np.array([1, 1]),
            )

    def test_edgeless_and_tiny_graphs(self):
        from pydcop_tpu.partition import chunk_targets, multilevel_assign

        # no edges: blocks fill in index order
        n, k = 10, 4
        assign = multilevel_assign(
            np.zeros(n + 1, dtype=np.int64),
            np.zeros(0, dtype=np.int64),
            np.zeros(0),
            chunk_targets(n, k),
        )
        assert np.array_equal(
            np.bincount(assign, minlength=k), chunk_targets(n, k)
        )
        # more parts than vertices: trailing parts legitimately empty
        n, k = 5, 8
        targets = chunk_targets(n, k)
        assert targets.sum() == n
        assign = multilevel_assign(
            np.zeros(n + 1, dtype=np.int64),
            np.zeros(0, dtype=np.int64),
            np.zeros(0),
            targets,
        )
        assert np.array_equal(np.bincount(assign, minlength=k), targets)


class TestShardedSolveWithPartition:
    def test_sharded_ell_solve_costs_match_across_orderings(self):
        """The graftpart ordering can never change a trajectory: sharded
        solves under none/bfs/multilevel orderings and the single-device
        solve all produce the same cost (per-variable math is
        order-invariant)."""
        import jax

        from pydcop_tpu.algorithms import maxsum
        from pydcop_tpu.compile.kernels import to_device
        from pydcop_tpu.parallel.mesh import (
            make_mesh,
            pad_device_dcop,
            shard_device_dcop,
        )

        if jax.device_count() < 8:
            pytest.skip("needs 8 (virtual) devices")
        c = generate_coloring_arrays(
            192, 3, graph="scalefree", m_edge=2, seed=9
        )
        mesh = make_mesh(8)
        dev = shard_device_dcop(
            pad_device_dcop(to_device(c), mesh.size), mesh
        )
        params = {"noise": 0.0, "stop_cycle": 8}
        ref = maxsum.solve(c, dict(params), n_cycles=8, seed=0)
        for ordering in ("none", "bfs", "multilevel", "auto"):
            res = maxsum.solve(
                c, dict(params, ordering=ordering),
                n_cycles=8, seed=0, dev=dev,
            )
            assert res.cost == ref.cost, ordering
            assert res.assignment == ref.assignment, ordering

    def test_warm_cache_keys_carry_strategy(self):
        """Two orderings solved back to back on ONE compiled problem must
        not share ELL plans (the satellite fix: the ell_host cache key
        carries the resolved strategy)."""
        from pydcop_tpu.partition import ell_shard_assignment

        c = generate_coloring_arrays(
            100, 3, graph="scalefree", m_edge=2, seed=4
        )
        a1, t1 = ell_shard_assignment(c, 4, None, "multilevel")
        a2, t2 = ell_shard_assignment(c, 4, None, "bfs")
        assert t1 != t2
        # the layouts genuinely differ, so a shared key would serve the
        # wrong pair permutation
        assert not np.array_equal(a1, a2)
        from pydcop_tpu.compile.kernels import build_ell

        e1 = build_ell(c, 4, None, shard_of=a1)
        e2 = build_ell(c, 4, None, shard_of=a2)
        assert not np.array_equal(e1.var_perm, e2.var_perm)


class TestTpuPartDistribution:
    def _dcop_graph(self):
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_graph_coloring,
        )
        from pydcop_tpu.computations_graph import factor_graph

        dcop = generate_graph_coloring(
            24, 3, graph="scalefree", m_edge=2, seed=3, n_agents=4
        )
        return dcop, factor_graph.build_computation_graph(dcop)

    def test_distribute_places_everything(self):
        from pydcop_tpu.distribution import tpu_part

        dcop, cg = self._dcop_graph()
        agents = list(dcop.agents.values())
        dist = tpu_part.distribute(cg, agents)
        placed = [
            c for a in dist.mapping.values() for c in a
        ] if isinstance(dist.mapping, dict) else []
        node_names = sorted(n.name for n in cg.nodes)
        assert sorted(placed) == node_names
        # node-count balance proportional to (equal) capacities
        sizes = sorted(len(cs) for cs in dist.mapping.values())
        assert sizes[-1] - sizes[0] <= 1

    def test_distribution_cost_beats_round_robin(self):
        """The shared distribution_cost API prices tpu_part 1:1 against
        any other method — and at equal balance the global partitioner
        must beat a blind balanced placement on communication cost.
        (An UNbalanced greedy like gh_cgdp with idle capacity trivially
        reaches zero comm by colocating everything; balance is the whole
        constraint here, as it is on the mesh.)"""
        from pydcop_tpu.distribution import tpu_part
        from pydcop_tpu.distribution.objects import Distribution

        dcop, cg = self._dcop_graph()
        agents = sorted(dcop.agents.values(), key=lambda a: a.name)
        d_part = tpu_part.distribute(cg, agents)
        names = sorted(n.name for n in cg.nodes)
        rr = Distribution({
            a.name: names[i :: len(agents)]
            for i, a in enumerate(agents)
        })
        cost_part, comm_part, _ = tpu_part.distribution_cost(
            d_part, cg, agents
        )
        cost_rr, comm_rr, _ = tpu_part.distribution_cost(
            rr, cg, agents
        )
        assert comm_part < comm_rr
        assert cost_part < cost_rr

    def test_capacity_violation_raises(self):
        from pydcop_tpu.dcop.objects import AgentDef
        from pydcop_tpu.distribution import tpu_part
        from pydcop_tpu.distribution.objects import (
            ImpossibleDistributionException,
        )

        dcop, cg = self._dcop_graph()
        tiny = [
            AgentDef(f"t{i}", capacity=1) for i in range(4)
        ]
        with pytest.raises(ImpossibleDistributionException):
            tpu_part.distribute(
                cg, tiny, computation_memory=lambda n: 10.0
            )

    def test_no_agents_raises(self):
        from pydcop_tpu.distribution import tpu_part
        from pydcop_tpu.distribution.objects import (
            ImpossibleDistributionException,
        )

        dcop, cg = self._dcop_graph()
        with pytest.raises(ImpossibleDistributionException):
            tpu_part.distribute(cg, [])
