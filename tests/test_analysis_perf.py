"""graftperf (graftlint pass 6): rule fixtures, the suppression
grammar, cache/SARIF integration, and the perf *budget* ratchet —
tools/perf_budget.json pinned both statically (AST site census,
analysis/budget.py) and at runtime (graftprof's jit_census/readback
counters must report exactly what the manifest promises for a warm
solve on each engine path).
"""

import json
import os
import textwrap

import pytest

from pydcop_tpu.analysis import collect_findings
from pydcop_tpu.analysis.budget import (
    check_budget,
    chunk_count,
    chunk_schedule,
    load_manifest,
    static_census,
)
from pydcop_tpu.analysis.cli import main as lint_main

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MANIFEST = os.path.join(REPO_ROOT, "tools", "perf_budget.json")

PERF_RULES = (
    "perf-host-sync",
    "perf-dispatch-in-loop",
    "perf-transfer-in-loop",
    "perf-recompile-hazard",
    "perf-donate-miss",
    "perf-nonjit-hot",
)


def lint_source(tmp_path, source, name="sample.py", select=None):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return collect_findings([str(p)], select=select)


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------
# perf-host-sync
# ---------------------------------------------------------------------


class TestHostSync:
    def test_float_in_jit_body_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import jax

            @jax.jit
            def step(x):
                return x + float(x.sum())
            """,
            select=["perf-host-sync"],
        )
        assert rules_of(fs) == {"perf-host-sync"}
        assert all(f.severity == "error" for f in fs)

    def test_hot_root_implicit_bool_true_positive(self, tmp_path):
        # _fused_core is an engine hot root: walked even though it
        # carries no jit decorator, with tracedness from annotations
        fs = lint_source(
            tmp_path,
            """
            def _fused_core(dev, carry, key):
                if carry:
                    return carry
                return dev
            """,
            select=["perf-host-sync"],
        )
        (f,) = fs
        assert f.message.startswith("implicit __bool__ host sync:")

    def test_hot_root_static_annotation_negative(self, tmp_path):
        # int/bool/Callable-annotated params are configuration, not
        # traced values: branching on them is free
        fs = lint_source(
            tmp_path,
            """
            def _fused_core(dev, n_cycles: int, collect: bool):
                if collect and n_cycles:
                    return dev
                return dev
            """,
            select=["perf-host-sync"],
        )
        assert fs == []

    def test_plain_function_negative(self, tmp_path):
        # neither jit-decorated nor a hot root: host code may sync
        fs = lint_source(
            tmp_path,
            """
            def summarize(x):
                return float(x.sum())
            """,
            select=["perf-host-sync"],
        )
        assert fs == []


# ---------------------------------------------------------------------
# perf-dispatch-in-loop
# ---------------------------------------------------------------------


DISPATCH_LOOP = """
    import jax

    @jax.jit
    def kernel(x):
        return x * 2

    def drive(xs):
        out = []
        for x in xs:
            out.append(kernel(x))
        return out
    """


class TestDispatchInLoop:
    def test_for_loop_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path, DISPATCH_LOOP, select=["perf-dispatch-in-loop"]
        )
        (f,) = fs
        assert "kernel()" in f.message and "drive()" in f.message

    def test_comprehension_counts_as_loop(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import jax

            @jax.jit
            def kernel(x):
                return x * 2

            def drive(xs):
                return [kernel(x) for x in xs]
            """,
            select=["perf-dispatch-in-loop"],
        )
        assert rules_of(fs) == {"perf-dispatch-in-loop"}

    def test_jit_assigned_name_is_an_entry(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import jax

            def kernel(x):
                return x * 2

            fast = jax.jit(kernel)

            def drive(xs):
                return [fast(x) for x in xs]
            """,
            select=["perf-dispatch-in-loop"],
        )
        assert rules_of(fs) == {"perf-dispatch-in-loop"}

    def test_call_outside_loop_negative(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import jax

            @jax.jit
            def kernel(x):
                return x * 2

            def drive(x):
                return kernel(x)
            """,
            select=["perf-dispatch-in-loop"],
        )
        assert fs == []

    def test_loop_inside_traced_wrapper_negative(self, tmp_path):
        # the dpop.replay shape: the loop lives in a function that is
        # itself handed to jit, so it unrolls into ONE program
        fs = lint_source(
            tmp_path,
            """
            import jax

            @jax.jit
            def kernel(x):
                return x * 2

            def replay(xs):
                acc = xs[0]
                for x in xs:
                    acc = kernel(acc)
                return acc

            replay_c = jax.jit(replay)
            """,
            select=["perf-dispatch-in-loop"],
        )
        assert fs == []


# ---------------------------------------------------------------------
# perf-transfer-in-loop
# ---------------------------------------------------------------------


class TestTransferInLoop:
    def test_upload_per_iteration_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            from pydcop_tpu.compile.kernels import to_device

            def drive(rows):
                out = []
                for r in rows:
                    out.append(to_device(r))
                return out
            """,
            select=["perf-transfer-in-loop"],
        )
        (f,) = fs
        assert "to_device" in f.message

    def test_upload_before_loop_negative(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            from pydcop_tpu.compile.kernels import to_device

            def drive(rows):
                dev = to_device(rows)
                out = []
                for r in dev:
                    out.append(r)
                return out
            """,
            select=["perf-transfer-in-loop"],
        )
        assert fs == []


# ---------------------------------------------------------------------
# perf-recompile-hazard
# ---------------------------------------------------------------------


class TestRecompileHazard:
    def test_len_of_mutated_container_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            from functools import partial
            import jax

            @partial(jax.jit, static_argnames=("n",))
            def kernel(x, n):
                return x[:n]

            def drive(x, acc):
                acc.append(x)
                return kernel(x, n=len(acc))
            """,
            select=["perf-recompile-hazard"],
        )
        (f,) = fs
        assert "len(acc)" in f.message and "mutated" in f.message

    def test_dict_order_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            from functools import partial
            import jax

            @partial(jax.jit, static_argnames=("names",))
            def kernel(x, names):
                return x

            def drive(x, d):
                return kernel(x, names=tuple(d.keys()))
            """,
            select=["perf-recompile-hazard"],
        )
        (f,) = fs
        assert "dict iteration order" in f.message

    def test_float_is_comparison_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            def pick(threshold):
                if threshold is 0.5:
                    return 1
                return 0
            """,
            select=["perf-recompile-hazard"],
        )
        (f,) = fs
        assert "float" in f.message and "`is`" in f.message

    def test_stable_len_negative(self, tmp_path):
        # len() of a container never mutated in this scope is stable
        fs = lint_source(
            tmp_path,
            """
            from functools import partial
            import jax

            @partial(jax.jit, static_argnames=("n",))
            def kernel(x, n):
                return x[:n]

            def drive(x, xs):
                return kernel(x, n=len(xs))
            """,
            select=["perf-recompile-hazard"],
        )
        assert fs == []

    def test_sorted_stabilizes_negative(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            from functools import partial
            import jax

            @partial(jax.jit, static_argnames=("names",))
            def kernel(x, names):
                return x

            def drive(x, d):
                return kernel(x, names=tuple(sorted(d.keys())))
            """,
            select=["perf-recompile-hazard"],
        )
        assert fs == []


# ---------------------------------------------------------------------
# perf-donate-miss
# ---------------------------------------------------------------------


class TestDonateMiss:
    def test_undonated_carry_true_positive(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import jax

            @jax.jit
            def advance(state: PulseCarry):
                return state._replace(step=state.step + 1)
            """,
            select=["perf-donate-miss"],
        )
        (f,) = fs
        assert "advance()" in f.message and "'state'" in f.message

    def test_donated_carry_negative(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            from functools import partial
            import jax

            @partial(jax.jit, donate_argnums=(0,))
            def advance(state: PulseCarry):
                return state._replace(step=state.step + 1)
            """,
            select=["perf-donate-miss"],
        )
        assert fs == []

    def test_read_only_record_negative(self, tmp_path):
        # the record is consumed, not threaded: nothing to donate
        fs = lint_source(
            tmp_path,
            """
            import jax

            @jax.jit
            def score(dev: DeviceDCOP, values):
                return values.sum()
            """,
            select=["perf-donate-miss"],
        )
        assert fs == []


# ---------------------------------------------------------------------
# perf-nonjit-hot
# ---------------------------------------------------------------------


class TestNonjitHot:
    def test_lanes_fallback_shape_true_positive(self, tmp_path):
        # the PR-8 regression shape: a per-cycle step kernel invoked
        # eagerly from a Python fallback loop, ~6x slower
        fs = lint_source(
            tmp_path,
            """
            import jax.numpy as jnp

            # graftperf: hot
            def step(dev, values):
                return jnp.argmin(values, axis=1)

            def fallback(dev, values, n):
                for _ in range(n):
                    values = step(dev, values)
                return values
            """,
            select=["perf-nonjit-hot"],
        )
        (f,) = fs
        assert "step()" in f.message
        assert "lanes-fallback" in f.message

    def test_passed_to_engine_negative(self, tmp_path):
        # handed by name into a call (run_cycles-style factory wiring):
        # the callee chooses the traced context
        fs = lint_source(
            tmp_path,
            """
            import jax.numpy as jnp

            # graftperf: hot
            def step(dev, values):
                return jnp.argmin(values, axis=1)

            def solve(dev, values):
                return run_cycles(dev, step, values)
            """,
            select=["perf-nonjit-hot"],
        )
        assert fs == []

    def test_returned_from_factory_negative(self, tmp_path):
        # the _make_step idiom: the marked closure escapes via return
        fs = lint_source(
            tmp_path,
            """
            import jax.numpy as jnp

            def _make_step(p):
                # graftperf: hot
                def step(dev, values):
                    return jnp.argmin(values * p, axis=1)
                return step
            """,
            select=["perf-nonjit-hot"],
        )
        assert fs == []

    def test_jit_decorated_negative(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import jax
            import jax.numpy as jnp

            # graftperf: hot
            @jax.jit
            def step(dev, values):
                return jnp.argmin(values, axis=1)
            """,
            select=["perf-nonjit-hot"],
        )
        assert fs == []

    def test_unmarked_eager_function_negative(self, tmp_path):
        # no marker -> not this rule's business
        fs = lint_source(
            tmp_path,
            """
            import jax.numpy as jnp

            def step(dev, values):
                return jnp.argmin(values, axis=1)
            """,
            select=["perf-nonjit-hot"],
        )
        assert fs == []


# ---------------------------------------------------------------------
# suppression grammar
# ---------------------------------------------------------------------


class TestSuppression:
    def test_graftperf_alias_suppresses(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import jax

            @jax.jit
            def kernel(x):
                return x * 2

            def drive(xs):
                return [kernel(x) for x in xs]  # graftperf: disable=perf-dispatch-in-loop (measured floor)
            """,
            select=["perf-dispatch-in-loop"],
        )
        assert fs == []

    def test_graftlint_prefix_also_works(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import jax

            @jax.jit
            def kernel(x):
                return x * 2

            def drive(xs):
                return [kernel(x) for x in xs]  # graftlint: disable=perf-dispatch-in-loop
            """,
            select=["perf-dispatch-in-loop"],
        )
        assert fs == []

    def test_unrelated_rule_does_not_suppress(self, tmp_path):
        fs = lint_source(
            tmp_path,
            """
            import jax

            @jax.jit
            def kernel(x):
                return x * 2

            def drive(xs):
                return [kernel(x) for x in xs]  # graftperf: disable=perf-host-sync
            """,
            select=["perf-dispatch-in-loop"],
        )
        assert rules_of(fs) == {"perf-dispatch-in-loop"}


# ---------------------------------------------------------------------
# CLI wiring: --explain, --list-rules, cache, SARIF
# ---------------------------------------------------------------------


class TestCliWiring:
    def test_explain_covers_every_perf_rule(self, capsys):
        for rule in PERF_RULES:
            assert lint_main(["--explain", rule]) == 0
            out = capsys.readouterr().out
            assert rule in out and "Minimal failing example" in out

    def test_list_rules_includes_pass_six(self, capsys):
        assert lint_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in PERF_RULES:
            assert rule in out

    def test_passes_flag_selects_perf(self, tmp_path, capsys):
        p = tmp_path / "s.py"
        p.write_text(textwrap.dedent(DISPATCH_LOOP))
        rc = lint_main(
            ["--no-cache", "--passes", "perf", "--format", "json", str(p)]
        )
        out = capsys.readouterr().out
        assert rc == 1
        doc = json.loads(out)
        assert {f["rule"] for f in doc["new"]} == {
            "perf-dispatch-in-loop"
        }

    def test_sarif_carries_perf_findings(self, tmp_path, capsys):
        p = tmp_path / "s.py"
        p.write_text(textwrap.dedent(DISPATCH_LOOP))
        rc = lint_main(["--no-cache", "--format", "sarif", str(p)])
        out = capsys.readouterr().out
        assert rc == 1
        doc = json.loads(out)
        driver = doc["runs"][0]["tool"]["driver"]
        assert driver["name"] == "graftlint"
        rule_ids = {r["id"] for r in driver["rules"]}
        assert set(PERF_RULES) <= rule_ids
        assert any(
            r["ruleId"] == "perf-dispatch-in-loop"
            for r in doc["runs"][0]["results"]
        )


class TestCacheIntegration:
    @pytest.fixture(autouse=True)
    def _state_dir(self, tmp_path, monkeypatch):
        monkeypatch.setenv(
            "PYDCOP_TPU_STATE_DIR", str(tmp_path / "state")
        )

    def test_warm_run_serves_perf_findings(self, tmp_path, monkeypatch):
        p = tmp_path / "s.py"
        p.write_text(textwrap.dedent(DISPATCH_LOOP))
        cold = collect_findings([str(p)], use_cache=True)
        assert "perf-dispatch-in-loop" in rules_of(cold)
        from pydcop_tpu.analysis import core as core_mod

        def boom(text, rpath):
            raise AssertionError("cache miss: source was parsed")

        monkeypatch.setattr(core_mod, "source_from_text", boom)
        warm = collect_findings([str(p)], use_cache=True)
        assert [f.as_dict() for f in warm] == [
            f.as_dict() for f in cold
        ]

    def test_perf_version_bump_invalidates(self, tmp_path, monkeypatch):
        p = tmp_path / "s.py"
        p.write_text(textwrap.dedent(DISPATCH_LOOP))
        collect_findings([str(p)], use_cache=True)
        from pydcop_tpu.analysis import core as core_mod, perf

        monkeypatch.setattr(perf, "VERSION", perf.VERSION + 1)

        def boom(text, rpath):
            raise RuntimeError("re-ran after version bump")

        monkeypatch.setattr(core_mod, "source_from_text", boom)
        with pytest.raises(RuntimeError, match="version bump"):
            collect_findings([str(p)], use_cache=True)


# ---------------------------------------------------------------------
# the repo itself is clean (the ratchet stays empty)
# ---------------------------------------------------------------------


class TestRepoClean:
    def test_pass_six_repo_findings_all_resolved(self):
        """Satellite 1: every real graftperf finding in the package is
        either fixed or carries an inline suppression with a reason —
        the checked-in baseline stays EMPTY."""
        fs = collect_findings(
            [os.path.join(REPO_ROOT, "pydcop_tpu")], passes=["perf"]
        )
        assert fs == [], [f.format() for f in fs]


# ---------------------------------------------------------------------
# budget: static census vs the pinned manifest
# ---------------------------------------------------------------------


class TestBudgetStatic:
    def test_manifest_pins_hold_against_repo(self):
        manifest = load_manifest(MANIFEST)
        problems = check_budget(manifest, root=REPO_ROOT)
        assert problems == []

    def test_census_covers_every_engine_path(self):
        manifest = load_manifest(MANIFEST)
        census = static_census(manifest, root=REPO_ROOT)
        assert set(census) >= {
            "fused", "chunked", "serve_vmap", "checkpointed_chunked",
            "chunk_schedule",
        }
        # fused contract: exactly one straight-line dispatch and one
        # straight-line packed readback — no dispatch under any loop
        fused = census["fused"]
        assert fused["dispatch_sites"] == {
            "straight": 1, "conditional": 0, "loop": 0
        }
        assert fused["readback_sites"]["straight"] == 1
        # chunked contract: dispatches only inside the chunk loop
        chunked = census["chunked"]
        assert chunked["dispatch_sites"]["straight"] == 0
        assert chunked["dispatch_sites"]["loop"] >= 1
        # checkpointing adds zero dispatches
        ckpt = census["checkpointed_chunked"]
        assert ckpt["dispatch_sites"] == {
            "straight": 0, "conditional": 0, "loop": 0
        }

    def test_chunk_schedule_matches_base_constants(self):
        manifest = load_manifest(MANIFEST)
        census = static_census(manifest, root=REPO_ROOT)
        cs = manifest["chunk_schedule"]
        assert census["chunk_schedule"] == {
            "start": cs["start"], "cap": cs["cap"]
        }
        assert chunk_schedule(40, start=cs["start"], cap=cs["cap"]) == [
            16, 24
        ]
        assert chunk_count(40, manifest) == 2
        assert chunk_count(16, manifest) == 1
        # the ladder doubles then saturates at the cap
        sched = chunk_schedule(200, start=16, cap=64)
        assert sched == [16, 32, 64, 64, 24]

    def test_tampered_manifest_fails(self):
        manifest = load_manifest(MANIFEST)
        manifest["static"]["fused"]["dispatch_sites"]["straight"] += 1
        problems = check_budget(manifest, root=REPO_ROOT)
        assert any("fused.dispatch_sites" in p for p in problems)

    def _mini_engine(self, tmp_path, extra_fused_dispatch=False):
        extra = "        out = _kernel(out)\n" if extra_fused_dispatch else ""
        (tmp_path / "engine.py").write_text(
            "import jax\n\n"
            "@jax.jit\n"
            "def _kernel(x):\n"
            "    return x\n\n"
            "def run_cycles(dev, n_cycles, timeout=None):\n"
            "    if timeout is None:\n"
            "        out = _kernel(dev)\n"
            + extra
            + "        return to_host(out)\n"
            "    acc = dev\n"
            "    for _ in range(n_cycles):\n"
            "        acc = _kernel(acc)\n"
            "    return to_host(acc)\n"
        )
        return {
            "static": {
                "fused": {
                    "region": "engine.py::run_cycles[fused]",
                    "dispatch_sites": {
                        "straight": 1, "conditional": 0, "loop": 0
                    },
                    "readback_sites": {
                        "straight": 1, "conditional": 0, "loop": 0
                    },
                },
                "chunked": {
                    "region": "engine.py::run_cycles[chunked]",
                    "dispatch_sites": {
                        "straight": 0, "conditional": 0, "loop": 1
                    },
                    "readback_sites": {
                        "straight": 1, "conditional": 0, "loop": 0
                    },
                },
            }
        }

    def test_deliberate_break_is_caught(self, tmp_path):
        """The ratchet's reason to exist: an engine edit that adds a
        dispatch site must fail check_budget until the manifest is
        consciously re-pinned."""
        manifest = self._mini_engine(tmp_path)
        assert check_budget(manifest, root=str(tmp_path)) == []
        manifest = self._mini_engine(
            tmp_path, extra_fused_dispatch=True
        )
        problems = check_budget(manifest, root=str(tmp_path))
        assert len(problems) == 1
        assert "fused.dispatch_sites" in problems[0]
        assert "'straight': 2" in problems[0]

    def test_fused_region_anchor_is_required(self, tmp_path):
        (tmp_path / "engine.py").write_text(
            "def run_cycles(dev):\n    return dev\n"
        )
        manifest = {
            "static": {
                "fused": {
                    "region": "engine.py::run_cycles[fused]",
                    "dispatch_sites": {
                        "straight": 0, "conditional": 0, "loop": 0
                    },
                    "readback_sites": {
                        "straight": 0, "conditional": 0, "loop": 0
                    },
                }
            }
        }
        with pytest.raises(ValueError, match="timeout"):
            check_budget(manifest, root=str(tmp_path))


# ---------------------------------------------------------------------
# budget: runtime cross-validation (static == runtime)
# ---------------------------------------------------------------------


class TestBudgetRuntime:
    """The manifest's ``runtime`` half must be what graftprof actually
    measures: a warm solve on each engine path reports exactly the
    pinned dispatch/readback counts, and those pins are consistent with
    the static site census (one straight dispatch site <-> one dispatch
    per solve; dispatch sites only in the chunk loop <-> one dispatch
    per chunk)."""

    @pytest.fixture(autouse=True)
    def _telemetry(self):
        pytest.importorskip("jax")
        yield
        from pydcop_tpu.telemetry import telemetry_off

        telemetry_off()

    def _compiled_chain(self):
        import sys

        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        try:
            from test_algorithms import simple_chain
        finally:
            sys.path.pop(0)
        from pydcop_tpu.compile.core import compile_dcop

        return compile_dcop(simple_chain())

    def _measure(self, fn):
        """Warm-up once (compiles), then measure a second, warm run."""
        from pydcop_tpu.telemetry import metrics_registry
        from pydcop_tpu.telemetry.profiling import (
            jit_census,
            readback_census,
        )

        fn()
        metrics_registry.reset()
        metrics_registry.enabled = True
        try:
            fn()
        finally:
            metrics_registry.enabled = False
        return jit_census(), readback_census()

    def test_fused_runtime_matches_manifest(self):
        from pydcop_tpu.algorithms import load_algorithm_module

        manifest = load_manifest(MANIFEST)
        rt = manifest["runtime"]["fused"]
        compiled = self._compiled_chain()
        mod = load_algorithm_module("dsa")
        jc, rb = self._measure(
            lambda: mod.solve(compiled, n_cycles=8, seed=0)
        )
        entry = jc[rt["entry"]]
        assert entry["dispatches"] == rt["dispatches_per_solve"] == 1
        assert entry["compiles"] == rt["warm_compiles"] == 0
        assert rb["windows"] == rt["readback_windows_per_solve"] == 1
        assert rb["readbacks"] == rt["packed_readbacks_per_solve"] == 1
        # static == runtime: the one straight-line dispatch site IS the
        # one dispatch the warm solve performs
        static = static_census(manifest, root=REPO_ROOT)["fused"]
        assert (
            static["dispatch_sites"]["straight"]
            == rt["dispatches_per_solve"]
        )

    def test_chunked_runtime_matches_manifest(self):
        from pydcop_tpu.algorithms import load_algorithm_module

        manifest = load_manifest(MANIFEST)
        rt = manifest["runtime"]["chunked"]
        compiled = self._compiled_chain()
        mod = load_algorithm_module("dsa")
        n_cycles = 40
        chunks = chunk_count(n_cycles, manifest)
        assert chunks == 2  # [16, 24]: the cross-check is non-trivial
        jc, rb = self._measure(
            lambda: mod.solve(
                compiled, n_cycles=n_cycles, seed=0, timeout=1e6
            )
        )
        entry = jc[rt["entry"]]
        assert (
            entry["dispatches"]
            == chunks * rt["dispatches_per_chunk"]
        )
        assert entry["compiles"] == rt["warm_compiles"] == 0
        assert (
            rb["windows"] == chunks * rt["readback_windows_per_chunk"]
        )
        assert rb["readbacks"] == rt["final_readbacks_per_solve"] == 1
        # static == runtime: every dispatch site sits in the chunk
        # loop, so the count scales with the schedule, not the code
        static = static_census(manifest, root=REPO_ROOT)["chunked"]
        assert static["dispatch_sites"]["straight"] == 0
        assert static["dispatch_sites"]["loop"] >= 1

    def test_serve_runtime_matches_manifest(self):
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_coloring_arrays,
        )
        from pydcop_tpu.serve import SolveRequest, solve_batched

        manifest = load_manifest(MANIFEST)
        rt = manifest["runtime"]["serve_vmap"]
        reqs = [
            SolveRequest(
                f"dsa-9-{i}",
                generate_coloring_arrays(9, 3, graph="grid", seed=50 + i),
                "dsa",
                {},
                20,
                i,
            )
            for i in range(4)
        ]
        jc, _ = self._measure(lambda: solve_batched(reqs))
        entry = jc[rt["entry"]]
        # all four same-bucket requests ride ONE vmapped dispatch
        assert entry["dispatches"] == rt["dispatches_per_batch"] == 1
        assert entry["compiles"] == rt["warm_compiles"] == 0
        static = static_census(manifest, root=REPO_ROOT)["serve_vmap"]
        assert (
            static["dispatch_sites"]["straight"]
            == rt["dispatches_per_batch"]
        )

    def test_deliberate_runtime_break_fails_the_check(self):
        """Runtime half of the deliberate break: if the engine grew an
        extra warm dispatch, the manifest comparison above would fail —
        simulate by tampering the pin and re-asserting the census."""
        from pydcop_tpu.algorithms import load_algorithm_module
        from pydcop_tpu.telemetry.profiling import jit_census

        manifest = load_manifest(MANIFEST)
        rt = dict(manifest["runtime"]["fused"])
        rt["dispatches_per_solve"] += 1  # the tampered pin
        compiled = self._compiled_chain()
        mod = load_algorithm_module("dsa")
        jc, _ = self._measure(
            lambda: mod.solve(compiled, n_cycles=8, seed=0)
        )
        assert (
            jc[rt["entry"]]["dispatches"] != rt["dispatches_per_solve"]
        )
