"""CLI end-to-end tests (SURVEY.md §4 tier 4): run the real
``python -m pydcop_tpu ...`` as a subprocess on instance files and parse the
JSON result, like the reference's tests/dcop_cli tier — but with seeded PRNG
so results are deterministic."""

import json
import os
import subprocess
import sys

import pytest

REF_INSTANCES = "/root/reference/tests/instances"

ENV = dict(os.environ, JAX_PLATFORMS="cpu")


def run_cli(*args, timeout=90, env=None):
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        env={**ENV, **(env or {})},
        cwd="/root/repo",
    )


def run_json(*args, timeout=90):
    r = run_cli(*args, timeout=timeout)
    assert r.returncode == 0, r.stderr
    return json.loads(r.stdout)


class TestSolveCli:
    def test_solve_dpop(self):
        out = run_json(
            "solve", "-a", "dpop",
            f"{REF_INSTANCES}/graph_coloring1.yaml",
        )
        assert out["status"] == "FINISHED"
        assert out["cost"] == pytest.approx(-0.1)
        assert out["violation"] == 0
        assert set(out["assignment"]) == {"v1", "v2", "v3"}

    def test_solve_infinity_threshold(self, tmp_path):
        # --infinity moves the hard-constraint reporting threshold: a soft
        # cost above it becomes a counted violation excluded from the cost
        f = tmp_path / "t.yaml"
        f.write_text(
            """
name: t
objective: min
domains: {d: {values: [a, b]}}
variables: {v1: {domain: d}, v2: {domain: d}}
constraints:
  c12: {type: intention, function: 500 if v1 == v2 else 600}
agents: [a1]
"""
        )
        default = run_json("solve", "-a", "dsa", "-n", "10", str(f))
        assert default["violation"] == 0
        assert default["cost"] == pytest.approx(500.0)
        low = run_json(
            "solve", "-a", "dsa", "-n", "10", "-i", "100", str(f)
        )
        assert low["violation"] == 1
        assert low["cost"] == pytest.approx(0.0)

    def test_solve_maxsum_with_params(self):
        out = run_json(
            "solve", "-a", "maxsum", "-p", "damping:0.7", "-n", "30",
            "--seed", "3",
            f"{REF_INSTANCES}/graph_coloring_3agts_10vars.yaml",
        )
        assert out["status"] == "FINISHED"
        assert out["violation"] <= 2

    def test_solve_thread_mode(self):
        out = run_json(
            "solve", "-a", "dpop", "-m", "thread",
            f"{REF_INSTANCES}/graph_coloring1.yaml",
        )
        assert out["status"] == "FINISHED"
        assert out["cost"] == pytest.approx(-0.1)

    def test_invalid_algo_param_rejected(self):
        r = run_cli(
            "solve", "-a", "dsa", "-p", "variant:Z",
            f"{REF_INSTANCES}/graph_coloring1.yaml",
        )
        assert r.returncode != 0


class TestSolveCliModeMatrix:
    """Mode x algorithm breadth over the runtime paths (round-4 verdict
    missing item 5): thread mode drives the orchestrator + threaded
    agents, process mode spawns one OS process per agent over HTTP —
    both must produce the reference-schema result for representative
    algorithms of each family."""

    @pytest.mark.parametrize(
        "algo", ["maxsum", "amaxsum", "dsa", "mgm2", "dpop"]
    )
    def test_thread_mode(self, algo):
        out = run_json(
            "solve", "-a", algo, "-m", "thread", "-n", "30",
            f"{REF_INSTANCES}/graph_coloring1.yaml",
            timeout=180,
        )
        assert out["status"] == "FINISHED"
        # the instance's optimum is -0.1; every family reaches it within
        # 30 cycles (complete solvers exactly, local search on this tiny
        # 3-variable instance reliably)
        assert out["cost"] == pytest.approx(-0.1)

    @pytest.mark.slow
    @pytest.mark.parametrize("algo", ["maxsum", "dsa"])
    def test_process_mode(self, algo):
        # one OS process per agent; spawn + the site plugin's jax import
        # cost seconds per child, hence the generous timeout.  This is
        # the path that silently broke when __main__ lacked its spawn
        # guard (agents re-entered the CLI and never registered).
        out = run_json(
            "solve", "-a", algo, "-m", "process", "-n", "20",
            f"{REF_INSTANCES}/graph_coloring1.yaml",
            timeout=280,
        )
        assert out["status"] == "FINISHED"
        assert out["cost"] == pytest.approx(-0.1)


class TestGraphCli:
    def test_graph_metrics(self):
        out = run_json(
            "graph", "-g", "constraints_hypergraph",
            f"{REF_INSTANCES}/graph_coloring1.yaml",
        )
        assert out["graph"]["nodes_count"] == 3
        assert out["graph"]["edges_count"] == 2


class TestDistributeCli:
    def test_distribute_adhoc(self):
        out = run_json(
            "distribute", "-d", "adhoc", "-a", "dsa",
            f"{REF_INSTANCES}/graph_coloring_3agts_10vars.yaml",
        )
        hosted = [
            c for comps in out["distribution"].values() for c in comps
        ]
        assert len(hosted) == 10

    def test_distribute_maxsum_factorgraph(self):
        out = run_json(
            "distribute", "-d", "adhoc", "-a", "maxsum",
            f"{REF_INSTANCES}/graph_coloring_3agts_10vars.yaml",
        )
        assert out["status"] == "OK"


class TestMetricsCsvCli:
    def test_run_metrics_writes_per_cycle_costs(self, tmp_path):
        run_csv = tmp_path / "run.csv"
        out = run_json(
            "solve", "-a", "dsa", "-n", "10", "--seed", "1",
            "--run_metrics", str(run_csv),
            f"{REF_INSTANCES}/graph_coloring1.yaml",
        )
        assert out["status"] == "FINISHED"
        lines = run_csv.read_text().splitlines()
        assert len(lines) == 11  # header + one row per cycle
        # costs parse as floats
        for row in lines[1:]:
            float(row.split(",")[-1])

    def test_end_metrics_appends_across_runs(self, tmp_path):
        end_csv = tmp_path / "end.csv"
        for seed in ("1", "2"):
            run_json(
                "solve", "-a", "dsa", "-n", "5", "--seed", seed,
                "--end_metrics", str(end_csv),
                f"{REF_INSTANCES}/graph_coloring1.yaml",
            )
        lines = end_csv.read_text().splitlines()
        assert lines[0].startswith("time,status,cost")
        assert len(lines) == 3  # one header, two appended rows


class TestGenerateCli:
    def test_generated_coloring_solves(self, tmp_path):
        f = tmp_path / "gc.yaml"
        r = run_cli(
            "generate", "graph_coloring", "-v", "6", "-c", "3",
            "--soft", "--seed", "1", "-o", str(f),
        )
        assert r.returncode == 0 and f.exists()
        out = run_json("solve", "-a", "dpop", str(f))
        assert out["status"] == "FINISHED"

    def test_generated_ising_solves(self, tmp_path):
        f = tmp_path / "ising.yaml"
        r = run_cli(
            "generate", "ising", "--row_count", "3", "--seed", "2",
            "-o", str(f),
        )
        assert r.returncode == 0
        out = run_json("solve", "-a", "mgm", "-n", "20", str(f))
        assert out["status"] == "FINISHED"

    def test_generated_meetings_solves(self, tmp_path):
        f = tmp_path / "ms.yaml"
        r = run_cli(
            "generate", "meeting_scheduling",
            "--resources_count", "2", "--events_count", "2",
            "--seed", "1", "-o", str(f),
        )
        assert r.returncode == 0
        out = run_json("solve", "-a", "dpop", str(f))
        assert out["status"] == "FINISHED"
        assert out["violation"] == 0

    def test_generated_iot_solves(self, tmp_path):
        # powerlaw IoT problems (reference generate.py iot subcommand)
        f = tmp_path / "iot.yaml"
        r = run_cli(
            "generate", "iot", "--num", "15", "--seed", "1", "-o", str(f),
        )
        assert r.returncode == 0
        out = run_json("solve", "-a", "dsa", "-n", "30", str(f))
        assert out["status"] == "FINISHED"
        assert len(out["assignment"]) == 15

    def test_generated_small_world_solves(self, tmp_path):
        f = tmp_path / "sw.yaml"
        r = run_cli(
            "generate", "small_world", "--num", "12", "--seed", "1",
            "-o", str(f),
        )
        assert r.returncode == 0
        out = run_json("solve", "-a", "mgm", "-n", "30", str(f))
        assert out["status"] == "FINISHED"
        assert len(out["assignment"]) == 12

    def test_generated_secp_solves(self, tmp_path):
        f = tmp_path / "secp.yaml"
        r = run_cli(
            "generate", "secp", "-l", "3", "-m", "1", "-r", "1",
            "--seed", "0", "-o", str(f),
        )
        assert r.returncode == 0
        out = run_json("solve", "-a", "dsa", "-n", "30", str(f))
        assert out["status"] == "FINISHED"

    def test_generated_mixed_problem_solves(self, tmp_path):
        # hard+soft mix, binary: MixedDSA's natural workload (reference
        # generate_mixed_problem, commands/generate.py:449)
        f = tmp_path / "mixed.yaml"
        r = run_cli(
            "generate", "mixed_problem", "-v", "6", "-c", "6",
            "-H", "0.4", "-r", "3", "-d", "0.4", "--seed", "1",
            "-o", str(f),
        )
        assert r.returncode == 0, r.stderr
        text = f.read_text()
        assert "inf" in text  # some hard constraints made it in
        out = run_json("solve", "-a", "mixeddsa", "-n", "40", str(f))
        assert out["status"] == "FINISHED"
        # hard pair constraints are disequalities over 3 levels on a sparse
        # graph: always satisfiable
        assert out["violation"] == 0

    def test_generated_mixed_problem_nary(self, tmp_path):
        # arity-3 scopes go through the bipartite scope builder; every
        # variable must appear in some constraint and no scope exceeds 3
        f = tmp_path / "mixed3.yaml"
        r = run_cli(
            "generate", "mixed_problem", "-v", "8", "-c", "10",
            "-H", "0.2", "-A", "3", "-r", "4", "-d", "0.5", "--seed", "5",
            "-o", str(f),
        )
        assert r.returncode == 0, r.stderr
        from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

        dcop = load_dcop_from_file(str(f))
        assert len(dcop.variables) == 8
        covered = set()
        for c in dcop.constraints.values():
            assert 1 <= len(c.dimensions) <= 3
            covered.update(v.name for v in c.dimensions)
        assert covered == set(dcop.variables)
        out = run_json("solve", "-a", "dsa", "-n", "40", str(f))
        assert out["status"] == "FINISHED"

    def test_generated_mixed_problem_unary(self, tmp_path):
        f = tmp_path / "mixed1.yaml"
        r = run_cli(
            "generate", "mixed_problem", "-v", "5", "-c", "5",
            "-H", "0.4", "-A", "1", "-r", "3", "-d", "1.0", "--seed", "2",
            "-o", str(f),
        )
        assert r.returncode == 0, r.stderr
        out = run_json("solve", "-a", "dpop", str(f))
        assert out["status"] == "FINISHED"
        # unary hard targets are reachable by construction: exactly optimal
        assert out["violation"] == 0

    def test_scenario_generation(self, tmp_path):
        f = tmp_path / "scenario.yaml"
        r = run_cli(
            "generate", "scenario", "--evts_count", "1",
            "--agents", "a0", "a1", "a2", "--delay", "0.1",
            "--initial_delay", "0.1", "--end_delay", "0.1",
            "-o", str(f),
        )
        assert r.returncode == 0
        from pydcop_tpu.dcop.yamldcop import load_scenario_from_file

        s = load_scenario_from_file(str(f))
        assert len(s.events) >= 2


class TestBatchCli:
    def test_batch_simulate(self, tmp_path):
        bench = tmp_path / "bench.yaml"
        bench.write_text(
            f"""
sets:
  tiny:
    path: "{REF_INSTANCES}/graph_coloring1.yaml"
batches:
  solve_two_algos:
    command: solve
    command_options:
      algo: [dpop, dsa]
      n_cycles: 10
"""
        )
        r = run_cli("batch", str(bench), "--simulate")
        assert r.returncode == 0
        lines = [l for l in r.stdout.splitlines() if "solve" in l]
        assert len(lines) == 2
        assert any("dpop" in l for l in lines)
        assert any("dsa" in l for l in lines)

    def test_batch_runs_and_resumes(self, tmp_path):
        bench = tmp_path / "bench2.yaml"
        out_file = tmp_path / "res_{batch}.json"
        bench.write_text(
            f"""
sets:
  tiny:
    path: "{REF_INSTANCES}/graph_coloring1.yaml"
batches:
  b1:
    command: solve
    command_options:
      algo: dpop
    global_options:
      output: "{out_file}"
"""
        )
        state = tmp_path / "state"
        env = {"PYDCOP_TPU_STATE_DIR": str(state)}
        r = run_cli("batch", str(bench), timeout=180, env=env)
        assert r.returncode == 0, r.stderr
        assert "1 jobs run" in r.stderr
        # progress file renamed to done_* in the STATE dir — never the
        # cwd (the repo root used to accumulate done_bench2_* markers)
        done = [
            p for p in os.listdir(state) if p.startswith("done_bench2")
        ]
        assert len(done) == 1
        # list the subprocess's cwd (run_cli pins it), not pytest's
        assert not [
            p
            for p in os.listdir("/root/repo")
            if p.startswith("done_bench2")
        ]
        # resume: a fresh run with the marker gone but a recreated
        # progress file skips the completed job
        (state / "progress_bench2").write_text(
            (state / done[0]).read_text()
        )
        r = run_cli("batch", str(bench), timeout=180, env=env)
        assert r.returncode == 0, r.stderr
        assert "0 jobs run, 1 skipped" in r.stderr


class TestBatchExpansion:
    """Pure config-expansion semantics (reference tests/unit/test_batch.py
    :58-318): cartesian grids, option formatting and context expansion."""

    def test_one_parameter_grid(self):
        from pydcop_tpu.commands.batch import parameters_configuration

        got = parameters_configuration({"algo": ["dsa", "mgm"]})
        assert got == [{"algo": "dsa"}, {"algo": "mgm"}]

    def test_two_parameter_cartesian_product(self):
        from pydcop_tpu.commands.batch import parameters_configuration

        got = parameters_configuration(
            {"algo": ["dsa", "mgm"], "n": [10, 20, 30]}
        )
        assert len(got) == 6
        assert {(g["algo"], g["n"]) for g in got} == {
            (a, n) for a in ("dsa", "mgm") for n in (10, 20, 30)
        }

    def test_scalar_and_single_element_list(self):
        from pydcop_tpu.commands.batch import parameters_configuration

        got = parameters_configuration({"a": "x", "b": [1]})
        assert got == [{"a": "x", "b": 1}]

    def test_deterministic_order(self):
        from pydcop_tpu.commands.batch import parameters_configuration

        g1 = parameters_configuration({"b": [1, 2], "a": ["x"]})
        g2 = parameters_configuration({"a": ["x"], "b": [1, 2]})
        assert g1 == g2 == [{"a": "x", "b": 1}, {"a": "x", "b": 2}]

    def test_build_command_options_and_context(self):
        from pydcop_tpu.commands.batch import _build_command

        cmd = _build_command(
            "solve",
            {"algo": "dsa", "timeout": 5, "flag": True,
             "algo_params": ["variant:B", "p:0.5"]},
            {"output": "out_{set}.json"},
            {"set": "tiny"},
            file_path="problem.yaml",
        )
        assert cmd[-1] == "problem.yaml"
        assert "--output" in cmd
        assert cmd[cmd.index("--output") + 1] == "out_tiny.json"
        assert cmd[cmd.index("--algo") + 1] == "dsa"
        # True-valued options are bare flags
        i = cmd.index("--flag")
        assert i == len(cmd) - 2 or cmd[i + 1].startswith("--") or (
            cmd[i + 1] == "problem.yaml"
        )
        # list-valued options repeat the flag
        assert cmd.count("--algo_params") == 2

    def test_job_id_stable_and_distinct(self):
        from pydcop_tpu.commands.batch import _job_id

        a = _job_id({"set": "s", "file": "f"}, {"algo": "dsa"})
        b = _job_id({"file": "f", "set": "s"}, {"algo": "dsa"})
        c = _job_id({"set": "s", "file": "f"}, {"algo": "mgm"})
        assert a == b
        assert a != c


class TestConsolidateCli:
    def test_consolidate(self, tmp_path):
        for i, cost in enumerate((1.0, 2.0)):
            (tmp_path / f"r{i}.json").write_text(
                json.dumps({"cost": cost, "status": "FINISHED"})
            )
        out_csv = tmp_path / "all.csv"
        r = run_cli(
            "consolidate", str(tmp_path / "r*.json"),
            "--csv_output", str(out_csv),
        )
        assert r.returncode == 0, r.stderr
        content = out_csv.read_text().splitlines()
        assert len(content) == 3  # header + 2 rows

    def test_consolidate_solution_appends(self, tmp_path):
        # reference --solution semantics (consolidate.py:135): fixed metric
        # columns, repeated invocations append to one campaign table,
        # --replace_output starts over
        for i in range(2):
            (tmp_path / f"r{i}.json").write_text(
                json.dumps(
                    {
                        "time": 0.1 * (i + 1), "cost": float(i),
                        "cycle": 5, "msg_count": 10, "msg_size": 20,
                        "status": "FINISHED",
                    }
                )
            )
        out_csv = tmp_path / "sol.csv"
        for i in range(2):
            r = run_cli(
                "consolidate", "--solution",
                str(tmp_path / f"r{i}.json"),
                "--csv_output", str(out_csv),
            )
            assert r.returncode == 0, r.stderr
        lines = out_csv.read_text().splitlines()
        assert lines[0].split(",") == [
            "time", "cost", "cycle", "msg_count", "msg_size", "status"
        ]
        assert len(lines) == 3  # one header, appended rows
        r = run_cli(
            "consolidate", "--solution", "--replace_output",
            str(tmp_path / "r0.json"), "--csv_output", str(out_csv),
        )
        assert r.returncode == 0, r.stderr
        assert len(out_csv.read_text().splitlines()) == 2  # restarted

    def test_consolidate_distribution_cost(self, tmp_path):
        # reference --distribution_cost semantics (consolidate.py:149):
        # price distribution files against a dcop under an algo's model
        import yaml as _yaml

        dist = tmp_path / "dist.yaml"
        dist.write_text(
            _yaml.dump(
                {
                    "distribution": {
                        "a1": ["v1", "v2"], "a2": ["v3"], "a3": [],
                    }
                }
            )
        )
        out_csv = tmp_path / "cost.csv"
        r = run_cli(
            "consolidate",
            f"{REF_INSTANCES}/graph_coloring1.yaml",
            "--distribution_cost", str(dist), "--algo", "dsa",
            "--csv_output", str(out_csv),
        )
        assert r.returncode == 0, r.stderr
        lines = out_csv.read_text().splitlines()
        assert lines[0].split(",") == [
            "dcop", "distribution", "cost", "hosting", "communication"
        ]
        assert len(lines) == 2
        cost = float(lines[1].split(",")[2])
        assert cost >= 0


class TestReplicaDistCli:
    def test_replica_dist(self):
        out = run_json(
            "replica_dist", "-k", "1", "-a", "dsa", "-d", "adhoc",
            f"{REF_INSTANCES}/graph_coloring_3agts_10vars.yaml",
        )
        assert out["ktarget"] == 1
        placements = out["replica_dist"]
        assert len(placements) == 10
        for hosts in placements.values():
            assert len(hosts) == 1


@pytest.mark.slow
class TestMultiMachineCli:
    @pytest.mark.parametrize("algo,n_expect", [("dpop", 3), ("mgm2", 3)])
    def test_orchestrator_and_agents_over_http(self, tmp_path, algo,
                                               n_expect):
        """The reference's multi-machine deployment: a standalone
        orchestrator process + a standalone agents process talking HTTP,
        driven purely through the CLI — one complete solver (dpop) and
        one local-search cycle protocol (mgm2) over the same topology."""
        import socket
        import time as _time

        def free_port():
            with socket.socket() as s_:
                s_.bind(("127.0.0.1", 0))
                return s_.getsockname()[1]

        orch_port, agent_port = free_port(), free_port()
        gc = tmp_path / "mm.yaml"
        r = run_cli(
            "generate", "graph_coloring", "-v", "3", "-c", "3", "--soft",
            "--seed", "2", "-o", str(gc),
        )
        assert r.returncode == 0
        # the coloring generator declares agents a00000..a00002 in the dcop
        orch = subprocess.Popen(
            [
                sys.executable, "-m", "pydcop_tpu", "orchestrator",
                "-a", algo, "--port", str(orch_port),
                "--address", "127.0.0.1",
                "--register_timeout", "60", str(gc),
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=ENV,
            cwd="/root/repo",
        )
        _time.sleep(2)  # let the orchestrator bind its port
        agents = subprocess.Popen(
            [
                sys.executable, "-m", "pydcop_tpu", "agent",
                "-n", "a00000", "a00001", "a00002", "-p", str(agent_port),
                "--orchestrator", f"127.0.0.1:{orch_port}",
            ],
            stdout=subprocess.DEVNULL,  # never fills: agents must not
            stderr=subprocess.DEVNULL,  # stall on a full pipe mid-solve
            env=ENV,
            cwd="/root/repo",
        )
        try:
            out, err = orch.communicate(timeout=120)
            assert orch.returncode == 0, err
            result = json.loads(out)
            assert result["status"] == "FINISHED"
            assert len(result["assignment"]) == n_expect
        finally:
            for p in (agents, orch):
                if p.poll() is None:
                    p.terminate()
                    try:
                        p.wait(5)
                    except subprocess.TimeoutExpired:
                        p.kill()


class TestDistributionHints:
    def test_must_host_hints_honored_from_yaml(self):
        # SimpleHouse.yml declares distribution_hints.must_host; the adhoc
        # method must keep those computations on their designated agents
        out = run_json(
            "distribute", "-d", "adhoc", "-g", "constraints_hypergraph",
            f"{REF_INSTANCES}/SimpleHouse.yml",
        )
        import yaml as _yaml

        with open(f"{REF_INSTANCES}/SimpleHouse.yml") as f:
            hints = _yaml.safe_load(f)["distribution_hints"]["must_host"]
        dist = out["distribution"]
        for agent, comps in hints.items():
            for c in comps:
                if c in {x for v in dist.values() for x in v}:
                    assert c in dist.get(agent, []), (agent, c, dist)


@pytest.mark.slow
class TestRunCli:
    def test_dynamic_run_with_scenario_and_replication(self, tmp_path):
        gc = tmp_path / "dyn.yaml"
        r = run_cli(
            "generate", "graph_coloring", "-v", "6", "-c", "3", "--soft",
            "--seed", "4", "-o", str(gc),
        )
        assert r.returncode == 0
        scen = tmp_path / "scen.yaml"
        r = run_cli(
            "generate", "scenario", "--evts_count", "1",
            "--dcop_files", str(gc), "--delay", "0.2",
            "--initial_delay", "0.2", "--end_delay", "0.2",
            "--seed", "1", "-o", str(scen),
        )
        assert r.returncode == 0
        out = run_json(
            "run", "-a", "dsa", "-n", "40", "-k", "1",
            "-s", str(scen), str(gc),
            timeout=180,
        )
        assert out["status"] == "FINISHED"
        assert out["violation"] == 0
        assert out["repair_metrics"], "scenario removal must trigger repair"
        rm = out["repair_metrics"][0]
        assert rm["orphans"] and rm["migrated"]


class TestDurabilityCli:
    """graftdur through the real CLI (docs/durability.md): --checkpoint
    writes rotated manifests, --resume continues to the bit-identical
    result, and the checkpoints verb lists/inspects/prunes them."""

    INSTANCE = "tests/instances/graph_coloring.yaml"

    def test_checkpoint_resume_bit_identical(self, tmp_path):
        ck = tmp_path / "ck"
        ref = run_json(
            "solve", "-a", "dsa", "-n", "60", "--seed", "4",
            self.INSTANCE,
        )
        ckpt = run_json(
            "solve", "-a", "dsa", "-n", "60", "--seed", "4",
            "--checkpoint", str(ck), "--checkpoint-every", "16",
            "--checkpoint-keep", "8", self.INSTANCE,
        )
        assert ckpt["cost"] == ref["cost"]
        assert ckpt["assignment"] == ref["assignment"]
        files = sorted(p.name for p in ck.glob("*.npz"))
        assert files == [
            "ckpt-c000000016.npz", "ckpt-c000000032.npz",
            "ckpt-c000000048.npz",
        ]
        rm_csv = tmp_path / "rm.csv"
        resumed = run_json(
            "solve", "-a", "dsa", "-n", "60", "--seed", "4",
            "--resume", str(ck / "ckpt-c000000032.npz"),
            "--run_metrics", str(rm_csv), self.INSTANCE,
        )
        assert resumed["cost"] == ref["cost"]
        assert resumed["assignment"] == ref["assignment"]
        # the per-cycle CSV labels a resumed curve in ABSOLUTE cycles
        rows = rm_csv.read_text().strip().splitlines()
        assert rows[0] == "cycle,cost"
        assert rows[1].startswith("33,")
        assert rows[-1].startswith("60,")
        # resume from the DIRECTORY picks the newest checkpoint
        resumed2 = run_json(
            "solve", "-a", "dsa", "-n", "60", "--seed", "4",
            "--resume", str(ck), self.INSTANCE,
        )
        assert resumed2["assignment"] == ref["assignment"]

    def test_resume_wrong_seed_fails_loudly(self, tmp_path):
        ck = tmp_path / "ck"
        run_json(
            "solve", "-a", "dsa", "-n", "40", "--seed", "4",
            "--checkpoint", str(ck), "--checkpoint-every", "16",
            self.INSTANCE,
        )
        r = run_cli(
            "solve", "-a", "dsa", "-n", "40", "--seed", "5",
            "--resume", str(ck), self.INSTANCE,
        )
        assert r.returncode != 0
        assert "seed" in r.stderr

    def test_checkpoints_verb(self, tmp_path):
        ck = tmp_path / "ck"
        run_json(
            "solve", "-a", "dsa", "-n", "48", "--seed", "1",
            "--checkpoint", str(ck), "--checkpoint-every", "12",
            "--checkpoint-keep", "8", self.INSTANCE,
        )
        r = run_cli("checkpoints", "list", str(ck))
        assert r.returncode == 0
        assert "4 checkpoint(s)" in r.stdout
        assert "dsa" in r.stdout
        out = run_json(
            "checkpoints", "inspect", str(ck / "ckpt-c000000024.npz")
        )
        man = out["manifest"]
        assert man["algo"] == "dsa" and man["cycle"] == 24
        assert man["format"] == "graftdur-v1"
        out = run_json("checkpoints", "prune", str(ck), "--keep", "1")
        assert out["removed"] == 3
        assert len(list(ck.glob("*.npz"))) == 1

    def test_checkpoint_default_dir_under_state_dir(self, tmp_path):
        state = tmp_path / "state"
        r = run_cli(
            "solve", "-a", "dsa", "-n", "40", "--seed", "1",
            "--checkpoint", "--checkpoint-every", "16", self.INSTANCE,
            env={"PYDCOP_TPU_STATE_DIR": str(state)},
        )
        assert r.returncode == 0, r.stderr
        assert list((state / "checkpoints").glob("ckpt-c*.npz"))


class TestCliTimeout:
    """Global -t/--timeout through the CLI (reference dcop_cli.py:59,128):
    an expiring budget must yield the anytime assignment with status
    TIMEOUT, not a crash or an empty result."""

    def test_timeout_reports_anytime_result(self, tmp_path):
        # a 1k-variable MaxSum with a tiny budget cannot finish its
        # 500-cycle request; the result must still carry an assignment
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_graph_coloring,
        )
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        f = tmp_path / "big.yaml"
        f.write_text(dcop_yaml(generate_graph_coloring(
            400, 3, graph="scalefree", m_edge=2, seed=3, soft=True,
        )))
        out = run_json(
            "-t", "0.05", "solve", "-a", "maxsum", "-n", "500",
            str(f), timeout=240,
        )
        assert out["status"] == "TIMEOUT"
        assert len(out["assignment"]) == 400
        assert out["cycle"] < 500

    def test_generous_timeout_finishes(self):
        out = run_json(
            "-t", "60", "solve", "-a", "dpop",
            f"{REF_INSTANCES}/graph_coloring1.yaml",
        )
        assert out["status"] == "FINISHED"
        assert out["cost"] == pytest.approx(-0.1)
