"""graftmem: the analytic HBM capacity model, the live memory plane and
the OOM guardrails (pydcop_tpu/telemetry/memplane.py, docs/observability.md).

The model-vs-measured pins run real CPU solves with the opportunistic
memory_analysis() path on: the prediction must land within ±20% of XLA's
own peak for bench-config-shaped problems (acceptance criterion of
ISSUE 20).  Sizes are deliberately off-round (1013/20021/29x31) so these
tests always see a FRESH compile — a warm jit cache from another test
file would skip the analysis hook.
"""

import argparse
import json

import pytest

from pydcop_tpu.commands.generators.graphcoloring import (
    generate_coloring_arrays,
)
from pydcop_tpu.commands.generators.ising import generate_ising_arrays
from pydcop_tpu.telemetry import metrics_registry, telemetry_off
from pydcop_tpu.telemetry.memplane import (
    DEVICE_GENERATIONS,
    GIB,
    MemoryBudgetExceeded,
    device_limit_bytes,
    hbm_capacity_bytes,
    max_batch_k,
    max_vars_per_device,
    measured_peak_bytes,
    memguard,
    memory_status,
    predict_solve_bytes,
    sample_device_memory,
    shape_of,
    synthetic_shape,
)
from pydcop_tpu.telemetry.profiling import profiling


@pytest.fixture(autouse=True)
def _telemetry_reset():
    yield
    telemetry_off()


def _measured_peak(compiled, algo_mod, params, n_cycles):
    """Run a real solve with the opportunistic memory_analysis() hook on
    and return XLA's peak bytes for the fused solve program."""
    telemetry_off()
    metrics_registry.reset()
    metrics_registry.enabled = True
    profiling.opportunistic_memory = True
    try:
        algo_mod.solve(compiled, dict(params), n_cycles=n_cycles, seed=0)
        return measured_peak_bytes()
    finally:
        telemetry_off()


# ---------------------------------------------------------------------------
# the analytic model: pure-shape properties
# ---------------------------------------------------------------------------


class TestModel:
    def test_shape_of_matches_compiled(self):
        c = generate_coloring_arrays(64, 3, graph="grid", seed=4)
        s = shape_of(c)
        assert s.n_vars == c.n_vars
        assert s.max_domain == 3
        assert s.n_edges == c.n_edges
        assert s.table_bytes > 0 and s.index_bytes > 0

    def test_synthetic_shape_headline_numbers(self):
        s = synthetic_shape(1000, 3, degree=4.0)
        assert s.n_vars == 1000
        assert s.n_edges == 4000
        assert s.n_constraints == 2000
        # each variable's ELL row pads to the next pow2 of its degree
        assert s.ell_n_pad == 1000 * 4

    def test_components_sum_to_total(self):
        pred = predict_solve_bytes(
            algo="maxsum", shape=synthetic_shape(1000, 3)
        )
        informational = {"serve_padding", "donation_saved"}
        total = sum(
            v for k, v in pred["components"].items()
            if k not in informational
        )
        assert total == pred["total_bytes"]
        assert pred["dominant"] not in informational

    def test_batch_k_scales_per_instance_parts(self):
        s = synthetic_shape(500, 3)
        one = predict_solve_bytes(algo="dsa", shape=s, batch_k=1)
        eight = predict_solve_bytes(algo="dsa", shape=s, batch_k=8)
        assert eight["total_bytes"] > one["total_bytes"]
        # the problem plane is shared: 8 tenants cost < 8x one tenant
        assert eight["total_bytes"] < 8 * one["total_bytes"]

    def test_mesh_divides_per_device_bytes(self):
        s = synthetic_shape(4000, 3)
        one = predict_solve_bytes(algo="maxsum", shape=s, mesh=1)
        four = predict_solve_bytes(algo="maxsum", shape=s, mesh=4)
        assert four["per_device_bytes"] < one["per_device_bytes"]

    def test_serve_bucket_charges_pow2_padding(self):
        s = synthetic_shape(600, 3)
        exact = predict_solve_bytes(algo="dsa", shape=s)
        bucketed = predict_solve_bytes(
            algo="dsa", shape=s, serve_bucket=True
        )
        assert bucketed["total_bytes"] > exact["total_bytes"]

    def test_device_table_single_source(self):
        from pydcop_tpu.telemetry.kernelprof import HBM_PEAK_GBPS

        assert HBM_PEAK_GBPS == tuple(
            (kind, gbps) for kind, gbps, _cap in DEVICE_GENERATIONS
        )
        assert hbm_capacity_bytes("TPU v5e") == 16 * GIB
        assert hbm_capacity_bytes("warp core") is None

    def test_max_vars_per_device_monotone_in_limit(self):
        small = max_vars_per_device("maxsum", 3, 4.0, 1 * GIB)
        big = max_vars_per_device("maxsum", 3, 4.0, 16 * GIB)
        assert 0 < small < big
        # the answer actually fits: predict at the answer stays in budget
        pred = predict_solve_bytes(
            algo="maxsum", shape=synthetic_shape(small, 3, degree=4.0)
        )
        assert pred["total_bytes"] <= 1 * GIB * 0.9

    def test_max_batch_k_fits_budget(self):
        k = max_batch_k("dsa", 3, 1000, 4.0, 64 * 1024 * 1024)
        assert k >= 1
        pred = predict_solve_bytes(
            algo="dsa", shape=synthetic_shape(1000, 3, degree=4.0),
            batch_k=k, serve_bucket=True,
        )
        assert pred["total_bytes"] <= 64 * 1024 * 1024 * 0.9


# ---------------------------------------------------------------------------
# model vs measured: the ±20% acceptance pins (3 bench-config shapes)
# ---------------------------------------------------------------------------


class TestModelVsMeasured:
    def _pin(self, compiled, algo_mod, algo, params, n_cycles):
        peak = _measured_peak(compiled, algo_mod, params, n_cycles)
        assert peak is not None, (
            "memory_analysis() unavailable — the opportunistic graftprof "
            "path must provide the measured peak on CPU"
        )
        pred = predict_solve_bytes(
            compiled, algo, dict(params), n_cycles=n_cycles
        )
        ratio = pred["total_bytes"] / peak
        assert 0.8 <= ratio <= 1.2, (
            f"{algo}: predicted {pred['total_bytes']} vs measured "
            f"{peak:.0f} (ratio {ratio:.3f}) outside ±20%"
        )

    def test_maxsum_coloring_cfg2_shape(self):
        # bench config 2 shape: ~1k-var random coloring, D=3, maxsum
        c = generate_coloring_arrays(
            1013, 3, graph="random", p_edge=0.005, seed=11
        )
        from pydcop_tpu.algorithms import maxsum

        self._pin(c, maxsum, "maxsum", {"damping": 0.5}, 10)

    @pytest.mark.slow
    def test_maxsum_ell_scalefree_cfg4_shape(self):
        # bench config 4 shape: large scale-free coloring, D=3, maxsum
        # on the ELL layout (auto at this size)
        c = generate_coloring_arrays(
            20021, 3, graph="scalefree", m_edge=2, seed=7
        )
        from pydcop_tpu.algorithms import maxsum

        self._pin(c, maxsum, "maxsum", {"damping": 0.7}, 6)

    def test_mgm2_ising_cfg3_shape(self):
        # bench config 3 shape: periodic Ising grid, D=2, mgm2
        c = generate_ising_arrays(29, 31, seed=3)
        from pydcop_tpu.algorithms import mgm2

        self._pin(c, mgm2, "mgm2", {}, 8)


# ---------------------------------------------------------------------------
# live memory plane
# ---------------------------------------------------------------------------


class TestLivePlane:
    def test_sample_degrades_gracefully_on_cpu(self):
        # CPU backends offer no memory_stats(): the sample returns None,
        # the degradation is COUNTED, and nothing raises
        metrics_registry.reset()
        metrics_registry.enabled = True
        sample = sample_device_memory("test")
        snap = metrics_registry.snapshot()["metrics"]
        if sample is None:
            unavailable = snap["mem.stats_unavailable"]["values"]
            assert any(
                v["labels"].get("api") == "memory_stats"
                for v in unavailable
            )
        else:  # a backend with real stats publishes the gauges
            assert sample["bytes_in_use"] >= 0

    def test_limit_override_feeds_gauge_and_status(self):
        metrics_registry.reset()
        metrics_registry.enabled = True
        memguard.configure(limit_bytes=123 * 1024 * 1024)
        assert device_limit_bytes() == 123 * 1024 * 1024
        sample_device_memory("test")
        snap = metrics_registry.snapshot()["metrics"]
        assert snap["mem.limit_bytes"]["values"][0]["value"] == (
            123 * 1024 * 1024
        )
        st = memory_status()
        assert st["limit_bytes"] == 123 * 1024 * 1024
        assert st["guard"]["limit_bytes"] == 123 * 1024 * 1024
        assert st["refusals_total"] == 0

    def test_prom_path_carries_mem_series(self):
        from pydcop_tpu.telemetry import render_prometheus

        metrics_registry.reset()
        metrics_registry.enabled = True
        memguard.configure(limit_bytes=1 * GIB)
        sample_device_memory("test")
        text = render_prometheus(metrics_registry.snapshot())
        assert "mem_limit_bytes" in text

    def test_solve_publishes_predicted_bytes(self):
        # run_cycles consults the guard pre-dispatch: with the guard on
        # and no limit breach, the prediction gauge is published
        c = generate_coloring_arrays(36, 3, graph="grid", seed=9)
        from pydcop_tpu.algorithms import dsa

        metrics_registry.reset()
        metrics_registry.enabled = True
        memguard.configure(enabled=True, limit_bytes=1 * GIB)
        dsa.solve(c, {}, n_cycles=5, seed=0)
        snap = metrics_registry.snapshot()["metrics"]
        assert snap["mem.predicted_bytes"]["values"][0]["value"] > 0


# ---------------------------------------------------------------------------
# OOM guardrails
# ---------------------------------------------------------------------------


class TestGuard:
    def test_direct_solve_refusal_names_the_breach(self):
        c = generate_coloring_arrays(64, 3, graph="grid", seed=2)
        from pydcop_tpu.algorithms import dsa

        metrics_registry.reset()
        metrics_registry.enabled = True
        memguard.configure(
            enabled=True, reserve_pct=10.0, limit_bytes=1024
        )
        with pytest.raises(MemoryBudgetExceeded) as exc:
            dsa.solve(c, {}, n_cycles=5, seed=0)
        msg = str(exc.value)
        assert "predicted" in msg and "budget" in msg
        assert exc.value.breach["reason"] == "memory_budget"
        assert exc.value.breach["dominant_component"]
        assert exc.value.breach["limit_bytes"] == 1024
        snap = metrics_registry.snapshot()["metrics"]
        refusals = snap["mem.refusals_total"]["values"]
        assert any(
            v["labels"].get("reason") == "solve" and v["value"] >= 1
            for v in refusals
        )
        assert memory_status()["refusals_total"] >= 1

    def test_no_limit_known_never_refuses(self):
        c = generate_coloring_arrays(25, 3, graph="grid", seed=2)
        from pydcop_tpu.algorithms import dsa

        memguard.configure(enabled=True)  # no override; CPU has no stats
        r = dsa.solve(c, {}, n_cycles=3, seed=0)
        assert r.assignment is not None

    def test_serve_admission_refuses_at_the_door(self):
        from pydcop_tpu.serve import ServeServer, SolveRequest

        srv = ServeServer(port=None, window_ms=5)
        try:
            memguard.configure(enabled=True, limit_bytes=1024)
            with pytest.raises(MemoryBudgetExceeded):
                srv.submit(
                    SolveRequest(
                        "big", generate_coloring_arrays(
                            64, 3, graph="grid", seed=1
                        ), "dsa", {}, 10, 0,
                    )
                )
            # the refused tenant never entered the queue
            assert "big" not in srv.status()["tenants"]
        finally:
            memguard.reset()
            srv.shutdown(drain=True)

    def test_serve_http_structured_503_with_breach(self):
        import urllib.error
        import urllib.request

        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_graph_coloring,
        )
        from pydcop_tpu.dcop.yamldcop import dcop_yaml
        from pydcop_tpu.serve import ServeServer

        metrics_registry.reset()
        metrics_registry.enabled = True  # refusal counters are gated
        srv = ServeServer(port=0, window_ms=5)
        base = f"http://127.0.0.1:{srv.http.port}"
        try:
            memguard.configure(enabled=True, limit_bytes=1024)
            body = json.dumps({
                "dcop_yaml": dcop_yaml(
                    generate_graph_coloring(
                        9, 3, graph="grid", seed=5, extensive=True
                    )
                ),
                "algo": "dsa", "n_cycles": 5, "tenant": "oom",
            }).encode()
            req = urllib.request.Request(
                base + "/solve", data=body, method="POST"
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=30)
            assert exc.value.code == 503
            doc = json.loads(exc.value.read())
            assert doc["mem"]["reason"] == "memory_budget"
            assert doc["mem"]["predicted_bytes"] > doc["mem"]["budget_bytes"]
            assert doc["mem"]["dominant_component"]
            # the /status surface carries the refusal + guard config
            mem_st = srv.status()["memory"]
            assert mem_st["guard"]["enabled"] is True
            assert mem_st["refusals_total"] >= 1
        finally:
            memguard.reset()
            srv.shutdown(drain=True)

    def test_telemetry_off_resets_guard(self):
        memguard.configure(enabled=True, limit_bytes=1)
        telemetry_off()
        assert memguard.enabled is False
        assert memguard.limit_bytes is None


# ---------------------------------------------------------------------------
# rendering: watch memory line, fleet columns, telemetry section
# ---------------------------------------------------------------------------


class TestRendering:
    def test_watch_frame_memory_line(self):
        from pydcop_tpu.commands.watch import _render_frame

        status = {
            "status": "RUNNING", "time": 1.0, "cycle": 5, "cost": -1.0,
            "memory": {
                "bytes_in_use": 2 * GIB, "peak_bytes": 3 * GIB,
                "limit_bytes": 16 * GIB, "headroom_pct": 81.2,
                "refusals_total": 2,
                "guard": {"enabled": True, "reserve_pct": 10.0,
                          "limit_bytes": None},
            },
        }
        frame = _render_frame(status, {}, {})
        (mem_line,) = [
            ln for ln in frame.splitlines() if ln.startswith("memory:")
        ]
        assert "in_use=2.0GiB" in mem_line
        assert "limit=16.0GiB" in mem_line
        assert "headroom=81.2%" in mem_line
        assert "guard=on(10%)" in mem_line
        assert "refusals=2" in mem_line

    def test_watch_frame_degraded_memory_line(self):
        from pydcop_tpu.commands.watch import _render_frame

        status = {
            "status": "RUNNING",
            "memory": {
                "bytes_in_use": None, "peak_bytes": None,
                "limit_bytes": None, "headroom_pct": None,
                "guard": {"enabled": True, "reserve_pct": 15.0,
                          "limit_bytes": None},
            },
        }
        frame = _render_frame(status, {}, {})
        (mem_line,) = [
            ln for ln in frame.splitlines() if ln.startswith("memory:")
        ]
        assert "in_use=-" in mem_line and "guard=on(15%)" in mem_line

    def test_fleet_table_memory_columns(self):
        from pydcop_tpu.commands.watch import _render_fleet_frame

        status = {
            "workers_up": 1, "workers_total": 1,
            "fleet": {"solves": 3, "queue_depth": 0, "dead_letters": 0,
                      "solves_s": 1.0},
            "workers": {
                "w0": {
                    "up": True, "age_s": 0.5, "queue_depth": 1,
                    "queue_watermark": 2, "solves": 3,
                    "occupancy_pct": 50.0,
                    "mem_bytes_in_use": 4 * GIB,
                    "mem_headroom_pct": 74.9, "mem_refusals": 1,
                },
            },
        }
        frame = _render_fleet_frame(status, {})
        header = [
            ln for ln in frame.splitlines() if ln.startswith("worker")
        ][0]
        assert "mem" in header and "hdrm%" in header
        row = [ln for ln in frame.splitlines() if ln.startswith("w0")][0]
        assert "4.0GiB" in row
        assert "74.9" in row
        assert "mem_refused=1" in row

    def test_fleet_collector_lifts_memory_columns(self):
        # the federation row builder lifts the worker's /status memory
        # block into the mem_* columns the fleet table renders
        from pydcop_tpu.telemetry.federate import (
            FleetCollector,
            FleetTarget,
        )

        coll = FleetCollector([FleetTarget("w0", "http://x")])
        w = coll._workers["w0"]
        w["up"] = True
        w["last_ok"] = __import__("time").monotonic()
        w["status"] = {
            "state": "serving", "solves": 1, "queue_depth": 0,
            "memory": {
                "bytes_in_use": 1024, "headroom_pct": 99.0,
                "refusals_total": 2,
            },
        }
        row = coll.status()["workers"]["w0"]
        assert row["mem_bytes_in_use"] == 1024
        assert row["mem_headroom_pct"] == 99.0
        assert row["mem_refusals"] == 2

    def test_telemetry_metrics_memory_section(self, tmp_path, capsys):
        from pydcop_tpu.commands.telemetry import run_cmd as telemetry_cmd

        metrics_registry.reset()
        metrics_registry.enabled = True
        memguard.configure(limit_bytes=1 * GIB)
        sample_device_memory("test")
        snap_file = tmp_path / "metrics.json"
        snap_file.write_text(json.dumps(metrics_registry.snapshot()))
        args = argparse.Namespace(
            trace_file=[], prom=None, metrics=str(snap_file), top=20,
            as_json=False, validate=False, out=None, openmetrics=False,
            output=None,
        )
        assert telemetry_cmd(args) == 0
        out = capsys.readouterr().out
        assert "memory metric" in out
        assert "mem.limit_bytes" in out


# ---------------------------------------------------------------------------
# the memplan verb (output pinned)
# ---------------------------------------------------------------------------


def _memplan(*argv):
    from pydcop_tpu.commands import memplan

    parser = argparse.ArgumentParser()
    sub = parser.add_subparsers(dest="command")
    memplan.set_parser(sub)
    args = parser.parse_args(["memplan", *argv])
    args.output = None
    return args.func(args)


class TestMemplanVerb:
    def test_breakdown_and_verdict_pinned(self, capsys):
        rc = _memplan(
            "--algo", "maxsum", "--n-vars", "100000", "--domain", "3",
            "--degree", "4", "--device", "v5e",
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert (
            "graftmem memplan — algo maxsum (family maxsum, layout ell)"
            in out
        )
        assert "shape: 100000 vars, domain 3, 400000 edges" in out
        assert "device v5e: limit 16.00 GiB, reserve 10% -> budget" in out
        assert "verdict: FITS" in out
        assert "dominant component:" in out

    def test_refuse_verdict(self, capsys):
        rc = _memplan(
            "--algo", "maxsum", "--n-vars", "100000", "--domain", "3",
            "--limit-bytes", str(16 * 1024 * 1024),
        )
        assert rc == 0
        assert "verdict: REFUSE" in capsys.readouterr().out

    def test_capacity_answers(self, capsys):
        rc = _memplan(
            "--algo", "maxsum", "--domain", "3", "--degree", "4",
            "--n-vars", "100000", "--device", "v5e",
            "--max-vars", "--max-batch-k",
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "max vars/device (maxsum, D=3, degree 4):" in out
        assert "max batch-K (maxsum, D=3, 100000 vars):" in out
        # the answers are real numbers, not zeros
        import re

        (n_vars,) = re.findall(r"max vars/device.*: (\d+)", out)
        (batch_k,) = re.findall(r"max batch-K.*: (\d+)", out)
        assert int(n_vars) > 100000
        assert int(batch_k) >= 1

    def test_json_mode(self, capsys):
        rc = _memplan(
            "--algo", "mgm2", "--n-vars", "1000", "--domain", "2",
            "--device", "v4", "--json",
        )
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["fits"] is True
        assert doc["plan"]["total_bytes"] > 0
        assert doc["device"] == "v4"

    def test_errors_without_shape_or_limit(self, capsys):
        assert _memplan("--algo", "maxsum") == 2
        assert _memplan(
            "--algo", "maxsum", "--domain", "3", "--max-vars"
        ) == 2

    def test_dcop_file_exact_shape(self, capsys, tmp_path):
        f = tmp_path / "c.yaml"
        f.write_text(
            """
name: t
objective: min
domains: {d: {values: [0, 1, 2]}}
variables: {v1: {domain: d}, v2: {domain: d}, v3: {domain: d}}
constraints:
  c12: {type: intention, function: 1.0 if v1 == v2 else 0.0}
  c23: {type: intention, function: 1.0 if v2 == v3 else 0.0}
agents: [a1, a2, a3]
"""
        )
        rc = _memplan(str(f), "-a", "dsa", "--device", "v5e")
        assert rc == 0
        out = capsys.readouterr().out
        assert "shape: 3 vars, domain 3, 4 edges, 2 constraints" in out
        assert "verdict: FITS" in out


# ---------------------------------------------------------------------------
# perfdiff memory drift
# ---------------------------------------------------------------------------


class TestPerfdiffMemory:
    def _record(self, predicted, peak, wall=1.0):
        return {
            "metric": "m", "value": wall, "unit": "s",
            "device": "cpu",
            "memory": {
                "predicted_bytes": predicted,
                "measured_peak_bytes": peak,
            },
        }

    def test_memory_growth_flagged(self):
        from pydcop_tpu.telemetry.perfdiff import diff_records

        base = self._record(100 * 1024 * 1024, 100 * 1024 * 1024)
        fresh = self._record(150 * 1024 * 1024, 150 * 1024 * 1024)
        md = diff_records(base, fresh)
        assert any(
            f.startswith("memory predicted bytes") for f in md["flags"]
        )
        assert md["memory"]["predicted_bytes"] == [
            100 * 1024 * 1024, 150 * 1024 * 1024
        ]

    def test_small_drift_not_flagged(self):
        from pydcop_tpu.telemetry.perfdiff import diff_records

        base = self._record(100 * 1024 * 1024, None)
        fresh = self._record(104 * 1024 * 1024, None)
        md = diff_records(base, fresh)
        assert not any(f.startswith("memory ") for f in md["flags"])
