"""Discovery depth tests, modeled on the reference's coverage
(/root/reference/tests/unit/test_infra_discovery.py, ~620 LoC): local
cache semantics, directory publication, subscription callbacks with
state sync, and replica visibility — run over real agent threads with an
in-process directory host, like the runtime does."""

import time

import pytest

pytest.importorskip("jax")

from pydcop_tpu.infrastructure.agents import Agent  # noqa: E402
from pydcop_tpu.infrastructure.communication import (  # noqa: E402
    InProcessCommunicationLayer,
)
from pydcop_tpu.infrastructure.discovery import (  # noqa: E402
    DIRECTORY_COMP_NAME,
    Directory,
    DirectoryComputation,
    Discovery,
    UnknownAgent,
    UnknownComputation,
)


def _wait(predicate, timeout=3.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return predicate()


class TestLocalCache:
    """Synchronous Discovery cache behavior — no directory involved
    (reference :87-110)."""

    def test_register_agent_without_publish(self):
        d = Discovery("a1", "addr1")
        d.register_agent("a2", "addr2", publish=False)
        assert d.agent_address("a2") == "addr2"

    def test_unregister_agent_drops_its_computations(self):
        d = Discovery("a1", "addr1")
        d.register_agent("a2", "addr2", publish=False)
        d.register_computation("c2", agent="a2", publish=False)
        d.unregister_agent("a2", publish=False)
        assert "a2" not in d.agents()
        with pytest.raises(UnknownComputation):
            d.computation_agent("c2")

    def test_unknown_agent_raises(self):
        d = Discovery("a1", "addr1")
        with pytest.raises(UnknownAgent):
            d.agent_address("nope")

    def test_register_computation_defaults_to_own_agent(self):
        d = Discovery("a1", "addr1")
        d.register_computation("c1", publish=False)
        assert d.computation_agent("c1") == "a1"
        # the agent's own address was cached alongside
        assert d.agent_address("a1") == "addr1"

    def test_agent_computations_filter(self):
        d = Discovery("a1", "addr1")
        d.register_computation("c1", publish=False)
        d.register_computation("c2", publish=False)
        d.register_computation("c3", agent="a9", address="x", publish=False)
        assert sorted(d.agent_computations("a1")) == ["c1", "c2"]
        assert d.agent_computations("a9") == ["c3"]


class _Net:
    """A directory host plus n client agents with wired routes."""

    def __init__(self, n_clients=2):
        self.host = Agent("host", InProcessCommunicationLayer())
        self.directory = Directory()
        self.dir_comp = DirectoryComputation(self.directory)
        self.host.add_computation(self.dir_comp, publish=False)
        self.clients = []
        for i in range(n_clients):
            a = Agent(f"a{i}", InProcessCommunicationLayer())
            a.messaging.register_route(
                DIRECTORY_COMP_NAME, "host", self.host.communication.address
            )
            self.host.messaging.register_route(
                f"_discovery_a{i}", f"a{i}", a.communication.address
            )
            self.clients.append(a)
        self.host.start()
        self.dir_comp.start()
        for a in self.clients:
            a.start()
            a.discovery.discovery_computation.start()

    def stop(self):
        for a in self.clients:
            a.clean_shutdown()
            a.join()
        self.host.clean_shutdown()
        self.host.join()


@pytest.fixture()
def net():
    n = _Net()
    yield n
    n.stop()


class TestDirectoryPublication:
    def test_publish_agent_reaches_directory(self, net):
        net.clients[0].discovery.register_agent("a0", "addr0")
        assert _wait(lambda: "a0" in net.directory.agents)

    def test_unpublish_agent(self, net):
        d = net.clients[0].discovery
        d.register_agent("a0", "addr0")
        assert _wait(lambda: "a0" in net.directory.agents)
        d.unregister_agent("a0")
        assert _wait(lambda: "a0" not in net.directory.agents)

    def test_publish_computation_records_host(self, net):
        net.clients[0].discovery.register_computation(
            "comp_x", agent="a0", address="addr0"
        )
        assert _wait(
            lambda: net.directory.computations.get("comp_x") == "a0"
        )


class TestSubscriptions:
    def test_subscribe_gets_current_state_then_updates(self, net):
        d0, d1 = net.clients[0].discovery, net.clients[1].discovery
        d0.register_agent("a0", "addr0")
        assert _wait(lambda: "a0" in net.directory.agents)
        events = []
        d1.subscribe_all_agents(
            lambda evt, name, val: events.append((evt, name))
        )
        # state sync: the already-registered agent arrives on subscribe
        assert _wait(lambda: "a0" in d1.agents())
        # live update: a later registration is pushed too
        d0.register_agent("a0b", "addr0b")
        assert _wait(lambda: "a0b" in d1.agents())
        assert ("agent_added", "a0b") in events

    def test_agent_removal_notifies_subscribers(self, net):
        d0, d1 = net.clients[0].discovery, net.clients[1].discovery
        events = []
        d1.subscribe_all_agents(
            lambda evt, name, val: events.append((evt, name))
        )
        d0.register_agent("gone", "addr")
        assert _wait(lambda: "gone" in d1.agents())
        d0.unregister_agent("gone")
        assert _wait(lambda: ("agent_removed", "gone") in events)
        assert "gone" not in d1.agents()

    def test_subscribe_computation_add_and_remove(self, net):
        d0, d1 = net.clients[0].discovery, net.clients[1].discovery
        events = []
        d1.subscribe_computation(
            "comp_y", lambda evt, name, val: events.append((evt, name, val))
        )
        d0.register_computation("comp_y", agent="a0", address="addr0")
        assert _wait(
            lambda: ("computation_added", "comp_y", "a0") in events
        )
        assert d1.computation_agent("comp_y") == "a0"
        d0.unregister_computation("comp_y")
        assert _wait(
            lambda: ("computation_removed", "comp_y", None) in events
        )
        with pytest.raises(UnknownComputation):
            d1.computation_agent("comp_y")

    def test_unsubscribed_computation_not_pushed(self, net):
        d0, d1 = net.clients[0].discovery, net.clients[1].discovery
        d0.register_computation("quiet", agent="a0", address="addr0")
        assert _wait(
            lambda: "quiet" in net.directory.computations
        )
        time.sleep(0.1)  # give any (wrong) push time to land
        with pytest.raises(UnknownComputation):
            d1.computation_agent("quiet")


class TestReplicas:
    def test_replica_visible_only_to_subscribers(self, net):
        d0, d1 = net.clients[0].discovery, net.clients[1].discovery
        events = []
        d1.subscribe_replica(
            "comp_r", lambda evt, name, val: events.append((evt, name, val))
        )
        d0.register_replica("comp_r", agent="a0")
        assert _wait(
            lambda: ("replica_added", "comp_r", "a0") in events
        )
        assert d1.replica_agents("comp_r") == {"a0"}
        # d0 itself keeps its local view
        assert d0.replica_agents("comp_r") == {"a0"}

    def test_replica_removal_is_pushed(self, net):
        d0, d1 = net.clients[0].discovery, net.clients[1].discovery
        events = []
        d1.subscribe_replica(
            "comp_s", lambda evt, name, val: events.append((evt, name, val))
        )
        d0.register_replica("comp_s", agent="a0")
        assert _wait(lambda: d1.replica_agents("comp_s") == {"a0"})
        d0.unregister_replica("comp_s", agent="a0")
        assert _wait(
            lambda: ("replica_removed", "comp_s", "a0") in events
        )
        assert d1.replica_agents("comp_s") == set()


class TestOneShotAndUnsubscribe:
    """Reference parity (discovery.py one-shot subscriptions +
    unsubscribe, tests test_subscribe_agent_cb_one_shot /
    test_unsubscribe_*): a one-shot callback fires for exactly one event
    then auto-removes; unsubscribing the last callback tells the
    directory to stop pushing."""

    def test_one_shot_agent_callback_fires_once_then_tears_down(self, net):
        d0, d1 = net.clients[0].discovery, net.clients[1].discovery
        events = []
        d1.subscribe_all_agents(
            lambda evt, name, val: events.append(name), one_shot=True
        )
        assert _wait(
            lambda: "a1" in net.directory.subscribers("agent", None)
        )
        d0.register_agent("a0", "addr0")
        assert _wait(lambda: len(events) == 1)
        # the fired one-shot was the only local interest: the directory
        # subscription is torn down like an explicit unsubscribe
        assert _wait(
            lambda: "a1" not in net.directory.subscribers("agent", None)
        )
        d0.register_agent("a0b", "addr0b")
        assert _wait(lambda: "a0b" in net.directory.agents)
        assert events == [events[0]]  # the callback never re-fired

    def test_persistent_callback_keeps_firing(self, net):
        d0, d1 = net.clients[0].discovery, net.clients[1].discovery
        events = []
        d1.subscribe_all_agents(
            lambda evt, name, val: events.append(name)
        )
        d0.register_agent("a0", "addr0")
        d0.register_agent("a0b", "addr0b")
        assert _wait(lambda: len(events) >= 2)

    def test_unsubscribe_specific_callback(self, net):
        d0, d1 = net.clients[0].discovery, net.clients[1].discovery
        kept, dropped = [], []

        def cb_kept(evt, name, val):
            kept.append(name)

        def cb_dropped(evt, name, val):
            dropped.append(name)

        d1.subscribe_all_agents(cb_kept)
        d1.subscribe_all_agents(cb_dropped)
        d1.unsubscribe_all_agents(cb_dropped)
        d0.register_agent("a0", "addr0")
        assert _wait(lambda: kept)
        assert dropped == []

    def test_unsubscribe_computation_stops_directory_pushes(self, net):
        d0, d1 = net.clients[0].discovery, net.clients[1].discovery
        events = []
        d1.subscribe_computation(
            "comp_x", lambda evt, name, val: events.append(evt)
        )
        d1.unsubscribe_computation("comp_x")
        # the directory-side subscription table must be empty again
        assert _wait(
            lambda: "a1" not in net.directory.subscribers(
                "computation", "comp_x"
            )
        )
        d0.register_computation("comp_x", agent="a0", address="addr0")
        assert _wait(
            lambda: net.directory.computations.get("comp_x") == "a0"
        )
        assert events == []

    def test_one_shot_replica_callback(self, net):
        d0, d1 = net.clients[0].discovery, net.clients[1].discovery
        events = []
        d1.subscribe_replica(
            "rep_c", lambda evt, name, val: events.append(evt),
            one_shot=True,
        )
        assert _wait(
            lambda: "a1" in net.directory.subscribers("replica", "rep_c")
        )
        d0.register_replica("rep_c", "a0")
        assert _wait(lambda: events == ["replica_added"])
        # the fired one-shot was the only local interest: the directory
        # stops pushing replica events to a1 (teardown, not just removal)
        assert _wait(
            lambda: "a1" not in net.directory.subscribers(
                "replica", "rep_c"
            )
        )
        d0.unregister_replica("rep_c", "a0")
        assert _wait(lambda: "a0" not in net.directory.replicas["rep_c"])
        assert events == ["replica_added"]  # one-shot: no removal event


class TestUnsubscribePostDiscipline:
    """The directory subscribe/unsubscribe posts must be serialized with
    the local record mutation, and an unsubscribe with no subscription
    must not reach the directory at all (the round-5 lock-gap fix)."""

    @staticmethod
    def _recording_discovery():
        d = Discovery("a1", "addr1")
        posts = []
        d.discovery_computation.post_msg = (
            lambda target, msg, prio=None: posts.append((target, msg))
        )
        return d, posts

    def test_unsubscribe_without_subscription_posts_nothing(self):
        d, posts = self._recording_discovery()
        d.unsubscribe_all_agents()
        d.unsubscribe_computation("never_subscribed")
        d.unsubscribe_replica("never_subscribed")
        assert posts == []

    def test_unsubscribe_after_subscribe_posts_once(self):
        d, posts = self._recording_discovery()
        d.subscribe_computation("comp_x")
        d.unsubscribe_computation("comp_x")
        kinds = [(m.kind, m.subscribe) for _, m in posts]
        assert kinds == [("computation", True), ("computation", False)]
        # a second unsubscribe is a no-op, not another directory post
        d.unsubscribe_computation("comp_x")
        assert len(posts) == 2

    def test_resubscribe_from_oneshot_callback_keeps_subscription(self):
        # the race the fix closes, exercised deterministically: a
        # one-shot callback that re-subscribes runs between the record
        # teardown and (pre-fix) the unsubscribe post — the directory
        # must end up with subscribe=True last, not unsubscribe
        d, posts = self._recording_discovery()

        def resubscribe(evt, name, val):
            d.subscribe_computation("comp_x", lambda *a: None)

        d.subscribe_computation("comp_x", resubscribe, one_shot=True)
        d._fire(
            "computation", "comp_x", "computation_added", "comp_x", "a0"
        )
        flags = [
            m.subscribe for _, m in posts if m.type == "subscribe"
        ]
        # subscribe, teardown, re-subscribe — in exactly that order
        assert flags == [True, False, True]
